#!/usr/bin/env python
"""Generate the committed host-observatory demo evidence trio.

Three schema-valid run records over the same synthetic stage skeleton
(``consensus`` → ``wilcox_test`` → ``tree``), constructed to differ in
exactly one named host cause so the attribution plane's split is
demonstrable (and pinned by test) on committed evidence:

* ``baseline``      — wilcox_test 2.0 s, light GC, one cold compile;
* ``gc-heavy``      — wilcox_test 3.2 s, the growth driven by +1.2 s of
                      measured GC pauses (``host_profile.stages``);
* ``retrace-heavy`` — wilcox_test 3.2 s, the growth driven by +1.2 s of
                      compile wall with 6 retraces (``compile.by_stage``).

``tools/perf_diff.py gc-heavy baseline`` must name ``gc`` as the top
cause and ``retrace-heavy baseline`` must name ``compile/retrace`` —
that is the round-19 acceptance demo, asserted by tests/test_obs_attr.py
against the ledger-ingested copies of these records.

Every section goes through the real builders (obs.hostprof /
obs.compilelog pure functions) and the real ``build_run_record`` +
``Ledger.ingest`` path, so the committed records exercise the same
validators as live bench output. Deterministic: fixed created_unix
stamps, fixed sample streams, no randomness.

Usage:  python tools/make_hostprof_demo.py [--evidence DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.compilelog import build_compile_section  # noqa: E402
from scconsensus_tpu.obs.export import build_run_record  # noqa: E402
from scconsensus_tpu.obs.hostprof import (  # noqa: E402
    build_host_profile,
    build_memory_timeline,
)

PERIOD_S = 0.02  # 50 Hz, the default sampler grid

# fixed identity: distinct created stamps make distinct ledger filenames
# under one shared run key (dataset=hostprofdemo backend=cpu)
CREATED = {"baseline": 1786000001, "gc-heavy": 1786000002,
           "retrace-heavy": 1786000003}


def _spans(walls: List[Tuple[str, float]]) -> List[Dict[str, Any]]:
    out, t0 = [], 0.0
    for i, (name, wall) in enumerate(walls):
        out.append({
            "name": name, "span_id": i, "parent_id": None, "depth": 0,
            "kind": "stage", "t0_s": round(t0, 6),
            "wall_submitted_s": round(wall, 6),
            "wall_synced_s": round(wall, 6), "synced": True,
        })
        t0 += wall
    return out


def _stack_samples(stage_cause_s: Dict[str, Dict[str, float]],
                   frames: Dict[str, str]
                   ) -> List[Tuple[float, Optional[str], str,
                                   Optional[str]]]:
    """Deterministic sample stream: each (stage, cause) contributes
    seconds/PERIOD samples on a synthetic time grid."""
    samples = []
    t = 0.0
    for stage, causes in stage_cause_s.items():
        for cause, secs in causes.items():
            for _ in range(int(round(secs / PERIOD_S))):
                t += PERIOD_S
                samples.append((round(t, 4), stage, cause,
                                frames.get(stage)))
    return samples


def _mem_samples(total_s: float, base_rss: int, peak_rss: int
                 ) -> List[Tuple[float, int, Optional[int],
                                 Optional[str]]]:
    """RSS ramp base → peak → settle over the run, sampled at 10 Hz."""
    n = max(int(total_s * 10), 2)
    out = []
    for i in range(n):
        frac = i / (n - 1)
        ramp = 1.0 - abs(2.0 * frac - 1.0)  # 0 → 1 → 0 triangle
        rss = int(base_rss + (peak_rss - base_rss) * ramp)
        out.append((round(i * 0.1, 4), rss, None,
                    "wilcox_test" if 0.25 <= frac <= 0.75 else None))
    return out


def _compile_events(retraces: int) -> List[Tuple[str, float, str, int]]:
    """One cold compile on wilcox_test entry 1, plus ``retraces``
    re-trace+recompile pairs on entry 2 (0.2 s wall each)."""
    ev = [
        ("/jax/core/compile/jaxpr_trace_duration", 0.05, "wilcox_test", 1),
        ("/jax/core/compile/backend_compile_duration", 0.10,
         "wilcox_test", 1),
    ]
    for _ in range(retraces):
        ev.append(("/jax/core/compile/jaxpr_trace_duration", 0.08,
                   "wilcox_test", 2))
        ev.append(("/jax/core/compile/backend_compile_duration", 0.12,
                   "wilcox_test", 2))
    return ev


def _record(kind: str) -> Dict[str, Any]:
    wilcox_wall = 2.0 if kind == "baseline" else 3.2
    gc_pause = 1.3 if kind == "gc-heavy" else 0.1
    retraces = 6 if kind == "retrace-heavy" else 0
    # python fills whatever wall the named cause doesn't explain — the
    # deltas between records must isolate ONE cause past the noise floor
    python_s = {"baseline": 1.5, "gc-heavy": 1.5,
                "retrace-heavy": 1.5}[kind]

    walls = [("consensus", 1.0), ("wilcox_test", wilcox_wall),
             ("tree", 1.5)]
    cause_s = {
        "consensus": {"python": 0.8},
        "wilcox_test": {"python": python_s, "blocking_wait": 0.2},
        "tree": {"python": 1.2, "serialization": 0.1},
    }
    frames = {
        "consensus": "consensus.py:vote_matrix:88",
        "wilcox_test": "engine.py:rank_chunk:142",
        "tree": "recluster.py:ward_merge:57",
    }
    host_profile = build_host_profile(
        _stack_samples(cause_s, frames),
        gc={"collections": int(round(gc_pause / 0.01)),
            "by_stage": {"wilcox_test": {
                "pauses": int(round(gc_pause / 0.01)),
                "pause_s": gc_pause}}},
        period_s=PERIOD_S,
        sampler_self_s=0.012,
    )
    compile_sec = build_compile_section(
        _compile_events(retraces),
        cache_hits=2 if kind == "baseline" else 0,
    )
    memory_timeline = build_memory_timeline(
        _mem_samples(sum(w for _, w in walls), 310 << 20,
                     (360 if kind == "baseline" else 395) << 20),
        period_s=0.1,
    )
    total = sum(w for _, w in walls)
    rec = build_run_record(
        metric="hostprof demo pipeline wall (synthetic, round 19)",
        value=round(total, 3),
        unit="seconds",
        extra={"config": "hostprofdemo", "platform": "cpu",
               "demo_kind": kind, "synthetic": True},
        spans=_spans(walls),
        host_profile=host_profile,
        compile=compile_sec,
        memory_timeline=memory_timeline,
    )
    rec["run"]["created_unix"] = CREATED[kind]  # deterministic identity
    return rec


def build_demo_records() -> Dict[str, Dict[str, Any]]:
    """kind → record, the importable surface tests pin against."""
    return {kind: _record(kind) for kind in CREATED}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="generate + ingest the host-observatory demo trio")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    args = ap.parse_args(argv)

    from scconsensus_tpu.obs.ledger import Ledger, default_evidence_dir

    led = Ledger(args.evidence or default_evidence_dir(_REPO))
    for kind, rec in build_demo_records().items():
        entry = led.ingest(rec, source="hostprof-demo")
        print(f"{kind:>14}: {entry['file']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
