"""Mesh-vs-serial overhead pin on the 8-virtual-device CPU mesh.

One physical core emulates 8 devices, so parallel SPEEDUP is impossible
by construction — the end-to-end mesh/serial ratio prices the SHARDING
TAX (collectives, padding, per-shard dispatch), which is the quantity a
single-host environment can honestly pin (VERDICT r3 weak #8 / r4 weak
#7: r4 pinned ≤8k-cell toy sizes; this tool is the committed,
reproducible form and extends the range).

Fairness note: the serial CPU path normally takes the r5 tied-run
rank-sum kernel while the mesh path keeps the shard_mapped scan body, so
a naive ratio would mix kernel choice into the sharding tax. Both runs
here set SCC_NO_RUNSPACE=1 to pin the same scan kernel on both sides.

Per-stage dicts are recorded but only the end-to-end totals are
load-bearing under async dispatch (work lands on whichever stage first
blocks). Usage:

    python tools/mesh_overhead.py [NxG ...]     # default 8000x3000 16000x6000

Writes MESH_OVERHEAD_r05.json at the repo root.
"""

import json
import os
import sys
import time

os.environ["SCC_NO_RUNSPACE"] = "1"   # same rank-sum kernel on both sides
os.environ["JAX_PLATFORMS"] = "cpu"   # before ANY jax-importing module

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _REPO)

# 8 virtual devices + the raised collective-rendezvous timeouts: on one
# physical core the default 20/40 s rendezvous aborts the process whenever
# a collective's participants are starved by another in-flight program
# (observed at 16k cells in the mesh silhouette ring). File-path-load the
# shared bootstrap exactly like tests/conftest.py — importing it through
# the package would pull jax into sys.modules BEFORE the flags are set.
import importlib.util as _ilu  # noqa: E402

_spec = _ilu.spec_from_file_location(
    "_xla_bootstrap",
    os.path.join(_REPO, "scconsensus_tpu", "utils", "xla_bootstrap.py"),
)
_boot = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_boot)
_boot.apply_virtual_cpu_xla_flags(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def run_one(n_cells: int, n_genes: int, mesh) -> tuple:
    from scconsensus_tpu import recluster_de_consensus_fast
    from scconsensus_tpu.utils import synthetic_scrna
    from scconsensus_tpu.utils.synthetic import noisy_labeling
    from scconsensus_tpu.consensus.contingency import plot_contingency_table

    k = max(4, min(12, n_cells // 1200))
    data, truth, _ = synthetic_scrna(
        n_genes=n_genes, n_cells=n_cells, n_clusters=k, seed=3
    )
    sup = noisy_labeling(truth, 0.05, seed=1, prefix="S")
    uns = noisy_labeling(truth, 0.08, seed=2, prefix="U")
    cons = plot_contingency_table(sup, uns, filename=None)

    def once():
        t0 = time.perf_counter()
        res = recluster_de_consensus_fast(data, cons, q_val_thrs=0.1,
                                          mesh=mesh)
        return time.perf_counter() - t0, res

    once()                      # compile pass
    secs, res = once()          # steady
    stages = {s["stage"]: round(s["wall_s"], 3)
              for s in res.metrics.get("stages", []) if "wall_s" in s}
    return secs, stages


def main() -> None:
    from scconsensus_tpu.parallel.mesh import make_mesh

    sizes = sys.argv[1:] or ["8000x3000", "16000x6000"]
    out = {
        "note": (
            "8 virtual CPU devices on one physical core: the end-to-end "
            "mesh/serial ratio prices the sharding tax (collectives, "
            "padding, dispatch), not ICI scaling. Both sides run the scan "
            "rank-sum kernel (SCC_NO_RUNSPACE=1) so kernel choice cannot "
            "masquerade as mesh overhead. Stage dicts are async-smeared; "
            "only totals are load-bearing."
        ),
        "sizes": {},
    }
    for s in sizes:
        n, g = (int(v) for v in s.split("x"))
        mesh = make_mesh(8)
        m_secs, m_stages = run_one(n, g, mesh)
        s_secs, s_stages = run_one(n, g, None)
        out["sizes"][s] = {
            "mesh8": round(m_secs, 3), "mesh8_stages": m_stages,
            "serial": round(s_secs, 3), "serial_stages": s_stages,
            "ratio": round(m_secs / s_secs, 3),
        }
        print(f"{s}: mesh {m_secs:.2f}s serial {s_secs:.2f}s "
              f"ratio {m_secs / s_secs:.3f}", flush=True)
    path = os.path.join(_REPO, "MESH_OVERHEAD_r05.json")
    # preserve any hand-recorded negative results (e.g. the 26k virtual-CPU
    # deadlock note) and previously measured sizes across reruns — a
    # refresh of one size must not silently destroy the others
    try:
        with open(path) as f:
            prior = json.load(f)
        if "extra_notes" in prior:
            out["extra_notes"] = prior["extra_notes"]
        for k, v in prior.get("sizes", {}).items():
            out["sizes"].setdefault(k, v)
    except (OSError, json.JSONDecodeError):
        pass
    with open(path, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
