#!/usr/bin/env python
"""Chaos harness: run a bench config under a named fault plan and ingest
the recovered record into the evidence ledger.

    chaos_run.py --plan PLAN.json [--config quick] [--evidence DIR]
                 [--timeout S] [--no-fork] [--expect-recovery]
    chaos_run.py --soak [--soak-plans a,b,...] [--config quick] ...

The bench runs with ``SCC_FAULT_PLAN`` pointing at the plan (robust.faults
injects the named fault classes at their sites) and auto-ingest disabled;
afterwards this tool loads the final checkpoint record, requires a
populated ``robustness`` section (a chaos run that injected nothing is a
FAILED chaos run — it proved nothing), re-keys the record's dataset as
``<config>-chaos`` so chaos walls can NEVER blend into the real config's
regression baselines, and ingests it with ``source="chaos"``.
``--expect-recovery`` additionally fails unless the section claims (and
evidences — validate_run_record enforces that) recovery.

``--soak`` runs the NAMED matrices of fault plans back-to-back under ONE
wall-clock budget (``--timeout`` covers the whole soak; a plan that
would start past the budget is failed as budget-exhausted, never
silently skipped) and emits a single pass/fail soak summary line:
:data:`SOAK_MATRIX` (transient/oom/stall at the classic pipeline sites
plus the elastic device-loss plans, which force an 8-virtual-device CPU
mesh so the shrink ladder is exercised without hardware) and
:data:`SERVE_SOAK_MATRIX` (the serving sites: kill mid-batch with a
restart-and-replay identity check, corrupt model artifact with a typed
quarantine refusal, stalled device calls against short deadlines, oom
under load tripping the breaker into flagged degraded mode — each plan
verifying the serve worker's request accounting) and
:data:`STREAM_SOAK_MATRIX` (round 17, the disk/host-memory axis:
SIGKILL mid-ingest → resume to byte-identical labels, injected torn
chunk → quarantine-and-recompute to identical labels, ENOSPC at the
chunk-write site → typed disk-class recovery, host-budget breach →
window-halving recovery, plus the standing device-loss plan run against
the atlas_query fleet shape so device-class recovery is proven beyond
the anchor pipeline) and :data:`INTEGRITY_SOAK_MATRIX` (round 18, the
silent-corruption axis, driven through the replayable worker ``python
-m scconsensus_tpu.robust.soak`` under ``SCC_INTEGRITY=enforce``:
injected in-computation corruption at a ladder window → detected by an
invariant/ghost-replay check → typed silent_corruption recompute →
labels byte-identical to a clean reference run; repeated corruption
pinned to one device of a forced 8-virtual-device mesh → the elastic
supervisor evicts the miscomputing chip — mesh shrink recorded — and
the run still lands byte-identical labels, extending the r14 plan from
chips that die to chips that lie) and :data:`WORKLOAD_SOAK_MATRIX`
(round 19, the workload-zoo axis, driven through the replayable
scenario worker ``python -m scconsensus_tpu.workloads.soak``: SIGKILL
at a pipeline stage site mid-multi-sample-scenario → the resumed run
adopts the durable stage artifacts and lands labels byte-identical to
an uninterrupted reference, with the evidence carrying the validated
``scenario`` section — kill-resume identity proven beyond the anchor
data geometry). ``--soak-plans`` filters all five matrices by name
(comma-separated) for bounded CI runs.

Exit codes: 0 chaos contract held; 1 it did not; 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.export import validate_run_record  # noqa: E402
from scconsensus_tpu.obs.ledger import (  # noqa: E402
    Ledger,
    default_evidence_dir,
)


# The standing soak matrix: (name, fault rules, expect_recovery,
# needs_mesh). needs_mesh plans run the bench under a forced
# 8-virtual-device CPU mesh (XLA_FLAGS) so device-loss recovery — the
# elastic shrink ladder — is exercised deterministically on any box.
SOAK_MATRIX: List[Tuple[str, List[Dict[str, Any]], bool, bool]] = [
    ("transient-embed",
     [{"site": "stage:embed", "class": "transient", "after": 0},
      {"site": "stage:embed", "class": "transient", "after": 2}],
     True, False),
    ("oom-wilcox-bucket",
     [{"site": "wilcox_bucket", "class": "oom"}],
     True, False),
    ("stall-de",
     [{"site": "stage:de", "class": "stall", "stall_s": 0.2}],
     False, False),
    ("device-loss-de",
     [{"site": "stage:de", "class": "device_loss"}],
     True, True),
    ("device-loss-tree",
     [{"site": "stage:tree", "class": "device_loss"}],
     True, True),
]

# The serving fault-plan matrix (round 15): each plan drives the
# replayable serve-soak worker (python -m scconsensus_tpu.serve.soak)
# under injected faults at the serve sites. The contract every plan
# checks: NO request is silently dropped or mislabeled — each ends as a
# success, a flagged degraded response, a typed rejection, or a
# quarantine entry, and the worker's validated `serving` section
# accounts for all of them (that validation is what "ok" means).
# Modes: "soak" (run under the plan, require accounting + any named
# expectations), "refusal" (corrupt-model plan: the load must refuse
# typed, with the artifact quarantined), "kill-restart" (SIGKILL
# mid-batch, then a restart over the same frozen model must replay the
# reference request set to IDENTICAL labels), "fleet-swap" (round 16:
# hot-swap mid-traffic through the wire front — zero accounting loss
# and no request served by a half-loaded model: every post-swap
# response carries the v2 fingerprint, every response carries exactly
# one known fingerprint), "fleet-replay" (round 16: the same request
# set through 1 vs N replicas must produce the IDENTICAL label sha —
# routing must never change an answer).
SERVE_SOAK_MATRIX: List[Tuple[str, List[Dict[str, Any]], str,
                              Dict[str, Any]]] = [
    ("serve-transient-device",
     [{"site": "serve_device", "class": "transient", "times": 2}],
     "soak", {"expect_all_served": True}),
    ("serve-oom-under-load",
     [{"site": "serve_device", "class": "oom", "times": 6}],
     "soak", {"expect_degraded": True}),
    ("serve-stall-device",
     [{"site": "serve_device", "class": "stall", "stall_s": 0.6,
       "times": 2}],
     "soak", {"deadline_s": 0.25, "expect_deadline": True}),
    ("serve-corrupt-model",
     [{"site": "artifact:consensus_model", "class": "corrupt"}],
     "refusal", {}),
    ("serve-kill-mid-batch",
     [{"site": "serve_batch", "class": "kill", "after": 1}],
     "kill-restart", {}),
    ("swap-under-load", [], "fleet-swap",
     {"replicas": 3, "swap_after_frac": 0.33}),
    ("replay-across-replicas", [], "fleet-replay", {"replicas": 3}),
    # round 20 (telemetry plane): hard-kill one replica mid-traffic.
    # The contract is trace-id CONTINUITY across kill → pool respawn →
    # retry: the killed replica's queued requests refuse typed, the
    # pumps resubmit them under their ORIGINAL trace id, and the
    # postmortem bundle (tools/postmortem.py over the workdir's
    # heartbeat stream + partial record + summary) shows both attempts
    # under one trace — plus the kill itself on the merged timeline.
    ("kill-replica-under-load", [], "fleet-kill",
     {"replicas": 2, "kill_after_frac": 0.2}),
]

# The out-of-core streaming matrix (round 17): each plan drives the
# replayable streaming worker (python -m scconsensus_tpu.stream.soak —
# a deterministic chunked synthetic dataset whose labels_sha is a pure
# function of the seed) under disk-axis faults. The contract: a
# SIGKILLed ingest resumes from the last durable chunk to IDENTICAL
# labels, a torn chunk quarantines-and-recomputes to identical labels,
# ENOSPC at the chunk-write site recovers through the disk-class
# sweep-and-retry with the recovery recorded typed, and a host-budget
# breach recovers through the window-halving ladder — all without a
# byte of label drift. The matrix additionally covers ONE non-anchor
# scenario (ROADMAP item 4 note): the standing device-loss plan run
# against the atlas_query fleet shape, proving device-class recovery
# (breaker → flagged degraded, zero lost requests) beyond the anchor
# refine pipeline.
STREAM_SOAK_MATRIX: List[Tuple[str, List[Dict[str, Any]], str,
                               Dict[str, Any]]] = [
    ("stream-kill-mid-ingest",
     [{"site": "stream_chunk_write", "class": "kill", "after": 2}],
     "stream-kill-resume", {}),
    ("stream-torn-chunk",
     [{"site": "artifact:stream_chunk", "class": "corrupt", "after": 1}],
     "stream-torn", {}),
    ("stream-enospc",
     [{"site": "stream_chunk_write", "class": "disk", "after": 1}],
     "stream-soak", {"expect_disk_recovery": True}),
    ("stream-budget-breach", [], "stream-soak",
     {"stage_budget_mb": 0.7, "expect_halving": True}),
    ("atlas-device-loss",
     [{"site": "serve_device", "class": "device_loss", "times": 6}],
     "atlas-device-loss", {"replicas": 2}),
]

# The workload-zoo matrix (round 19, ROADMAP item 4): each plan drives
# the replayable scenario worker (python -m scconsensus_tpu.workloads
# .soak — the multi-sample scenario's dataset + unaligned per-sample
# labelings, pure functions of the seed, refined over a DURABLE
# artifact store). Mirrors STREAM_SOAK_MATRIX's kill-resume contract on
# a NON-anchor data geometry: a run SIGKILLed at a stage site leaves
# its completed stage artifacts durable; the resumed run must ADOPT
# them (resumed_stages >= 1, never a silent from-zero restart) and land
# a labels_sha byte-identical to an uninterrupted reference — recovery
# proven on a scenario shape, with the evidence scenario-stamped
# (validated `scenario` section + per-batch ARI on the record).
WORKLOAD_SOAK_MATRIX: List[Tuple[str, List[Dict[str, Any]], str,
                                 Dict[str, Any]]] = [
    ("workload-kill-resume",
     [{"site": "stage:tree", "class": "kill", "after": 0}],
     "workload-kill-resume", {}),
]

# The computation-integrity matrix (round 18): each plan drives the
# replayable in-memory worker (python -m scconsensus_tpu.robust.soak —
# the SAME seed-pure planted-marker workload as the streaming soak)
# under SCC_INTEGRITY=enforce with injected IN-COMPUTATION corruption
# (robust.faults "corruption" class: wrong-but-finite values, not
# crashes). The contract: every corruption is DETECTED (an invariant or
# ghost-replay check), recovered through the typed silent_corruption
# recompute, recorded on the validated integrity section, and the
# recovered run's labels_sha is byte-identical to a clean reference.
# The eviction plan pins the corruption to device 7 of a forced
# 8-virtual-device mesh with a large window: in-place recomputes keep
# failing, the eviction threshold trips, and the elastic supervisor
# shrinks the mesh off the lying chip (8 → 4 keeps ids 0-3) — after
# which the device-gated rule stops firing and labels land identical.
INTEGRITY_SOAK_MATRIX: List[Tuple[str, List[Dict[str, Any]], str,
                                  Dict[str, Any]]] = [
    ("integrity-corrupt-ladder",
     [{"site": "wilcox_bucket_out", "class": "corruption",
       "mode": "signflip"}],
     "integrity-recover", {}),
    ("integrity-corrupt-stream",
     [{"site": "stream_block", "class": "corruption",
       "mode": "signflip"}],
     "integrity-recover", {"stream": True}),
    ("integrity-evict-device",
     [{"site": "wilcox_bucket_out", "class": "corruption",
       "mode": "signflip", "times": 99, "device": 7}],
     "integrity-evict", {}),
]


def _fleet_worker(workdir: str, timeout_s: float, n_requests: int,
                  extra_args: Optional[List[str]] = None,
                  summary_name: str = "FLEET_SOAK_SUMMARY.json",
                  plan_path: Optional[str] = None,
                  ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """One fleet-soak worker subprocess; returns (rc, summary|None)."""
    summary_path = os.path.join(workdir, summary_name)
    try:
        os.remove(summary_path)
    except OSError:
        pass
    env = dict(os.environ)
    env.pop("SCC_FAULT_PLAN", None)
    if plan_path:
        env["SCC_FAULT_PLAN"] = os.path.abspath(plan_path)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "scconsensus_tpu.serve.fleet.soak",
           "--dir", workdir, "--requests", str(n_requests),
           "--summary", summary_path] + list(extra_args or [])
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout_s, cwd=_REPO)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        return 124, None
    if rc != 0 and proc.stderr:
        for ln in proc.stderr.strip().splitlines()[-4:]:
            print(f"[fleet-soak] {ln}", file=sys.stderr)
    try:
        with open(summary_path) as f:
            return rc, json.load(f)
    except (OSError, json.JSONDecodeError):
        return rc, None


def _soak_subprocess(module: str, summary_name: str, tag: str,
                     workdir: str, plan_path: Optional[str],
                     timeout_s: float,
                     cmd_extra: Optional[List[str]] = None,
                     env_extra: Optional[Dict[str, str]] = None,
                     ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """The shared soak-worker subprocess spine (one copy for all four
    matrices): stale-summary removal, SCC_FAULT_PLAN arming, CPU
    platform default, timeout→124, stderr tail under ``tag``, summary
    JSON read. Returns (rc, summary|None); rc -9 (SIGKILL) with no
    fresh summary is a kill-plan's expected shape."""
    summary_path = os.path.join(workdir, summary_name)
    try:
        os.remove(summary_path)
    except OSError:
        pass
    env = dict(os.environ)
    env.pop("SCC_FAULT_PLAN", None)
    if plan_path:
        env["SCC_FAULT_PLAN"] = os.path.abspath(plan_path)
    env.setdefault("JAX_PLATFORMS", "cpu")
    for k, v in (env_extra or {}).items():
        env[k] = (env.get(k, "") + " " + v).strip() \
            if k == "XLA_FLAGS" else v
    cmd = [sys.executable, "-m", module,
           "--dir", workdir, "--summary", summary_path] \
        + list(cmd_extra or [])
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout_s, cwd=_REPO)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        return 124, None
    if rc != 0 and proc.stderr:
        for ln in proc.stderr.strip().splitlines()[-4:]:
            print(f"[{tag}] {ln}", file=sys.stderr)
    try:
        with open(summary_path) as f:
            return rc, json.load(f)
    except (OSError, json.JSONDecodeError):
        return rc, None


def _serve_worker(workdir: str, plan_path: Optional[str],
                  timeout_s: float, n_requests: int,
                  extra_args: Optional[List[str]] = None
                  ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """One serve-soak worker subprocess; returns (rc, summary|None)."""
    return _soak_subprocess(
        "scconsensus_tpu.serve.soak", "SOAK_SUMMARY.json", "serve-soak",
        workdir, plan_path, timeout_s,
        cmd_extra=["--requests", str(n_requests)] + list(extra_args or []),
    )


def _stream_worker(workdir: str, plan_path: Optional[str],
                   timeout_s: float,
                   extra_args: Optional[List[str]] = None
                   ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """One streaming-soak worker subprocess; returns (rc, summary|None)."""
    return _soak_subprocess(
        "scconsensus_tpu.stream.soak", "STREAM_SOAK_SUMMARY.json",
        "stream-soak", workdir, plan_path, timeout_s,
        cmd_extra=list(extra_args or []),
    )


def _integrity_worker(workdir: str, plan_path: Optional[str],
                      timeout_s: float,
                      extra_args: Optional[List[str]] = None,
                      mesh8: bool = False,
                      ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """One integrity-soak worker subprocess (SCC_INTEGRITY=enforce);
    returns (rc, summary|None)."""
    env_extra: Dict[str, str] = {"SCC_INTEGRITY": "enforce"}
    if mesh8:
        env_extra["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=8"
    return _soak_subprocess(
        "scconsensus_tpu.robust.soak", "INTEGRITY_SOAK_SUMMARY.json",
        "integrity-soak", workdir, plan_path, timeout_s,
        cmd_extra=["--fresh"] + list(extra_args or []),
        env_extra=env_extra,
    )


def _workload_worker(workdir: str, plan_path: Optional[str],
                     timeout_s: float,
                     extra_args: Optional[List[str]] = None,
                     ) -> Tuple[int, Optional[Dict[str, Any]]]:
    """One workload-zoo soak worker subprocess; returns
    (rc, summary|None)."""
    return _soak_subprocess(
        "scconsensus_tpu.workloads.soak", "WORKLOAD_SOAK_SUMMARY.json",
        "workload-soak", workdir, plan_path, timeout_s,
        cmd_extra=list(extra_args or []),
    )


def run_workload_plan(name: str, rules: List[Dict[str, Any]],
                      mode: str, extra: Dict[str, Any], tmp: str,
                      timeout_s: float, ref_cache: Dict[str, Any]
                      ) -> int:
    """Run one workload-zoo plan; 0 = the scenario chaos contract held.
    ``ref_cache`` shares ONE uninterrupted reference run's labels_sha
    (the scenario is a pure function of the seed)."""
    workdir = os.path.join(tmp, name)
    os.makedirs(workdir, exist_ok=True)
    plan_path = os.path.join(workdir, "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": rules}, f)
    checks: List[Tuple[str, bool]] = []
    deadline = time.monotonic() + timeout_s

    def _left() -> float:
        return max(deadline - time.monotonic(), 1.0)

    if "sha" not in ref_cache:
        ref_dir = os.path.join(tmp, "workload-reference")
        os.makedirs(ref_dir, exist_ok=True)
        rc, ref = _workload_worker(ref_dir, None, _left(), ["--fresh"])
        ref_cache["sha"] = (ref or {}).get("labels_sha") \
            if rc == 0 and ref and ref.get("ok") else None
    ref_sha = ref_cache["sha"]
    checks.append(("reference scenario run clean", ref_sha is not None))
    rc1, _ = _workload_worker(workdir, plan_path, _left(), ["--fresh"])
    checks.append(("kill plan killed the worker mid-pipeline",
                   rc1 != 0))
    rc2, resumed = _workload_worker(workdir, None, _left())
    checks.append(("resume run clean (scenario record validated)",
                   rc2 == 0 and bool(resumed) and resumed.get("ok")))
    checks.append((
        "resume ADOPTED durable stage artifacts (did not restart "
        "from zero)",
        bool(resumed) and len(resumed.get("resumed_stages") or []) >= 1,
    ))
    checks.append((
        "killed-and-resumed scenario produced byte-identical labels",
        bool(resumed) and ref_sha is not None
        and resumed.get("labels_sha") == ref_sha,
    ))
    checks.append((
        "record carries the validated scenario section + per-batch ARI",
        bool(resumed)
        and ((resumed.get("record") or {}).get("scenario") or {}
             ).get("name") == "multi_sample"
        and bool(resumed.get("per_batch_ari")),
    ))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos:{name}] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    return 0 if ok else 1


def run_integrity_plan(name: str, rules: List[Dict[str, Any]],
                       mode: str, extra: Dict[str, Any], tmp: str,
                       timeout_s: float, ref_cache: Dict[str, Any]
                       ) -> int:
    """Run one silent-corruption plan; 0 = the integrity chaos contract
    held. ``ref_cache`` shares ONE clean reference run's labels_sha
    (the workload is a pure function of the seed, and the cross-shape
    audit pins every execution shape to the same sha — one reference
    covers all plans)."""
    workdir = os.path.join(tmp, name)
    os.makedirs(workdir, exist_ok=True)
    plan_path = os.path.join(workdir, "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": rules}, f)
    checks: List[Tuple[str, bool]] = []
    deadline = time.monotonic() + timeout_s

    def _left() -> float:
        return max(deadline - time.monotonic(), 1.0)

    def _reference_sha() -> Optional[str]:
        if "sha" not in ref_cache:
            ref_dir = os.path.join(tmp, "integrity-reference")
            os.makedirs(ref_dir, exist_ok=True)
            rc, ref = _integrity_worker(ref_dir, None, _left())
            ref_cache["sha"] = (ref or {}).get("labels_sha") \
                if rc == 0 and ref and ref.get("ok") else None
        return ref_cache["sha"]

    ref_sha = _reference_sha()
    checks.append(("clean reference run produced labels",
                   ref_sha is not None))
    worker_args = ["--stream"] if extra.get("stream") else []
    if mode == "integrity-evict":
        worker_args += ["--mesh", "auto"]
    rc, summary = _integrity_worker(
        workdir, plan_path, _left(), worker_args,
        mesh8=(mode == "integrity-evict"),
    )
    ig = (summary or {}).get("integrity") or {}
    checks.append(("worker exited 0 (integrity section validated)",
                   rc == 0 and bool(summary) and summary.get("ok")))
    checks.append((
        "injected corruption DETECTED (invariant or ghost replay)",
        bool(summary) and summary.get("detections", 0) >= 1,
    ))
    checks.append((
        "corrupted unit recomputed via typed silent_corruption",
        bool(summary) and (summary.get("recomputes", 0) >= 1
                           or summary.get("sc_retries_recovered", 0)
                           >= 1),
    ))
    if mode == "integrity-evict":
        checks.append((
            "repeated corruption evicted the miscomputing device "
            "(mesh shrink recorded)",
            bool(summary) and summary.get("mesh_transitions", 0) >= 1
            and (summary.get("mesh_final_devices") or 8) < 8,
        ))
    checks.append((
        "recovered run produced byte-identical labels",
        bool(summary) and ref_sha is not None
        and summary.get("labels_sha") == ref_sha,
    ))
    checks.append((
        "detection recorded on the validated integrity section",
        bool(ig) and (
            len(ig.get("violations") or [])
            + len((ig.get("ghost") or {}).get("mismatches") or [])
        ) >= 1,
    ))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos:{name}] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    return 0 if ok else 1


def run_stream_plan(name: str, rules: List[Dict[str, Any]], mode: str,
                    extra: Dict[str, Any], tmp: str, timeout_s: float,
                    ref_cache: Dict[str, Any]) -> int:
    """Run one streaming (or atlas-fleet) fault plan; 0 = the streaming
    chaos contract held. ``ref_cache`` shares ONE uninterrupted
    reference run's labels_sha across the plans that pin label identity
    (the workload is a pure function of the seed, so one reference
    covers them all)."""
    workdir = os.path.join(tmp, name)
    os.makedirs(workdir, exist_ok=True)
    plan_path = os.path.join(workdir, "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": rules}, f)
    checks: List[Tuple[str, bool]] = []
    deadline = time.monotonic() + timeout_s

    def _left() -> float:
        return max(deadline - time.monotonic(), 1.0)

    def _reference_sha() -> Optional[str]:
        if "sha" not in ref_cache:
            ref_dir = os.path.join(tmp, "stream-reference")
            os.makedirs(ref_dir, exist_ok=True)
            rc, ref = _stream_worker(ref_dir, None, _left(), ["--fresh"])
            ref_cache["sha"] = (ref or {}).get("labels_sha") \
                if rc == 0 and ref and ref.get("ok") else None
        return ref_cache["sha"]

    if mode == "stream-kill-resume":
        ref_sha = _reference_sha()
        checks.append(("reference run clean", ref_sha is not None))
        rc1, _ = _stream_worker(workdir, plan_path, _left(), ["--fresh"])
        checks.append(("kill plan killed the worker mid-ingest",
                       rc1 != 0))
        rc2, resumed = _stream_worker(workdir, None, _left())
        checks.append(("resume run clean", rc2 == 0 and bool(resumed)
                       and resumed.get("ok")))
        checks.append((
            "resume adopted durable chunks (did not restart from zero)",
            bool(resumed)
            and (resumed.get("chunks") or {}).get("resumed", 0) >= 1,
        ))
        checks.append((
            "killed-and-resumed run produced byte-identical labels",
            bool(resumed) and resumed.get("labels_sha") == ref_sha,
        ))
    elif mode == "stream-torn":
        ref_sha = _reference_sha()
        checks.append(("reference run clean", ref_sha is not None))
        rc, summary = _stream_worker(workdir, plan_path, _left(),
                                     ["--fresh"])
        checks.append(("worker exited 0 under the torn-chunk plan",
                       rc == 0 and bool(summary) and summary.get("ok")))
        ch = (summary or {}).get("chunks") or {}
        checks.append(("torn chunk quarantined",
                       ch.get("quarantined", 0) >= 1))
        checks.append(("quarantined chunk recomputed through the "
                       "generator", ch.get("recomputed", 0) >= 1))
        checks.append((
            "quarantine-and-recompute produced byte-identical labels",
            bool(summary) and summary.get("labels_sha") == ref_sha,
        ))
    elif mode == "atlas-device-loss":
        # the standing device-loss plan against the atlas_query fleet
        # shape (serve path as a batch workload): device_lost classified
        # by the shared classifier must trip the breaker into flagged
        # degraded service with ZERO lost requests — recovery proven on
        # a non-anchor workload
        rc, summary = _fleet_worker(
            workdir, _left(), 16,
            ["--fresh", "--replicas", str(extra.get("replicas", 2))],
            plan_path=plan_path,
        )
        sv = ((summary or {}).get("record") or {}).get("serving") or {}
        counts = (summary or {}).get("outcome_counts") or {}
        checks.append(("worker exited 0 (wire accounting held under "
                       "device loss)", rc == 0))
        checks.append(("every request resolved", bool(summary)
                       and summary.get("resolved")
                       == summary.get("requests")))
        checks.append(("degraded responses served and flagged",
                       counts.get("degraded", 0) > 0))
        checks.append((
            "breaker tripped on the device_lost class",
            int(((sv.get("breaker") or {}).get("trips")) or 0) >= 1,
        ))
    else:  # "stream-soak"
        args = ["--fresh"]
        if extra.get("stage_budget_mb"):
            args += ["--stage-budget-mb", str(extra["stage_budget_mb"])]
        rc, summary = _stream_worker(workdir, plan_path or None, _left(),
                                     args)
        checks.append(("worker exited 0 (streaming section validated, "
                       "all chunks completed)",
                       rc == 0 and bool(summary) and summary.get("ok")))
        if extra.get("expect_disk_recovery"):
            rb = ((summary or {}).get("record") or {}).get(
                "robustness") or {}
            checks.append((
                "disk-class fault recovered typed at "
                "stream_chunk_write",
                any(r.get("error_class") == "disk" and r.get("recovered")
                    for r in rb.get("retries") or []),
            ))
        if extra.get("expect_halving"):
            checks.append((
                "host-budget breach recovered by halving the window",
                (summary or {}).get("halvings", 0) >= 1,
            ))
            # determinism under degradation: the same tight budget must
            # reproduce the same plan and the same labels (the
            # constrained run swaps the embed to the Gram basis, so it
            # pins against ITSELF, not the unconstrained reference)
            rc2, again = _stream_worker(
                os.path.join(tmp, f"{name}-again"), plan_path or None,
                _left(), args)
            checks.append((
                "same budget reproduces byte-identical labels",
                rc2 == 0 and bool(again) and bool(summary)
                and again.get("labels_sha") == summary.get("labels_sha"),
            ))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos:{name}] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    return 0 if ok else 1


def run_serve_plan(name: str, rules: List[Dict[str, Any]], mode: str,
                   extra: Dict[str, Any], tmp: str,
                   timeout_s: float, n_requests: int = 16) -> int:
    """Run one serving fault plan; 0 = the serving chaos contract held."""
    workdir = os.path.join(tmp, name)
    os.makedirs(workdir, exist_ok=True)
    plan_path = os.path.join(workdir, "plan.json")
    with open(plan_path, "w") as f:
        json.dump({"faults": rules}, f)
    checks: List[Tuple[str, bool]] = []
    # one DEADLINE for the whole plan: multi-run modes (kill-restart is
    # three worker runs) share it, so the plan can never overrun the
    # soak budget by stacking full timeouts per subprocess
    deadline = time.monotonic() + timeout_s

    def _left() -> float:
        return max(deadline - time.monotonic(), 1.0)

    if mode == "fleet-swap":
        # hot-swap mid-traffic through the wire front: the swap IS the
        # chaos — no fault plan needed
        n_fleet = max(int(n_requests), 12)
        swap_after = max(int(n_fleet * float(
            extra.get("swap_after_frac", 0.33))), 1)
        rc, summary = _fleet_worker(
            workdir, _left(), n_fleet,
            ["--fresh", "--replicas", str(extra.get("replicas", 3)),
             "--swap-after", str(swap_after)],
        )
        sv = ((summary or {}).get("record") or {}).get("serving") or {}
        fps = set((summary or {}).get("fps_seen") or [])
        known = {(summary or {}).get("fp_v1"),
                 (summary or {}).get("fp_v2")}
        checks.append(("worker exited 0 (wire+fleet accounting held, "
                       "serving section validated)", rc == 0))
        checks.append(("zero accounting loss across the swap",
                       bool(summary) and summary.get("resolved")
                       == summary.get("requests")
                       and summary.get("accounting_ok") is True))
        checks.append(("hot-swap actually happened mid-traffic",
                       bool(summary and summary.get("swapped")
                            and summary.get("post_swap_responses"))))
        checks.append((
            "no request served by a half-loaded model (every response "
            "carries exactly one known fingerprint)",
            bool(fps) and fps <= known,
        ))
        checks.append(("post-swap requests served by v2 only",
                       bool(summary)
                       and summary.get("post_swap_pure") is True))
        checks.append((
            "swap recorded in the fleet section",
            len((sv.get("fleet") or {}).get("swaps") or []) >= 1,
        ))
    elif mode == "fleet-kill":
        # replica kill under load: trace-id continuity across kill →
        # respawn → retry, proven twice — once on the worker's own
        # attempt log, once through the postmortem bundle's merged
        # cross-process timeline
        n_fleet = max(int(n_requests), 30)
        kill_after = max(int(n_fleet * float(
            extra.get("kill_after_frac", 0.2))), 1)
        rc, summary = _fleet_worker(
            workdir, _left(), n_fleet,
            ["--fresh", "--replicas", str(extra.get("replicas", 2)),
             "--kill-after", str(kill_after), "--heartbeat", "0.15",
             # heavy payloads + extra pumps keep the replicas
             # compute-bound, so their queues hold real depth and the
             # kill deterministically catches queued requests (the
             # refusal -> retry arc under test; the worker retries the
             # kill up to 3x if the first one caught nothing)
             "--cells", "256", "--concurrency", "6"],
        )
        kills = (summary or {}).get("kills") or []
        retried = (summary or {}).get("retried") or {}
        counts = (summary or {}).get("outcome_counts") or {}
        checks.append(("worker exited 0 (accounting held across the "
                       "kill, serving + slo sections validated)",
                       rc == 0))
        checks.append(("replica killed AND respawned back to width",
                       any(k.get("respawned") is not None
                           for k in kills)))
        checks.append((
            "zero lost requests: every request ended served despite "
            "the kill",
            bool(summary) and summary.get("resolved")
            == summary.get("requests")
            and all(k in ("ok", "degraded", "quarantined")
                    for k in counts),
        ))
        checks.append((
            "refused requests were retried and KEPT their trace id "
            "(continuity across kill -> respawn -> retry)",
            len(retried) >= 1
            and summary.get("trace_continuity") is True,
        ))
        # the postmortem bundle over the workdir: both attempts of a
        # retried request under ONE trace, joined across the summary's
        # wire log and the replica process's heartbeat/span evidence
        bundle_path = os.path.join(workdir, "POSTMORTEM_BUNDLE.json")
        pm = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "postmortem.py"),
             workdir, "--out", bundle_path, "--json"],
            capture_output=True, text=True, timeout=_left(), cwd=_REPO,
        )
        bundle: Dict[str, Any] = {}
        try:
            with open(bundle_path) as f:
                bundle = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
        checks.append(("postmortem bundle built", pm.returncode == 0
                       and bool(bundle.get("traces"))))
        two_attempt = {
            tid: evs for tid, evs in (bundle.get("traces") or {}).items()
            if len([e for e in evs
                    if e.get("kind") == "wire_response"]) >= 2
        }
        retried_ids = {atts[0].get("trace_id")
                       for atts in retried.values() if atts}
        checks.append((
            "bundle shows BOTH attempts of a retried request under one "
            "trace id",
            any(tid in two_attempt for tid in retried_ids if tid),
        ))
        checks.append((
            "retried trace joined across sources (wire log + replica "
            "heartbeat/span evidence)",
            any(len({e.get("src") for e in evs}) >= 2
                for evs in two_attempt.values()),
        ))
        checks.append((
            "the kill itself is on the merged timeline",
            any(e.get("kind") == "replica_kill"
                for e in bundle.get("timeline") or []),
        ))
    elif mode == "fleet-replay":
        # same request set through 1 vs N replicas: identical label sha
        reps = int(extra.get("replicas", 3))
        rc1, s1 = _fleet_worker(
            workdir, _left(), n_requests,
            ["--fresh", "--replicas", "1", "--summary",
             os.path.join(workdir, "REPLAY_R1.json")],
            summary_name="REPLAY_R1.json",
        )
        rc2, s2 = _fleet_worker(
            workdir, _left(), n_requests,
            ["--replicas", str(reps), "--summary",
             os.path.join(workdir, f"REPLAY_R{reps}.json")],
            summary_name=f"REPLAY_R{reps}.json",
        )
        checks.append(("1-replica run clean", rc1 == 0 and bool(s1)
                       and s1.get("ok")))
        checks.append((f"{reps}-replica run clean",
                       rc2 == 0 and bool(s2) and s2.get("ok")))
        checks.append((
            f"replayed request set through 1 vs {reps} replicas "
            "produced identical label sha",
            bool(s1) and bool(s2)
            and s1.get("labels_sha") == s2.get("labels_sha"),
        ))
        checks.append((
            "both runs answered from the SAME frozen model",
            bool(s1) and bool(s2)
            and s1.get("fp_v1") == s2.get("fp_v1"),
        ))
    elif mode == "refusal":
        rc, summary = _serve_worker(
            workdir, plan_path, _left(), n_requests,
            ["--fresh", "--expect-refusal"],
        )
        checks.append(("worker exited 0 (typed refusal observed)",
                       rc == 0))
        checks.append(("load refused", bool(summary
                                            and summary.get("refused"))))
        checks.append(("corrupt artifact quarantined",
                       bool(summary and summary.get("quarantined"))))
    elif mode == "kill-restart":
        rc0, ref = _serve_worker(workdir, None, _left(), n_requests)
        checks.append(("reference run clean", rc0 == 0 and bool(ref)
                       and ref.get("ok")))
        rc1, _ = _serve_worker(workdir, plan_path, _left(), n_requests)
        checks.append(("kill plan killed the worker mid-batch",
                       rc1 != 0))
        rc2, restart = _serve_worker(workdir, None, _left(), n_requests)
        checks.append(("restart run clean", rc2 == 0 and bool(restart)
                       and restart.get("ok")))
        checks.append((
            "restart LOADED the frozen model (did not rebuild)",
            bool(restart) and restart.get("model_built") is False,
        ))
        checks.append((
            "replayed request set produced identical labels",
            bool(ref) and bool(restart)
            and ref.get("labels_sha") == restart.get("labels_sha"),
        ))
    else:  # "soak"
        args: List[str] = []
        if extra.get("deadline_s"):
            args += ["--deadline", str(extra["deadline_s"])]
        rc, summary = _serve_worker(workdir, plan_path, _left(),
                                    n_requests, args)
        counts = (summary or {}).get("outcome_counts") or {}
        sv = ((summary or {}).get("record") or {}).get("serving") or {}
        checks.append(("worker exited 0 (accounting held, serving "
                       "section validated)", rc == 0))
        checks.append(("every request resolved", bool(summary)
                       and summary.get("resolved")
                       == summary.get("requests")))
        if extra.get("expect_all_served"):
            checks.append((
                "transient blip recovered in-batch (all ok, none "
                "degraded)",
                counts.get("ok", 0) == n_requests,
            ))
        if extra.get("expect_degraded"):
            checks.append(("degraded responses served and flagged",
                           counts.get("degraded", 0) > 0))
            checks.append((
                "breaker tripped",
                int(((sv.get("breaker") or {}).get("trips")) or 0) >= 1,
            ))
        if extra.get("expect_deadline"):
            checks.append(("stalled requests failed typed "
                           "DeadlineExceeded",
                           counts.get("DeadlineExceeded", 0) > 0))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos:{name}] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    return 0 if ok else 1


def run_soak(config: str, evidence_dir: str, budget_s: float,
             no_fork: bool, only: Optional[List[str]] = None,
             serve_requests: int = 16) -> int:
    """Run the soak matrices (pipeline + serving) back-to-back under one
    wall-clock budget and print a single pass/fail summary JSON line."""
    matrix = [m for m in SOAK_MATRIX if not only or m[0] in only]
    serve_matrix = [m for m in SERVE_SOAK_MATRIX
                    if not only or m[0] in only]
    stream_matrix = [m for m in STREAM_SOAK_MATRIX
                     if not only or m[0] in only]
    integrity_matrix = [m for m in INTEGRITY_SOAK_MATRIX
                        if not only or m[0] in only]
    workload_matrix = [m for m in WORKLOAD_SOAK_MATRIX
                       if not only or m[0] in only]
    if not matrix and not serve_matrix and not stream_matrix \
            and not integrity_matrix and not workload_matrix:
        known = ([m[0] for m in SOAK_MATRIX]
                 + [m[0] for m in SERVE_SOAK_MATRIX]
                 + [m[0] for m in STREAM_SOAK_MATRIX]
                 + [m[0] for m in INTEGRITY_SOAK_MATRIX]
                 + [m[0] for m in WORKLOAD_SOAK_MATRIX])
        print(f"chaos_run: --soak-plans matched nothing "
              f"(known: {known})", file=sys.stderr)
        return 2
    t0 = time.monotonic()
    results: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory(prefix="scc-soak-") as tmp:
        for name, rules, expect_recovery, needs_mesh in matrix:
            remaining = budget_s - (time.monotonic() - t0)
            if remaining <= 0:
                results.append({"plan": name, "ok": False,
                                "outcome": "budget-exhausted"})
                continue
            plan_path = os.path.join(tmp, f"{name}.json")
            with open(plan_path, "w") as f:
                json.dump({"faults": rules}, f)
            saved_xla = os.environ.get("XLA_FLAGS")
            if needs_mesh:
                os.environ["XLA_FLAGS"] = (
                    (saved_xla or "")
                    + " --xla_force_host_platform_device_count=8"
                ).strip()
            t_plan = time.monotonic()
            try:
                rc = run_chaos(plan_path, config, evidence_dir,
                               remaining, no_fork, expect_recovery)
            finally:
                if needs_mesh:
                    if saved_xla is None:
                        os.environ.pop("XLA_FLAGS", None)
                    else:
                        os.environ["XLA_FLAGS"] = saved_xla
            results.append({
                "plan": name, "ok": rc == 0,
                "outcome": "ok" if rc == 0 else f"rc={rc}",
                "elapsed_s": round(time.monotonic() - t_plan, 1),
            })
        for name, rules, mode, extra in serve_matrix:
            remaining = budget_s - (time.monotonic() - t0)
            if remaining <= 0:
                # budget exhaustion is a FAILURE, never a silent skip
                results.append({"plan": name, "ok": False,
                                "outcome": "budget-exhausted"})
                continue
            t_plan = time.monotonic()
            rc = run_serve_plan(name, rules, mode, extra, tmp,
                                remaining, n_requests=serve_requests)
            results.append({
                "plan": name, "ok": rc == 0,
                "outcome": "ok" if rc == 0 else f"rc={rc}",
                "elapsed_s": round(time.monotonic() - t_plan, 1),
            })
        stream_ref: Dict[str, Any] = {}  # one shared reference sha
        for name, rules, mode, extra in stream_matrix:
            remaining = budget_s - (time.monotonic() - t0)
            if remaining <= 0:
                results.append({"plan": name, "ok": False,
                                "outcome": "budget-exhausted"})
                continue
            t_plan = time.monotonic()
            rc = run_stream_plan(name, rules, mode, extra, tmp,
                                 remaining, stream_ref)
            results.append({
                "plan": name, "ok": rc == 0,
                "outcome": "ok" if rc == 0 else f"rc={rc}",
                "elapsed_s": round(time.monotonic() - t_plan, 1),
            })
        workload_ref: Dict[str, Any] = {}  # one shared reference sha
        for name, rules, mode, extra in workload_matrix:
            remaining = budget_s - (time.monotonic() - t0)
            if remaining <= 0:
                results.append({"plan": name, "ok": False,
                                "outcome": "budget-exhausted"})
                continue
            t_plan = time.monotonic()
            rc = run_workload_plan(name, rules, mode, extra, tmp,
                                   remaining, workload_ref)
            results.append({
                "plan": name, "ok": rc == 0,
                "outcome": "ok" if rc == 0 else f"rc={rc}",
                "elapsed_s": round(time.monotonic() - t_plan, 1),
            })
        integrity_ref: Dict[str, Any] = {}  # one shared reference sha
        for name, rules, mode, extra in integrity_matrix:
            remaining = budget_s - (time.monotonic() - t0)
            if remaining <= 0:
                results.append({"plan": name, "ok": False,
                                "outcome": "budget-exhausted"})
                continue
            t_plan = time.monotonic()
            rc = run_integrity_plan(name, rules, mode, extra, tmp,
                                    remaining, integrity_ref)
            results.append({
                "plan": name, "ok": rc == 0,
                "outcome": "ok" if rc == 0 else f"rc={rc}",
                "elapsed_s": round(time.monotonic() - t_plan, 1),
            })
    ok = all(r["ok"] for r in results)
    print(json.dumps({
        "soak": "ok" if ok else "FAIL",
        "config": config,
        "plans": results,
        "budget_s": budget_s,
        "consumed_s": round(time.monotonic() - t0, 1),
    }))
    return 0 if ok else 1


def run_chaos(plan: str, config: str, evidence_dir: str, timeout_s: float,
              no_fork: bool, expect_recovery: bool) -> int:
    if not os.path.exists(plan):
        print(f"chaos_run: plan {plan!r} not found", file=sys.stderr)
        return 2
    ckpt = os.path.join(evidence_dir, f"CHAOS_CHECKPOINT_{config}.json")
    try:  # a stale checkpoint must not masquerade as this run's evidence
        os.remove(ckpt)
    except OSError:
        pass
    env = dict(os.environ)
    env.update({
        "SCC_FAULT_PLAN": os.path.abspath(plan),
        "SCC_BENCH_CONFIG": config,
        "SCC_BENCH_CKPT": ckpt,
        "SCC_BENCH_LEDGER": "0",  # this tool ingests, re-keyed, below
        "SCC_EVIDENCE_DIR": evidence_dir,
    })
    env.setdefault("SCC_BENCH_PLATFORM", "cpu")
    if no_fork:
        env["SCC_BENCH_NO_FORK"] = "1"
    cmd = [sys.executable, os.path.join(_REPO, "bench.py")]
    print(f"[chaos] {config} under plan {plan} "
          f"({'in-process' if no_fork else 'orchestrated'})",
          file=sys.stderr)
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print("[chaos] bench run exceeded the chaos timeout",
              file=sys.stderr)
        return 1
    tail = (proc.stderr or "").strip().splitlines()[-8:]
    for ln in tail:
        print(f"[bench] {ln}", file=sys.stderr)

    rec = None
    try:
        with open(ckpt) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        # fall back to the stdout tail's last JSON line (trimmed record)
        for line in reversed((proc.stdout or "").strip().splitlines()):
            if line.strip().startswith("{"):
                try:
                    rec = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
    if rec is None:
        print("[chaos] bench left no record at all — even dying runs "
              "must checkpoint (that is the robustness contract)",
              file=sys.stderr)
        return 1

    rb = rec.get("robustness")
    checks = [
        ("bench produced a record", True),
        ("record carries a robustness section", bool(rb)),
        ("faults were actually injected",
         bool(rb and (rb.get("faults_injected")
                      or (rb.get("orchestration") or {}).get("attempts")))),
    ]
    if expect_recovery:
        checks.append(("run recovered", bool(rb and rb.get("recovered"))))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    if not ok:
        return 1

    # re-key: chaos walls (backoffs, degraded shapes) must never become
    # the real config's noise-banded baselines
    rec.setdefault("extra", {})["config"] = f"{config}-chaos"
    rec["extra"]["chaos_plan"] = os.path.basename(plan)
    try:
        validate_run_record(rec)
        entry = Ledger(evidence_dir).ingest(rec, source="chaos")
        print(f"[chaos] ingested {entry['file']}", file=sys.stderr)
    except (OSError, ValueError) as e:
        print(f"[chaos] ingest failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps({
        "chaos": "ok", "config": config, "plan": os.path.basename(plan),
        "recovered": bool(rb.get("recovered")),
        "faults_injected": len(rb.get("faults_injected") or []),
        "retries": len(rb.get("retries") or []),
    }))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="fault-plan chaos harness")
    ap.add_argument("--plan", help="fault plan JSON")
    ap.add_argument("--config", default="quick",
                    help="bench config (default: quick)")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-run timeout; with --soak, the ONE budget "
                         "the whole matrix shares")
    ap.add_argument("--no-fork", action="store_true",
                    help="run the worker in-process (no orchestrator "
                         "ladder — kill-class faults then end the run)")
    ap.add_argument("--expect-recovery", action="store_true",
                    help="fail unless the record claims recovery")
    ap.add_argument("--soak", action="store_true",
                    help="run the named soak matrix of fault plans "
                         "back-to-back under one budget")
    ap.add_argument("--soak-plans", default=None,
                    help="comma-separated soak plan names to run "
                         "(default: the full pipeline + serve matrices)")
    ap.add_argument("--serve-requests", type=int, default=16,
                    help="requests per serve-soak plan (default 16)")
    args = ap.parse_args(argv)
    evidence = args.evidence or default_evidence_dir(_REPO)
    os.makedirs(evidence, exist_ok=True)
    if args.soak:
        only = ([s.strip() for s in args.soak_plans.split(",") if s.strip()]
                if args.soak_plans else None)
        return run_soak(args.config, evidence, args.timeout,
                        args.no_fork, only,
                        serve_requests=args.serve_requests)
    if not args.plan:
        ap.error("--plan required (or --soak)")
    return run_chaos(args.plan, args.config, evidence, args.timeout,
                     args.no_fork, args.expect_recovery)


if __name__ == "__main__":
    raise SystemExit(main())
