#!/usr/bin/env python
"""Chaos harness: run a bench config under a named fault plan and ingest
the recovered record into the evidence ledger.

    chaos_run.py --plan PLAN.json [--config quick] [--evidence DIR]
                 [--timeout S] [--no-fork] [--expect-recovery]

The bench runs with ``SCC_FAULT_PLAN`` pointing at the plan (robust.faults
injects the named fault classes at their sites) and auto-ingest disabled;
afterwards this tool loads the final checkpoint record, requires a
populated ``robustness`` section (a chaos run that injected nothing is a
FAILED chaos run — it proved nothing), re-keys the record's dataset as
``<config>-chaos`` so chaos walls can NEVER blend into the real config's
regression baselines, and ingests it with ``source="chaos"``.
``--expect-recovery`` additionally fails unless the section claims (and
evidences — validate_run_record enforces that) recovery.

Exit codes: 0 chaos contract held; 1 it did not; 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.export import validate_run_record  # noqa: E402
from scconsensus_tpu.obs.ledger import (  # noqa: E402
    Ledger,
    default_evidence_dir,
)


def run_chaos(plan: str, config: str, evidence_dir: str, timeout_s: float,
              no_fork: bool, expect_recovery: bool) -> int:
    if not os.path.exists(plan):
        print(f"chaos_run: plan {plan!r} not found", file=sys.stderr)
        return 2
    ckpt = os.path.join(evidence_dir, f"CHAOS_CHECKPOINT_{config}.json")
    try:  # a stale checkpoint must not masquerade as this run's evidence
        os.remove(ckpt)
    except OSError:
        pass
    env = dict(os.environ)
    env.update({
        "SCC_FAULT_PLAN": os.path.abspath(plan),
        "SCC_BENCH_CONFIG": config,
        "SCC_BENCH_CKPT": ckpt,
        "SCC_BENCH_LEDGER": "0",  # this tool ingests, re-keyed, below
        "SCC_EVIDENCE_DIR": evidence_dir,
    })
    env.setdefault("SCC_BENCH_PLATFORM", "cpu")
    if no_fork:
        env["SCC_BENCH_NO_FORK"] = "1"
    cmd = [sys.executable, os.path.join(_REPO, "bench.py")]
    print(f"[chaos] {config} under plan {plan} "
          f"({'in-process' if no_fork else 'orchestrated'})",
          file=sys.stderr)
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print("[chaos] bench run exceeded the chaos timeout",
              file=sys.stderr)
        return 1
    tail = (proc.stderr or "").strip().splitlines()[-8:]
    for ln in tail:
        print(f"[bench] {ln}", file=sys.stderr)

    rec = None
    try:
        with open(ckpt) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        # fall back to the stdout tail's last JSON line (trimmed record)
        for line in reversed((proc.stdout or "").strip().splitlines()):
            if line.strip().startswith("{"):
                try:
                    rec = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
    if rec is None:
        print("[chaos] bench left no record at all — even dying runs "
              "must checkpoint (that is the robustness contract)",
              file=sys.stderr)
        return 1

    rb = rec.get("robustness")
    checks = [
        ("bench produced a record", True),
        ("record carries a robustness section", bool(rb)),
        ("faults were actually injected",
         bool(rb and (rb.get("faults_injected")
                      or (rb.get("orchestration") or {}).get("attempts")))),
    ]
    if expect_recovery:
        checks.append(("run recovered", bool(rb and rb.get("recovered"))))
    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[chaos] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    if not ok:
        return 1

    # re-key: chaos walls (backoffs, degraded shapes) must never become
    # the real config's noise-banded baselines
    rec.setdefault("extra", {})["config"] = f"{config}-chaos"
    rec["extra"]["chaos_plan"] = os.path.basename(plan)
    try:
        validate_run_record(rec)
        entry = Ledger(evidence_dir).ingest(rec, source="chaos")
        print(f"[chaos] ingested {entry['file']}", file=sys.stderr)
    except (OSError, ValueError) as e:
        print(f"[chaos] ingest failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps({
        "chaos": "ok", "config": config, "plan": os.path.basename(plan),
        "recovered": bool(rb.get("recovered")),
        "faults_injected": len(rb.get("faults_injected") or []),
        "retries": len(rb.get("retries") or []),
    }))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="fault-plan chaos harness")
    ap.add_argument("--plan", required=True, help="fault plan JSON")
    ap.add_argument("--config", default="quick",
                    help="bench config (default: quick)")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    ap.add_argument("--timeout", type=float, default=1800.0)
    ap.add_argument("--no-fork", action="store_true",
                    help="run the worker in-process (no orchestrator "
                         "ladder — kill-class faults then end the run)")
    ap.add_argument("--expect-recovery", action="store_true",
                    help="fail unless the record claims recovery")
    args = ap.parse_args(argv)
    evidence = args.evidence or default_evidence_dir(_REPO)
    os.makedirs(evidence, exist_ok=True)
    return run_chaos(args.plan, args.config, evidence, args.timeout,
                     args.no_fork, args.expect_recovery)


if __name__ == "__main__":
    raise SystemExit(main())
