"""1M-cell sparse-in FULL-pipeline proof (VERDICT r4 #5; r7 refresh).

The brain1m bench config times the clustering tail only (landmark
recluster+cut+silhouette on an embedding). This runner exercises the
never-densify contract (SURVEY.md §2b N12) at its design scale through the
WHOLE product pipeline: sparse CSR 1M×G expression matrix → consensus →
all-pairs DE (CSR-compacted window ladder, r6) → union → PCA embed →
landmark recluster (r7: sketch-fitted Lloyd, Ward on k ≪ N landmarks,
device nearest-landmark cut propagation) → dynamic cuts → pooled
silhouette estimator reusing the landmark pool → NODG — the path the
reference densifies at R/reclusterDEConsensus.R:32 and must never be
densified here. r7 change vs the r6 artifact: the tree stage's 11
full-data Lloyd sweeps (396 s of the 676 s pipe) are replaced by the
landmark engine above SCC_TREE_LANDMARK_THRESHOLD.

The matrix is generated DIRECTLY in CSR form (per-gene nonzero draws;
no dense intermediate at any point). Evidence artifact: ingested into
the ledger as SCALE_r07_cpu_<cells//1000>k_fullpipe_sparse.json with
the stage dict, peak RSS, the dense-equivalent size it never allocated,
and the numeric fingerprint (drift-gated via NUMERIC_PINS.json). With
SCC_WILCOX_PROBE=1 the run is a synced occupancy DIAGNOSIS (per-bucket
walls serialize dispatch) and instead writes a PROFILE_r07 record.

Run:  python tools/run_sparse_1m.py           (CPU, ~6-10 min at 1M)
Env:  SCC_1M_CELLS / SCC_1M_GENES override the shape (testing).
"""

import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import scipy.sparse as sp


def gen_sparse_scrna(n_cells: int, n_genes: int, n_clusters: int, seed: int = 0):
    """Planted-cluster scRNA-like CSR (G, N) built row-by-row — the dense
    (G, N) matrix never exists. ~5% global nonzero fraction; each cluster
    has marker genes with elevated rates (so consensus/DE have signal)."""
    rng = np.random.default_rng(seed)
    cid = rng.integers(0, n_clusters, n_cells).astype(np.int32)
    base_p = rng.uniform(0.005, 0.05, n_genes)
    # ~8 marker genes per cluster with strongly elevated expression
    markers = {
        k: rng.choice(n_genes, size=8, replace=False)
        for k in range(n_clusters)
    }
    boost = np.ones((n_genes, n_clusters), np.float32)
    for k, gs in markers.items():
        boost[gs, k] = rng.uniform(8.0, 15.0, gs.size)

    indptr = np.zeros(n_genes + 1, np.int64)
    idx_parts, val_parts = [], []
    p_cell = np.empty(n_cells, np.float32)
    for g in range(n_genes):
        np.take(base_p[g] * boost[g], cid, out=p_cell)
        np.clip(p_cell, 0.0, 0.6, out=p_cell)
        mask = rng.random(n_cells, dtype=np.float32) < p_cell
        pos = np.nonzero(mask)[0].astype(np.int32)
        lam = 1.0 + 4.0 * (boost[g, cid[pos]] > 1.0)
        vals = np.log1p(rng.poisson(lam).astype(np.float32) + 1.0)
        idx_parts.append(pos)
        val_parts.append(vals)
        indptr[g + 1] = indptr[g] + pos.size
    mat = sp.csr_matrix(
        (np.concatenate(val_parts), np.concatenate(idx_parts), indptr),
        shape=(n_genes, n_cells),
    )
    return mat, cid


def noisy(labels: np.ndarray, flip: float, k: int, seed: int, prefix: str):
    rng = np.random.default_rng(seed)
    out = labels.copy()
    n = out.size
    m = rng.random(n) < flip
    out[m] = rng.integers(0, k, int(m.sum()))
    return np.array([f"{prefix}{v}" for v in out])


def main() -> None:
    from scconsensus_tpu.config import env_flag

    # numeric-health sentinels default ON for this driver (like bench
    # workers): a NaN born 40 minutes into a 1M run must land span-
    # attributed on the artifact, not in the labels
    os.environ.setdefault("SCC_OBS_NUMERIC", "1")
    # residency audit too: at 1M the transfer ledger IS the scale story
    # (which stages still stream through the host link, and how much)
    os.environ.setdefault("SCC_OBS_RESIDENCY", "audit")

    import jax

    # The env var alone is NOT enough here: the site's axon sitecustomize
    # registers the TPU plugin and wins, hanging backend init on a dead
    # tunnel. Pin CPU via jax.config BEFORE the first backend touch
    # (SCC_1M_PLATFORM overrides for a real accelerator run).
    jax.config.update("jax_platforms", env_flag("SCC_1M_PLATFORM"))
    n_cells = int(env_flag("SCC_1M_CELLS"))
    n_genes = int(env_flag("SCC_1M_GENES"))
    n_clusters = 16

    from scconsensus_tpu import plot_contingency_table, recluster_de_consensus_fast
    from scconsensus_tpu.config import CompatFlags

    probed = bool(env_flag("SCC_WILCOX_PROBE"))
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    from scconsensus_tpu.obs.ledger import default_evidence_dir

    evidence = default_evidence_dir(base)
    os.makedirs(evidence, exist_ok=True)
    if probed:
        # a probed wall is a diagnosis, not a benchmark: route the full
        # occupancy record to the PROFILE artifact and leave the SCALE
        # artifact to an unprobed run
        name = (
            f"PROFILE_r07_wilcox_{n_cells//1000 // 1000}m.json"
            if n_cells >= 1_000_000
            else f"PROFILE_r07_wilcox_{n_cells//1000}k.json"
        )
    else:
        name = f"SCALE_r07_cpu_{n_cells//1000}k_fullpipe_sparse.json"
    # records land INSIDE the ledger now (r7): no root stray + relocate
    # cycle, and the manifest entry carries the fingerprint/transfer
    # stamps the perf gate compares future runs against
    out = os.path.join(evidence, name)

    # Flight recorder: this driver runs 30-60 min and used to leave NOTHING
    # when killed. Heartbeats default ON here (SCC_OBS_HEARTBEAT still
    # overrides the tick; the in-process stall watchdog dumps stacks after
    # SCC_OBS_STALL_S, default 10 min for this driver).
    from scconsensus_tpu.obs.live import LiveRecorder

    # driver defaults apply only when the flags are UNSET — an explicit
    # SCC_OBS_HEARTBEAT=0 / SCC_OBS_STALL_S=0 still means off (the
    # registered semantics), same as every other recorder call site
    recorder = LiveRecorder(
        os.path.splitext(out)[0],
        metric="sparse 1M full-pipeline flight record",
        extra={"platform": env_flag("SCC_1M_PLATFORM"),
               "n_cells": n_cells, "n_genes": n_genes},
        heartbeat_s=(float(env_flag("SCC_OBS_HEARTBEAT"))
                     if "SCC_OBS_HEARTBEAT" in os.environ else 30.0),
        stall_s=(float(env_flag("SCC_OBS_STALL_S"))
                 if "SCC_OBS_STALL_S" in os.environ else 600.0),
    ).start()

    t_all = time.perf_counter()
    t0 = time.perf_counter()
    mat, truth = gen_sparse_scrna(n_cells, n_genes, n_clusters, seed=7)
    gen_s = time.perf_counter() - t0
    nnz_frac = mat.nnz / (n_cells * n_genes)
    print(f"[1m] generated CSR {mat.shape} nnz={mat.nnz} "
          f"({100*nnz_frac:.1f}%) in {gen_s:.1f}s", flush=True)

    sup = noisy(truth, 0.05, n_clusters, 1, "S")
    uns = noisy(truth, 0.10, n_clusters, 2, "U")
    t0 = time.perf_counter()
    consensus = plot_contingency_table(sup, uns, filename=None)
    consensus_s = time.perf_counter() - t0
    print(f"[1m] consensus: {len(set(consensus))} labels in "
          f"{consensus_s:.1f}s", flush=True)

    # r6: silhouette runs (pooled estimator reusing the tree stage's pool;
    # the exact O(N²) path is only taken below approx_threshold). r7: above
    # SCC_TREE_LANDMARK_THRESHOLD (default 200k) the pooled tree path runs
    # the landmark engine; between 50k and the landmark threshold the
    # legacy full-data Lloyd runs byte-identically to r6.
    t0 = time.perf_counter()
    res = recluster_de_consensus_fast(
        mat, consensus,
        q_val_thrs=0.05,
        approx_threshold=50_000,           # force the pooled tree path
        compat=CompatFlags(),
        mesh=None,
    )
    refine_s = time.perf_counter() - t0

    stage_recs = res.metrics.get("stages", [])
    stages = {
        s["stage"]: round(s["wall_s"], 3)
        for s in stage_recs
        if "wall_s" in s
    }
    occupancy = next(
        (s["occupancy"] for s in stage_recs
         if s.get("stage") == "wilcox_test" and "occupancy" in s), None
    )
    peak_rss_gb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    dense_gb = n_cells * n_genes * 4 / 1e9
    sil = [
        {k: d[k] for k in ("deep_split", "silhouette", "silhouette_method")
         if k in d}
        for d in res.deep_split_info
    ]
    from scconsensus_tpu.obs.export import build_run_record, write_json_atomic

    extra = {
        "platform": jax.devices()[0].platform,
        # dataset key for the ledger (run_key): probed runs key apart so
        # their dispatch-serialized walls can never anchor baselines
        "dataset": "sparse-fullpipe" + ("-probed" if probed else ""),
        "n_cells": n_cells, "n_genes": n_genes,
        "nnz_frac": round(nnz_frac, 4),
        "gen_s": round(gen_s, 1),
        "consensus_s": round(consensus_s, 1),
        "stages": stages,
        "union_size": int(res.de_gene_union_idx.size),
        "deep_split_info": res.deep_split_info,
        "peak_rss_gb": round(peak_rss_gb, 2),
        "dense_equivalent_gb": round(dense_gb, 1),
        "never_densified": bool(peak_rss_gb < dense_gb),
        "silhouette": sil,
        "total_wall_s": round(time.perf_counter() - t_all, 1),
    }
    try:
        # numeric fingerprint (obs.regress): DE log-p quantiles + final-
        # label ARI vs the input consensus — drift on future captures
        # gates against the NUMERIC_PINS entry / previous clean run
        from scconsensus_tpu.obs.regress import drift_fingerprint

        fp = drift_fingerprint(log_p=res.de.log_p)
        q = (res.metrics or {}).get("quality") or {}
        ari = (q.get("cluster_structure") or {}).get("ari_vs_input") or {}
        if ari:
            fp["label_ari_vs_input"] = list(ari.values())[-1]
        extra["numeric_fingerprint"] = fp
    except Exception as e:
        print(f"[1m] fingerprint failed: {e!r}", flush=True)

    record = build_run_record(
        metric=f"{n_cells//1000}k-cell sparse-in FULL pipeline "
               "(consensus+DE+union+embed+landmark recluster"
               "+pooled silhouette+nodg) wall-clock"
               + (" PROBED (per-bucket syncs serialize dispatch)"
                  if probed else ""),
        value=round(refine_s + consensus_s, 3),
        unit="seconds",
        vs_baseline=None,  # no reference number exists (BASELINE.md)
        spans=res.metrics.get("spans", []),
        quality=res.metrics.get("quality"),
        residency=res.metrics.get("residency"),
        kernels=res.metrics.get("kernels"),
        extra=extra,
    )
    if probed:
        record["extra"]["occupancy"] = occupancy
    elif occupancy is not None:
        # unprobed runs still carry the cheap (unsynced) bucket shape stats
        record["extra"]["occupancy_buckets"] = occupancy.get("buckets")
        record["extra"]["occupancy_meta"] = {
            k: v for k, v in occupancy.items() if k != "buckets"
        }
    if probed:
        # diagnosis artifact: written but never manifest-indexed — probed
        # walls must not become baselines
        write_json_atomic(out, record)
    else:
        from scconsensus_tpu.obs.ledger import Ledger

        # first capture claims the round-stamped name; repeats take the
        # ledger's timestamped default so ingest's same-name dedup can't
        # eat the prior entry — per-key history must ACCUMULATE (that is
        # what the gate's median-of-≤3 baselines and history_pins read)
        try:
            entry = Ledger(evidence).ingest(
                record, name=None if os.path.exists(out) else name
            )
            out = os.path.join(evidence, entry["file"])
        except ValueError as e:
            # a record that fails schema/quality validation is EXACTLY
            # the anomalous-run evidence worth keeping: write it un-
            # indexed (never a baseline) instead of losing a 10-min run
            print(f"[1m] record failed validation ({e}); writing "
                  "un-indexed", file=sys.stderr, flush=True)
            record["validation_error"] = str(e)
            out = out.replace(".json", "_INVALID.json")
            write_json_atomic(out, record)
    # Perfetto-openable sibling: the same spans as Chrome trace events
    from scconsensus_tpu.obs.export import write_chrome_trace

    write_chrome_trace(out.replace(".json", "_trace.json"),
                       record["spans"])
    recorder.stop("clean")
    print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
