#!/usr/bin/env python
"""Structural perf diff of two run records: rank the root causes.

Where ``tools/perf_gate.py`` answers "did THIS run regress against its
ledger baseline", this answers "what changed between THESE TWO runs" —
any pair of scc-run-record files (committed evidence, fresh bench
checkpoints, two backends' captures), no ledger required. The report is
the obs.attr differential attribution: per-stage wall deltas ranked by
magnitude, each annotated with its driver (transfer bytes at a declared
residency boundary, device-kernel time, dispatched FLOPs, or host-side
by elimination) plus the residency burn-down delta per boundary.

Deterministic by construction: the same two files always print the same
report (pinned by test), so a report pasted into a PR discussion can be
reproduced by anyone from the committed records.

Usage: perf_diff.py CANDIDATE.json BASELINE.json [--json] [--max-causes N]

Exit codes: 0 = report printed, 2 = unreadable/legacy input.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.attr import (  # noqa: E402
    diff_records,
    format_report,
    top_suspect,
)
from scconsensus_tpu.obs.export import check_schema_version  # noqa: E402


def _load(path: str) -> dict:
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SystemExit(f"perf_diff: cannot read {path}: {e}")
    try:
        if check_schema_version(rec, source=path) == "legacy":
            raise ValueError("legacy (pre-schema) record")
    except ValueError as e:
        print(f"perf_diff: {path}: {e} — run tools/perf_gate.py "
              "--upgrade first", file=sys.stderr)
        raise SystemExit(2)
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="rank the root causes between two run records"
    )
    ap.add_argument("candidate", help="the run being explained")
    ap.add_argument("baseline", help="the run it is compared against")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff object instead of text")
    ap.add_argument("--max-causes", type=int, default=10)
    args = ap.parse_args(argv)

    cand = _load(args.candidate)
    base = _load(args.baseline)
    diff = diff_records(
        cand, base,
        candidate_label=os.path.basename(args.candidate),
        baseline_label=os.path.basename(args.baseline),
    )
    if args.json:
        print(json.dumps(diff, indent=1))
    else:
        print(format_report(diff, max_causes=args.max_causes))
        suspect = top_suspect(diff)
        if suspect is not None:
            print(f"top suspect: {suspect['summary']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
