#!/usr/bin/env python
"""Traffic control plane CLI: open-loop load + burn-rate autoscaling.

Composes the round-21 pair — ``serve.fleet.loadgen`` (seeded open-loop
arrival schedules driven through the real wire front) and
``serve.fleet.autoscale`` (the burn-rate control loop actuating replica
width, admission tightening, and degraded mode) — into one run whose
record headlines **sustained RPS at SLO** and is gateable by
``tools/perf_gate.py`` against the evidence ledger's noise bands.

Two modes:

* default — one load run at the chosen profile/rate/mix; writes the
  full summary (with the validated run record) to ``LOAD_SUMMARY.json``
  in the workdir, optionally gates it (``--gate``) and ingests it into
  an evidence ledger (``--evidence``).
* ``--spike-soak`` — the acceptance proof: a spike profile over a small
  admission queue and a 1-replica floor. The contract, checked the
  ``tools/chaos_run.py`` way (one printed checks list): the fleet
  SHEDS the spike via typed 429s (client-class — shed load never burns
  the SLO budget), SCALES UP from its floor to absorb it, RECOVERS back
  to the floor, with ZERO SLO breaches — and every actuation shows up
  on the postmortem bundle's merged incident timeline.

Exit codes: 0 contract held, 1 broken, 2 usage/environment.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

__all__ = ["run_traffic", "run_spike_soak", "main"]

SUMMARY_NAME = "LOAD_SUMMARY.json"
BUNDLE_NAME = "POSTMORTEM_BUNDLE.json"


def _parse_mix(spec: Optional[str]) -> Optional[Dict[str, float]]:
    """``"multi_sample=2,cite_dual=1"`` → weight dict (None passes
    through: loadgen defaults to an equal mix over the zoo)."""
    if not spec:
        return None
    mix: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise argparse.ArgumentTypeError(
                f"mix entry {part!r} is not name=weight")
        name, _, w = part.partition("=")
        try:
            mix[name.strip()] = float(w)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"mix weight {w!r} is not a number")
    return mix or None


def _trim(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The stdout one-liner: the summary minus its bulky members."""
    out = {k: v for k, v in summary.items()
           if k not in ("record", "actuations", "scales",
                        "outcome_counts", "mix_counts")}
    out["n_actuations"] = len(summary.get("actuations") or [])
    out["n_scales"] = len(summary.get("scales") or [])
    return out


def _write_summary(workdir: str, summary: Dict[str, Any]) -> str:
    path = os.path.join(workdir, SUMMARY_NAME)
    with open(path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    return path


def _gate(record: Dict[str, Any], workdir: str,
          evidence_dir: str) -> Tuple[bool, Dict[str, Any]]:
    """Run tools/perf_gate.py over the candidate record; (ok, verdict)."""
    cand = os.path.join(workdir, "LOAD_RECORD.json")
    with open(cand, "w") as f:
        json.dump(record, f, default=str)
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "perf_gate.py"),
         cand, "--evidence", evidence_dir, "--json"],
        capture_output=True, text=True, timeout=300, cwd=_REPO,
    )
    verdict: Dict[str, Any] = {}
    try:
        verdict = json.loads(proc.stdout or "")  # one indented object
    except json.JSONDecodeError:
        pass
    return proc.returncode == 0 and bool(verdict.get("ok")), verdict


def _ingest(record: Dict[str, Any], evidence_dir: str) -> bool:
    from scconsensus_tpu.obs.ledger import Ledger

    try:
        entry = Ledger(evidence_dir).ingest(record, source="loadgen")
        print(f"[load] ingested {entry['file']}", file=sys.stderr)
        return True
    except (OSError, ValueError) as e:
        print(f"[load] ingest failed: {e}", file=sys.stderr)
        return False


def run_traffic(workdir: str, args: argparse.Namespace) -> int:
    """One load run at the requested shape; 0 = clean (and gated clean
    when ``--gate``)."""
    from scconsensus_tpu.serve.fleet.autoscale import AutoscalePolicy
    from scconsensus_tpu.serve.fleet.loadgen import run_load

    policy = None
    if not args.no_autoscale:
        policy = AutoscalePolicy.from_env(
            min_replicas=args.replicas,
            max_replicas=max(args.max_replicas, args.replicas),
        )
    summary = run_load(
        workdir,
        profile=args.profile,
        base_rps=args.rps,
        peak_rps=args.peak,
        duration_s=args.duration,
        seed=args.seed,
        mix=_parse_mix(args.mix),
        arrival=args.arrival,
        replicas=args.replicas,
        cells_per=args.cells,
        n_genes=args.genes,
        queue_capacity=args.queue_cap,
        autoscale=not args.no_autoscale,
        policy=policy,
        heartbeat_s=args.heartbeat,
        fresh=args.fresh,
    )
    _write_summary(workdir, summary)
    ok = bool(summary["ok"])
    print(f"[load] {'ok  ' if ok else 'FAIL'} run clean "
          f"(offered={summary['offered']} good={summary['good']} "
          f"rps_at_slo={summary['rps_at_slo']})", file=sys.stderr)
    rec = summary["record"]
    if args.gate:
        if "invalid" in rec:
            print(f"[load] FAIL record invalid: {rec['invalid']}",
                  file=sys.stderr)
            ok = False
        else:
            gate_ok, verdict = _gate(rec, workdir, args.evidence)
            print(f"[load] {'ok  ' if gate_ok else 'FAIL'} perf gate "
                  f"({len(verdict.get('loadgen') or [])} traffic "
                  "verdict(s))", file=sys.stderr)
            ok = ok and gate_ok
    if ok and args.ingest:
        ok = _ingest(rec, args.evidence)
    print(json.dumps(_trim(summary)))
    return 0 if ok else 1


def run_spike_soak(workdir: str, args: argparse.Namespace) -> int:
    """The spike-soak acceptance proof (shed / scale / recover / zero
    breaches), chaos_run-style: one checks list, exit 0 iff all hold."""
    from scconsensus_tpu.serve.fleet.autoscale import AutoscalePolicy
    from scconsensus_tpu.serve.fleet.loadgen import run_load

    floor = args.replicas
    policy = AutoscalePolicy.from_env(
        min_replicas=floor,
        max_replicas=max(args.max_replicas, floor + 1),
        # spike-tuned hysteresis: react within ~2 ticks, release the
        # extra width soon after the spike clears so the recovery leg
        # fits inside the run's post-spike third. The batcher merges
        # queued requests aggressively, so sampled depth is spiky —
        # ANY standing queue at two consecutive ticks is pressure
        up_ticks=2, down_ticks=4, cooldown_ticks=3,
        queue_high=0.25, queue_low=0.05,
    )
    summary = run_load(
        workdir,
        profile="spike",
        base_rps=args.rps,
        peak_rps=args.peak,
        duration_s=args.duration,
        seed=args.seed,
        mix=_parse_mix(args.mix),
        arrival=args.arrival,
        replicas=floor,
        cells_per=args.cells,
        n_genes=args.genes,
        queue_capacity=args.queue_cap,
        autoscale=True,
        policy=policy,
        heartbeat_s=args.heartbeat,
        fresh=args.fresh,
    )
    # the summary file must exist BEFORE the postmortem runs: the
    # bundle's replica_scale events come from its record's fleet section
    _write_summary(workdir, summary)

    acts = summary.get("actuations") or []
    scales = summary.get("scales") or []
    counts = summary.get("outcome_counts") or {}
    ups = [a for a in acts if a.get("kind") == "scale_up"]
    downs = [a for a in acts if a.get("kind") == "scale_down"]

    checks: List[Tuple[str, bool]] = []
    checks.append(("run clean (every offered request sent, wire "
                   "accounting held)", bool(summary["ok"])))
    checks.append(("fleet scaled UP from its floor to absorb the spike",
                   any(a.get("from") == floor for a in ups)))
    checks.append(("fleet recovered back to its floor after the spike",
                   bool(downs) and bool(scales)
                   and scales[-1].get("to") == floor))
    checks.append(("spike shed via typed 429s (rejected_queue >= 1, "
                   "client-class so the SLO budget never burned)",
                   counts.get("rejected_queue", 0) >= 1))
    checks.append(("zero SLO breaches across the whole run",
                   bool(summary["slo_held"])
                   and not summary["breaches"]))
    checks.append(("nonzero sustained RPS at SLO",
                   float(summary["rps_at_slo"]) > 0.0))
    checks.append(("run record validated (loadgen + serving + slo "
                   "sections)", "invalid" not in summary["record"]))

    # the postmortem bundle over the workdir: the actuation ledger rows
    # and the record's fleet.scales stamps must BOTH land on the merged
    # incident timeline — the control plane is traceable evidence, not
    # a side effect
    bundle_path = os.path.join(workdir, BUNDLE_NAME)
    pm = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "postmortem.py"),
         workdir, "--out", bundle_path, "--json"],
        capture_output=True, text=True, timeout=120, cwd=_REPO,
    )
    bundle: Dict[str, Any] = {}
    try:
        with open(bundle_path) as f:
            bundle = json.load(f)
    except (OSError, json.JSONDecodeError):
        pass
    timeline = bundle.get("timeline") or []
    tl_acts = [e for e in timeline if e.get("kind") == "actuation"]
    checks.append(("postmortem bundle built over the workdir",
                   pm.returncode == 0 and bool(timeline)))
    checks.append(("every actuation is on the merged incident timeline",
                   bool(acts) and len(tl_acts) >= len(acts)))
    checks.append(("fleet resizes mirrored onto the timeline from the "
                   "record's fleet section",
                   any(e.get("kind") == "replica_scale"
                       for e in timeline)))

    ok = all(c for _, c in checks)
    for label, c in checks:
        print(f"[load:spike-soak] {'ok  ' if c else 'FAIL'} {label}",
              file=sys.stderr)
    if ok and args.ingest:
        ok = _ingest(summary["record"], args.evidence)
    print(json.dumps({
        "spike_soak": "ok" if ok else "fail",
        "rps_at_slo": summary["rps_at_slo"],
        "achieved_rps": summary["achieved_rps"],
        "sheds": counts.get("rejected_queue", 0),
        "actuations": len(acts),
        "scale_ups": len(ups),
        "scale_downs": len(downs),
        "breaches": len(summary["breaches"]),
        "workdir": workdir,
    }))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop load generator + burn-rate autoscaler "
                    "over the real wire front")
    ap.add_argument("--dir", default=None,
                    help="workdir (model, ledgers, summary, bundle); "
                         "default: a fresh temp dir")
    ap.add_argument("--profile", default=None,
                    choices=["steady", "diurnal", "spike", "ramp"],
                    help="rate profile (default: SCC_LOADGEN_PROFILE)")
    ap.add_argument("--rps", type=float, default=None,
                    help="base offered rate (default: SCC_LOADGEN_RPS)")
    ap.add_argument("--peak", type=float, default=None,
                    help="peak rate for spike/ramp/diurnal "
                         "(default: 4x base)")
    ap.add_argument("--duration", type=float, default=None,
                    help="run length in seconds "
                         "(default: SCC_LOADGEN_DURATION_S)")
    ap.add_argument("--seed", type=int, default=None,
                    help="arrival-schedule seed "
                         "(default: SCC_LOADGEN_SEED)")
    ap.add_argument("--mix", default=None,
                    help="scenario mix, e.g. multi_sample=2,cite_dual=1 "
                         "(default: equal over the workload zoo)")
    ap.add_argument("--arrival", default="poisson",
                    choices=["poisson", "burst"])
    ap.add_argument("--replicas", type=int, default=1,
                    help="fleet floor (the autoscaler's min width)")
    ap.add_argument("--max-replicas", type=int, default=3,
                    help="autoscaler ceiling")
    ap.add_argument("--cells", type=int, default=None,
                    help="cells per request, scenario-scaled "
                         "(default: 8; spike-soak: 96)")
    ap.add_argument("--genes", type=int, default=120)
    ap.add_argument("--queue-cap", type=int, default=None,
                    help="admission queue capacity (spike-soak "
                         "default: 8)")
    ap.add_argument("--no-autoscale", action="store_true",
                    help="pure load run: no control loop over the pool")
    ap.add_argument("--heartbeat", type=float, default=None,
                    help="live flight-recorder heartbeat seconds")
    ap.add_argument("--fresh", action="store_true",
                    help="rebuild the frozen model artifact")
    ap.add_argument("--evidence", default=None,
                    help="evidence ledger dir (default: "
                         "SCC_EVIDENCE_DIR or <repo>/evidence)")
    ap.add_argument("--ingest", action="store_true",
                    help="ingest the validated record into the "
                         "evidence ledger")
    ap.add_argument("--gate", action="store_true",
                    help="run tools/perf_gate.py over the record "
                         "before ingesting")
    ap.add_argument("--spike-soak", action="store_true",
                    help="run the shed/scale/recover acceptance proof")
    args = ap.parse_args(argv)

    args.evidence = args.evidence or os.environ.get(
        "SCC_EVIDENCE_DIR") or os.path.join(_REPO, "evidence")
    if args.spike_soak:
        # soak-shaped defaults: a 1-replica floor behind a tiny
        # admission queue, heavy payloads (the replica must be
        # compute-bound for queues to hold sampled depth), a >12x spike
        # in the middle third, a tail long enough for the recovery leg
        if args.rps is None:
            args.rps = 12.0
        if args.peak is None:
            args.peak = 12.5 * args.rps
        if args.duration is None:
            args.duration = 15.0
        if args.seed is None:
            args.seed = 7
        if args.queue_cap is None:
            args.queue_cap = 4
        if args.cells is None:
            args.cells = 96
        os.environ.setdefault("SCC_AUTOSCALE_TICK_S", "0.1")
    if args.cells is None:
        args.cells = 8

    workdir = args.dir or tempfile.mkdtemp(prefix="scc_load_")
    os.makedirs(workdir, exist_ok=True)
    try:
        if args.spike_soak:
            return run_spike_soak(workdir, args)
        return run_traffic(workdir, args)
    except KeyboardInterrupt:
        print("[load] interrupted", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
