#!/usr/bin/env python
"""Cross-process postmortem bundle: one merged incident timeline.

A wire request that goes wrong leaves its evidence scattered across
processes: the front counts a typed outcome, the replica's heartbeat
stream logs the request in its recent-trace ring, the quarantine ledger
gets a row, a killed process leaves a ``*_partial.json`` termination
stamp. This tool joins all of it — keyed on the round-20 trace id — into
one bundle:

  * every ``*_heartbeat.jsonl`` stream under the given roots (process
    starts/ends, stall events, and each tick's ``serving.recent``
    trace-id ring, deduplicated across ticks);
  * every ``*_partial.json`` flight-record (termination stamps, plus any
    ``serve_request`` spans carrying a ``trace_id`` attr);
  * every ``*LEDGER*.jsonl`` (the quarantine/drift ledger rows, trace-id
    keyed since round 20, and the autoscaler's typed ``actuation`` rows
    from ``ACTUATION_LEDGER.jsonl`` since round 21);
  * every ``*SUMMARY*.json`` / run-record JSON with per-request
    ``outcomes`` entries or a ``serving`` section (the wire's view:
    status codes, attempts, trace ids).

Output: one JSON bundle (``--out``) and a rendered text timeline. The
bundle's ``traces`` index maps each trace id to its merged cross-process
story — a retried request shows BOTH attempts under one id, which is the
kill-under-load soak's acceptance check (tools/chaos_run.py
``kill-replica-under-load``).

Usage:
  python tools/postmortem.py DIR [DIR2 ...] [--trace ID] [--out PATH]
      [--json] [--max-events N]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

__all__ = [
    "collect_sources",
    "build_bundle",
    "render_text",
    "main",
]


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a torn mid-append line is expected
                if isinstance(doc, dict):
                    out.append(doc)
    except OSError:
        pass
    return out


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, json.JSONDecodeError):
        return None


def collect_sources(roots: List[str]) -> Dict[str, List[str]]:
    """Classified evidence files under the roots (recursive):
    ``{"heartbeat": [...], "partial": [...], "ledger": [...],
    "summary": [...]}`` — each list sorted for deterministic bundles."""
    hb: List[str] = []
    partial: List[str] = []
    ledger: List[str] = []
    summary: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            cands = [root]
        else:
            cands = glob.glob(os.path.join(root, "**", "*"),
                              recursive=True)
        for p in cands:
            if not os.path.isfile(p):
                continue
            name = os.path.basename(p)
            if name.endswith("_heartbeat.jsonl"):
                hb.append(p)
            elif name.endswith("_partial.json"):
                partial.append(p)
            elif "LEDGER" in name.upper() and name.endswith(".jsonl"):
                ledger.append(p)
            elif name.endswith(".json") and ("SUMMARY" in name.upper()
                                             or name.startswith("RUN_")):
                summary.append(p)
    return {"heartbeat": sorted(hb), "partial": sorted(partial),
            "ledger": sorted(ledger), "summary": sorted(summary)}


def _rel(path: str, roots: List[str]) -> str:
    for root in roots:
        if os.path.isdir(root):
            try:
                r = os.path.relpath(path, root)
                if not r.startswith(".."):
                    return r
            except ValueError:
                pass
    return os.path.basename(path)


def _heartbeat_events(path: str, src: str) -> Tuple[
        List[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """(events, process summary) from one heartbeat stream. Request
    events come from each tick's ``serving.recent`` ring, deduplicated
    across ticks on (trace_id, ts, outcome) — the ring is cumulative
    evidence, not per-tick increments."""
    events: List[Dict[str, Any]] = []
    proc: Dict[str, Any] = {"stream": src}
    seen: set = set()
    last_hb_ts = None
    for ln in _read_jsonl(path):
        t = ln.get("t")
        ts = ln.get("ts")
        if t == "header":
            proc.update({"pid": ln.get("pid"),
                         "metric": ln.get("metric"),
                         "started": ts})
            events.append({"ts": ts, "src": src, "kind": "process_start",
                           "pid": ln.get("pid"),
                           "metric": ln.get("metric")})
        elif t == "hb":
            last_hb_ts = ts
            sv = ln.get("serving") or {}
            recents = list(sv.get("recent") or [])
            for rep in (sv.get("fleet") or {}).get("replicas") or []:
                recents.extend(rep.get("recent") or [])
            for r in recents:
                tid = r.get("trace_id")
                key = (tid, r.get("ts"), r.get("outcome"))
                if not tid or key in seen:
                    continue
                seen.add(key)
                ev = {"ts": r.get("ts"), "src": src, "kind": "request",
                      "trace_id": tid, "outcome": r.get("outcome")}
                for k in ("latency_ms", "status"):
                    if r.get(k) is not None:
                        ev[k] = r[k]
                events.append(ev)
            slo = sv.get("slo") or {}
            burns = slo.get("burn") or {}
            worst = max((float(v) for v in burns.values()), default=0.0)
            if worst > 1.0:
                # budget burning faster than it replenishes: worth a
                # timeline mark even without a failed request in the ring
                events.append({"ts": ts, "src": src, "kind": "slo_burn",
                               "availability": slo.get("availability"),
                               "burn": burns})
        elif t == "stall":
            events.append({"ts": ts, "src": src, "kind": "stall",
                           "since_progress_s": ln.get("since_progress_s"),
                           "stalls": ln.get("stalls")})
        elif t == "end":
            proc.update({"ended": ts, "cause": ln.get("cause"),
                         "ticks": ln.get("ticks"),
                         "stalls": ln.get("stalls")})
            events.append({"ts": ts, "src": src, "kind": "process_end",
                           "cause": ln.get("cause")})
    proc.setdefault("last_heartbeat", last_hb_ts)
    return events, (proc if proc.get("pid") is not None
                    or proc.get("ended") else None)


def _partial_events(path: str, src: str) -> List[Dict[str, Any]]:
    """Termination stamp + trace-carrying serve_request spans of one
    ``*_partial.json`` flight record."""
    rec = _read_json(path)
    if rec is None:
        return []
    events: List[Dict[str, Any]] = []
    term = rec.get("termination")
    if isinstance(term, dict):
        events.append({
            "ts": term.get("flushed_unix"), "src": src,
            "kind": "termination", "cause": term.get("cause"),
            "last_span": term.get("last_span"),
            "stalls": term.get("stall_count"),
        })
    # round-22 attribution facts: how many bytes the dying run had
    # already crossed per the burn-down ledger, and whether the
    # accelerator tunnel was known-dead/stale when it ran — both answer
    # "why is the accelerator evidence missing from this bundle"
    bd = rec.get("residency_burndown")
    if isinstance(bd, dict):
        events.append({
            "ts": None, "src": src, "kind": "burndown",
            "total_bytes": bd.get("total_bytes"),
            "todo_item2_bytes": bd.get("todo_item2_bytes"),
            "n_boundaries": bd.get("n_boundaries"),
        })
    tun = rec.get("tunnel")
    if isinstance(tun, dict):
        events.append({
            "ts": None, "src": src, "kind": "tunnel",
            "state": tun.get("state"), "age_s": tun.get("age_s"),
            "last_outcome": tun.get("last_outcome"),
        })
    # round-19 host-observatory facts: what the dying run's host was
    # DOING (sampled causes + GC pauses) and whether it was fighting
    # recompilation — the two classic "slow but no kernel evidence"
    # stories a postmortem has to tell
    hp = rec.get("host_profile")
    if isinstance(hp, dict):
        g = hp.get("gc") or {}
        events.append({
            "ts": None, "src": src, "kind": "host_profile",
            "n_samples": hp.get("n_samples"),
            "gc_pause_s": g.get("pause_s"),
            "gc_collections": g.get("collections"),
        })
    comp = rec.get("compile")
    if isinstance(comp, dict):
        events.append({
            "ts": None, "src": src, "kind": "compile",
            "compiles": comp.get("compiles"),
            "retraces": comp.get("retraces"),
            "cache_hits": comp.get("cache_hits"),
            "compile_wall_s": comp.get("compile_wall_s"),
        })
    # round-24 graph-passport facts: did the dying run's COMPILED
    # programs carry host crossings (transfer ops / callbacks) or
    # donation misses — the static complement to the runtime burndown
    gr = rec.get("graphs")
    if isinstance(gr, dict):
        tot = gr.get("totals") or {}
        events.append({
            "ts": None, "src": src, "kind": "graphs",
            "programs": tot.get("programs"),
            "transfer_ops": tot.get("transfer_ops"),
            "host_callbacks": tot.get("host_callbacks"),
            "donation_misses": tot.get("donation_misses"),
        })
    for sp in rec.get("spans") or []:
        if not isinstance(sp, dict):
            continue
        attrs = sp.get("attrs") or {}
        tid = attrs.get("trace_id")
        if sp.get("name") == "serve_request" and tid:
            # span t0 is tracer-relative: the span proves WHICH process
            # served the trace (and its outcome/wall); the wall-clock
            # ordering comes from the heartbeat/ledger twins
            events.append({
                "ts": None, "src": src, "kind": "span",
                "trace_id": tid, "outcome": attrs.get("outcome"),
                "wall_s": sp.get("wall_submitted_s"),
                "req_id": attrs.get("req_id"),
            })
    return events


def _ledger_events(path: str, src: str) -> List[Dict[str, Any]]:
    events = []
    for row in _read_jsonl(path):
        if row.get("kind") == "actuation":
            # autoscaler control action (ACTUATION_LEDGER.jsonl): the
            # fleet changing its own shape is timeline evidence on par
            # with the requests that provoked it
            reason = row.get("reason") or {}
            ev = {"ts": row.get("ts"), "src": src, "kind": "actuation",
                  "action": row.get("action"),
                  "from": row.get("from"), "to": row.get("to"),
                  "trace_id": row.get("trace_id")}
            for k in ("worst_burn", "queue_frac"):
                if reason.get(k) is not None:
                    ev[k] = reason[k]
            events.append(ev)
            continue
        ev = {"ts": row.get("ts"), "src": src, "kind": "quarantine",
              "trace_id": row.get("trace_id"),
              "req_id": row.get("req_id"),
              "drift_fraction": row.get("drift_fraction")}
        if row.get("cells_path"):
            ev["cells_path"] = row["cells_path"]
        events.append(ev)
    return events


def _summary_events(path: str, src: str) -> Tuple[
        List[Dict[str, Any]], Dict[str, Any]]:
    """Per-request wire outcomes (+ kill stamps) from a soak summary or
    run record; the record-level wire/serving/slo sections ride the
    bundle's ``sections`` index."""
    doc = _read_json(path)
    if doc is None:
        return [], {}
    events: List[Dict[str, Any]] = []
    # prefer the per-ATTEMPT log when the summary carries one: a retried
    # request's refused first attempt is exactly the evidence a
    # postmortem exists to surface
    for o in doc.get("attempts") or doc.get("outcomes") or []:
        if not isinstance(o, dict) or not o.get("trace_id"):
            continue
        ev = {"ts": o.get("ts"), "src": src, "kind": "wire_response",
              "trace_id": o["trace_id"], "outcome": o.get("outcome"),
              "status": o.get("status")}
        if o.get("attempt") is not None:
            ev["attempt"] = o["attempt"]
        events.append(ev)
    rec = doc.get("record") if isinstance(doc.get("record"), dict) \
        else doc
    serving = rec.get("serving") if isinstance(rec, dict) else None
    sections: Dict[str, Any] = {}
    if isinstance(serving, dict):
        sec = {}
        for k in ("wire", "latency_ms", "requests"):
            if serving.get(k) is not None:
                sec[k] = serving[k]
        for kill in (serving.get("fleet") or {}).get("kills") or []:
            events.append({"ts": kill.get("ts"), "src": src,
                           "kind": "replica_kill",
                           "replica": kill.get("replica"),
                           "respawned": kill.get("respawned"),
                           "refused": kill.get("refused")})
        for sc in (serving.get("fleet") or {}).get("scales") or []:
            events.append({"ts": sc.get("ts"), "src": src,
                           "kind": "replica_scale",
                           "from": sc.get("from"), "to": sc.get("to"),
                           "reason": sc.get("reason")})
        if sec:
            sections["serving"] = sec
    if isinstance(rec, dict) and isinstance(rec.get("slo"), dict):
        slo = rec["slo"]
        sections["slo"] = {
            "availability": slo.get("availability"),
            "worst_burn": slo.get("worst_burn"),
            "latency": slo.get("latency"),
            "obs_overhead": slo.get("obs_overhead"),
        }
    return events, sections


def build_bundle(roots: List[str],
                 trace: Optional[str] = None) -> Dict[str, Any]:
    """The merged incident bundle for every evidence file under the
    roots. With ``trace``, the timeline and trace index are filtered to
    that id (sources and processes stay complete — the surrounding
    context is the point of a postmortem)."""
    sources = collect_sources(roots)
    events: List[Dict[str, Any]] = []
    processes: List[Dict[str, Any]] = []
    sections: Dict[str, Dict[str, Any]] = {}
    for p in sources["heartbeat"]:
        evs, proc = _heartbeat_events(p, _rel(p, roots))
        events.extend(evs)
        if proc:
            processes.append(proc)
    for p in sources["partial"]:
        events.extend(_partial_events(p, _rel(p, roots)))
    for p in sources["ledger"]:
        events.extend(_ledger_events(p, _rel(p, roots)))
    for p in sources["summary"]:
        evs, secs = _summary_events(p, _rel(p, roots))
        events.extend(evs)
        if secs:
            sections[_rel(p, roots)] = secs
    if trace:
        events = [e for e in events
                  if e.get("trace_id") in (None, trace)
                  and (e.get("trace_id") == trace
                       or e["kind"] in ("process_start", "process_end",
                                        "termination", "stall",
                                        "replica_kill", "replica_scale",
                                        "actuation"))]
    # timestamped events sort by wall clock; timestamp-less span
    # evidence sinks to the end of its trace's story, never the timeline
    timeline = sorted(
        (e for e in events if e.get("ts") is not None),
        key=lambda e: (float(e["ts"]), e["src"]),
    )
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for e in events:
        tid = e.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(e)
    for tid, evs in traces.items():
        evs.sort(key=lambda e: (e.get("ts") is None,
                                float(e.get("ts") or 0.0), e["src"]))
    return {
        "schema": "scc-postmortem-bundle",
        "schema_version": 1,
        "roots": [os.path.abspath(r) for r in roots],
        "sources": {k: [_rel(p, roots) for p in v]
                    for k, v in sources.items()},
        "processes": processes,
        "sections": sections,
        "n_events": len(timeline),
        "timeline": timeline,
        "traces": traces,
    }


def _fmt_ev(e: Dict[str, Any], t0: float) -> str:
    ts = e.get("ts")
    reltime = f"+{float(ts) - t0:8.3f}s" if ts is not None else "   (span)"
    bits = [reltime, f"[{e['src']}]", e["kind"]]
    for k in ("trace_id", "outcome", "status", "attempt", "latency_ms",
              "cause", "replica", "respawned", "drift_fraction",
              "last_span", "wall_s", "action", "from", "to", "reason",
              "worst_burn", "queue_frac", "total_bytes",
              "todo_item2_bytes", "n_boundaries", "state", "age_s",
              "last_outcome", "n_samples", "gc_pause_s",
              "gc_collections", "compiles", "retraces", "cache_hits",
              "compile_wall_s", "programs", "transfer_ops",
              "host_callbacks", "donation_misses"):
        if e.get(k) is not None:
            bits.append(f"{k}={e[k]}")
    if e.get("kind") == "slo_burn":
        bits.append(f"burn={e.get('burn')}")
    return "  ".join(bits)


def render_text(bundle: Dict[str, Any], max_events: int = 200) -> str:
    """The human timeline: processes, merged events, per-trace stories."""
    out: List[str] = ["postmortem bundle"]
    for proc in bundle["processes"]:
        bits = [f"  process {proc.get('stream')}"]
        if proc.get("pid") is not None:
            bits.append(f"pid {proc['pid']}")
        if proc.get("ended") is not None:
            bits.append(f"ended cause={proc.get('cause')}")
        elif proc.get("last_heartbeat") is not None:
            bits.append("no end stamp (died hard?)")
        out.append("  ".join(bits))
    for src, secs in sorted(bundle.get("sections", {}).items()):
        sv = secs.get("serving") or {}
        wire = sv.get("wire") or {}
        if wire:
            out.append(f"  wire [{src}]: "
                       + " ".join(f"{k}={v}" for k, v in sorted(
                           (wire.get("status_codes") or {}).items())))
        slo = secs.get("slo") or {}
        if slo and slo.get("worst_burn") is not None:
            avail = (slo.get("availability") or {}).get("ratio")
            out.append(f"  slo  [{src}]: availability={avail}"
                       f" worst_burn={slo['worst_burn']}x")
    timeline = bundle["timeline"]
    t0 = float(timeline[0]["ts"]) if timeline else 0.0
    out.append(f"  timeline ({len(timeline)} event(s)"
               + (f", showing last {max_events}"
                  if len(timeline) > max_events else "") + "):")
    for e in timeline[-max_events:]:
        out.append("    " + _fmt_ev(e, t0))
    traces = bundle["traces"]
    multi = {tid: evs for tid, evs in traces.items() if len(evs) > 1}
    out.append(f"  traces: {len(traces)} id(s), "
               f"{len(multi)} with a cross-source story")
    for tid in sorted(traces):
        evs = traces[tid]
        srcs = {e["src"] for e in evs}
        attempts = [e for e in evs if e["kind"] == "wire_response"]
        story = f"  trace {tid}: {len(evs)} event(s) / {len(srcs)} source(s)"
        if len(attempts) > 1:
            story += f"  ({len(attempts)} wire attempts)"
        out.append(story)
        for e in evs:
            out.append("    " + _fmt_ev(e, t0))
    return "\n".join(out) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge cross-process serving evidence into one "
                    "incident timeline")
    ap.add_argument("roots", nargs="+",
                    help="directories (or files) holding heartbeat "
                         "streams, partial records, ledgers, summaries")
    ap.add_argument("--trace", default=None,
                    help="filter the timeline to one trace id")
    ap.add_argument("--out", default=None,
                    help="write the JSON bundle here")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the JSON bundle instead of text")
    ap.add_argument("--max-events", type=int, default=200)
    args = ap.parse_args(argv)

    for root in args.roots:
        if not os.path.exists(root):
            print(f"postmortem: no such path {root}", file=sys.stderr)
            return 2
    bundle = build_bundle(args.roots, trace=args.trace)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(bundle, f, indent=1, default=str)
    if args.as_json:
        print(json.dumps(bundle, indent=1, default=str))
    else:
        sys.stdout.write(render_text(bundle,
                                     max_events=args.max_events))
    if not bundle["timeline"] and not bundle["traces"]:
        print("postmortem: no evidence found under the given roots",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
