#!/usr/bin/env python
"""Render one evidence run record — or a two-run diff — as a Markdown
report.

The ledger answers "where did the time go"; the quality section answers
"what did the pipeline compute". This tool folds both into the artifact a
reviewer reads instead of raw JSON: stage walls against the key's
noise-banded baselines, the DE gate funnel (aggregate + worst pairs),
rank-sum ladder occupancy, cluster structure (sizes, silhouette, ARI,
churn), the residency audit (per-stage/per-boundary transfer tables,
worst individual transfers, enforce-mode violations), the device-kernel
timeline (top-K kernels by device time + achieved device-time rates vs
the cost model), numeric-health sentinel trips, and the numeric
fingerprint with its drift status (against NUMERIC_PINS.json when the
dataset is pinned, else against the key's previous clean run).

Usage:
  python tools/explain_run.py RECORD.json                # one report
  python tools/explain_run.py RECORD.json --baseline OLD.json   # diff
  ... [--evidence DIR] [--out report.md]

RECORD may be a path or a bare evidence entry name (resolved against the
evidence dir). Output goes to stdout unless --out is given.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs import regress  # noqa: E402
from scconsensus_tpu.obs.export import (  # noqa: E402
    check_schema_version,
    validate_run_record,
)
from scconsensus_tpu.obs.ledger import (  # noqa: E402
    Ledger,
    default_evidence_dir,
    run_key,
    stage_walls,
    termination_cause,
)

_TOP_PAIRS = 8  # funnel table: worst pairs shown individually


def _fmt(v, digits: int = 3) -> str:
    if v is None:
        return "–"
    if isinstance(v, float):
        return f"{v:.{digits}f}"
    return str(v)


def _load_record(spec: str, evidence_dir: str) -> Dict[str, Any]:
    path = spec
    if not os.path.exists(path):
        cand = os.path.join(evidence_dir, spec)
        if os.path.exists(cand):
            path = cand
        else:
            raise FileNotFoundError(f"no such record: {spec}")
    with open(path) as f:
        rec = json.load(f)
    if check_schema_version(rec, source=spec) == "legacy":
        raise ValueError(
            f"{spec}: pre-schema record — upgrade it first "
            "(tools/perf_gate.py --upgrade)"
        )
    validate_run_record(rec)
    rec["_source_file"] = os.path.basename(path)
    return rec


# --------------------------------------------------------------------------
# sections
# --------------------------------------------------------------------------

def _header(rec: Dict[str, Any]) -> List[str]:
    key = run_key(rec)
    out = [f"# Run report: {rec.get('metric')}", ""]
    out.append(f"- **headline**: {_fmt(rec.get('value'))} "
               f"{rec.get('unit')}"
               + (f" (vs_baseline {_fmt(rec.get('vs_baseline'))})"
                  if rec.get("vs_baseline") is not None else ""))
    out.append(f"- **key**: dataset=`{key['dataset']}` "
               f"backend=`{key['backend']}` config_fp=`{key['config_fp']}`")
    run = rec.get("run") or {}
    out.append(f"- **created_unix**: {run.get('created_unix')}"
               + (f", jax {run['jax_version']}"
                  if run.get("jax_version") else ""))
    cause = termination_cause(rec)
    if cause is not None and cause != "clean":
        term = rec["termination"]
        out.append(f"- **PARTIAL record**: termination.cause=`{cause}`"
                   + (f" at span `{term.get('last_span')}`"
                      if term.get("last_span") else ""))
    return out


def stage_table(rec: Dict[str, Any],
                baselines: Dict[str, Dict[str, float]]) -> List[str]:
    walls = stage_walls(rec)
    if not walls:
        return []
    out = ["## Stage walls", ""]
    if baselines:
        out += ["| stage | wall s | baseline s | band s | status |",
                "|---|---:|---:|---:|---|"]
    else:
        out += ["| stage | wall s |", "|---|---:|"]
    for stage, wall in sorted(walls.items(), key=lambda kv: -kv[1]):
        if baselines:
            b = baselines.get(stage)
            if b is None:
                status = "no baseline (new stage)"
                out.append(f"| {stage} | {wall:.3f} | – | – | {status} |")
                continue
            limit = b["baseline_s"] + b["band_s"]
            status = ("**REGRESSED** "
                      f"(+{wall - limit:.3f}s past band)"
                      if wall > limit else "ok")
            out.append(f"| {stage} | {wall:.3f} | {b['baseline_s']:.3f} "
                       f"| {b['band_s']:.3f} | {status} |")
        else:
            out.append(f"| {stage} | {wall:.3f} |")
    return out


def funnel_table(quality: Dict[str, Any]) -> List[str]:
    f = (quality or {}).get("de_funnel")
    if not f:
        return []
    total = f.get("total") or {}
    stages = [s for s in ("input", "pct_gate", "logfc_gate", "tested",
                          "significant") if s in total]
    out = ["## DE gate funnel", "",
           f"{f.get('n_pairs')} pairs × {f.get('n_genes')} genes", "",
           "| stage | genes (all pairs) | % of input |", "|---|---:|---:|"]
    inp = float(total.get("input") or 1) or 1.0
    for s in stages:
        out.append(f"| {s} | {total[s]} | {100.0 * total[s] / inp:.1f}% |")
    pp = f.get("per_pair") or {}
    names = f.get("cluster_names") or []
    pi, pj = f.get("pair_i") or [], f.get("pair_j") or []
    sig = pp.get("significant")
    if sig and pi and pj:
        def pair_name(r):
            try:
                return f"{names[pi[r]]} vs {names[pj[r]]}"
            except (IndexError, TypeError):
                return f"pair {r}"

        order = sorted(range(len(sig)), key=lambda r: sig[r])
        worst = order[:_TOP_PAIRS]
        out += ["", f"Fewest-significant pairs (bottom {len(worst)}):", "",
                "| pair | " + " | ".join(stages) + " |",
                "|---|" + "---:|" * len(stages)]
        for r in worst:
            out.append(f"| {pair_name(r)} | " + " | ".join(
                str(pp[s][r]) if s in pp else "–" for s in stages
            ) + " |")
    return out


def ladder_table(quality: Dict[str, Any]) -> List[str]:
    lad = (quality or {}).get("wilcox_ladder")
    if not lad:
        return []
    out = ["## Rank-sum window-ladder occupancy", "",
           f"input=`{lad.get('input')}` kernel=`{lad.get('kernel')}` "
           f"windowed={lad.get('windowed')} "
           f"window_floor={lad.get('window_floor')}",
           "",
           f"- buckets: {lad.get('n_buckets')} covering "
           f"{lad.get('genes_bucketed')} genes",
           f"- padded vs real elements: {lad.get('padded_elems')} / "
           f"{lad.get('real_elems')}"
           + (f" (pad ratio {lad.get('pad_ratio')})"
              if lad.get("pad_ratio") is not None else ""),
           f"- overflow redos: {lad.get('overflow_genes')}"]
    buckets = lad.get("buckets") or []
    if buckets:
        out += ["", "| window | genes | pad ratio | nnz range | overflow |",
                "|---:|---:|---:|---|---:|"]
        for b in buckets:
            out.append(
                f"| {b.get('window')} | {b.get('n_genes')} "
                f"| {_fmt(b.get('pad_ratio'))} "
                f"| {b.get('nnz_min')}–{b.get('nnz_max')} "
                f"| {b.get('overflow_genes', 0)} |"
            )
    return out


def cluster_table(quality: Dict[str, Any]) -> List[str]:
    cs = (quality or {}).get("cluster_structure")
    if not cs:
        return []
    out = ["## Cluster structure", "",
           "| cut | clusters | largest | smallest | unassigned "
           "| silhouette | entropy | ARI vs input |",
           "|---|---:|---:|---:|---:|---:|---:|---:|"]
    ari = cs.get("ari_vs_input") or {}
    for cut in cs.get("cuts") or []:
        sizes = cut.get("sizes") or []
        out.append(
            f"| {cut.get('cut')} | {cut.get('n_clusters')} "
            f"| {sizes[0] if sizes else '–'} "
            f"| {sizes[-1] if sizes else '–'} "
            f"| {cut.get('n_unassigned', 0)} "
            f"| {_fmt(cut.get('silhouette'))} "
            f"| {_fmt(cut.get('contingency_entropy'))} "
            f"| {_fmt(ari.get(cut.get('cut')))} |"
        )
    churn = cs.get("churn") or []
    if churn:
        out += ["", "Label churn across the deepSplit ladder:"]
        for c in churn:
            out.append(f"- `{c.get('from')}` → `{c.get('to')}`: "
                       f"ARI {_fmt(c.get('ari'))}")
    refs = cs.get("ari_final_vs") or {}
    if refs:
        out += ["", "Final cut vs input labelings: "
                + ", ".join(f"{k}={_fmt(v)}" for k, v in refs.items())]
    if cs.get("input_entropy") is not None:
        out += ["", f"Input labeling: {cs.get('n_input_clusters')} "
                f"clusters, entropy {_fmt(cs['input_entropy'])}"]
    lm = cs.get("landmark")
    if lm:
        out += ["", "### Landmark recluster", "",
                f"Branch taken: `{lm.get('branch')}` — k={lm.get('k')} "
                f"landmarks (sketch {lm.get('sketch')}, "
                f"{lm.get('linkage')} linkage"
                + (f", threshold {lm.get('threshold'):,} cells"
                   if lm.get("threshold") else "") + ")"]
        ave = lm.get("ari_vs_exact")
        if ave:
            vals = [v for v in ave.values() if v is not None]
            out += ["",
                    "ARI vs the exact tree (verify run): "
                    + ", ".join(f"{k}={_fmt(v)}" for k, v in ave.items())
                    + (f" — min {_fmt(min(vals))}" if vals else "")]
        else:
            out += ["", "_No ARI-vs-exact stamp (production run — the "
                    "pin is asserted on mid-size verify runs in tier-1; "
                    "accuracy evidence here is ari_vs_input above)._"]
        occ = lm.get("occupancy")
        if occ:
            out += ["", "Per-cut landmark occupancy: "
                    + ", ".join(
                        f"{k}={v.get('landmarks_assigned')}/"
                        f"{v.get('n_landmarks')}"
                        for k, v in occ.items())]
    return out


def _fmt_bytes(n) -> str:
    if n is None:
        return "–"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return "–"


def residency_table(rec: Dict[str, Any]) -> List[str]:
    res = rec.get("residency")
    if not res:
        return []
    out = ["## Residency (host↔device transfers)", "",
           f"Audit mode: `{res.get('mode')}`"]
    td, th = res.get("to_device") or {}, res.get("to_host") or {}
    out.append(f"- host→device: {_fmt_bytes(td.get('bytes'))} over "
               f"{td.get('calls', 0)} calls; device→host: "
               f"{_fmt_bytes(th.get('bytes'))} over "
               f"{th.get('calls', 0)} calls"
               + (f" ({res.get('events_dropped')} events past the cap "
                  "not itemized)" if res.get("events_dropped") else ""))
    viols = res.get("violations") or []
    if viols:
        out += ["", f"**{len(viols)} enforce-mode violation(s):**"]
        for v in viols:
            out.append(f"- {v.get('direction')} "
                       f"{_fmt_bytes(v.get('nbytes'))} via "
                       f"`{v.get('api')}` in span `{v.get('span')}` "
                       f"at `{v.get('where')}`")
    by_stage = res.get("by_stage") or {}
    if by_stage:
        out += ["", "| stage | d2h | h2d | calls |",
                "|---|---:|---:|---:|"]
        ranked = sorted(
            by_stage.items(),
            key=lambda kv: -(kv[1].get("to_host_bytes", 0)
                             + kv[1].get("to_device_bytes", 0)),
        )
        for stage, d in ranked:
            out.append(f"| {stage} | {_fmt_bytes(d.get('to_host_bytes'))} "
                       f"| {_fmt_bytes(d.get('to_device_bytes'))} "
                       f"| {d.get('calls', 0)} |")
    by_bound = res.get("by_boundary") or {}
    if by_bound:
        out += ["", "Declared boundary crossings:", "",
                "| boundary | d2h | h2d | calls |", "|---|---:|---:|---:|"]
        for name, d in sorted(by_bound.items()):
            out.append(f"| {name} | {_fmt_bytes(d.get('to_host_bytes'))} "
                       f"| {_fmt_bytes(d.get('to_device_bytes'))} "
                       f"| {d.get('calls', 0)} |")
    # worst individual transfers, span-attributed
    events = sorted(res.get("events") or [],
                    key=lambda e: -e.get("nbytes", 0))[:5]
    if events:
        out += ["", "Largest transfers:"]
        for e in events:
            out.append(f"- {e.get('direction')} "
                       f"{_fmt_bytes(e.get('nbytes'))} via "
                       f"`{e.get('api')}` in span `{e.get('span')}` "
                       f"(boundary {e.get('boundary') or '—'}, "
                       f"`{e.get('where')}`)")
    return out


def kernels_table(rec: Dict[str, Any]) -> List[str]:
    sec = rec.get("kernels")
    if not sec:
        return []
    out = ["## Device-kernel timeline", ""]
    if sec.get("error"):
        out.append(f"Capture attempted but degraded: `{sec['error']}`")
        return out
    out.append(f"{sec.get('n_events')} device-op events, "
               f"{sec.get('n_kernels')} distinct kernels, "
               f"{_fmt(sec.get('total_device_time_s'))}s total device "
               "time")
    top = sec.get("top") or []
    if top:
        out += ["", "| kernel | module | device s | count | % | span |",
                "|---|---|---:|---:|---:|---|"]
        for a in top:
            out.append(f"| `{a.get('kernel')}` | {a.get('hlo_module')} "
                       f"| {_fmt(a.get('device_time_s'), 4)} "
                       f"| {a.get('count')} | {_fmt(a.get('pct'), 1)} "
                       f"| {a.get('span') or '—'} |")
    vc = sec.get("vs_cost_model") or {}
    if vc:
        out += ["", "Achieved rates over DEVICE time vs the cost model "
                "(the roofline-style denominator; wall-based rates "
                "understate whenever the host is the bottleneck):", "",
                "| stage | device s | wall s | GFLOP/s (dev) | GB/s (dev) |",
                "|---|---:|---:|---:|---:|"]
        for stage, row in sorted(vc.items()):
            out.append(f"| {stage} | {_fmt(row.get('device_time_s'), 4)} "
                       f"| {_fmt(row.get('wall_s'))} "
                       f"| {_fmt(row.get('achieved_gflops_device'))} "
                       f"| {_fmt(row.get('achieved_gbps_device'))} |")
    return out


def health_section(quality: Dict[str, Any]) -> List[str]:
    nh = (quality or {}).get("numeric_health")
    if not nh:
        return []
    out = ["## Numeric health", ""]
    trips = nh.get("trips") or []
    if not trips:
        state = "enabled" if nh.get("enabled") else "DISABLED"
        out.append(f"No sentinel trips ({nh.get('checks', 0)} checks, "
                   f"sentinels {state}).")
        return out
    out += [f"**{len(trips)} sentinel trip(s)** over "
            f"{nh.get('checks', 0)} checks:", "",
            "| span | array | NaN | Inf |", "|---|---|---:|---:|"]
    for t in trips:
        out.append(f"| {t.get('span')} | {t.get('array')} "
                   f"| {t.get('nan', 0)} | {t.get('inf', 0)} |")
    return out


def robustness_section(rec: Dict[str, Any]) -> List[str]:
    """The survival story of a run: faults injected, typed retries,
    degradations, mid-stage resume points, orchestration adaptations.
    Absent section -> no lines (healthy runs say nothing)."""
    rb = rec.get("robustness")
    if not rb:
        return []
    out = ["## Robustness", ""]
    verdict = ("**recovered**" if rb.get("recovered")
               else "no recovery claimed")
    budget = rb.get("budget") or {}
    out.append(
        f"{verdict} — retry budget {budget.get('used', 0)}"
        f"/{budget.get('limit', '?')} used"
        + (f", robustness-layer overhead {rb['consumed_s']}s"
           if rb.get("consumed_s") else "")
    )
    faults = rb.get("faults_injected") or []
    if faults:
        out += ["", f"**{len(faults)} injected fault(s)** "
                    "(SCC_FAULT_PLAN):", "",
                "| site | class | seq |", "|---|---|---:|"]
        out += [f"| {f.get('site')} | {f.get('class')} | {f.get('seq', 0)} |"
                for f in faults]
    retries = rb.get("retries") or []
    if retries:
        out += ["", "| retry site | class | attempts | recovered "
                    "| backoff |", "|---|---|---:|---|---:|"]
        out += [
            f"| {r.get('site')} | {r.get('error_class')} "
            f"| {r.get('attempts')} "
            f"| {'yes' if r.get('recovered') else 'NO'} "
            f"| {_fmt(r.get('backoff_s'), 3)}s |"
            for r in retries
        ]
    degr = rb.get("degradations") or []
    if degr:
        out += ["", "Degradations:", ""]
        out += [f"- `{d.get('site')}`: {d.get('action')}"
                + (f" — {d.get('detail')}" if d.get("detail") else "")
                for d in degr]
    resumes = rb.get("resume_points") or []
    if resumes:
        out += ["", "Mid-stage resume points:", ""]
        out += [
            f"- `{p.get('stage')}`: {p.get('completed')}/{p.get('total')} "
            f"{p.get('unit', 'unit')}(s) loaded instead of recomputed"
            for p in resumes
        ]
    transitions = rb.get("mesh_transitions") or []
    if transitions:
        path = " → ".join(
            [str(len(transitions[0].get("from_devices") or []))]
            + [str(len(t.get("to_devices") or [])) for t in transitions]
        )
        out += ["", f"**Elastic mesh transitions** (device path: {path}):",
                "",
                "| stage | from | to | cause | recovered state |",
                "|---|---|---|---|---:|"]
        out += [
            f"| {t.get('stage')} "
            f"| {len(t.get('from_devices') or [])} dev "
            f"{t.get('from_devices')} "
            f"| {len(t.get('to_devices') or [])} dev "
            f"{t.get('to_devices')} "
            f"| {t.get('cause', 'device_loss')} "
            f"| {t.get('recovered_state_bytes', 0):,} B |"
            for t in transitions
        ]
    orch = rb.get("orchestration") or {}
    if orch:
        att = orch.get("attempts") or []
        out += ["", "Orchestration: " + " → ".join(
            f"{a.get('attempt')}[{a.get('outcome')}]" for a in att
        )]
        out += [f"- adaptation after {a.get('after')}: {a.get('reason')}"
                for a in (orch.get("adaptations") or [])]
    return out


def fingerprint_section(rec: Dict[str, Any], evidence_dir: str,
                        history: List[Dict[str, Any]]) -> List[str]:
    fp = (rec.get("extra") or {}).get("numeric_fingerprint")
    if not fp:
        return []
    out = ["## Numeric fingerprint", ""]
    # shared resolution with perf_gate (regress.resolve_pins): the gate
    # and this report must name the same comparison target
    pins, source = regress.resolve_pins(
        evidence_dir, run_key(rec)["dataset"], history
    )
    if source == "history":
        source = "previous clean run of this key (history)"
    if pins is None:
        out.append("No pins and no prior fingerprint for this key — this "
                   "run seeds the quality baseline.")
        for k, v in sorted(fp.items()):
            if not k.startswith("_"):
                out.append(f"- `{k}`: {_fmt(v, 6)}")
        return out
    acks = regress.load_drift_acks(
        os.path.join(evidence_dir, regress.DRIFT_LEDGER_NAME)
    )
    drifts = regress.check_drift(fp, pins, acks)
    by_field = {d["field"]: d for d in drifts}
    out += [f"Compared against: {source}", "",
            "| field | current | pinned | status |", "|---|---|---|---|"]
    for k in sorted(set(fp) | set(pins)):
        if k.startswith("_"):
            continue
        d = by_field.get(k)
        if d is None:
            status = "match"
        elif d["acknowledged"]:
            status = "drift (acknowledged)"
        else:
            status = "**DRIFT (unacknowledged)**"
        out.append(f"| {k} | {_fmt(fp.get(k), 6)} "
                   f"| {_fmt(pins.get(k), 6)} | {status} |")
    return out


# --------------------------------------------------------------------------
# two-run diff
# --------------------------------------------------------------------------

def diff_report(cand: Dict[str, Any], base: Dict[str, Any]) -> str:
    out = [f"# Run diff: {cand.get('metric')}", "",
           f"- candidate: `{cand.get('_source_file', '?')}` "
           f"value={_fmt(cand.get('value'))} {cand.get('unit')}",
           f"- baseline:  `{base.get('_source_file', '?')}` "
           f"value={_fmt(base.get('value'))} {base.get('unit')}", ""]
    cw, bw = stage_walls(cand), stage_walls(base)
    if cw or bw:
        out += ["## Stage walls", "",
                "| stage | candidate s | baseline s | delta s |",
                "|---|---:|---:|---:|"]
        deltas = {
            s: cw.get(s, 0.0) - bw.get(s, 0.0) for s in set(cw) | set(bw)
        }
        for s in sorted(deltas, key=lambda k: -abs(deltas[k])):
            out.append(f"| {s} | {_fmt(cw.get(s))} | {_fmt(bw.get(s))} "
                       f"| {deltas[s]:+.3f} |")
    cf = ((cand.get("quality") or {}).get("de_funnel") or {}).get("total")
    bf = ((base.get("quality") or {}).get("de_funnel") or {}).get("total")
    if cf or bf:
        cf, bf = cf or {}, bf or {}
        out += ["", "## DE gate funnel (totals)", "",
                "| stage | candidate | baseline | delta |",
                "|---|---:|---:|---:|"]
        for s in ("input", "pct_gate", "logfc_gate", "tested",
                  "significant"):
            if s in cf or s in bf:
                # +g, not +d: validate_quality admits float counts
                d = (cf.get(s) or 0) - (bf.get(s) or 0)
                out.append(f"| {s} | {_fmt(cf.get(s))} "
                           f"| {_fmt(bf.get(s))} | {d:+g} |")
    cfp = (cand.get("extra") or {}).get("numeric_fingerprint") or {}
    bfp = (base.get("extra") or {}).get("numeric_fingerprint") or {}
    fields = sorted((set(cfp) | set(bfp)))
    fields = [f for f in fields if not f.startswith("_")]
    if fields:
        out += ["", "## Fingerprint deltas", "",
                "| field | candidate | baseline | shifted |",
                "|---|---|---|---|"]
        drifts = {d["field"]: d for d in regress.check_drift(cfp, bfp)}
        for f in fields:
            out.append(f"| {f} | {_fmt(cfp.get(f), 6)} "
                       f"| {_fmt(bfp.get(f), 6)} "
                       f"| {'**yes**' if f in drifts else 'no'} |")
    for label, rec in (("candidate", cand), ("baseline", base)):
        trips = ((rec.get("quality") or {}).get("numeric_health") or {}
                 ).get("trips") or []
        if trips:
            out += ["", f"## Sentinel trips ({label})"]
            for t in trips:
                out.append(f"- {t.get('span')}/{t.get('array')}: "
                           f"nan={t.get('nan', 0)} inf={t.get('inf', 0)}")
    return "\n".join(out) + "\n"


def report(rec: Dict[str, Any], evidence_dir: str) -> str:
    history: List[Dict[str, Any]] = []
    baselines: Dict[str, Dict[str, float]] = {}
    try:
        ledger = Ledger(evidence_dir)
        history = ledger.history(
            run_key(rec),
            exclude_files=[rec.get("_source_file", "")],
        )
        baselines = regress.stage_baselines(history)
    except Exception:
        pass
    parts = [_header(rec)]
    quality = rec.get("quality") or {}
    parts.append(stage_table(rec, baselines))
    parts.append(funnel_table(quality))
    parts.append(ladder_table(quality))
    parts.append(cluster_table(quality))
    parts.append(residency_table(rec))
    parts.append(kernels_table(rec))
    parts.append(robustness_section(rec))
    parts.append(health_section(quality))
    parts.append(fingerprint_section(rec, evidence_dir, history))
    if not quality:
        parts.append(["_This record carries no quality section (emitted "
                      "before the quality-telemetry layer, or by a "
                      "quality-free emitter)._"])
    return "\n\n".join(
        "\n".join(p) for p in parts if p
    ) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render an evidence run record as a Markdown report")
    ap.add_argument("record", help="run-record JSON path or evidence "
                                   "entry name")
    ap.add_argument("--baseline", default=None,
                    help="second record: render a two-run diff instead")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    ap.add_argument("--out", default=None, help="write the report here "
                                                "instead of stdout")
    args = ap.parse_args(argv)
    evidence = args.evidence or default_evidence_dir(_REPO)
    try:
        rec = _load_record(args.record, evidence)
        if args.baseline:
            base = _load_record(args.baseline, evidence)
            text = diff_report(rec, base)
        else:
            text = report(rec, evidence)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"explain_run: {e}", file=sys.stderr)
        return 2
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"wrote {args.out}")
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
