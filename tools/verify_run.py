#!/usr/bin/env python
"""Cross-shape determinism audit: replay one workload's stages under
different chunk/mesh/batch shapes and pin the label shas identical.

    verify_run.py [--cells N] [--genes G] [--clusters K] [--seed S]
                  [--shapes serial,mesh8,...] [--timeout S]
                  [--integrity off|audit|enforce] [--json]

The scattered per-PR identity tests (mesh-vs-serial parity, streaming-
vs-in-memory bit identity, scan-vs-runspace kernel equivalence,
resume-to-identical-labels) all assert the same property: the answer is
a pure function of (data, config, seed) — never of the execution shape.
This tool is that property as ONE reusable auditor. Each shape runs the
deterministic ``robust.soak`` workload (the stream-soak generator:
every row a pure function of (seed, gene)) in its own subprocess with
the shape expressed through environment/flags, and every summary's
``labels_sha`` must equal the reference's. A shape-dependent code path
that returns a wrong-but-finite answer — the silent-corruption class
the SCC_INTEGRITY layer hunts at runtime — shows up here as a sha
split, with the disagreeing shapes named.

Shapes (``--shapes`` filters; default runs all):

  serial      the reference: in-memory CSR, single device, runspace
              kernel family (CPU default)
  mesh8       a forced 8-virtual-device CPU mesh (XLA_FLAGS) — the
              sharded gene-chunk path, r14's elastic substrate
  scan        SCC_NO_RUNSPACE=1 — the scan kernel family at the same
              shapes (the cross-KERNEL determinism pin)
  stream32    out-of-core through a ChunkedCSRStore, 32-row windows
  stream16    the same store shape at 16-row windows — different chunk
              boundaries must not change one label
  resume      stream32 run twice over the same stage store: the second
              run adopts every durable chunk (full resume) and must
              reproduce the sha without recomputing
  topo        the workload zoo's topology clusterer (workloads.soak
              --topo) on a seeded embedding — reference of the "topo"
              family (families pin shas independently: a topology
              labeling is a different answer than the refine workload)
  topo_mesh8  the same topology workload under a forced
              8-virtual-device CPU mesh
  topo_scan   ... under the scan kernel family (SCC_NO_RUNSPACE=1)

``--integrity`` additionally arms the SCC_INTEGRITY sentinels inside
every worker (default: inherit the environment), so the audit can run
with the runtime defense active.

Exit codes: 0 every shape agreed; 1 a shape disagreed or failed;
2 usage error.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# name -> (worker args, env overrides, family). Shapes in the same
# FAMILY replay the same workload and must agree on one sha; families
# have independent references (a topology labeling's sha is a different
# answer than the refine workload's — comparing them would prove
# nothing). "refine" shapes drive robust.soak; "topo" shapes drive the
# workload zoo's topology clusterer (workloads.soak --topo) under the
# same execution-shape axes — the cross-shape determinism pin ISSUE 14
# asks of the Mapper-style labeler.
SHAPES: List[Tuple[str, List[str], Dict[str, str], str]] = [
    ("serial", [], {}, "refine"),
    ("mesh8", ["--mesh", "auto"],
     {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
     "refine"),
    ("scan", [], {"SCC_NO_RUNSPACE": "1"}, "refine"),
    ("stream32", ["--stream", "--stream-window", "32"], {}, "refine"),
    ("stream16", ["--stream", "--stream-window", "16"], {}, "refine"),
    ("resume", ["--stream", "--stream-window", "32"], {}, "refine"),
    ("topo", [], {}, "topo"),
    ("topo_mesh8", [],
     {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
     "topo"),
    ("topo_scan", [], {"SCC_NO_RUNSPACE": "1"}, "topo"),
]

# family -> (worker module, reference shape)
FAMILIES: Dict[str, Tuple[str, str]] = {
    "refine": ("scconsensus_tpu.robust.soak", "serial"),
    "topo": ("scconsensus_tpu.workloads.soak", "topo"),
}


def run_shape(name: str, extra_args: List[str], env_over: Dict[str, str],
              workdir: str, shape_args: List[str], timeout_s: float,
              integrity: Optional[str], fresh: bool = True,
              module: str = "scconsensus_tpu.robust.soak",
              ) -> Tuple[bool, Optional[Dict[str, Any]], str]:
    """One worker subprocess; returns (ok, summary|None, note)."""
    summary_path = os.path.join(workdir, f"VERIFY_{name}.json")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("SCC_FAULT_PLAN", None)
    if integrity is not None:
        env["SCC_INTEGRITY"] = integrity
    for k, v in env_over.items():
        env[k] = (env.get(k, "") + " " + v).strip() \
            if k == "XLA_FLAGS" else v
    cmd = [sys.executable, "-m", module,
           "--dir", os.path.join(workdir, name),
           "--summary", summary_path] + shape_args + extra_args
    if fresh:
        cmd.append("--fresh")
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=timeout_s, cwd=_REPO)
    except subprocess.TimeoutExpired:
        return False, None, "timeout"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return False, None, f"rc={proc.returncode}: " + " | ".join(tail)
    try:
        with open(summary_path) as f:
            return True, json.load(f), ""
    except (OSError, json.JSONDecodeError) as e:
        return False, None, f"summary unreadable: {e}"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="cross-shape determinism audit")
    ap.add_argument("--cells", type=int, default=3000)
    ap.add_argument("--genes", type=int, default=120)
    ap.add_argument("--clusters", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--shapes", default=None,
                    help="comma-separated shape names "
                         f"(known: {[s[0] for s in SHAPES]})")
    ap.add_argument("--timeout", type=float, default=1200.0,
                    help="ONE wall-clock budget for the whole audit")
    ap.add_argument("--integrity", choices=("off", "audit", "enforce"),
                    default=None,
                    help="force SCC_INTEGRITY inside every worker "
                         "(default: inherit)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    only = ([s.strip() for s in args.shapes.split(",") if s.strip()]
            if args.shapes else None)
    shapes = [s for s in SHAPES if not only or s[0] in only]
    if not shapes:
        print(f"verify_run: --shapes matched nothing "
              f"(known: {[s[0] for s in SHAPES]})", file=sys.stderr)
        return 2
    shape_args = ["--cells", str(args.cells), "--genes", str(args.genes),
                  "--clusters", str(args.clusters), "--seed",
                  str(args.seed)]
    t0 = time.monotonic()
    results: List[Dict[str, Any]] = []
    with tempfile.TemporaryDirectory(prefix="scc-verify-") as tmp:
        for name, extra, env_over, family in shapes:
            module, _ = FAMILIES[family]
            if family == "topo":
                extra = list(extra) + ["--topo"]
            left = args.timeout - (time.monotonic() - t0)
            if left <= 0:
                results.append({"shape": name, "family": family,
                                "ok": False, "labels_sha": None,
                                "note": "budget-exhausted"})
                continue
            t_s = time.monotonic()
            if name == "resume":
                # prime the durable store, then the audited run resumes
                # every chunk — a full resume must reproduce the sha
                ok0, _, note0 = run_shape(
                    name, extra, env_over, tmp, shape_args, left,
                    args.integrity, fresh=True, module=module,
                )
                left = args.timeout - (time.monotonic() - t0)
                if not ok0 or left <= 0:
                    results.append({"shape": name, "family": family,
                                    "ok": False, "labels_sha": None,
                                    "note": f"prime failed: {note0}"})
                    continue
                ok, summary, note = run_shape(
                    name, extra, env_over, tmp, shape_args, left,
                    args.integrity, fresh=False, module=module,
                )
                if ok and summary is not None and not (
                        (summary.get("record") or {}).get(
                            "streaming", {}).get("chunks", {}
                        ).get("resumed", 0) >= 1):
                    ok, note = False, "resume shape adopted no chunks"
            else:
                ok, summary, note = run_shape(
                    name, extra, env_over, tmp, shape_args, left,
                    args.integrity, module=module,
                )
            results.append({
                "shape": name,
                "family": family,
                "ok": bool(ok and summary and summary.get("ok")),
                "labels_sha": (summary or {}).get("labels_sha"),
                "note": note,
                "elapsed_s": round(time.monotonic() - t_s, 1),
            })
    # one reference PER FAMILY: shapes only ever pin against shapes
    # replaying the same workload
    refs: Dict[str, Optional[str]] = {}
    for fam, (_, ref_shape) in FAMILIES.items():
        fam_results = [r for r in results if r["family"] == fam]
        if not fam_results:
            continue
        refs[fam] = next(
            (r["labels_sha"] for r in fam_results
             if r["shape"] == ref_shape and r.get("labels_sha")),
            next((r["labels_sha"] for r in fam_results
                  if r.get("labels_sha")), None),
        )
    for r in results:
        ref = refs.get(r["family"])
        if r["ok"] and ref is not None and r.get("labels_sha") != ref:
            r["ok"] = False
            r["note"] = (f"labels diverged from reference "
                         f"({(r['labels_sha'] or '?')[:16]} != "
                         f"{ref[:16]}) — a shape-dependent answer")
    ok_all = bool(results) and all(r["ok"] for r in results) \
        and bool(refs) and all(v is not None for v in refs.values())
    # top-level labels_sha keeps the pre-family contract: the refine
    # sha when refine shapes ran, else the sole family's sha (a
    # topo-only audit must not print null for a passing run)
    top_sha = refs.get("refine")
    if top_sha is None and len(refs) == 1:
        top_sha = next(iter(refs.values()))
    verdict = {
        "verify": "ok" if ok_all else "FAIL",
        "labels_sha": top_sha,
        "labels_sha_by_family": refs,
        "shapes": results,
        "consumed_s": round(time.monotonic() - t0, 1),
    }
    if args.as_json:
        print(json.dumps(verdict, indent=1))
    else:
        for r in results:
            mark = "ok  " if r["ok"] else "FAIL"
            print(f"[verify:{r['shape']}] {mark} "
                  f"sha={(r.get('labels_sha') or '?')[:16]}"
                  + (f"  ({r['note']})" if r.get("note") else ""))
        print(json.dumps({k: verdict[k] for k in
                          ("verify", "labels_sha", "consumed_s")}))
    return 0 if ok_all else 1


if __name__ == "__main__":
    raise SystemExit(main())
