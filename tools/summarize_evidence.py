#!/usr/bin/env python
"""Print one table from every benchmark/evidence artifact in the repo.

Artifacts live under ``evidence/`` (the ledger layout: schema-v1 records
indexed by MANIFEST.json; legacy files relocated there by
``tools/perf_gate.py --upgrade`` carry their original payload under
``extra["legacy"]`` and render through their original shape). The
root-level transition scan was removed in round 10: all 32 legacy root
artifacts were relocated in r8, so the deprecation path was dead code —
a stray RELOCATABLE root artifact now gets one stderr notice pointing
at the upgrader instead of rendering as if it were indexed evidence.
Live working files at the root (BENCH_TPU_* watcher capture targets)
are the exception: the upgrader can never relocate them, so they keep
rendering.

Covers driver artifacts (BENCH_r*.json: {n, cmd, rc, tail, parsed}),
watcher TPU evidence (BENCH_TPU_*.json), bench checkpoints
(BENCH_CHECKPOINT_*.json), committed SCALE_/MESH_/MULTICHIP_/PROFILE_
files, ledger-ingested RUN_*.json records and the manifest itself.

Ingest contract: artifacts carrying the ``scc-run-record`` schema are
version-checked (obs.export.check_schema_version); an unknown schema name
or version is a hard error (exit != 0), never a silently garbled row.
Legacy pre-schema artifacts are accepted as-is.

Usage: python tools/summarize_evidence.py [root]
"""

from __future__ import annotations

import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROOT = sys.argv[1] if len(sys.argv) > 1 else _REPO
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.export import check_schema_version  # noqa: E402

Row = Tuple[str, str]


def _fmt(rec: dict) -> str:
    ex = rec.get("extra", {})
    bits = [
        f"value={rec.get('value')}",
        f"unit={rec.get('unit')}",
        f"vs_baseline={rec.get('vs_baseline')}",
        f"platform={ex.get('platform') or rec.get('run', {}).get('platform')}",
    ]
    if "schema" in rec:
        bits.append(f"schema={rec.get('schema_version')}")
        if rec.get("spans"):
            bits.append(f"spans={len(rec['spans'])}")
    if ex.get("degraded"):
        bits.append("DEGRADED")
    if ex.get("partial"):
        bits.append("PARTIAL")
    term = rec.get("termination")
    if isinstance(term, dict) and term.get("cause") not in (None, "clean"):
        # flight-recorder partial: say how the run died and where it was
        desc = f"TERMINATED={term['cause']}"
        if term.get("last_span"):
            desc += f"@{term['last_span']}"
        bits.append(desc)
    if ex.get("wilcox_s") is not None:
        bits.append(f"wilcox_s={ex['wilcox_s']}")
    if ex.get("stage_throughput"):
        bits.append(f"costed_stages={len(ex['stage_throughput'])}")
    q = rec.get("quality")
    if isinstance(q, dict):
        tot = (q.get("de_funnel") or {}).get("total") or {}
        if tot.get("significant") is not None:
            bits.append(f"de_sig={tot['significant']}")
        trips = (q.get("numeric_health") or {}).get("trips") or []
        if trips:
            bits.append(f"SENTINEL_TRIPS={len(trips)}")
    return "  ".join(str(b) for b in bits)


def _load(path: str):
    """A mid-write (truncated) artifact must degrade to one 'unreadable'
    row, never crash the whole table — but an artifact declaring an
    UNKNOWN run-record schema version is a hard error (SystemExit): this
    tool must not render future-schema records as if it understood them.
    """
    try:
        d = json.load(open(path))
    except (json.JSONDecodeError, OSError) as e:
        return None, f"unreadable: {e!r}"
    try:
        if isinstance(d, dict):
            check_schema_version(d, source=os.path.basename(path))
    except ValueError as e:
        raise SystemExit(f"schema validation failed: {e}")
    return d, None


# --------------------------------------------------------------------------
# per-shape renderers, dispatched on the artifact's (original) name
# --------------------------------------------------------------------------

def _rows_bench_driver(label: str, d: dict) -> List[Row]:
    parsed = d.get("parsed")
    return [(label, f"rc={d.get('rc')}  parsed="
             + ("null" if parsed is None else _fmt(parsed)))]


def _rows_scale(label: str, d: dict) -> List[Row]:
    # three shapes: a single bench record ({"metric", "value", ...}),
    # {"configs": {name: record}}, or a top-level map of records
    if "metric" in d and "value" in d:
        return [(label, _fmt(d))]
    entries = d.get("configs") or {
        k: v for k, v in d.items()
        if isinstance(v, dict) and ("metric" in v or "value" in v)
    }
    if entries:
        return [(f"{label}:{cfg}", _fmt(rec)) for cfg, rec in entries.items()]
    return [(label, _fmt(d))]


def _rows_mesh(label: str, d: dict) -> List[Row]:
    rows = []
    for size, rec in d.get("sizes", {}).items():
        rows.append((
            f"{label}:{size}",
            f"mesh={rec.get('mesh8')}s serial={rec.get('serial')}s "
            f"ratio={rec.get('ratio', rec.get('mesh_over_serial'))}",
        ))
    if not rows:
        rows.append((label, _fmt(d) if "value" in d else
                     f"keys={sorted(d)[:6]}"))
    return rows


def _rows_generic(label: str, d: dict) -> List[Row]:
    if "value" in d or "metric" in d:
        return [(label, _fmt(d))]
    return [(label, f"keys={sorted(d)[:8]}")]


def _rows_for(name: str, d: dict) -> List[Row]:
    """Dispatch on the artifact's original filename. A relocated legacy
    artifact (schema envelope with extra.legacy) unwraps first, so the
    table reads the same before and after the relocation."""
    label = name
    ex = d.get("extra") if isinstance(d, dict) else None
    if isinstance(ex, dict) and isinstance(ex.get("legacy"), dict):
        d = ex["legacy"]
        name = ex.get("legacy_source") or name
    if name.startswith("BENCH_r") and "parsed" in d:
        return _rows_bench_driver(label, d)
    if name.startswith("SCALE_"):
        return _rows_scale(label, d)
    if name.startswith(("MESH_", "MULTICHIP_")) and "sizes" in d:
        return _rows_mesh(label, d)
    return _rows_generic(label, d)


_PATTERNS = (
    "BENCH_r*.json",
    "BENCH_TPU_*.json",
    "BENCH_CHECKPOINT_*.json",
    "SCALE_*.json",
    "MESH_*.json",
    "MULTICHIP_*.json",
    "PROFILE_*.json",
    "RUN_*.json",
)


def _render_file(path: str, prefix: str) -> List[Row]:
    name = os.path.basename(path)
    d, err = _load(path)
    if err:
        return [(prefix + name, err)]
    if not isinstance(d, dict):
        return [(prefix + name, f"unexpected type {type(d).__name__}")]
    return [(prefix + label, desc) for label, desc in _rows_for(name, d)]


def _iter_artifacts(root: str):
    seen = set()
    for pat in _PATTERNS:
        for path in sorted(glob.glob(os.path.join(root, pat))):
            if path not in seen:
                seen.add(path)
                yield path


def _scan_dir(root: str, prefix: str = "") -> List[Row]:
    """Render every evidence artifact under ``root`` (the evidence-dir
    mode: everything there is indexed or a live checkpoint)."""
    rows: List[Row] = []
    for path in _iter_artifacts(root):
        rows.extend(_render_file(path, prefix))
    return rows


def _scan_root(root: str) -> Tuple[List[Row], List[str]]:
    """ONE pass over the repo root: live working files (BENCH_TPU_*
    watcher targets, checkpoints — the upgrader can never relocate them)
    render; relocatable strays are returned by name for the stderr
    notice, never rendered as if they were indexed evidence."""
    from scconsensus_tpu.obs.ledger import is_transient_artifact

    rows: List[Row] = []
    stray: List[str] = []
    for path in _iter_artifacts(root):
        if is_transient_artifact(path):
            rows.extend(_render_file(path, prefix=""))
        else:
            stray.append(os.path.basename(path))
    return rows, stray


def _tunnel_row(root: str) -> Optional[Row]:
    tlog = os.path.join(root, "TUNNEL_LOG.jsonl")
    if not os.path.exists(tlog):
        return None
    try:
        import statistics

        alive = down = 0
        bw = []
        with open(tlog) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                p = rec.get("probe") if isinstance(rec, dict) else None
                if not isinstance(p, dict):
                    continue
                if p.get("alive"):
                    alive += 1
                    if p.get("up_MBps"):
                        bw.append(float(p["up_MBps"]))
                else:
                    down += 1
        desc = f"probes: {alive} alive / {down} down"
        if bw:
            desc += (f"; up-bandwidth MB/s min={min(bw):.1f} "
                     f"median={statistics.median(bw):.1f} "
                     f"max={max(bw):.1f}")
    except (OSError, ValueError, TypeError) as e:
        desc = f"unreadable: {e!r}"
    return ("TUNNEL_LOG.jsonl", desc)


def _manifest_row(ev_dir: str) -> Optional[Row]:
    path = os.path.join(ev_dir, "MANIFEST.json")
    if not os.path.exists(path):
        return None
    try:
        m = json.load(open(path))
        entries = m.get("entries", [])
        keys = {json.dumps(e.get("key"), sort_keys=True) for e in entries}
        desc = (f"entries={len(entries)} keys={len(keys)} "
                f"version={m.get('version')}")
    except (OSError, ValueError) as e:
        desc = f"unreadable: {e!r}"
    return ("evidence/MANIFEST.json", desc)


def _stray_root_files(root: str) -> List[str]:
    """Relocatable evidence files sitting at the root (the repo-hygiene
    test's hook; main() gets the same list from its single scan)."""
    return _scan_root(root)[1]


def main() -> None:
    rows: List[Row] = []
    # live working files at the root (BENCH_TPU_* capture targets) still
    # render — the watcher writes them there mid-campaign by design
    root_rows, stray = _scan_root(ROOT)
    rows.extend(root_rows)
    if stray:
        print(
            f"NOTE: {len(stray)} un-indexed root-level evidence file(s) "
            f"under {ROOT} ({', '.join(stray[:5])}"
            + ("…" if len(stray) > 5 else "")
            + ") — not rendered; relocate into evidence/ with "
            "`python tools/perf_gate.py --upgrade`",
            file=sys.stderr,
        )
    ev_dir = os.path.join(ROOT, "evidence")
    if os.path.isdir(ev_dir):
        mrow = _manifest_row(ev_dir)
        if mrow:
            rows.append(mrow)
        rows.extend(_scan_dir(ev_dir, prefix="evidence/"))
    trow = _tunnel_row(ROOT)
    if trow:
        rows.append(trow)
    width = max(len(r[0]) for r in rows) if rows else 0
    for name, desc in rows:
        print(f"{name:<{width}}  {desc}")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # `| head` closing early is fine
        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
