#!/usr/bin/env python
"""Print one table from every benchmark/evidence artifact in the repo root.

Covers driver artifacts (BENCH_r*.json: {n, cmd, rc, tail, parsed}),
watcher TPU evidence (BENCH_TPU_*.json), bench checkpoints
(BENCH_CHECKPOINT_*.json), and the committed SCALE_/MESH_ evidence files.

Ingest contract: artifacts carrying the ``scc-run-record`` schema are
version-checked (obs.export.check_schema_version); an unknown schema name
or version is a hard error (exit != 0), never a silently garbled row.
Legacy pre-schema artifacts are accepted as-is.

Usage: python tools/summarize_evidence.py [root]
"""

from __future__ import annotations

import glob
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROOT = sys.argv[1] if len(sys.argv) > 1 else _REPO
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.export import check_schema_version  # noqa: E402


def _fmt(rec: dict) -> str:
    ex = rec.get("extra", {})
    bits = [
        f"value={rec.get('value')}",
        f"unit={rec.get('unit')}",
        f"vs_baseline={rec.get('vs_baseline')}",
        f"platform={ex.get('platform') or rec.get('run', {}).get('platform')}",
    ]
    if "schema" in rec:
        bits.append(f"schema={rec.get('schema_version')}")
        if rec.get("spans"):
            bits.append(f"spans={len(rec['spans'])}")
    if ex.get("degraded"):
        bits.append("DEGRADED")
    if ex.get("partial"):
        bits.append("PARTIAL")
    if ex.get("wilcox_s") is not None:
        bits.append(f"wilcox_s={ex['wilcox_s']}")
    return "  ".join(str(b) for b in bits)


def _load(path: str):
    """A mid-write (truncated) artifact must degrade to one 'unreadable'
    row, never crash the whole table — but an artifact declaring an
    UNKNOWN run-record schema version is a hard error (SystemExit): this
    tool must not render future-schema records as if it understood them.
    """
    try:
        d = json.load(open(path))
    except (json.JSONDecodeError, OSError) as e:
        return None, f"unreadable: {e!r}"
    try:
        if isinstance(d, dict):
            check_schema_version(d, source=os.path.basename(path))
    except ValueError as e:
        raise SystemExit(f"schema validation failed: {e}")
    return d, None


def main() -> None:
    rows = []
    for path in sorted(glob.glob(os.path.join(ROOT, "BENCH_r*.json"))):
        d, err = _load(path)
        if err:
            rows.append((os.path.basename(path), err))
            continue
        parsed = d.get("parsed")
        rows.append((os.path.basename(path),
                     f"rc={d.get('rc')}  parsed="
                     + ("null" if parsed is None else _fmt(parsed))))
    for pat in ("BENCH_TPU_*.json", "BENCH_CHECKPOINT_*.json"):
        for path in sorted(glob.glob(os.path.join(ROOT, pat))):
            d, err = _load(path)
            rows.append((os.path.basename(path), err or _fmt(d)))
    for path in sorted(glob.glob(os.path.join(ROOT, "SCALE_*.json"))):
        d, err = _load(path)
        if err:
            rows.append((os.path.basename(path), err))
            continue
        # three shapes: a single bench record ({"metric", "value", ...}),
        # {"configs": {name: record}}, or a top-level map of records
        if "metric" in d and "value" in d:
            rows.append((os.path.basename(path), _fmt(d)))
            continue
        entries = d.get("configs") or {
            k: v for k, v in d.items()
            if isinstance(v, dict) and ("metric" in v or "value" in v)
        }
        if entries:
            for cfg, rec in entries.items():
                rows.append((f"{os.path.basename(path)}:{cfg}", _fmt(rec)))
        else:
            rows.append((os.path.basename(path), _fmt(d)))
    for path in sorted(glob.glob(os.path.join(ROOT, "MESH_*.json"))):
        d, err = _load(path)
        if err:
            rows.append((os.path.basename(path), err))
            continue
        for size, rec in d.get("sizes", {}).items():
            rows.append((
                f"{os.path.basename(path)}:{size}",
                f"mesh={rec.get('mesh8')}s serial={rec.get('serial')}s "
                f"ratio={rec.get('ratio', rec.get('mesh_over_serial'))}",
            ))
    tlog = os.path.join(ROOT, "TUNNEL_LOG.jsonl")
    if os.path.exists(tlog):
        try:
            import statistics

            alive = down = 0
            bw = []
            with open(tlog) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    p = rec.get("probe") if isinstance(rec, dict) else None
                    if not isinstance(p, dict):
                        continue
                    if p.get("alive"):
                        alive += 1
                        if p.get("up_MBps"):
                            bw.append(float(p["up_MBps"]))
                    else:
                        down += 1
            desc = f"probes: {alive} alive / {down} down"
            if bw:
                desc += (f"; up-bandwidth MB/s min={min(bw):.1f} "
                         f"median={statistics.median(bw):.1f} "
                         f"max={max(bw):.1f}")
        except (OSError, ValueError, TypeError) as e:
            desc = f"unreadable: {e!r}"
        rows.append(("TUNNEL_LOG.jsonl", desc))
    width = max(len(r[0]) for r in rows) if rows else 0
    for name, desc in rows:
        print(f"{name:<{width}}  {desc}")


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # `| head` closing early is fine
        os.dup2(os.open(os.devnull, os.O_WRONLY), 1)
