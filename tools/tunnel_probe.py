#!/usr/bin/env python
"""Tunnel health + bandwidth probe for the axon TPU, with hard timeouts.

Run between capture attempts (never concurrently with a bench: the worker
holds the device). The last stdout line is one JSON object:
  {"alive": bool, "init_s": ..., "up_MBps": ..., "down_MBps": ..., "matmul_s": ...}

The numbers size the capture timeouts: the flagship dataset is ~1.5 GB f32,
so at up_MBps=U the one-time upload inside the edgeR cold run costs
~1500/U seconds, which must fit inside the bench attempt window.

Robustness (VERDICT r5: the judge's probe hung 45 s until killed by hand):
the actual jax work runs in a ``--once`` child subprocess under a HARD
per-probe timeout — a dead tunnel wedges backend init inside a C++ RPC
wait where no in-process signal fires, so only a kill from outside works.
The parent retries with logged exponential backoff and appends one
structured record per attempt to TUNNEL_LOG.jsonl:

  {"ts", "attempt", "of", "timeout_s", "wall_s", "outcome",
   "backoff_s", "probe": {...}}

``outcome``: alive | dead (probe answered but backend down) | timeout
(killed at the deadline) | error (crashed / non-JSON output).

Flight recorder (round 9): each child probe runs its phases (backend
init / upload / download / matmul) inside tracer spans and emits an
obs.live heartbeat stream; the parent records the probe's last heartbeat
age + last open span in the TUNNEL_LOG entry (``"heartbeat"``), so a
post-mortem can tell tunnel death (wedged mid-``upload``) from slow
backend init (wedged in ``backend_init``) from an interpreter that never
came up at all (no heartbeats).

Usage: tunnel_probe.py [mb] [--timeout S] [--attempts N] [--log PATH]
       (defaults: 64 MB payload, 90 s per probe, 2 attempts,
       <repo>/TUNNEL_LOG.jsonl; --log '' disables logging)
"""
import argparse
import datetime
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

BACKOFF_BASE_S = 2.0
BACKOFF_CAP_S = 60.0
PROBE_HEARTBEAT_S = 1.0  # probes always heartbeat (short-lived, cheap)
# TUNNEL_LOG rotation: 133 dead probes and counting — cap the committed
# log and roll the old half to <log>.1 (gitignored) instead of growing
# without bound.
LOG_CAP_BYTES = 512 << 10


def classify_outcome(outcome: str, probe: dict) -> "str | None":
    """Typed error class for one probe attempt (robust.retry classes):
    'transient' (tunnel wedged / backend down — the retry-later class),
    'resource', 'fatal', or None for a healthy probe. Stamped on every
    TUNNEL_LOG record so the capture watcher and post-mortems can filter
    dead-tunnel noise from real breakage without re-parsing error text."""
    if outcome == "alive":
        return None
    try:
        from scconsensus_tpu.robust.retry import classify_text
    except Exception:
        return None
    cls = classify_text((probe or {}).get("error"))
    if cls is not None:
        return cls
    if outcome in ("timeout", "dead"):
        # a probe killed at its deadline or a backend that answered
        # "down": the wait-and-retry class by definition
        return "transient"
    return "fatal"


def _start_recorder(hb_base: str):
    """Child-side flight recorder + tracer (obs.live): the stream is the
    parent's post-mortem when this process wedges and gets killed."""
    try:
        from scconsensus_tpu.config import env_flag
        from scconsensus_tpu.obs.live import LiveRecorder
        from scconsensus_tpu.obs.trace import Tracer

        rec = LiveRecorder(
            hb_base, metric="tunnel probe",
            heartbeat_s=float(env_flag("SCC_OBS_HEARTBEAT"))
            or PROBE_HEARTBEAT_S,
            flush_every_s=10.0,
        ).start(install_signals=False)  # SIGKILLed children get no signals
        return rec, Tracer(sync="off")
    except Exception as e:
        print(f"[tunnel_probe] recorder failed: {e!r}", file=sys.stderr)
        return None, None


def probe_once(mb: float, hb_base: str = "", hang_s: float = 0.0) -> dict:
    """The measurement itself (child side). Any hang here is the parent's
    problem — by design this function takes no defensive timeouts; the
    heartbeat stream (phase spans: backend_init / upload / download /
    matmul) is what tells the parent WHERE it wedged."""
    from contextlib import nullcontext

    recorder, tracer = _start_recorder(hb_base) if hb_base else (None, None)

    def _sp(name):
        return (tracer.span(name, kind="stage", sync=False)
                if tracer is not None else nullcontext())

    out = {"alive": False}
    auditor = None
    t0 = time.perf_counter()
    try:
        with _sp("backend_init"):
            if hang_s:  # simulated wedged backend init (tests)
                time.sleep(hang_s)
            import jax
            import jax.numpy as jnp
            import numpy as np

            dev = jax.devices()[0]
        out["platform"] = dev.platform
        out["init_s"] = round(time.perf_counter() - t0, 2)

        # residency audit over the transfer phases (obs.residency): the
        # probe's byte accounting rides TUNNEL_LOG so the standing
        # residency/kernel capture lane (tpu_capture_watcher.sh) can be
        # sanity-checked against what the tunnel actually moved
        try:
            from scconsensus_tpu.obs.residency import ResidencyAuditor

            auditor = ResidencyAuditor(mode="audit").__enter__()
        except Exception:
            auditor = None

        host = np.ones((int(mb * 1e6 / 4),), np.float32)
        with _sp("upload"):
            t = time.perf_counter()
            d = jax.device_put(host, dev)
            d.block_until_ready()
            up = time.perf_counter() - t
        out["up_MBps"] = round(mb / up, 2)

        with _sp("download"):
            t = time.perf_counter()
            _ = np.asarray(d)
            out["down_MBps"] = round(mb / (time.perf_counter() - t), 2)

        with _sp("matmul"):
            x = jnp.ones((2048, 2048), jnp.float32)
            y = (x @ x).block_until_ready()  # noqa: F841  (compile + run)
            t = time.perf_counter()
            (x @ x).block_until_ready()
            out["matmul_s"] = round(time.perf_counter() - t, 4)
        out["alive"] = True
    except Exception as e:  # fast failures; hangs are killed by the parent
        out["error"] = repr(e)[:300]
    finally:
        if auditor is not None:
            try:
                auditor.__exit__(None, None, None)
                out["transfers"] = {
                    "to_device_bytes": auditor.to_device_bytes,
                    "to_host_bytes": auditor.to_host_bytes,
                }
            except Exception:
                pass
        if recorder is not None:
            recorder.stop("clean" if out["alive"] else "crash")
    return out


def _heartbeat_summary(hb_base: str) -> "dict | None":
    """Parent-side post-mortem of a child's stream: last heartbeat age,
    tick count, and the span it was inside when last heard from. None =
    the child never heartbeat at all (died before the recorder started —
    itself diagnostic: not even the interpreter came up)."""
    try:
        from scconsensus_tpu.obs.live import (
            heartbeat_path,
            read_heartbeat_tail,
        )

        tail = read_heartbeat_tail(heartbeat_path(hb_base))
    except Exception:
        return None
    if not tail:
        return None
    opens = tail.get("open_spans") or []
    return {
        "age_s": round(time.time() - float(tail.get("ts") or 0.0), 2),
        "ticks": tail.get("seq"),
        "last_t": tail.get("t"),
        "last_span": opens[-1]["name"] if opens else None,
        "since_progress_s": tail.get("since_progress_s"),
    }


# A TUNNEL_LOG heartbeat older than this is STALE: the watcher loop
# probes far more often than hourly, so an hour of silence means the
# probe lane itself is down (box offline, cron dead), which is a
# different fact from a probed-and-dead tunnel — and bench records must
# say which one it was.
STALE_AFTER_S = 3600.0


def tunnel_status(log_path: "str | None" = None,
                  now: "float | None" = None,
                  stale_after_s: float = STALE_AFTER_S) -> dict:
    """Freshness verdict over TUNNEL_LOG.jsonl — the stamp bench puts on
    run records whenever accelerator evidence is expected but absent
    (satellite: ``tunnel: stale`` instead of silent omission).

    Returns ``{"state": alive|stale|dead|missing|error, "age_s"?,
    "last_outcome"?, "log"}``:

    * ``missing`` — no log at all (this host never ran the probe lane);
    * ``error``   — log exists but no line parses (corrupt tail);
    * ``stale``   — freshest entry is older than ``stale_after_s``:
      nothing has even *tried* the tunnel recently, so "no accelerator
      evidence" is a monitoring gap, not a measured-dead tunnel;
    * ``dead``    — fresh entry, probe answered dead/timeout/error;
    * ``alive``   — fresh entry and the probe got a live backend.

    ``SCC_TUNNEL_LOG`` overrides the default log path (tests, hosts
    with a relocated probe lane).
    """
    path = log_path or os.environ.get("SCC_TUNNEL_LOG") \
        or os.path.join(_REPO, "TUNNEL_LOG.jsonl")
    out: dict = {"log": path}
    if not os.path.exists(path):
        out["state"] = "missing"
        return out
    last = None
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and rec.get("ts"):
                    last = rec
    except OSError:
        out["state"] = "error"
        return out
    if last is None:
        out["state"] = "error"
        return out
    try:
        ts = datetime.datetime.fromisoformat(str(last["ts"]))
        if ts.tzinfo is None:
            ts = ts.replace(tzinfo=datetime.timezone.utc)
        age = (now if now is not None else time.time()) - ts.timestamp()
    except (ValueError, TypeError, OverflowError):
        out["state"] = "error"
        return out
    out["age_s"] = round(max(age, 0.0), 1)
    out["last_outcome"] = last.get("outcome")
    if age > stale_after_s:
        out["state"] = "stale"
    elif last.get("outcome") == "alive":
        out["state"] = "alive"
    else:
        out["state"] = "dead"
    return out


def _append_log(path: str, record: dict) -> None:
    """One JSON line per attempt; logging failure never kills the probe.
    Rotation: past LOG_CAP_BYTES the log rolls to ``<path>.1`` (one
    generation kept) so five rounds of dead probes cannot grow the file
    without bound."""
    if not path:
        return
    try:
        try:
            if os.path.getsize(path) > LOG_CAP_BYTES:
                os.replace(path, path + ".1")
                print(f"[tunnel_probe] rotated {path} -> {path}.1",
                      file=sys.stderr)
        except OSError:
            pass
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
    except OSError as e:
        print(f"[tunnel_probe] log append failed: {e!r}", file=sys.stderr)


def _run_child(mb: float, timeout_s: float, hang_s: float,
               hb_base: str = "") -> tuple:
    """(outcome, probe_dict, wall_s) for one hard-timeout child attempt."""
    cmd = [sys.executable, os.path.abspath(__file__), str(mb), "--once"]
    if hang_s:
        cmd += ["--test-hang-s", str(hang_s)]
    if hb_base:
        cmd += ["--hb-base", hb_base]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        wall = time.perf_counter() - t0
        return "timeout", {
            "alive": False,
            "error": f"probe killed at hard {timeout_s:.0f}s timeout "
                     "(backend init / transfer never returned)",
        }, wall
    wall = time.perf_counter() - t0
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                probe = json.loads(line)
            except json.JSONDecodeError:
                continue
            return ("alive" if probe.get("alive") else "dead"), probe, wall
    return "error", {
        "alive": False,
        "error": f"probe produced no JSON (rc={proc.returncode}): "
                 + (proc.stderr or "")[-200:],
    }, wall


def main() -> int:
    ap = argparse.ArgumentParser(description="tunnel health probe")
    ap.add_argument("mb", nargs="?", type=float, default=64.0)
    ap.add_argument("--timeout", type=float, default=90.0,
                    help="hard per-probe timeout (seconds)")
    ap.add_argument("--attempts", type=int, default=2)
    ap.add_argument("--log", default=None,
                    help="attempt-log path ('' disables; default "
                         "<repo>/TUNNEL_LOG.jsonl)")
    ap.add_argument("--once", action="store_true",
                    help="run the measurement in-process (child mode)")
    ap.add_argument("--status", action="store_true",
                    help="no probe: print the TUNNEL_LOG freshness "
                         "verdict as JSON (exit 0 only when alive)")
    ap.add_argument("--stale-after", type=float, default=STALE_AFTER_S,
                    help="seconds before the last log entry counts as "
                         "stale (--status mode)")
    ap.add_argument("--hb-base", default="",
                    help="flight-recorder path base for the child probe "
                         "(parent-managed; '' skips the recorder)")
    ap.add_argument("--test-hang-s", type=float, default=0.0,
                    help=argparse.SUPPRESS)  # simulates a wedged backend
    args = ap.parse_args()
    if args.log is None:
        # --status leaves None so tunnel_status can honor SCC_TUNNEL_LOG;
        # probe mode writes the canonical repo log
        if not args.status:
            args.log = os.path.join(_REPO, "TUNNEL_LOG.jsonl")

    if args.status:
        st = tunnel_status(args.log or None,
                           stale_after_s=args.stale_after)
        print(json.dumps(st), flush=True)
        return 0 if st["state"] == "alive" else 1

    if args.once:
        print(json.dumps(probe_once(
            args.mb, hb_base=args.hb_base, hang_s=args.test_hang_s
        )), flush=True)
        return 0

    import shutil
    import tempfile

    hb_dir = tempfile.mkdtemp(prefix="scc-probe-hb-")
    probe: dict = {"alive": False}
    try:
        for attempt in range(1, max(1, args.attempts) + 1):
            hb_base = os.path.join(hb_dir, f"attempt{attempt}")
            outcome, probe, wall = _run_child(
                args.mb, args.timeout, args.test_hang_s, hb_base=hb_base
            )
            last = outcome == "alive" or attempt >= args.attempts
            backoff = 0.0 if last else min(
                BACKOFF_BASE_S * 2 ** (attempt - 1), BACKOFF_CAP_S
            )
            _append_log(args.log, {
                "ts": datetime.datetime.now(
                    datetime.timezone.utc).isoformat(),
                "attempt": attempt,
                "of": max(1, args.attempts),
                "timeout_s": args.timeout,
                "wall_s": round(wall, 2),
                "outcome": outcome,
                "error_class": classify_outcome(outcome, probe),
                "backoff_s": backoff,
                "probe": probe,
                "heartbeat": _heartbeat_summary(hb_base),
            })
            if outcome == "alive":
                break
            print(f"[tunnel_probe] attempt {attempt}/{args.attempts}: "
                  f"{outcome} after {wall:.1f}s"
                  + (f"; backing off {backoff:.0f}s" if backoff else ""),
                  file=sys.stderr, flush=True)
            if backoff:
                time.sleep(backoff)
    finally:
        shutil.rmtree(hb_dir, ignore_errors=True)
    print(json.dumps(probe), flush=True)
    return 0 if probe.get("alive") else 1


if __name__ == "__main__":
    raise SystemExit(main())
