"""Tunnel health + bandwidth probe for the axon TPU.

Run between capture attempts (never concurrently with a bench: the worker
holds the device). Prints one JSON line:
  {"alive": bool, "init_s": ..., "up_MBps": ..., "down_MBps": ..., "matmul_s": ...}

The numbers size the capture timeouts: the flagship dataset is ~1.5 GB f32,
so at up_MBps=U the one-time upload inside the edgeR cold run costs
~1500/U seconds, which must fit inside the bench attempt window.
"""
import json
import sys
import time

out = {"alive": False}


def main() -> None:
    t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        out["init_s"] = round(time.perf_counter() - t0, 2)

        mb = float(sys.argv[1]) if len(sys.argv) > 1 else 64.0
        host = np.ones((int(mb * 1e6 / 4),), np.float32)
        t = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        up = time.perf_counter() - t
        out["up_MBps"] = round(mb / up, 2)

        t = time.perf_counter()
        _ = np.asarray(d)
        out["down_MBps"] = round(mb / (time.perf_counter() - t), 2)

        x = jnp.ones((2048, 2048), jnp.float32)
        y = (x @ x).block_until_ready()  # noqa: F841  (compile + run)
        t = time.perf_counter()
        (x @ x).block_until_ready()
        out["matmul_s"] = round(time.perf_counter() - t, 4)
        out["alive"] = True
    except Exception as e:  # tunnel down / init hang handled by caller timeout
        out["error"] = repr(e)[:300]
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
