#!/usr/bin/env python
"""Tunnel health + bandwidth probe for the axon TPU, with hard timeouts.

Run between capture attempts (never concurrently with a bench: the worker
holds the device). The last stdout line is one JSON object:
  {"alive": bool, "init_s": ..., "up_MBps": ..., "down_MBps": ..., "matmul_s": ...}

The numbers size the capture timeouts: the flagship dataset is ~1.5 GB f32,
so at up_MBps=U the one-time upload inside the edgeR cold run costs
~1500/U seconds, which must fit inside the bench attempt window.

Robustness (VERDICT r5: the judge's probe hung 45 s until killed by hand):
the actual jax work runs in a ``--once`` child subprocess under a HARD
per-probe timeout — a dead tunnel wedges backend init inside a C++ RPC
wait where no in-process signal fires, so only a kill from outside works.
The parent retries with logged exponential backoff and appends one
structured record per attempt to TUNNEL_LOG.jsonl:

  {"ts", "attempt", "of", "timeout_s", "wall_s", "outcome",
   "backoff_s", "probe": {...}}

``outcome``: alive | dead (probe answered but backend down) | timeout
(killed at the deadline) | error (crashed / non-JSON output).

Usage: tunnel_probe.py [mb] [--timeout S] [--attempts N] [--log PATH]
       (defaults: 64 MB payload, 90 s per probe, 2 attempts,
       <repo>/TUNNEL_LOG.jsonl; --log '' disables logging)
"""
import argparse
import datetime
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BACKOFF_BASE_S = 2.0
BACKOFF_CAP_S = 60.0


def probe_once(mb: float) -> dict:
    """The measurement itself (child side). Any hang here is the parent's
    problem — by design this function takes no defensive timeouts."""
    out = {"alive": False}
    t0 = time.perf_counter()
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        dev = jax.devices()[0]
        out["platform"] = dev.platform
        out["init_s"] = round(time.perf_counter() - t0, 2)

        host = np.ones((int(mb * 1e6 / 4),), np.float32)
        t = time.perf_counter()
        d = jax.device_put(host, dev)
        d.block_until_ready()
        up = time.perf_counter() - t
        out["up_MBps"] = round(mb / up, 2)

        t = time.perf_counter()
        _ = np.asarray(d)
        out["down_MBps"] = round(mb / (time.perf_counter() - t), 2)

        x = jnp.ones((2048, 2048), jnp.float32)
        y = (x @ x).block_until_ready()  # noqa: F841  (compile + run)
        t = time.perf_counter()
        (x @ x).block_until_ready()
        out["matmul_s"] = round(time.perf_counter() - t, 4)
        out["alive"] = True
    except Exception as e:  # fast failures; hangs are killed by the parent
        out["error"] = repr(e)[:300]
    return out


def _append_log(path: str, record: dict) -> None:
    """One JSON line per attempt; logging failure never kills the probe."""
    if not path:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
            f.flush()
    except OSError as e:
        print(f"[tunnel_probe] log append failed: {e!r}", file=sys.stderr)


def _run_child(mb: float, timeout_s: float, hang_s: float) -> tuple:
    """(outcome, probe_dict, wall_s) for one hard-timeout child attempt."""
    cmd = [sys.executable, os.path.abspath(__file__), str(mb), "--once"]
    if hang_s:
        cmd += ["--test-hang-s", str(hang_s)]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        wall = time.perf_counter() - t0
        return "timeout", {
            "alive": False,
            "error": f"probe killed at hard {timeout_s:.0f}s timeout "
                     "(backend init / transfer never returned)",
        }, wall
    wall = time.perf_counter() - t0
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                probe = json.loads(line)
            except json.JSONDecodeError:
                continue
            return ("alive" if probe.get("alive") else "dead"), probe, wall
    return "error", {
        "alive": False,
        "error": f"probe produced no JSON (rc={proc.returncode}): "
                 + (proc.stderr or "")[-200:],
    }, wall


def main() -> int:
    ap = argparse.ArgumentParser(description="tunnel health probe")
    ap.add_argument("mb", nargs="?", type=float, default=64.0)
    ap.add_argument("--timeout", type=float, default=90.0,
                    help="hard per-probe timeout (seconds)")
    ap.add_argument("--attempts", type=int, default=2)
    ap.add_argument("--log", default=os.path.join(_REPO, "TUNNEL_LOG.jsonl"),
                    help="attempt-log path ('' disables)")
    ap.add_argument("--once", action="store_true",
                    help="run the measurement in-process (child mode)")
    ap.add_argument("--test-hang-s", type=float, default=0.0,
                    help=argparse.SUPPRESS)  # simulates a wedged backend
    args = ap.parse_args()

    if args.once:
        if args.test_hang_s:
            time.sleep(args.test_hang_s)
        print(json.dumps(probe_once(args.mb)), flush=True)
        return 0

    probe: dict = {"alive": False}
    for attempt in range(1, max(1, args.attempts) + 1):
        outcome, probe, wall = _run_child(
            args.mb, args.timeout, args.test_hang_s
        )
        last = outcome == "alive" or attempt >= args.attempts
        backoff = 0.0 if last else min(
            BACKOFF_BASE_S * 2 ** (attempt - 1), BACKOFF_CAP_S
        )
        _append_log(args.log, {
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(),
            "attempt": attempt,
            "of": max(1, args.attempts),
            "timeout_s": args.timeout,
            "wall_s": round(wall, 2),
            "outcome": outcome,
            "backoff_s": backoff,
            "probe": probe,
        })
        if outcome == "alive":
            break
        print(f"[tunnel_probe] attempt {attempt}/{args.attempts}: "
              f"{outcome} after {wall:.1f}s"
              + (f"; backing off {backoff:.0f}s" if backoff else ""),
              file=sys.stderr, flush=True)
        if backoff:
            time.sleep(backoff)
    print(json.dumps(probe), flush=True)
    return 0 if probe.get("alive") else 1


if __name__ == "__main__":
    raise SystemExit(main())
