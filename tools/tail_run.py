#!/usr/bin/env python
"""Render a flight-recorder heartbeat stream as a live terminal view.

Reads the ``*_heartbeat.jsonl`` stream an obs.live recorder appends to
(bench workers, tools/run_sparse_1m.py, tunnel probes) and renders one
status panel: last heartbeat age, uptime, host RSS / device HBM, compile
stats, the open-span stack with elapsed walls, stall events, a quality
panel (numeric-sentinel trips + the latest DE-funnel totals, so NaN
storms and empty funnels are visible live), a transfer panel (cumulative
host↔device bytes from the residency auditor plus a live byte rate
differenced from consecutive ticks — a host-round-trip storm shows as
MB/s mid-run), a serving panel (queue depth, live p99, breaker state,
degraded/quarantined/rejected counters fed from serve.metrics via
obs.live — an online driver's vitals tick by tick), an integrity panel
(invariant checks passed/run, ghost-replay progress + lag, mismatches
and silent-corruption recomputes from robust.integrity — a run
fighting corruption shows it live), a graph-passport panel (per-stage
static transfer-op / host-callback / donation-miss counts from the
compiled programs, obs.graphs — the transfer-op ratchet's candidate
side), and — when
the evidence ledger holds baseline history for the run's key — a
per-stage ETA from the noise-banded baselines
(``obs.regress.stage_baselines``). The sibling ``*_partial.json`` record
(incrementally flushed by the same recorder) supplies completed-stage
walls and the termination stamp.

Usage:
  python tools/tail_run.py RUN_heartbeat.jsonl            # one snapshot
  python tools/tail_run.py RUN_heartbeat.jsonl --follow   # live view
  ... [--evidence DIR] (ETA baselines; default SCC_EVIDENCE_DIR or
      <repo>/evidence) [--interval S] [--no-eta]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _fmt_bytes(n: Optional[float]) -> str:
    if not n:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return "?"


_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(buckets: List[int]) -> str:
    """Unicode sparkline over histogram bucket counts (log-ish scale so
    a dominant bucket doesn't flatten the tail into invisibility)."""
    import math

    peak = max(buckets) if buckets else 0
    if peak <= 0:
        return "▁" * len(buckets)
    out = []
    for c in buckets:
        if c <= 0:
            out.append("▁")
        else:
            frac = math.log1p(c) / math.log1p(peak)
            out.append(_SPARK[min(int(frac * (len(_SPARK) - 1) + 0.5),
                                  len(_SPARK) - 1)])
    return "".join(out)


def _value_sparkline(vals: List[Optional[float]]) -> str:
    """Linear sparkline over a sampled VALUE series (the RSS timeline) —
    min..max scaled, unlike :func:`_sparkline`'s log-count scale for
    histogram buckets."""
    xs = [float(v) for v in vals if v is not None]
    if not xs:
        return ""
    lo, hi = min(xs), max(xs)
    if hi <= lo:
        return _SPARK[0] * len(xs)
    return "".join(
        _SPARK[min(int((v - lo) / (hi - lo) * (len(_SPARK) - 1) + 0.5),
                   len(_SPARK) - 1)]
        for v in xs
    )


def _fmt_dur(s: Optional[float]) -> str:
    if s is None:
        return "?"
    s = max(float(s), 0.0)
    if s < 60:
        return f"{s:.1f}s"
    m, sec = divmod(int(s), 60)
    h, m = divmod(m, 60)
    return f"{h}h{m:02d}m{sec:02d}s" if h else f"{m}m{sec:02d}s"


def read_stream(path: str, tail_bytes: int = 256 << 10
                ) -> List[Dict[str, Any]]:
    """Parsed stream lines: the file head (header/annotate always survive)
    plus the most recent ``tail_bytes``. Lines mid-append parse-fail and
    are skipped — crash-safety is line-granular by design."""
    out: List[Dict[str, Any]] = []
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            chunk = f.read(16 << 10)
            if size > len(chunk) + tail_bytes:
                f.seek(size - tail_bytes)
                chunk += b"\n" + f.read()
            else:
                chunk += f.read()
    except OSError as e:
        raise SystemExit(f"tail_run: cannot read {path}: {e}")
    for line in chunk.decode("utf-8", errors="replace").splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            out.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return out


def _stream_state(lines: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold the stream into one render state: header ∪ annotations, the
    last heartbeat, the last stall event, and the end stamp if any."""
    st: Dict[str, Any] = {"header": None, "key": None, "hb": None,
                          "hb_prev": None, "stall": None, "end": None,
                          "extra": {}}
    for ln in lines:
        t = ln.get("t")
        if t == "header":
            st["header"] = ln
            st["extra"].update(ln.get("extra") or {})
            st["key"] = ln.get("key") or st["key"]
        elif t == "annotate":
            st["extra"].update(ln.get("extra") or {})
            st["key"] = ln.get("key") or st["key"]
        elif t == "hb":
            st["hb_prev"] = st["hb"]
            st["hb"] = ln
        elif t == "stall":
            st["stall"] = ln
        elif t == "end":
            st["end"] = ln
    return st


def _baselines_for(key: Optional[Dict[str, str]], evidence_dir: str
                   ) -> Dict[str, Dict[str, float]]:
    """Noise-banded per-stage baselines for the stream's run key, or {}
    (no key, no ledger, no history — the view degrades to walls only)."""
    if not key or not os.path.isdir(evidence_dir):
        return {}
    try:
        from scconsensus_tpu.obs.ledger import Ledger
        from scconsensus_tpu.obs.regress import stage_baselines

        return stage_baselines(Ledger(evidence_dir).history(key))
    except Exception:
        return {}


def _partial_sidecar(stream_path: str) -> Optional[Dict[str, Any]]:
    """The `<base>_partial.json` the same recorder flushes, via obs.live's
    canonical naming (one scheme, no string-twin drift)."""
    from scconsensus_tpu.obs.live import heartbeat_path, partial_record_path

    base = stream_path[: -len("_heartbeat.jsonl")]
    if heartbeat_path(base) != stream_path:
        return None  # not a stream path the recorder would have produced
    try:
        with open(partial_record_path(base)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _span_line(sp: Dict[str, Any],
               baselines: Dict[str, Dict[str, float]]) -> str:
    name = sp.get("name", "?")
    indent = "  " * (1 + int(sp.get("depth") or 0))
    line = (f"{indent}{name:<24} {sp.get('kind', '?'):<7}"
            f" {_fmt_dur(sp.get('elapsed_s')):>9}")
    base = baselines.get(name)
    if base and sp.get("kind") == "stage":
        eta = base["baseline_s"] - float(sp.get("elapsed_s") or 0.0)
        line += (f"   [baseline {_fmt_dur(base['baseline_s'])}"
                 f" ±{_fmt_dur(base['band_s'])}"
                 + (f" → ETA ~{_fmt_dur(eta)}" if eta > 0
                    else " → over baseline"))
        if eta <= 0 and base["band_s"]:
            over = -eta
            line += (" (within band)" if over <= base["band_s"]
                     else f" by {_fmt_dur(over - base['band_s'])} past band")
        line += "]"
    return line


def render(lines: List[Dict[str, Any]],
           baselines: Optional[Dict[str, Dict[str, float]]] = None,
           partial: Optional[Dict[str, Any]] = None,
           now: Optional[float] = None,
           tunnel: Optional[Dict[str, Any]] = None) -> str:
    """One status panel as text (pure function of its inputs — the render
    smoke test drives it over a committed fixture stream). ``tunnel``
    (optional) is a tools/tunnel_probe ``tunnel_status()`` verdict,
    surfaced in the header so a dead TPU evidence channel is visible on
    every live run."""
    baselines = baselines or {}
    now = time.time() if now is None else now
    st = _stream_state(lines)
    out: List[str] = []
    hdr = st["header"] or {}
    head = (f"flight record: {hdr.get('metric', '?')}"
            + (f"   [pid {hdr['pid']}]" if hdr.get("pid") else ""))
    if isinstance(tunnel, dict) and tunnel.get("state"):
        state = str(tunnel["state"])
        tag = state if state == "alive" else state.upper()
        age = tunnel.get("age_s")
        head += (f"   [tunnel {tag}"
                 + (f", {_fmt_dur(age)} old" if age is not None else "")
                 + "]")
    out.append(head)
    if st["extra"]:
        ident = ", ".join(f"{k}={v}" for k, v in sorted(st["extra"].items())
                          if isinstance(v, (str, int, float, bool)))
        if ident:
            out.append(f"  workload: {ident}")
    hb = st["hb"]
    if hb is None:
        out.append("  no heartbeat yet"
                   + ("" if hdr else " (stream has no header either)"))
    else:
        age = now - float(hb.get("ts") or now)
        rss_bit = f"rss {_fmt_bytes(hb.get('rss_bytes'))}"
        if hb.get("rss_peak_bytes"):
            # the peak-since-start is the number the streaming budget
            # assertion is judged by — the live panel shows BOTH so a
            # spike between ticks is still visible
            rss_bit += f" (peak {_fmt_bytes(hb['rss_peak_bytes'])})"
        bits = [f"last heartbeat {_fmt_dur(age)} ago",
                f"tick #{hb.get('seq')}",
                f"up {_fmt_dur(hb.get('up_s'))}",
                rss_bit]
        hbm = hb.get("hbm") or {}
        if hbm.get("bytes_in_use") is not None:
            bits.append(f"hbm {_fmt_bytes(hbm['bytes_in_use'])}"
                        + (f"/{_fmt_bytes(hbm['bytes_limit'])}"
                           if hbm.get("bytes_limit") else ""))
        comp = hb.get("compile") or {}
        if comp.get("events"):
            bits.append(f"compiles {comp['events']}"
                        f" ({_fmt_dur(comp.get('total_s'))})")
        out.append("  " + "   ".join(bits))
        out.append(f"  progress: last transition "
                   f"{_fmt_dur(hb.get('since_progress_s'))} ago"
                   f"   spans done: {hb.get('spans_done')}"
                   f"   stalls: {hb.get('stalls', 0)}")
        opens = hb.get("open_spans") or []
        if opens:
            out.append("  open spans:")
            for sp in opens:
                out.append(_span_line(sp, baselines))
        else:
            out.append("  open spans: (none)")
        xf = hb.get("transfers") or {}
        if xf:
            bits = [f"h2d {_fmt_bytes(xf.get('to_device_bytes'))}",
                    f"d2h {_fmt_bytes(xf.get('to_host_bytes'))}"]
            prev = st["hb_prev"] or {}
            pxf = prev.get("transfers") or {}
            dt = float(hb.get("ts") or 0) - float(prev.get("ts") or 0)
            if pxf and dt > 0:
                # live byte rate from consecutive cumulative ticks — a
                # host-round-trip storm shows as MB/s mid-run
                rate = (
                    (xf.get("to_device_bytes") or 0)
                    + (xf.get("to_host_bytes") or 0)
                    - (pxf.get("to_device_bytes") or 0)
                    - (pxf.get("to_host_bytes") or 0)
                ) / dt
                bits.append(f"rate {_fmt_bytes(max(rate, 0.0))}/s")
            out.append("  transfers: " + "   ".join(bits))
        q = hb.get("quality") or {}
        if q:
            bits = []
            trips = q.get("trips")
            if trips:
                last = q.get("last_trip") or {}
                bits.append(
                    f"SENTINEL TRIPS: {trips}"
                    + (f" (last: {last.get('span')}/{last.get('array')}"
                       f" nan={last.get('nan', 0)}"
                       f" inf={last.get('inf', 0)})"
                       if last else "")
                )
            funnel = q.get("funnel") or {}
            if funnel:
                bits.append("funnel " + " → ".join(
                    f"{k}={funnel[k]}" for k in
                    ("input", "pct_gate", "logfc_gate", "tested",
                     "significant") if k in funnel
                ))
            if bits:
                out.append("  quality: " + "   ".join(bits))
        rb = hb.get("robust") or {}
        if rb:
            bits = []
            if rb.get("faults"):
                bits.append(f"faults {rb['faults']}")
            if rb.get("retries"):
                last = rb.get("last_retry") or {}
                bits.append(
                    f"RETRIES {rb['retries']}"
                    + (f" (last: {last.get('site')}"
                       f" {last.get('error_class')}"
                       f" {'ok' if last.get('recovered') else 'FAILED'})"
                       if last else "")
                )
            if rb.get("degradations"):
                bits.append(f"degraded x{rb['degradations']}")
            if rb.get("resumes"):
                bits.append(f"resumed x{rb['resumes']}")
            mesh = rb.get("mesh") or {}
            if mesh:
                # live elastic panel: current device count + the shrink
                # path so far (robust.record.live_summary feeds it)
                bits.append(
                    f"MESH {mesh.get('devices')} dev"
                    + (f" (path {mesh['path']})" if mesh.get("path")
                       else "")
                    + f" after {mesh.get('transitions')} transition(s)"
                )
            if bits:
                out.append("  robust: " + "   ".join(bits))
        sm = hb.get("streaming") or {}
        if sm:
            # streaming heartbeat panel (round 17, obs.live ←
            # stream.record): chunk progress, staged bytes, and the
            # peak-RSS-vs-budget headroom — an out-of-core run's vitals
            bits = []
            if sm.get("chunks_planned"):
                bits.append(f"chunks {sm.get('chunks_done', 0)}"
                            f"/{sm['chunks_planned']}"
                            + (f" ({sm['stage']})" if sm.get("stage")
                               else ""))
            bits.append(f"staged {_fmt_bytes(sm.get('staged_bytes'))}")
            peak, bud = sm.get("peak_rss_bytes"), sm.get("budget_bytes")
            if peak and bud:
                over = peak > bud
                bits.append(
                    ("PEAK RSS " if over else "peak rss ")
                    + f"{_fmt_bytes(peak)}/{_fmt_bytes(bud)}"
                    + (" OVER BUDGET" if over else "")
                )
            if sm.get("halvings"):
                bits.append(f"window halved x{sm['halvings']}")
            out.append("  streaming: " + "   ".join(bits))
        ig = hb.get("integrity") or {}
        if ig:
            # integrity heartbeat panel (round 18, obs.live ←
            # robust.integrity): invariant checks passed/run, ghost-
            # replay progress + lag, mismatches and recomputes — a run
            # silently fighting corruption shows it tick by tick
            bits = [f"checks {ig.get('checks_passed', 0)}"
                    f"/{ig.get('checks_run', 0)}"
                    + (f" (planned {ig['checks_planned']})"
                       if ig.get("checks_planned",
                                 0) > ig.get("checks_run", 0) else "")]
            if ig.get("violations"):
                bits.append(f"VIOLATIONS {ig['violations']}")
            bits.append(f"replay {ig.get('replays_run', 0)}"
                        f"/{ig.get('replays_planned', 0)}")
            if ig.get("replay_age_s") is not None:
                bits.append(f"lag {_fmt_dur(ig['replay_age_s'])}")
            if ig.get("mismatches"):
                bits.append(f"MISMATCHES {ig['mismatches']}")
            if ig.get("recomputes"):
                bits.append(f"recomputed x{ig['recomputes']}")
            mode_ig = ig.get("mode", "audit")
            if mode_ig != "audit":
                bits.append(mode_ig)
            out.append("  integrity: " + "   ".join(bits))
        sv = hb.get("serving") or {}
        if sv:
            # serving heartbeat panel (obs.live ← serve.metrics): queue
            # depth, live p99, breaker state, degraded/quarantined/
            # rejected counters — the online path's vitals at a glance
            bits = [f"queue {sv.get('queue_depth', 0)}"
                    + (f"/{sv['queue_cap']}" if sv.get("queue_cap")
                       else "")]
            if sv.get("p99_ms") is not None:
                bits.append(f"p99 {sv['p99_ms']:.1f}ms")
            state = sv.get("breaker", "closed")
            bits.append(
                ("BREAKER " if state != "closed" else "breaker ") + state
                + (f" ({sv['breaker_trips']} trip(s))"
                   if sv.get("breaker_trips") else "")
            )
            bits.append(f"ok {sv.get('ok', 0)}")
            if sv.get("degraded"):
                bits.append(f"DEGRADED {sv['degraded']}")
            if sv.get("quarantined"):
                bits.append(f"QUARANTINED {sv['quarantined']}")
            if sv.get("rejected"):
                bits.append(f"rejected {sv['rejected']}")
            if sv.get("deadline_exceeded"):
                bits.append(f"deadline {sv['deadline_exceeded']}")
            if sv.get("failed"):
                bits.append(f"failed {sv['failed']}")
            out.append("  serving: " + "   ".join(bits))
            slo = sv.get("slo") or {}
            if slo:
                # live SLO panel (round 20): availability + per-window
                # error-budget burn, straight off the heartbeat's
                # cumulative counters — burn >= 1 means the budget is
                # being eaten at least as fast as it replenishes
                sbits = [f"availability {slo.get('availability')}"]
                for w, b in sorted((slo.get("burn") or {}).items(),
                                   key=lambda kv: float(kv[0])):
                    sbits.append((f"BURN {w}s {b}x" if float(b) >= 1.0
                                  else f"burn {w}s {b}x"))
                out.append("  slo: " + "   ".join(sbits))
            hist = sv.get("lat_hist") or {}
            if hist:
                # per-outcome latency histograms (round 20): fixed
                # bucket grid (serve.slo.LATENCY_BUCKETS_MS + overflow)
                # rendered as sparklines — the latency SHAPE live, not
                # just a p99 scalar
                try:
                    from scconsensus_tpu.serve.slo import (
                        LATENCY_BUCKETS_MS,
                    )

                    lo, hi = LATENCY_BUCKETS_MS[0], LATENCY_BUCKETS_MS[-1]
                    grid = f" [{lo:g}ms..{hi:g}ms,+Inf]"
                except Exception:
                    grid = ""
                out.append(f"  latency histograms{grid}:")
                for o in sorted(hist):
                    h = hist[o] or {}
                    out.append(
                        f"    {o:<18} "
                        f"{_sparkline(list(h.get('buckets') or []))}"
                        f"  n={h.get('n', 0)}"
                    )
            fl = sv.get("fleet") or {}
            if fl:
                # fleet heartbeat panel (round 16): per-replica queue
                # depth / rolling p99 / breaker state plus the ACTIVE
                # model fingerprint — which model is answering, and
                # which replica is drowning, at a glance
                reps = fl.get("replicas") or []
                out.append(f"  fleet: active model "
                           f"{fl.get('active_fp', '?')}"
                           f"   {len(reps)} replica(s)")
                for r in reps:
                    rbits = [f"r{r.get('replica', '?')}",
                             f"model {r.get('model_fp', '?')}",
                             f"queue {r.get('queue_depth', 0)}"]
                    if r.get("p99_ms") is not None:
                        rbits.append(f"p99 {r['p99_ms']:.1f}ms")
                    rstate = r.get("breaker", "closed")
                    rbits.append(
                        ("BREAKER " if rstate != "closed"
                         else "breaker ") + rstate
                        + (f" ({r['trips']} trip(s))"
                           if r.get("trips") else "")
                    )
                    out.append("    " + "   ".join(rbits))
                for sc in fl.get("scales") or []:
                    # autoscale tail (round 21): the last few fleet
                    # resizes, so a width change is visible in the same
                    # panel as the queues that provoked it
                    sbits = [f"scale {sc.get('from', '?')}"
                             f"→{sc.get('to', '?')}"]
                    if sc.get("reason"):
                        sbits.append(f"({sc['reason']})")
                    out.append("    " + " ".join(sbits))
    if st["stall"]:
        sl = st["stall"]
        out.append(f"  STALL #{sl.get('stalls')} at +{_fmt_dur((sl.get('ts') or 0) - float((st['header'] or {}).get('ts') or 0))}"
                   f" (no progress {_fmt_dur(sl.get('since_progress_s'))});"
                   " all-thread stack dump in stream"
                   + (f"; capture → {sl['capture']}" if sl.get("capture")
                      else ""))
    if partial:
        def _guard(title: str, body) -> None:
            # satellite (round 24): a record written before a section
            # existed — or carrying a malformed one — must degrade to a
            # one-line note, never raise out of the whole view (pre-r22
            # records used to need hand-editing to render)
            try:
                body()
            except Exception as e:
                out.append(f"  {title}: section unreadable "
                           f"({type(e).__name__}) — skipped")

        def _walls_panel() -> None:
            walls: List[Tuple[str, float]] = []
            for s in partial.get("spans") or []:
                if (isinstance(s, dict) and s.get("kind") == "stage"
                        and not (s.get("attrs") or {}).get("open")):
                    w = s.get("wall_synced_s")
                    walls.append((s.get("name", "?"), float(
                        w if w is not None
                        else s.get("wall_submitted_s", 0.0))))
            if walls:
                out.append("  completed stages: " + " | ".join(
                    f"{n} {_fmt_dur(w)}" for n, w in walls[-12:]))

        _guard("completed stages", _walls_panel)
        # residency burn-down table (round 22): bytes crossed per
        # declared boundary, TODO(item-2) rows flagged — the ratchet the
        # device-residency refactor is measured by, rendered live from
        # the partial record's own section (or derived on the fly from
        # its residency audit for pre-round-22 checkpoints)
        def _burndown_panel() -> None:
            bd = partial.get("residency_burndown")
            if not isinstance(bd, dict):
                try:
                    from scconsensus_tpu.obs.profile import build_burndown

                    bd = build_burndown(partial.get("residency"))
                except Exception:
                    bd = None
            if not (isinstance(bd, dict) and bd.get("boundaries")):
                return
            out.append(
                "  residency burn-down: total "
                f"{_fmt_bytes(bd.get('total_bytes'))} across "
                f"{bd.get('n_boundaries', 0)} boundaries; TODO(item-2) "
                f"{_fmt_bytes(bd.get('todo_item2_bytes') or 0) if bd.get('todo_item2_bytes') else '0B'} "
                f"across {bd.get('n_todo_item2', 0)}"
            )
            rows = sorted(
                bd["boundaries"].items(),
                key=lambda kv: (-int(kv[1].get("bytes") or 0), kv[0]),
            )
            for bname, row in rows[:8]:
                tag = "  [item-2]" if row.get("todo_item2") else ""
                out.append(
                    f"    {bname:<24} {_fmt_bytes(row.get('bytes'))}"
                    f"  ({row.get('calls', 0)} call(s)){tag}"
                )
            if len(rows) > 8:
                out.append(f"    ... {len(rows) - 8} more boundaries")

        _guard("residency burn-down", _burndown_panel)
        # host-observatory panels (round 19): sampled host causes,
        # compile/retrace counters, and the RSS timeline — rendered only
        # when the record carries the sections (pre-19 partials degrade
        # to the panels above)
        def _hostprof_panel() -> None:
            hp = partial.get("host_profile")
            if not isinstance(hp, dict):
                return
            period = float(hp.get("period_s") or 0.0)
            hz = f"{1.0 / period:.0f}Hz" if period > 0 else "?"
            g = hp.get("gc") or {}
            out.append(
                f"  host profile: {hp.get('n_samples', 0)} samples @ {hz}"
                f"   gc x{g.get('collections', 0)}"
                f" ({_fmt_dur(g.get('pause_s', 0.0))} paused)"
                f"   sampler self "
                f"{_fmt_dur(hp.get('sampler_self_s', 0.0))}"
            )
            hrows = sorted(
                (hp.get("stages") or {}).items(),
                key=lambda kv: (-(kv[1].get("samples") or 0), kv[0]),
            )
            for sname, srow in hrows[:6]:
                causes = srow.get("causes") or {}
                line = f"    {sname:<24} {_fmt_dur(srow.get('est_s'))}"
                dom = max(causes, key=lambda k: causes.get(k) or 0.0) \
                    if causes else None
                if dom is not None and (causes.get(dom) or 0.0) > 0:
                    line += f"  mostly {dom} ({_fmt_dur(causes[dom])})"
                if srow.get("top_frame"):
                    line += f"  top {srow['top_frame']}"
                out.append(line)

        def _compile_panel() -> None:
            comp_sec = partial.get("compile")
            if not isinstance(comp_sec, dict):
                return
            rt = int(comp_sec.get("retraces") or 0)
            out.append(
                f"  compile: {comp_sec.get('compiles', 0)} compiles   "
                + (f"RETRACES {rt}" if rt else "0 retraces") + "   "
                f"{comp_sec.get('cache_hits', 0)} cache hits   "
                f"wall {_fmt_dur(comp_sec.get('compile_wall_s', 0.0))}"
            )

        def _memory_panel() -> None:
            mt = partial.get("memory_timeline")
            if not isinstance(mt, dict):
                return
            vals = [s.get("rss_bytes")
                    for s in (mt.get("samples") or [])
                    if isinstance(s, dict)]
            out.append(
                "  memory: rss " + _value_sparkline(vals[-48:])
                + f"  peak {_fmt_bytes(mt.get('rss_peak_bytes'))}"
                + (f"  hbm peak {_fmt_bytes(mt['hbm_peak_bytes'])}"
                   if mt.get("hbm_peak_bytes") else "")
            )

        def _graphs_panel() -> None:
            # graph-passport panel (round 24, obs.graphs): per-stage
            # static transfer-op / host-callback / donation-miss counts
            # from the compiled programs — the ratchet's candidate side,
            # visible wherever the record is
            sec = partial.get("graphs")
            if not isinstance(sec, dict):
                return
            totals = sec.get("totals") or {}
            fp = (sec.get("fingerprint") or {}).get("digest")
            out.append(
                f"  graph passports: {totals.get('programs', 0)} programs"
                f"   transfer ops {totals.get('transfer_ops', 0)}"
                f"   host callbacks {totals.get('host_callbacks', 0)}"
                f"   donation misses {totals.get('donation_misses', 0)}"
                f"   fusions {totals.get('fusions', 0)}"
                + (f"   [fp {fp}]" if fp else "")
            )
            rows = sorted(
                (sec.get("by_stage") or {}).items(),
                key=lambda kv: (
                    -(int(kv[1].get("transfer_ops") or 0)
                      + int(kv[1].get("host_callbacks") or 0)),
                    kv[0],
                ),
            )
            for sname, row in rows[:8]:
                progs = row.get("programs") or []
                flags = []
                if row.get("transfer_ops"):
                    flags.append(f"XFER OPS {row['transfer_ops']}")
                if row.get("host_callbacks"):
                    flags.append(f"CALLBACKS {row['host_callbacks']}")
                if row.get("donation_misses"):
                    flags.append(f"donation misses "
                                 f"{row['donation_misses']}")
                out.append(
                    f"    {sname:<24} {len(progs)} program(s)"
                    + ("   " + "   ".join(flags) if flags
                       else "   device-clean")
                )
            if len(rows) > 8:
                out.append(f"    ... {len(rows) - 8} more stages")
            errs = sec.get("errors") or []
            if errs:
                out.append(f"    capture errors: {len(errs)} "
                           f"(first: {errs[0]})")

        def _termination_panel() -> None:
            term = partial.get("termination")
            if not isinstance(term, dict):
                return
            out.append(f"  partial record: cause={term.get('cause')}"
                       + (f" last_span={term.get('last_span')}"
                          if term.get("last_span") else "")
                       + f" (flushed {_fmt_dur(now - float(term.get('flushed_unix') or now))} ago)")

        _guard("host profile", _hostprof_panel)
        _guard("compile", _compile_panel)
        _guard("memory", _memory_panel)
        _guard("graph passports", _graphs_panel)
        _guard("termination", _termination_panel)
        absent = [k for k in ("host_profile", "compile",
                              "memory_timeline", "graphs")
                  if k not in partial]
        if absent:
            # one-line absence note (satellite, round 24): an older
            # record simply predates these sections — say so instead of
            # rendering nothing or raising
            out.append("  sections absent (record predates them?): "
                       + ", ".join(absent))
    if st["end"]:
        out.append(f"  ended: cause={st['end'].get('cause')} after "
                   f"{st['end'].get('ticks')} ticks, "
                   f"{st['end'].get('stalls')} stall(s)")
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render a flight-recorder heartbeat stream")
    ap.add_argument("stream", help="*_heartbeat.jsonl path")
    ap.add_argument("--follow", action="store_true",
                    help="redraw every --interval seconds until the "
                         "stream ends")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--evidence", default=None,
                    help="ledger dir for per-stage ETA baselines "
                         "(default: SCC_EVIDENCE_DIR or <repo>/evidence)")
    ap.add_argument("--no-eta", action="store_true",
                    help="skip the ledger baseline lookup")
    args = ap.parse_args(argv)

    from scconsensus_tpu.obs.ledger import default_evidence_dir

    evidence = args.evidence or default_evidence_dir(_REPO)
    baselines: Dict[str, Dict[str, float]] = {}
    tunnel: Optional[Dict[str, Any]] = None
    try:
        # best-effort tunnel verdict for the header (satellite: the
        # still-dead TPU evidence channel must be visible on every run)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from tunnel_probe import tunnel_status
        finally:
            sys.path.pop(0)
        tunnel = tunnel_status()
    except Exception:
        tunnel = None
    while True:
        lines = read_stream(args.stream)
        if not args.no_eta and not baselines:
            baselines = _baselines_for(
                _stream_state(lines)["key"], evidence
            )
        panel = render(lines, baselines,
                       partial=_partial_sidecar(args.stream),
                       tunnel=tunnel)
        if args.follow:
            sys.stdout.write("\x1b[2J\x1b[H" + panel + "\n")
            sys.stdout.flush()
            if any(ln.get("t") == "end" for ln in lines):
                return 0
            time.sleep(args.interval)
        else:
            print(panel)
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
