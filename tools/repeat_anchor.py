"""Repeated-run error bars for a cheap bench anchor (VERDICT r5 #6).

Single-run numbers on the single-core host carry unexplained
process-state variance (the r5 tm100k record has 687 s vs 836 s for the
same synced stage in one process); for anchors cheap enough to repeat,
the round-6 policy (BASELINE.md) is median-of-≥3 with the spread on the
record. This runner executes `bench.py` N times sequentially under
SCC_BENCH_PLATFORM=cpu, parses the one-line JSON records, and commits
median + min/max + per-run values (full records included) to
SCALE_r06_cpu_<config>_repeats.json.

Run:  python tools/repeat_anchor.py [config] [n_runs]
      (defaults: cite8k, 3)
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time


def main() -> None:
    config = sys.argv[1] if len(sys.argv) > 1 else "cite8k"
    n_runs = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    base = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    sys.path.insert(0, base)
    from scconsensus_tpu.obs.export import (
        build_run_record,
        check_schema_version,
    )

    import tempfile

    runs = []
    ckpt_dir = tempfile.mkdtemp(prefix="scc-repeat-")
    for i in range(n_runs):
        # per-run checkpoint: the stdout line trims its span tree to fit
        # the driver tail window; the checkpoint keeps the full record
        ckpt = os.path.join(ckpt_dir, f"run{i}.json")
        env = dict(os.environ, SCC_BENCH_CONFIG=config,
                   SCC_BENCH_PLATFORM="cpu", SCC_BENCH_CKPT=ckpt)
        # the worker heartbeats by default (obs.live); name the stream so
        # a second terminal can watch: python tools/tail_run.py <stream>
        print(f"[repeat] run {i} flight record: "
              f"{os.path.splitext(ckpt)[0]}_heartbeat.jsonl", flush=True)
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.join(base, "bench.py")],
            capture_output=True, text=True, env=env,
        )
        wall = time.perf_counter() - t0
        rec = None
        for line in reversed((proc.stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
                break
            except ValueError:
                continue
        if rec is None or proc.returncode != 0:
            raise SystemExit(
                f"run {i}: rc={proc.returncode}, no JSON record\n"
                f"{(proc.stderr or '')[-2000:]}"
            )
        try:  # prefer the untrimmed on-disk record when values agree
            disk = json.load(open(ckpt))
            if disk.get("value") == rec.get("value"):
                rec = disk
        except (OSError, ValueError):
            pass
        # a child emitting a future schema is a hard error, not a silent
        # misread (check_schema_version raises); legacy records pass
        check_schema_version(rec, source=f"bench run {i}")
        print(f"[repeat] run {i}: value={rec.get('value')} "
              f"({wall:.1f}s incl. interpreter)", flush=True)
        runs.append(rec)
    import shutil

    shutil.rmtree(ckpt_dir, ignore_errors=True)
    values = [float(r["value"]) for r in runs]
    med = statistics.median(values)
    med_run = min(runs, key=lambda r: abs(float(r["value"]) - med))
    med_spans = med_run.get("spans", [])
    # ONE span tree on the committed artifact (the median run's, at top
    # level); per-run records keep everything except their span trees —
    # n_runs duplicated trees would bloat the repo-committed JSON
    runs = [{k: v for k, v in r.items() if k != "spans"} for r in runs]
    out = build_run_record(
        metric=f"{config} {runs[0].get('metric', 'bench')} — "
               f"median of {n_runs} sequential runs (BASELINE.md "
               "measurement policy, round 6)",
        value=round(med, 3),
        unit=runs[0].get("unit", "seconds"),
        vs_baseline=runs[0].get("vs_baseline"),
        spans=med_spans,  # the median run's span tree
        # the median BENCH run's device section, not this wrapper's RSS
        device=med_run.get("device"),
        extra={
            "policy": "median-of-n; per-run values and spread committed",
            "config": config,
            "platform": "cpu",
            "n_runs": n_runs,
            "values": [round(v, 3) for v in values],
            "spread_s": round(max(values) - min(values), 3),
            "min": round(min(values), 3),
            "max": round(max(values), 3),
            "stdev": round(statistics.stdev(values), 3) if n_runs > 1 else 0.0,
            "runs": runs,
        },
    )
    # anchors land in the evidence ledger (indexed, baseline-feeding), not
    # as loose root files — perf_gate reads its median-of-3 history here.
    # Auto-named (created_unix in the filename): each anchor run must ADD
    # a history entry, never overwrite the previous one, or the per-key
    # history can never reach the 3 runs the baseline policy medians over.
    from scconsensus_tpu.obs.ledger import Ledger, default_evidence_dir

    entry = Ledger(default_evidence_dir(os.path.abspath(base))).ingest(out)
    print(json.dumps({k: out[k] for k in ("metric", "value", "unit")}
                     | {"spread_s": out["extra"]["spread_s"],
                        "evidence": entry["file"]}), flush=True)


if __name__ == "__main__":
    main()
