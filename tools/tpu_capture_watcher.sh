#!/bin/bash
# TPU capture watcher v3: probe the tunnel; when up, run the bench configs in
# priority order (evidence files /root/repo/BENCH_TPU_<cfg>.json), then one
# phase-profiled flagship run for stage diagnosis, then one residency-audit +
# kernel-capture flagship run ingested into the evidence ledger (ROADMAP
# item-3 standing capture). Loops until all captured.
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO_ROOT"
CFGS="flagship tm100k brain1m pbmc68k cite8k"
LOG=/tmp/tpu_capture.log

# Growth cap (robust round): a watcher looping for days against a dead
# tunnel must not grow its logs without bound — past the cap, keep the
# newest half. tunnel_probe rotates TUNNEL_LOG.jsonl itself.
rotate() {
  f=$1; max=${2:-262144}
  if [ -f "$f" ] && [ "$(wc -c < "$f")" -gt "$max" ]; then
    tail -c $((max / 2)) "$f" > "$f.tmp" && mv "$f.tmp" "$f"
    echo "$(date +%H:%M:%S) rotated $f" >> "$LOG"
  fi
}

captured() {
  python - "$1" "$REPO_ROOT" <<'PY' 2>/dev/null
import json, sys
try:
    d = json.load(open(f"{sys.argv[2]}/BENCH_TPU_{sys.argv[1]}.json"))
except Exception:
    sys.exit(1)
ex = d.get("extra", {})
ok = (float(d.get("value", -1)) > 0 and ex.get("platform") not in (None, "cpu")
      and not ex.get("degraded"))
sys.exit(0 if ok else 1)
PY
}

all_done() {
  for c in $CFGS; do captured "$c" || return 1; done
  [ -f /tmp/tpu_profile_flagship.done ] || return 1
  [ -f /tmp/tpu_residency_flagship.done ] || return 1
  return 0
}

DEADLINE=${SCC_WATCHER_DEADLINE:-0}   # epoch seconds; 0 = no deadline
while true; do
  rotate "$LOG"
  # stale-log sentinel (round 22): if the freshest TUNNEL_LOG heartbeat
  # is older than an hour, say so explicitly — a silent watcher is
  # indistinguishable from a dead tunnel in the evidence, and bench
  # stamps `tunnel: stale` on records from the same verdict
  # (tools/tunnel_probe.py --status).
  status=$(python tools/tunnel_probe.py --status 2>/dev/null | tail -1)
  case "$status" in
    *'"state": "alive"'*) : ;;
    *) echo "$(date +%H:%M:%S) tunnel status: $status" >> $LOG ;;
  esac
  for cfg in $CFGS; do rotate "/tmp/tpu_capture_$cfg.out"; done
  if [ "$DEADLINE" -gt 0 ] && [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$(date +%H:%M:%S) DEADLINE reached, exiting" >> $LOG; exit 0
  fi
  if all_done; then echo "$(date +%H:%M:%S) ALL CAPTURED" >> $LOG; exit 0; fi
  # tunnel_probe appends one structured record PER ATTEMPT to
  # TUNNEL_LOG.jsonl itself (hard per-probe timeout + logged backoff) —
  # the watcher must NOT append its own wrapper record too, or every
  # probe double-counts in summarize_evidence's alive/down tally. The
  # outer timeout is the last-resort kill for a wedged probe PARENT;
  # attempts it already completed are logged.
  probe=$(timeout 240 python tools/tunnel_probe.py 16 2>/dev/null | tail -1)
  # one validation pass: emits "<plat>\t<canonical json>" only for real JSON
  parsed=$(echo "$probe" | python -c "import json,sys
try:
    d = json.loads(sys.stdin.read())
    print((d.get('platform','') if d.get('alive') else '') + '\t' + json.dumps(d))
except Exception:
    pass" 2>/dev/null)
  plat=${parsed%%$'\t'*}
  pjson=${parsed#*$'\t'}
  if [ -z "$pjson" ]; then
    pjson='{"alive": false, "error": "probe parent produced no JSON (killed by outer timeout; per-attempt records are in TUNNEL_LOG.jsonl)"}'
  fi
  echo "$(date +%H:%M:%S) probe plat=$plat $pjson" >> $LOG
  if [ -n "$plat" ] && [ "$plat" != "cpu" ]; then
    for cfg in $CFGS; do
      captured "$cfg" && continue
      echo "$(date +%H:%M:%S) RUN $cfg" >> $LOG
      SCC_BENCH_CONFIG=$cfg \
      SCC_BENCH_CKPT="$REPO_ROOT/BENCH_TPU_$cfg.json" \
      SCC_BENCH_NO_CPU_FALLBACK=1 \
      timeout 4000 python bench.py >> /tmp/tpu_capture_$cfg.out 2>&1
      echo "$(date +%H:%M:%S) DONE $cfg rc=$?" >> $LOG
      captured "$cfg" || break
    done
    if captured flagship && [ ! -f /tmp/tpu_profile_flagship.done ]; then
      echo "$(date +%H:%M:%S) RUN profile" >> $LOG
      SCC_BENCH_CONFIG=flagship SCC_BENCH_NO_FORK=1 SCC_EDGER_PROFILE=1 \
      SCC_STAGE_SYNC=1 SCC_BENCH_CKPT=/tmp/bench_profile_ckpt.json \
      timeout 4000 python bench.py > /tmp/tpu_profile_flagship.out 2>&1 \
        && touch /tmp/tpu_profile_flagship.done
      echo "$(date +%H:%M:%S) DONE profile rc=$?" >> $LOG
    fi
    # standing residency + kernel-timeline capture (ROADMAP item-3): one
    # flagship run on the first healthy probe with the transfer audit on
    # and a jax.profiler window around the pipeline; SCC_BENCH_NO_FORK
    # ingests the record (residency + kernels sections) straight into the
    # evidence ledger, which stamps per-stage transfer bytes for the gate.
    if captured flagship && [ ! -f /tmp/tpu_residency_flagship.done ]; then
      echo "$(date +%H:%M:%S) RUN residency+kernels" >> $LOG
      SCC_BENCH_CONFIG=flagship SCC_BENCH_NO_FORK=1 \
      SCC_OBS_RESIDENCY=audit SCC_OBS_KERNELS=/tmp/tpu_kernel_capture \
      SCC_BENCH_CKPT=/tmp/bench_residency_ckpt.json \
      timeout 4000 python bench.py > /tmp/tpu_residency_flagship.out 2>&1 \
        && touch /tmp/tpu_residency_flagship.done
      echo "$(date +%H:%M:%S) DONE residency+kernels rc=$?" >> $LOG
    fi
  fi
  sleep 180
done
