#!/usr/bin/env python
"""Generate the committed graph-passport demo evidence pair.

Two schema-valid run records over the same tiny jitted stage program,
differing in exactly one injected host crossing, so the round-24
acceptance demo is reproducible on committed evidence:

* ``clean`` — ``demo.tile`` is a pure device program (matmul + sin);
* ``leaky`` — the same program with a ``jax.pure_callback`` host hop
  spliced into the middle — the compiled HLO gains a
  ``custom-call(xla_python_cpu_callback)`` whose recorded source
  location is THIS file's ``_leaky_tile`` body.

``tools/graph_diff.py <leaky> <clean>`` must name the injected callback
with its source line and exit nonzero; that is the tentpole acceptance
check, asserted by tests/test_obs_graphs.py against the ledger-ingested
copies of these records.

Unlike the synthetic hostprof demo trio, the passports here are REAL:
captured by obs.graphs from actually-lowered-and-compiled programs on
the generating toolchain, through the same ``instrument`` →
``snapshot`` → ``build_run_record`` → ``Ledger.ingest`` path as live
bench output. Both records therefore share one environment
fingerprint — the pair stays diffable — and regenerating on a
different toolchain refreshes both sides together.

Usage:  python tools/make_graphs_demo.py [--evidence DIR]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Dict, List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs import graphs  # noqa: E402
from scconsensus_tpu.obs.export import build_run_record  # noqa: E402
from scconsensus_tpu.obs.trace import Tracer  # noqa: E402

# fixed identity: distinct created stamps make distinct ledger filenames
# under one shared run key (dataset=graphsdemo backend=cpu)
CREATED = {"clean": 1786100001, "leaky": 1786100002}

_SHAPE = (64, 32)


def _clean_tile(x):
    return (x @ x.T) + 1.0


def _double_on_host(a):
    import numpy as np

    return np.asarray(a) * 2.0


def _leaky_tile(x):
    import jax

    y = x @ x.T
    # the injected host crossing: graph_diff must name this line
    y = jax.pure_callback(
        _double_on_host, jax.ShapeDtypeStruct(y.shape, y.dtype), y
    )
    return y + 1.0


def _record(kind: str) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    fn = _leaky_tile if kind == "leaky" else _clean_tile
    graphs.install_and_mark(force=True)
    tile = graphs.instrument("demo.tile", jax.jit(fn))
    tr = Tracer(sync="off")
    x = jnp.ones(_SHAPE, jnp.float32)
    with tr.span("demo_tile"):
        tile(x).block_until_ready()
    sec = graphs.snapshot()
    graphs.reset()
    rec = build_run_record(
        metric="graph-passport demo tile wall (round 24)",
        value=0.001,
        unit="seconds",
        extra={"config": "graphsdemo", "platform": "cpu",
               "demo_kind": kind, "synthetic": True},
        spans=tr.span_records(),
        graphs=sec,
    )
    rec["run"]["created_unix"] = CREATED[kind]  # deterministic identity
    return rec


def build_demo_records() -> Dict[str, Dict[str, Any]]:
    """kind → record, the importable surface tests pin against."""
    return {kind: _record(kind) for kind in CREATED}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="generate + ingest the graph-passport demo pair")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    args = ap.parse_args(argv)

    from scconsensus_tpu.obs.ledger import Ledger, default_evidence_dir

    led = Ledger(args.evidence or default_evidence_dir(_REPO))
    for kind, rec in build_demo_records().items():
        entry = led.ingest(rec, source="graphs-demo")
        print(f"{kind:>6}: {entry['file']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
