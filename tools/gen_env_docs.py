#!/usr/bin/env python
"""Generate the SCC_* env-flag reference table in README.md from the
registry (config.ENV_FLAGS).

Three flags landed in round 9 without README updates — docs drifting from
the registry is exactly the failure the registry exists to prevent, so
the README table is now GENERATED: this tool rewrites the block between
the markers below from ``config.ENV_FLAGS``, and a tier-1 lint test runs
``--check`` so a new flag cannot ship without its doc row.

Usage:
  python tools/gen_env_docs.py            # rewrite README.md in place
  python tools/gen_env_docs.py --check    # exit 1 if README is stale
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.config import ENV_FLAGS  # noqa: E402

README = os.path.join(_REPO, "README.md")
BEGIN_MARK = ("<!-- BEGIN scc-env-flags "
              "(generated: python tools/gen_env_docs.py; do not edit) -->")
END_MARK = "<!-- END scc-env-flags -->"


def _md(text: str) -> str:
    """Escape a doc string for a Markdown table cell."""
    return str(text).replace("|", "\\|").replace("\n", " ")


def render_table() -> str:
    """The generated block, markers included."""
    lines: List[str] = [
        BEGIN_MARK,
        "| flag | type | default | effect |",
        "|---|---|---|---|",
    ]
    for name, spec in ENV_FLAGS.items():  # registry order is the doc order
        default = "unset" if spec.default is None else repr(spec.default)
        lines.append(
            f"| `{name}` | {spec.type.__name__} | `{default}` "
            f"| {_md(spec.doc)} |"
        )
    lines.append(END_MARK)
    return "\n".join(lines)


def update_readme(path: str = README, check: bool = False) -> bool:
    """Rewrite (or with ``check``, verify) the generated block. Returns
    True when the file already matched. Raises SystemExit if the markers
    are missing — the block must exist for the generator to own it."""
    with open(path) as f:
        text = f.read()
    try:
        head, rest = text.split(BEGIN_MARK, 1)
        _, tail = rest.split(END_MARK, 1)
    except ValueError:
        raise SystemExit(
            f"{path}: generated-block markers missing "
            f"({BEGIN_MARK!r} … {END_MARK!r})"
        )
    new = head + render_table() + tail
    if new == text:
        return True
    if not check:
        with open(path, "w") as f:
            f.write(new)
    return False


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="README SCC_* flag-table generator")
    ap.add_argument("--check", action="store_true",
                    help="verify only; exit 1 when README is stale")
    ap.add_argument("--readme", default=README, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    fresh = update_readme(args.readme, check=args.check)
    if args.check:
        if not fresh:
            print(f"{args.readme}: SCC_* flag table is STALE — run "
                  "`python tools/gen_env_docs.py`", file=sys.stderr)
            return 1
        print("README flag table matches config.ENV_FLAGS")
        return 0
    print(f"{args.readme}: flag table "
          + ("already current" if fresh else "rewritten"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
