#!/usr/bin/env python
"""Regression gate over the evidence ledger (plus the one-shot upgrader).

Modes:

  perf_gate.py CANDIDATE.json [--evidence DIR] [--json]
      Gate one run record against its key's baselines (median-of-3 with a
      noise band, BASELINE.md policy). Exit 0 = within band, 1 = regressed
      stage wall, regressed per-stage transfer bytes (residency-audited
      candidates vs the key's ledger-stamped transfer baselines — same
      banding machinery), or unacknowledged numeric drift; 2 = usage/IO
      error. A wall regression names the offending child span (span-tree
      diff vs the baseline run) and, when XLA cost attribution ran on
      both sides, the efficiency loss. Every FAIL additionally prints a
      ``top suspect`` line — the obs.attr differential attribution of
      the candidate against its key's freshest clean baseline record
      (stage, wall delta, and the driving signal: transfer bytes at a
      declared boundary, device time, FLOPs, or host-side). Run
      ``tools/perf_diff.py`` on the same pair for the full ranked
      report. When ``NUMERIC_PINS.json`` carries a ``graph_ratchet``
      entry for the candidate's dataset, the graph lane additionally
      gates the candidate's static per-stage transfer-op/host-callback
      counts (from its ``graphs`` section) and its TODO(item-2)
      residency-boundary call counts against the pinned ceilings — no
      noise band, counts may only decrease; a FAIL names the op kind
      and source line, and a candidate from a different environment
      fingerprint is reported, not gated (see tools/graph_diff.py).

  perf_gate.py --smoke
      Self-test against the committed fixture ledger
      (tests/fixtures/perf_gate): asserts the clean candidate PASSES, the
      regressed candidate FAILS naming its offender, and the drift
      sentinel flags an unacknowledged shift / accepts an acknowledged
      one. Exit 0 iff every expectation held — wired into tier-1.

  perf_gate.py --upgrade [--root DIR] [--keep-root]
      One-shot legacy lift: relocate root BENCH_*/SCALE_*/PROFILE_*/
      MESH_*/MULTICHIP_* artifacts into <root>/evidence as schema-v1
      records indexed by MANIFEST.json (lossless; see obs.ledger).

Drift workflow: a run record may carry ``extra["numeric_fingerprint"]``
(obs.regress.drift_fingerprint). When ``NUMERIC_PINS.json`` pins the
candidate's dataset, the gate compares against those pins; otherwise it
falls back to the key's PREVIOUS clean run (every ingested run is
fingerprint-stamped on the manifest entry — obs.ledger), so quality
drift gates on any dataset. Either way a shift fails unless it has a
matching acknowledgement in ``DRIFT_LEDGER.jsonl`` — acknowledge with
``obs.regress.append_drift_ack`` (and update the pin), never with prose.

Candidates are additionally validated against the full run-record schema
(quality section included): a record with a non-monotone DE funnel or
malformed sentinel trips is a usage error, not a gate verdict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs import regress  # noqa: E402
from scconsensus_tpu.obs.export import check_schema_version  # noqa: E402
from scconsensus_tpu.obs.ledger import (  # noqa: E402
    Ledger,
    default_evidence_dir,
    run_key,
    upgrade_tree,
)

PINS_NAME = regress.PINS_NAME  # one canonical filename (obs.regress)
FIXTURES = os.path.join(_REPO, "tests", "fixtures", "perf_gate")


def _load_json(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _baseline_context(ledger: Ledger, history: List[Dict[str, Any]]
                      ) -> Tuple[Optional[List[Dict]], Optional[Dict]]:
    """Span tree + stage-cost table of the freshest baseline run that
    recorded spans — the tree the offender diff runs against. Partial
    (flight-recorder) entries are skipped: their trees hold truncated
    open-span snapshots, not measurements."""
    from scconsensus_tpu.obs.ledger import is_partial_entry

    for entry in reversed(history):
        if is_partial_entry(entry):
            continue
        try:
            rec = ledger.load(entry["file"])
        except (OSError, ValueError, KeyError):
            continue
        spans = rec.get("spans")
        if spans:
            return spans, entry.get("stage_cost")
    return None, None


def run_gate(candidate_path: str, evidence_dir: str
             ) -> Tuple[regress.GateVerdict, List[Dict[str, Any]]]:
    """(perf verdict, drift records) for one candidate file."""
    from scconsensus_tpu.obs.export import validate_run_record

    candidate = _load_json(candidate_path)
    if check_schema_version(candidate, source=candidate_path) == "legacy":
        raise ValueError(
            f"{candidate_path}: pre-schema record — upgrade it first "
            "(perf_gate.py --upgrade)"
        )
    # full structural validation, quality section included: a candidate
    # with a non-monotone funnel or malformed sentinel trips must be
    # rejected here, not rendered as if the quality fields meant something
    validate_run_record(candidate)
    ledger = Ledger(evidence_dir)
    history = ledger.history(
        run_key(candidate),
        exclude_files=[os.path.basename(candidate_path)],
    )
    base_spans, base_cost = _baseline_context(ledger, history)
    verdict = regress.gate_record(candidate, history,
                                  baseline_spans=base_spans,
                                  baseline_cost=base_cost)
    # transfer-op ratchet (round 24): the candidate's static per-stage
    # transfer/callback counts and TODO(item-2) boundary calls may only
    # decrease relative to the pinned starting debt (NUMERIC_PINS.json
    # "graph_ratchet", keyed by dataset + environment fingerprint)
    try:
        pins_doc = _load_json(os.path.join(evidence_dir, PINS_NAME))
    except (OSError, json.JSONDecodeError):
        pins_doc = {}
    ratchet = (pins_doc.get("graph_ratchet") or {}).get(
        run_key(candidate)["dataset"]
    )
    gverdicts, gnote = regress.graphs_verdicts(candidate, ratchet)
    if gverdicts:
        verdict.graphs = gverdicts
        if verdict.graphs_regressions:
            verdict.ok = False
    if gnote:
        verdict.note = f"{verdict.note}; {gnote}" if verdict.note else gnote
    drifts: List[Dict[str, Any]] = []
    fp = (candidate.get("extra") or {}).get("numeric_fingerprint")
    if fp:
        # pins are keyed by dataset: the reference-workload pins must never
        # be compared against a cite8k/tm100k fingerprint (every real run
        # would read as bogus drift). A dataset with no pin entry falls
        # back to its key's previous clean run (every ingested run is
        # fingerprint-stamped on the manifest — obs.ledger), so quality
        # drift gates on ANY dataset; with no history either, the
        # candidate seeds. Resolution shared with explain_run
        # (regress.resolve_pins), so gate and report cannot disagree.
        pins, source = regress.resolve_pins(
            evidence_dir, run_key(candidate)["dataset"], history
        )
        if pins:
            acks = regress.load_drift_acks(
                os.path.join(evidence_dir, regress.DRIFT_LEDGER_NAME)
            )
            drifts = regress.check_drift(fp, pins, acks)
            for d in drifts:
                d["pins_source"] = source
    return verdict, drifts


def attribution_for(candidate_path: str, evidence_dir: str
                    ) -> Optional[Dict[str, Any]]:
    """Differential attribution (obs.attr) of a candidate against its
    key's freshest clean baseline RECORD — the root-cause annex a FAIL
    prints. Loads the full baseline file (not just the manifest entry)
    because the diff joins spans + residency + cost; returns None when
    the key has no usable baseline record. Never raises: attribution is
    an annex, and an annex failure must not change a verdict."""
    try:
        from scconsensus_tpu.obs.attr import diff_records, top_suspect
        from scconsensus_tpu.obs.ledger import is_partial_entry

        candidate = _load_json(candidate_path)
        ledger = Ledger(evidence_dir)
        history = ledger.history(
            run_key(candidate),
            exclude_files=[os.path.basename(candidate_path)],
        )
        for entry in reversed(history):
            if is_partial_entry(entry):
                continue
            try:
                rec = ledger.load(entry["file"])
            except (OSError, ValueError, KeyError):
                continue
            if not rec.get("spans"):
                continue
            diff = diff_records(
                candidate, rec,
                candidate_label=os.path.basename(candidate_path),
                baseline_label=entry["file"],
            )
            return {
                "baseline_file": entry["file"],
                "top_suspect": top_suspect(diff),
                "causes": (diff.get("causes") or [])[:5],
            }
    except Exception:
        pass
    return None


def _report(verdict: regress.GateVerdict, drifts: List[Dict[str, Any]],
            as_json: bool,
            attribution: Optional[Dict[str, Any]] = None) -> int:
    unacked = [d for d in drifts if not d["acknowledged"]]
    ok = verdict.ok and not unacked
    out = verdict.to_dict()
    out["drift"] = drifts
    out["ok"] = ok
    if attribution is not None:
        out["attribution"] = attribution
    if as_json:
        print(json.dumps(out, indent=1))
    else:
        k = verdict.key
        print(f"key: dataset={k['dataset']} backend={k['backend']} "
              f"config_fp={k['config_fp']}  history={verdict.n_history}")
        if verdict.n_partial_excluded:
            print(f"partial records in history: "
                  f"{verdict.n_partial_excluded} (reported, never "
                  "baselined)")
        if verdict.candidate_termination:
            print("candidate: PARTIAL record "
                  f"(termination.cause={verdict.candidate_termination})")
        if verdict.note:
            print(f"note: {verdict.note}")
        for sv in verdict.stages:
            mark = "REGRESSED" if sv.regressed else "ok"
            line = (f"  stage {sv.stage:<20} {sv.wall_s:>9.3f}s  "
                    f"baseline {sv.baseline_s:.3f}s ± {sv.band_s:.3f}s  "
                    f"{mark}")
            if sv.regressed and sv.offender:
                line += (f"  <- {sv.offender['span']} "
                         f"(+{sv.offender['delta_s']:.3f}s)")
            if sv.regressed and sv.efficiency:
                line += (f"  efficiency loss "
                         f"{sv.efficiency['efficiency_loss']:.1%}")
            print(line)
        for tv in verdict.transfers:
            mark = "REGRESSED" if tv.regressed else "ok"
            line = (f"  xfer  {tv.stage:<20} {tv.bytes:>12,}B  "
                    f"baseline {tv.baseline_bytes:,}B "
                    f"± {tv.band_bytes:,}B  {mark}")
            if tv.regressed:
                line += f"  (+{tv.excess_bytes:,}B past band)"
            print(line)
        for sv in verdict.serving:
            gated = sv.metric.startswith(("p99_ms", "throughput_rps"))
            mark = "REGRESSED" if sv.regressed else (
                "ok" if gated else "info")
            u = getattr(sv, "unit", "ms")
            line = (f"  serve {sv.metric:<20} {sv.value_ms:>9.3f}{u} "
                    f"baseline {sv.baseline_ms:.3f}{u} "
                    f"± {sv.band_ms:.3f}{u}  {mark}")
            if sv.regressed:
                sign = "-" if u == "rps" else "+"
                line += f"  ({sign}{sv.excess_ms:.3f}{u} past band)"
            print(line)
        for sv in verdict.streaming:
            mark = "REGRESSED" if sv.regressed else "ok"
            line = (f"  mem   {sv.metric:<20} {sv.value_mb:>9.1f}MB "
                    f"baseline {sv.baseline_mb:.1f}MB "
                    f"± {sv.band_mb:.1f}MB  {mark}")
            if sv.regressed:
                line += f"  (+{sv.excess_mb:.1f}MB past band)"
            print(line)
        for sv in verdict.slo:
            mark = "BREACHED" if sv.regressed else "ok"
            unit = "x" if sv.metric == "worst_burn" else "ms"
            line = (f"  slo   {sv.metric:<20} {sv.value:>9.3f}{unit} "
                    f"limit {sv.limit:.3f}{unit}  {mark}")
            if sv.regressed and sv.detail:
                line += f"  <- {sv.detail}"
            print(line)
        for lv in verdict.loadgen:
            mark = "REGRESSED" if lv.regressed else "ok"
            if lv.metric == "slo_breaches":
                line = (f"  load  {lv.metric:<20} {lv.value:>9.0f}   "
                        f"(zero-breach contract)  {mark}")
                if lv.regressed and lv.detail:
                    line += f"  <- {lv.detail}"
            else:
                line = (f"  load  {lv.metric:<20} {lv.value:>9.3f}rps "
                        f"baseline {lv.baseline:.3f}rps "
                        f"± {lv.band:.3f}rps  {mark}")
                if lv.regressed:
                    line += f"  (-{lv.excess:.3f}rps below floor)"
            print(line)
        for gv in verdict.graphs:
            mark = "REGRESSED" if gv.regressed else "ok"
            line = (f"  graph {gv.metric:<32} {gv.value:>4d}  "
                    f"pinned <= {gv.pinned}  {mark}")
            if gv.regressed and gv.detail:
                line += f"  <- {gv.detail}"
            print(line)
        for d in drifts:
            state = "acknowledged" if d["acknowledged"] else "UNACKNOWLEDGED"
            src = d.get("pins_source")
            print(f"  drift {d['field']}: pinned={d['pinned']} "
                  f"current={d['current']}  {state}"
                  + (f"  [vs {src}]" if src else ""))
        if not ok and attribution is not None:
            suspect = attribution.get("top_suspect")
            if suspect is not None:
                print(f"top suspect: {suspect['summary']}  "
                      f"(vs {attribution['baseline_file']})")
            else:
                print("top suspect: none past noise — the FAIL came "
                      "from a non-wall gate (see verdict lines above)")
        print("PASS" if ok else "FAIL")
    return 0 if ok else 1


def _smoke(fixtures: str, as_json: bool) -> int:
    """Fixture self-test: every expectation below must hold."""
    evidence = os.path.join(fixtures, "evidence")
    checks: List[Tuple[str, bool]] = []

    verdict, drifts = run_gate(
        os.path.join(fixtures, "candidate_clean.json"), evidence
    )
    checks.append(("clean candidate passes",
                   verdict.ok and not [d for d in drifts
                                       if not d["acknowledged"]]))
    # quality schema: the clean candidate carries funnel + cluster
    # structure and passed run_gate's full validation above
    clean = _load_json(os.path.join(fixtures, "candidate_clean.json"))
    q = clean.get("quality") or {}
    checks.append((
        "clean candidate carries schema-valid quality fields "
        "(funnel + cluster structure)",
        bool((q.get("de_funnel") or {}).get("total"))
        and bool((q.get("cluster_structure") or {}).get("cuts")),
    ))

    verdict_r, drifts_r = run_gate(
        os.path.join(fixtures, "candidate_regressed.json"), evidence
    )
    reg = verdict_r.regressions
    checks.append(("regressed candidate fails", not verdict_r.ok))
    checks.append((
        "offending child span named",
        any(s.offender and s.offender.get("span") for s in reg),
    ))
    checks.append((
        "regressed fingerprint drift flagged unacknowledged",
        any(not d["acknowledged"] for d in drifts_r),
    ))

    # a malformed quality section (non-monotone funnel) must be REJECTED
    # by validation, never gated as if the counts meant something
    try:
        run_gate(os.path.join(fixtures, "candidate_bad_quality.json"),
                 evidence)
        bad_rejected = False
    except ValueError as e:
        bad_rejected = "funnel" in str(e)
    checks.append(("non-monotone quality funnel rejected", bad_rejected))

    # transfer-bytes gate (obs.residency): the clean candidate's audited
    # stage bytes sit within the key's transfer baselines; a candidate
    # whose walls are fine but whose wilcox stage moved far more data
    # must FAIL on the transfer verdict alone
    checks.append((
        "clean candidate's transfer bytes gated within band",
        bool(verdict.transfers)
        and not any(t.regressed for t in verdict.transfers),
    ))
    verdict_t, _ = run_gate(
        os.path.join(fixtures, "candidate_transfer_regressed.json"),
        evidence,
    )
    treg = verdict_t.transfer_regressions
    checks.append((
        "transfer-regressed candidate fails naming the stage",
        (not verdict_t.ok) and any(t.stage == "wilcox_test" for t in treg)
        and not any(s.regressed for s in verdict_t.stages),
    ))

    # landmark recluster gate (r7): a landmark run whose tree wall blows
    # past the key's baseline must FAIL on the tree stage alone, with the
    # offending landmark child span named
    verdict_l, drifts_l = run_gate(
        os.path.join(fixtures, "candidate_landmark_tree_regressed.json"),
        evidence,
    )
    lreg = [s for s in verdict_l.regressions if s.stage == "tree"]
    checks.append((
        "landmark candidate with regressed tree wall fails on tree",
        (not verdict_l.ok) and bool(lreg)
        and not any(s.regressed for s in verdict_l.stages
                    if s.stage != "tree"),
    ))
    checks.append((
        "tree regression names the landmark child span",
        bool(lreg) and bool(lreg[0].offender)
        and "landmark" in str(lreg[0].offender.get("span", "")),
    ))

    # a landmark record that skips the ARI-vs-input accuracy evidence is
    # a SCHEMA violation (the approximation must carry its own pin), not
    # a gateable run
    try:
        run_gate(
            os.path.join(fixtures, "candidate_landmark_missing_ari.json"),
            evidence,
        )
        lm_rejected = False
    except ValueError as e:
        lm_rejected = "ari_vs_input" in str(e)
    checks.append((
        "landmark record missing ari_vs_input rejected by validation",
        lm_rejected,
    ))

    # robustness schema (robust round): a recovered-run record with a
    # populated robustness section (faults, retries, a resume point)
    # validates and gates normally on its walls...
    verdict_rb, drifts_rb = run_gate(
        os.path.join(fixtures, "candidate_recovered.json"), evidence
    )
    rb = _load_json(
        os.path.join(fixtures, "candidate_recovered.json")
    ).get("robustness") or {}
    checks.append((
        "recovered-run candidate validates and passes with a populated "
        "robustness section",
        verdict_rb.ok and bool(rb.get("resume_points"))
        and bool(rb.get("recovered")),
    ))
    # ...while a record CLAIMING recovery with no retry/resume evidence
    # is REJECTED by validation — survival must be demonstrated, not
    # asserted
    try:
        run_gate(os.path.join(fixtures, "candidate_bad_robustness.json"),
                 evidence)
        rb_rejected = False
    except ValueError as e:
        rb_rejected = "recovered" in str(e) and "resume" in str(e)
    checks.append((
        "recovery claim without resume/retry evidence rejected",
        rb_rejected,
    ))

    # elastic mesh schema (elastic round): a record whose run shrank its
    # mesh (in-process device loss + a shape-polymorphic checkpoint
    # resume, both stamped as mesh_transitions) validates and gates
    # normally on its walls...
    verdict_el, _ = run_gate(
        os.path.join(fixtures, "candidate_elastic_recovered.json"),
        evidence,
    )
    el_rb = _load_json(
        os.path.join(fixtures, "candidate_elastic_recovered.json")
    ).get("robustness") or {}
    el_tr = el_rb.get("mesh_transitions") or []
    checks.append((
        "elastic-recovered candidate validates and passes with "
        "mesh_transitions evidence",
        verdict_el.ok and len(el_tr) >= 2
        and any(t.get("cause") == "device_loss" for t in el_tr)
        and any(t.get("cause") == "resume" for t in el_tr),
    ))
    # ...while a transition whose device set GROWS is REJECTED — elastic
    # recovery only ever moves onto survivors
    try:
        run_gate(
            os.path.join(fixtures, "candidate_bad_mesh_transition.json"),
            evidence,
        )
        el_rejected = False
    except ValueError as e:
        el_rejected = "shrink" in str(e)
    checks.append((
        "mesh transition with a non-shrinking device set rejected",
        el_rejected,
    ))

    # streaming schema (round 17): an out-of-core record with a populated
    # streaming section (chunk counters summing, resume evidence, peak
    # RSS under its budget) validates and gates normally...
    verdict_sm, _ = run_gate(
        os.path.join(fixtures, "candidate_stream_recovered.json"),
        evidence,
    )
    sm_rec = _load_json(
        os.path.join(fixtures, "candidate_stream_recovered.json")
    )
    sm = sm_rec.get("streaming") or {}
    checks.append((
        "stream-recovered candidate validates and passes with chunk "
        "resume + budget evidence",
        verdict_sm.ok and (sm.get("chunks") or {}).get("resumed", 0) >= 1
        and (sm.get("budget") or {}).get("within_budget") is True,
    ))
    # ...while a section CLAIMING bounded memory with its peak RSS over
    # the budget is REJECTED naming the rule — the claim must not
    # contradict its own evidence
    try:
        run_gate(os.path.join(fixtures, "candidate_bad_streaming.json"),
                 evidence)
        sm_rejected = False
    except ValueError as e:
        sm_rejected = "over budget" in str(e)
    checks.append((
        "within_budget claim with peak RSS over budget rejected",
        sm_rejected,
    ))
    # ...and chunk counters that do not sum are equally a schema
    # violation, not a gateable record (a lost chunk is a lost shard of
    # the answer); scratch file to a temp dir like the serve twin below
    import copy as _copy0
    import tempfile as _tempfile0

    bad_sum = _copy0.deepcopy(sm_rec)
    bad_sum["streaming"]["chunks"]["resumed"] += 1  # one chunk vanishes
    with _tempfile0.TemporaryDirectory(prefix="scc-gate-smoke-") as tmp0:
        bad_path = os.path.join(tmp0, "candidate_stream_bad_sum.json")
        with open(bad_path, "w") as f:
            json.dump(bad_sum, f)
        try:
            run_gate(bad_path, evidence)
            sum_rejected = False
        except ValueError as e:
            sum_rejected = "chunk counts do not sum" in str(e)
    checks.append((
        "streaming chunk counts that do not sum rejected naming the "
        "rule",
        sum_rejected,
    ))

    # integrity schema (round 18): a record whose run DETECTED silent
    # corruption (an invariant violation + a ghost-replay mismatch) and
    # recovered it via typed silent_corruption recomputes validates and
    # gates normally on its walls...
    verdict_ig, _ = run_gate(
        os.path.join(fixtures, "candidate_integrity_recovered.json"),
        evidence,
    )
    ig_rec = _load_json(
        os.path.join(fixtures, "candidate_integrity_recovered.json")
    )
    ig = ig_rec.get("integrity") or {}
    ig_rb = ig_rec.get("robustness") or {}
    checks.append((
        "integrity-recovered candidate validates and passes with "
        "mismatch + recompute evidence",
        verdict_ig.ok
        and len((ig.get("ghost") or {}).get("mismatches") or []) >= 1
        and (ig.get("ghost") or {}).get("recomputes", 0) >= 1
        and any(r.get("error_class") == "silent_corruption"
                and r.get("recovered")
                for r in ig_rb.get("retries") or []),
    ))
    # ...while a section CLAIMING all_checks_passed with checks_run <
    # checks_planned is REJECTED naming the rule — a check that never
    # ran proves nothing, and claiming otherwise is the exact failure
    # the integrity layer exists to catch
    import copy as _copy_ig
    import tempfile as _tempfile_ig

    bad_ig = _copy_ig.deepcopy(ig_rec)
    bad_ig["integrity"] = {
        "mode": "audit",
        "checks": {"planned": 9, "run": 7, "passed": 7},
        "per_check": {},
        "violations": [],
        "ghost": {"planned": 0, "run": 0, "passed": 0,
                  "mismatches": [], "recomputes": 0},
        "all_checks_passed": True,
        "consumed_s": 0.01,
    }
    with _tempfile_ig.TemporaryDirectory(prefix="scc-gate-smoke-") as tig:
        bad_path = os.path.join(tig, "candidate_integrity_bad.json")
        with open(bad_path, "w") as f:
            json.dump(bad_ig, f)
        try:
            run_gate(bad_path, evidence)
            ig_rejected = False
        except ValueError as e:
            ig_rejected = "checks_run < checks_planned" in str(e)
    checks.append((
        "all_checks_passed claim with checks_run < checks_planned "
        "rejected naming the rule",
        ig_rejected,
    ))

    # serving-latency gate (round 15, BASELINE.md serving-latency
    # policy): the clean candidate's serving p99 sits inside the key's
    # latency band...
    checks.append((
        "clean candidate's serving latency gated within band",
        bool(verdict.serving)
        and not any(s.regressed for s in verdict.serving),
    ))
    # ...while a candidate with CLEAN stage walls but a 3× p99 must fail
    # on the serving verdict ALONE — tail latency is a first-class
    # regression even when every batch-stage wall is green
    verdict_sv, _ = run_gate(
        os.path.join(fixtures, "candidate_serve_latency_regressed.json"),
        evidence,
    )
    sreg = verdict_sv.serving_regressions
    checks.append((
        "serve-latency-regressed candidate fails on the serving verdict "
        "alone (clean walls, clean transfers)",
        (not verdict_sv.ok)
        and any(s.metric == "p99_ms" for s in sreg)
        and not any(s.regressed for s in verdict_sv.stages)
        and not any(t.regressed for t in verdict_sv.transfers),
    ))
    # fleet gate (round 16, replica-count-keyed baselines): a fleet
    # candidate whose single-replica p99 is CLEAN but whose aggregate
    # throughput collapsed must fail on the fleet throughput verdict
    # alone — tail latency and fleet throughput are independent
    # regressions
    verdict_fl, _ = run_gate(
        os.path.join(fixtures, "candidate_fleet_regressed.json"),
        evidence,
    )
    flreg = verdict_fl.serving_regressions
    checks.append((
        "fleet candidate with regressed throughput and clean p99 fails "
        "on the replica-keyed fleet verdict alone",
        (not verdict_fl.ok)
        and any(s.metric.startswith("throughput_rps@r") for s in flreg)
        and not any(s.metric.startswith("p99") for s in flreg)
        and not any(s.regressed for s in verdict_fl.stages)
        and not any(t.regressed for t in verdict_fl.transfers),
    ))
    # ...and its serving section carries validated wire + fleet
    # accounting (wire submitted == Σ outcomes == Σ status codes; the
    # submitted-by-owner split sums) — run_gate's validation enforced it
    fl = _load_json(
        os.path.join(fixtures, "candidate_fleet_regressed.json")
    ).get("serving") or {}
    checks.append((
        "fleet candidate carries wire + fleet accounting",
        bool((fl.get("wire") or {}).get("status_codes"))
        and bool((fl.get("fleet") or {}).get("submitted_by_owner")),
    ))

    # workload-zoo scenario records (round 19): a multi_sample candidate
    # with a validated top-level `scenario` section and a full
    # quality.scenario block (per-batch ARI + batch-mixing) validates
    # and gates normally — its key has no fixture history, so it SEEDS
    # (a first run cannot regress)...
    verdict_sc, _ = run_gate(
        os.path.join(fixtures, "candidate_scenario_clean.json"),
        evidence,
    )
    sc_rec = _load_json(
        os.path.join(fixtures, "candidate_scenario_clean.json")
    )
    sc_q = ((sc_rec.get("quality") or {}).get("scenario")) or {}
    checks.append((
        "multi_sample scenario candidate validates and seeds its key "
        "(scenario section + per-batch ARI + batch-mixing present)",
        verdict_sc.ok
        and (sc_rec.get("scenario") or {}).get("name") == "multi_sample"
        and bool(sc_q.get("per_batch_ari"))
        and bool(sc_q.get("batch_mixing")),
    ))
    # ...the atlas_transfer candidate additionally carries the serve
    # driver's validated serving section — the first serve-latency
    # evidence on a non-anchor key...
    verdict_at, _ = run_gate(
        os.path.join(fixtures, "candidate_scenario_atlas.json"),
        evidence,
    )
    at_rec = _load_json(
        os.path.join(fixtures, "candidate_scenario_atlas.json")
    )
    checks.append((
        "atlas_transfer scenario candidate validates with a serving "
        "section (p99 + accounting) on a non-anchor key",
        verdict_at.ok
        and (at_rec.get("scenario") or {}).get("name") == "atlas_transfer"
        and ((at_rec.get("serving") or {}).get("latency_ms") or {}
             ).get("p99") is not None,
    ))
    # ...while a scenario block carrying per-batch ARI WITHOUT mixing
    # evidence is REJECTED naming the rule — half an integration claim
    # must not gate as if it were whole...
    try:
        run_gate(os.path.join(fixtures, "candidate_scenario_bad.json"),
                 evidence)
        sc_rejected = False
    except ValueError as e:
        sc_rejected = "per_batch_ari and batch_mixing" in str(e)
    checks.append((
        "scenario block with per-batch ARI but no batch-mixing "
        "evidence rejected naming the rule",
        sc_rejected,
    ))
    # ...and a record claiming an UNREGISTERED scenario is equally a
    # schema violation (a scenario key outside the zoo has no baseline
    # semantics); scratch file to a temp dir like the twins above
    import copy as _copy_sc
    import tempfile as _tempfile_sc

    bad_sc = _copy_sc.deepcopy(sc_rec)
    bad_sc["scenario"]["name"] = "no_such_scenario"
    bad_sc["quality"]["scenario"]["name"] = "no_such_scenario"
    with _tempfile_sc.TemporaryDirectory(prefix="scc-gate-smoke-") as tsc:
        bad_path = os.path.join(tsc, "candidate_scenario_unknown.json")
        with open(bad_path, "w") as f:
            json.dump(bad_sc, f)
        try:
            run_gate(bad_path, evidence)
            sc_unknown_rejected = False
        except ValueError as e:
            sc_unknown_rejected = "unknown scenario" in str(e)
    checks.append((
        "record claiming an unregistered scenario rejected",
        sc_unknown_rejected,
    ))

    # SLO lane (round 20): a candidate whose slo section is internally
    # consistent AND inside its own declared objectives (burn under
    # burn_limit, p99 under target) passes, with both verdicts present
    verdict_slo, _ = run_gate(
        os.path.join(fixtures, "candidate_slo_clean.json"), evidence
    )
    slo_rec = _load_json(
        os.path.join(fixtures, "candidate_slo_clean.json")
    )
    checks.append((
        "clean slo candidate passes with burn + p99 judged against its "
        "own objectives",
        verdict_slo.ok
        and {s.metric for s in verdict_slo.slo} == {"worst_burn",
                                                    "p99_ms"}
        and not any(s.regressed for s in verdict_slo.slo),
    ))
    # ...a candidate with CLEAN walls whose error-budget burn breached
    # its own declared limit must fail on the slo verdict ALONE — the
    # record carries its targets, so this lane needs no history
    verdict_sb, _ = run_gate(
        os.path.join(fixtures, "candidate_slo_burn_regressed.json"),
        evidence,
    )
    sbreg = verdict_sb.slo_regressions
    checks.append((
        "burn-breached candidate fails on the slo verdict alone "
        "(clean walls, clean serving latency)",
        (not verdict_sb.ok)
        and any(s.metric == "worst_burn" for s in sbreg)
        and not any(s.metric == "p99_ms" for s in sbreg)
        and not any(s.regressed for s in verdict_sb.stages)
        and not any(s.regressed for s in verdict_sb.serving),
    ))
    # ...and an slo section whose histogram buckets do not sum to their
    # count is a SCHEMA violation (a histogram must account for every
    # observation), rejected before gating — same scratch-dir pattern
    import copy as _copy_slo
    import tempfile as _tempfile_slo

    bad_slo = _copy_slo.deepcopy(slo_rec)
    bad_slo["slo"]["latency_hist"]["ok"]["count"] += 1
    with _tempfile_slo.TemporaryDirectory(
            prefix="scc-gate-smoke-") as tslo:
        bad_path = os.path.join(tslo, "candidate_slo_bad_hist.json")
        with open(bad_path, "w") as f:
            json.dump(bad_slo, f)
        try:
            run_gate(bad_path, evidence)
            slo_rejected = False
        except ValueError as e:
            slo_rejected = "account for every" in str(e)
    checks.append((
        "slo histogram whose buckets do not sum to its count rejected "
        "naming the rule",
        slo_rejected,
    ))

    # traffic lane (round 21): a load-run candidate with a clean loadgen
    # section (mix over registered scenarios, open-loop accounting, zero
    # breaches) validates, seeds its fresh key, and carries the
    # zero-breach verdict...
    import copy as _copy_lg
    import tempfile as _tempfile_lg

    lg_section = {
        "profile": "steady", "arrival": "poisson",
        "base_rps": 20.0, "peak_rps": 80.0, "duration_s": 8.0,
        "seed": 7,
        "mix": {"multi_sample": 0.5, "atlas_transfer": 0.5},
        "offered": 160, "sent": 160, "completed": 160, "good": 158,
        "late_fraction": 0.0125, "achieved_rps": 19.75,
        "slo_held": True, "breaches": [], "rps_at_slo": 19.75,
        "autoscale": {
            "policy": {"min_replicas": 1, "max_replicas": 4},
            "ticks": 32, "final_target": 1, "degraded": False,
            "tightened": False,
            "actuations": [
                {"kind": "scale_up", "from": 1, "to": 2,
                 "reason": {"worst_burn": 0.0, "queue_frac": 0.81},
                 "ts": 1700000000.0},
                {"kind": "scale_down", "from": 2, "to": 1,
                 "reason": {"worst_burn": 0.0, "queue_frac": 0.0},
                 "ts": 1700000004.0},
            ],
        },
    }
    lg_rec = _copy_lg.deepcopy(slo_rec)
    lg_rec["extra"]["config"] = "loadgen-steady"
    lg_rec["metric"] = "sustained RPS at SLO"
    lg_rec["unit"] = "rps"
    lg_rec["value"] = 19.75
    lg_rec["loadgen"] = _copy_lg.deepcopy(lg_section)
    with _tempfile_lg.TemporaryDirectory(prefix="scc-gate-smoke-") as tlg:
        lg_path = os.path.join(tlg, "candidate_loadgen_clean.json")
        with open(lg_path, "w") as f:
            json.dump(lg_rec, f)
        verdict_lg, _ = run_gate(lg_path, evidence)
        checks.append((
            "clean load-run candidate validates, seeds its key, and "
            "carries the zero-breach traffic verdict",
            verdict_lg.ok
            and any(v.metric == "slo_breaches" and not v.regressed
                    for v in verdict_lg.loadgen),
        ))
        # ...a run that breached its SLO mid-spike fails on the traffic
        # verdict alone even with zero history (breaches gate
        # history-free, like the slo lane) and its headline is pinned
        # to 0.0 by the section's own consistency rule
        lg_bad = _copy_lg.deepcopy(lg_rec)
        lg_bad["loadgen"]["breaches"] = [
            "burn: worst_burn 20.1 > limit 14.4"]
        lg_bad["loadgen"]["slo_held"] = False
        lg_bad["loadgen"]["rps_at_slo"] = 0.0
        lg_bad["value"] = 0.0
        bad_path = os.path.join(tlg, "candidate_loadgen_breached.json")
        with open(bad_path, "w") as f:
            json.dump(lg_bad, f)
        verdict_lgb, _ = run_gate(bad_path, evidence)
        checks.append((
            "breached load run fails on the traffic verdict alone "
            "(zero history needed)",
            (not verdict_lgb.ok)
            and any(v.metric == "slo_breaches" and v.regressed
                    for v in verdict_lgb.loadgen)
            and not any(s.regressed for s in verdict_lgb.stages),
        ))
        # ...and a section claiming a nonzero sustained-RPS headline
        # alongside recorded breaches is a SCHEMA violation — a
        # breached run sustains nothing, and the record must not
        # contradict itself
        lg_lie = _copy_lg.deepcopy(lg_bad)
        lg_lie["loadgen"]["rps_at_slo"] = 19.75
        lie_path = os.path.join(tlg, "candidate_loadgen_lie.json")
        with open(lie_path, "w") as f:
            json.dump(lg_lie, f)
        try:
            run_gate(lie_path, evidence)
            lg_rejected = False
        except ValueError as e:
            lg_rejected = "rps_at_slo must be 0.0" in str(e)
        checks.append((
            "nonzero rps_at_slo claim on a breached run rejected "
            "naming the rule",
            lg_rejected,
        ))

    # a serving section that lost a request is a SCHEMA violation, not a
    # gateable record (the accounting rule is the serve contract);
    # scratch file goes to a temp dir — the committed fixture tree may
    # be a read-only checkout
    import copy as _copy
    import tempfile as _tempfile

    bad = _copy.deepcopy(_load_json(
        os.path.join(fixtures, "candidate_serve_latency_regressed.json")
    ))
    bad["serving"]["requests"]["ok"] -= 1  # one request vanishes
    with _tempfile.TemporaryDirectory(prefix="scc-gate-smoke-") as tmp:
        bad_path = os.path.join(tmp, "candidate_serve_bad.json")
        with open(bad_path, "w") as f:
            json.dump(bad, f)
        try:
            run_gate(bad_path, evidence)
            acct_rejected = False
        except ValueError as e:
            acct_rejected = "accounting" in str(e)
    checks.append((
        "serving section that lost a request rejected by validation",
        acct_rejected,
    ))

    # attribution annex (round 22): a FAIL must name its top suspect
    # stage — the regressed candidate's wilcox_test wall growth,
    # attributed against the key's freshest clean baseline record — and
    # the attribution must be deterministic (same pair, same report)
    import contextlib as _contextlib
    import io as _io

    attr_fail = attribution_for(
        os.path.join(fixtures, "candidate_regressed.json"), evidence
    )
    buf = _io.StringIO()
    with _contextlib.redirect_stdout(buf):
        rc_attr = _report(verdict_r, drifts_r, False, attr_fail)
    attr_out = buf.getvalue()
    checks.append((
        "perf-gate FAIL names the top suspect stage in its output",
        rc_attr == 1 and attr_fail is not None
        and (attr_fail.get("top_suspect") or {}).get("stage")
        == "wilcox_test"
        and "top suspect: stage `wilcox_test`" in attr_out,
    ))
    checks.append((
        "attribution annex is deterministic (same pair, same report)",
        attr_fail == attribution_for(
            os.path.join(fixtures, "candidate_regressed.json"), evidence
        ),
    ))
    # ...and a clean verdict prints no suspect (the annex never runs on
    # the green path — _report only adds the line on a FAIL)
    buf2 = _io.StringIO()
    with _contextlib.redirect_stdout(buf2):
        rc_clean = _report(verdict, drifts, False, None)
    checks.append((
        "clean verdict prints no top-suspect line",
        rc_clean == 0 and "top suspect" not in buf2.getvalue(),
    ))

    for label, ok in checks:
        print(f"[smoke] {'ok  ' if ok else 'FAIL'} {label}")
    ok_all = all(ok for _, ok in checks)
    print("SMOKE PASS" if ok_all else "SMOKE FAIL")
    return 0 if ok_all else 1


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="evidence-ledger regression gate")
    ap.add_argument("candidate", nargs="?", help="run-record JSON to gate")
    ap.add_argument("--evidence", default=None,
                    help="ledger dir (default: SCC_EVIDENCE_DIR or "
                         "<repo>/evidence)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable verdict on stdout")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test against the committed fixture ledger")
    ap.add_argument("--fixtures", default=FIXTURES, help=argparse.SUPPRESS)
    ap.add_argument("--upgrade", action="store_true",
                    help="one-shot legacy artifact relocation")
    ap.add_argument("--root", default=_REPO,
                    help="root dir for --upgrade (default: repo)")
    ap.add_argument("--keep-root", action="store_true",
                    help="--upgrade: keep the original root files")
    args = ap.parse_args(argv)

    if args.smoke:
        return _smoke(args.fixtures, args.as_json)
    if args.upgrade:
        # same dir every other ledger consumer resolves: --evidence, else
        # SCC_EVIDENCE_DIR, else <root>/evidence
        dest = args.evidence or default_evidence_dir(args.root)
        done, skipped = upgrade_tree(args.root, dest=dest,
                                     keep_root=args.keep_root)
        print(f"{len(done)} artifact(s) relocated into {dest}, "
              f"{len(skipped)} skipped")
        return 0
    if not args.candidate:
        ap.error("candidate record required (or --smoke / --upgrade)")
    evidence = args.evidence or default_evidence_dir(_REPO)
    try:
        verdict, drifts = run_gate(args.candidate, evidence)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 2
    # the attribution annex only runs on a failing verdict: a PASS needs
    # no root cause, and the annex must cost nothing on the green path
    attribution = None
    if not (verdict.ok and not [d for d in drifts
                                if not d["acknowledged"]]):
        attribution = attribution_for(args.candidate, evidence)
    return _report(verdict, drifts, args.as_json, attribution)


if __name__ == "__main__":
    raise SystemExit(main())
