#!/usr/bin/env python
"""Diff the graph passports of two run records (obs.graphs sections).

Usage:
    graph_diff.py CANDIDATE.json BASELINE.json [--json]

Compares the compiled-program observatory sections of two evidence
records program by program and prints, for every program present in
both: op-kind histogram deltas, fusion-count delta, donation-miss
delta, buffer-byte deltas, and — the part the ratchet cares about —
every transfer op or host callback present in the candidate but not
the baseline, named by op kind, count delta, and the source location
XLA recorded for it.

Exit codes:
    0  no host-crossing regression (op mix may still differ — reported)
    1  the candidate added transfer ops, host callbacks, or donation
       misses relative to the baseline (each named with its source line)
    2  usage/IO error — including a cross-fingerprint comparison: when
       the two records carry different environment-fingerprint digests
       (jax/jaxlib/backend/device/XLA flags), their op censuses are
       different programs by construction and diffing them would report
       toolchain noise as regressions. Re-record one side on the other's
       toolchain instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from scconsensus_tpu.obs.graphs import validate_graphs  # noqa: E402


def _load_section(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(record, graphs section) from an evidence-record path."""
    with open(path) as f:
        rec = json.load(f)
    sec = rec.get("graphs")
    if not isinstance(sec, dict):
        raise ValueError(
            f"error: {path} has no graphs section — re-run its bench "
            "with SCC_GRAPHS=1 (section absent on pre-r24 records)"
        )
    validate_graphs(sec)
    return rec, sec


def _sites(p: Dict[str, Any], kind: str) -> Dict[Tuple[str, str], int]:
    """{(op-or-target, where): count} for one passport's transfer ops or
    host callbacks — the unit of 'new host crossing'."""
    out: Dict[Tuple[str, str], int] = {}
    for s in (p.get(kind) or {}).get("sites") or []:
        key = (s.get("op") or s.get("target") or "?",
               s.get("where") or "unknown source")
        out[key] = out.get(key, 0) + 1
    return out


def diff_sections(cand: Dict[str, Any], base: Dict[str, Any]
                  ) -> Dict[str, Any]:
    """Structured passport diff (pure; the CLI renders it). Regressions
    are per-program lists of added transfer/callback sites plus donation
    misses introduced; ``changed`` holds the informational op-mix
    deltas."""
    cp, bp = cand.get("programs") or {}, base.get("programs") or {}
    regressions: List[Dict[str, Any]] = []
    changed: List[Dict[str, Any]] = []
    for name in sorted(set(cp) & set(bp)):
        c, b = cp[name], bp[name]
        entry: Dict[str, Any] = {"program": name}
        for kind, site_label in (("transfer_ops", "transfer op"),
                                 ("host_callbacks", "host callback")):
            cs, bs = _sites(c, kind), _sites(b, kind)
            added = []
            for (op, where), n in sorted(cs.items()):
                delta = n - bs.get((op, where), 0)
                if delta > 0:
                    added.append({"op": op, "where": where,
                                  "count_delta": delta,
                                  "kind": site_label})
            if added:
                entry.setdefault("added_crossings", []).extend(added)
        dmiss = ((c.get("donation") or {}).get("misses", 0)
                 - (b.get("donation") or {}).get("misses", 0))
        if dmiss > 0:
            entry["donation_misses_added"] = dmiss
        if "added_crossings" in entry or "donation_misses_added" in entry:
            regressions.append(entry)
        hist_delta = {}
        ch, bh = c.get("op_histogram") or {}, b.get("op_histogram") or {}
        for op in sorted(set(ch) | set(bh)):
            d = ch.get(op, 0) - bh.get(op, 0)
            if d:
                hist_delta[op] = d
        info: Dict[str, Any] = {}
        if hist_delta:
            info["op_histogram_delta"] = hist_delta
        fus = c.get("fusions", 0) - b.get("fusions", 0)
        if fus:
            info["fusions_delta"] = fus
        buf = {}
        cb_, bb_ = c.get("buffers") or {}, b.get("buffers") or {}
        for k in sorted(set(cb_) | set(bb_)):
            d = cb_.get(k, 0) - bb_.get(k, 0)
            if d:
                buf[k] = d
        if buf:
            info["buffers_delta"] = buf
        if info:
            info["program"] = name
            changed.append(info)
    return {
        "regressions": regressions,
        "changed": changed,
        "only_in_candidate": sorted(set(cp) - set(bp)),
        "only_in_baseline": sorted(set(bp) - set(cp)),
        "totals_delta": {
            k: (cand.get("totals") or {}).get(k, 0)
            - (base.get("totals") or {}).get(k, 0)
            for k in ("programs", "transfer_ops", "host_callbacks",
                      "donation_misses", "fusions")
        },
    }


def main(argv: List[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff the graph passports of two run records."
    )
    ap.add_argument("candidate", help="candidate evidence record (JSON)")
    ap.add_argument("baseline", help="baseline evidence record (JSON)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable diff on stdout")
    args = ap.parse_args(argv)

    try:
        _, cand = _load_section(args.candidate)
        _, base = _load_section(args.baseline)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    cfp = (cand.get("fingerprint") or {}).get("digest")
    bfp = (base.get("fingerprint") or {}).get("digest")
    if cfp and bfp and cfp != bfp:
        print(
            "error: cross-fingerprint comparison refused — candidate "
            f"toolchain digest {cfp} != baseline {bfp}.\n"
            "The two records were compiled by different toolchains "
            "(jax/jaxlib/backend/device/XLA flags), so their op censuses "
            "are different programs by construction; an op delta here "
            "would be toolchain noise, not a regression. Re-record one "
            "side on the other's toolchain and diff again.",
            file=sys.stderr,
        )
        return 2

    diff = diff_sections(cand, base)
    regressed = bool(diff["regressions"]) \
        or diff["totals_delta"]["transfer_ops"] > 0 \
        or diff["totals_delta"]["host_callbacks"] > 0
    if args.as_json:
        diff["regressed"] = regressed
        print(json.dumps(diff, indent=1))
        return 1 if regressed else 0

    td = diff["totals_delta"]
    print(f"programs: {len(cand.get('programs') or {})} candidate / "
          f"{len(base.get('programs') or {})} baseline "
          f"(+{len(diff['only_in_candidate'])} new, "
          f"-{len(diff['only_in_baseline'])} gone)")
    print(f"totals delta: transfer_ops {td['transfer_ops']:+d}  "
          f"host_callbacks {td['host_callbacks']:+d}  "
          f"donation_misses {td['donation_misses']:+d}  "
          f"fusions {td['fusions']:+d}")
    for r in diff["regressions"]:
        for site in r.get("added_crossings") or []:
            print(f"  REGRESSED {r['program']}: new {site['kind']} "
                  f"{site['op']} (+{site['count_delta']}) at "
                  f"{site['where']}")
        if r.get("donation_misses_added"):
            print(f"  REGRESSED {r['program']}: "
                  f"+{r['donation_misses_added']} donation miss(es) — "
                  "a declared donated buffer XLA no longer reuses")
    for info in diff["changed"]:
        bits = []
        if "fusions_delta" in info:
            bits.append(f"fusions {info['fusions_delta']:+d}")
        if "op_histogram_delta" in info:
            hd = info["op_histogram_delta"]
            bits.append("ops " + ", ".join(
                f"{op} {d:+d}" for op, d in sorted(hd.items())[:6]
            ))
        if "buffers_delta" in info and "peak_bytes" in info["buffers_delta"]:
            bits.append(f"peak {info['buffers_delta']['peak_bytes']:+,}B")
        if bits:
            print(f"  changed   {info['program']}: " + "  ".join(bits))
    for name in diff["only_in_candidate"]:
        print(f"  new program {name} (no baseline passport)")
    print("REGRESSED" if regressed else "clean")
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
