"""Benchmark harness over the BASELINE.json configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
The headline metric is the north star — 26k PBMC-scale
``recluster_de_consensus(method="edgeR")`` (the literal BASELINE.json:5
workload) end-to-end in < 30 s on one chip (vs_baseline = 30 / seconds;
> 1.0 beats the target). The fast-path Wilcoxon flagship, per-stage
wall-clocks, and achieved-FLOPs/MFU probes ride along in "extra".

Robustness contract (VERDICT r1 #1): this process NEVER exits with a bare
traceback. The default entry is an orchestrator that runs the measurement in
a worker subprocess under a timeout, retries once, then falls back to a
degraded CPU run; every failure is recorded in the final JSON line.

Select a config with SCC_BENCH_CONFIG:
  flagship  26k cells × 15k genes, K=22: edgeR slow path (headline) +
            fast Wilcoxon + MFU probes (+ pallas-vs-xla on TPU)
  pbmc68k   68k cells × 15k genes, 3-way consensus (chained), fast Wilcoxon
  cite8k    8k cells, ADT-style coarse supervised × RNA unsupervised
  tm100k    100k cells, 40 clusters, centroid-pooled approximate tree
  brain1m   1M-cell embedding → pooled Ward + dynamic cut + ring silhouette
            (reports cells/sec; DE is out of scope for this config)
  quick     2k cells × 1.5k genes smoke config (used by --quick / verify)

Synthetic NB data with planted clusters stands in for the public datasets
(no network egress). Extra knobs: SCC_BENCH_CELLS / _GENES / _CLUSTERS
override the flagship sizes; SCC_BENCH_COLD=1 reports the cold-compile run;
SCC_BENCH_PLATFORM pins the jax platform; SCC_BENCH_NO_FORK=1 runs the
measurement in-process (no orchestrator); SCC_BENCH_CRASH=<section> injects
a failure into one flagship section (edger|wilcox|mfu|pallas) to exercise
the partial-result contract.

Flagship sections are decoupled (VERDICT r2 #3): each of edgeR / wilcox /
MFU / Pallas runs under its own try/except, so one section's failure still
leaves every other section's numbers in the final line. Embedded failure
tails are truncated to keep the headline JSON line parseable by a driver
that only sees the last ~2 KB of output.

Checkpoint contract (VERDICT r3 #1): r03 recorded nothing because the
process only printed at the very end and the driver's timeout (SIGTERM,
rc=124) arrived first. Now every section completion (a) atomically writes a
cumulative record to BENCH_CHECKPOINT_<config>.json next to this file and
(b) prints a cumulative partial JSON line, so the driver's tail always holds
the latest numbers. The orchestrator recovers the checkpoint when an attempt
times out, and both worker and orchestrator trap SIGTERM to emit the best
record before dying. A value>0 partial is accepted as the attempt result."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The one versioned record shape every emitter in this repo shares
# (obs.export; summarize_evidence validates the version on ingest). Light
# import: obs.export never imports jax, so the orchestrator process stays
# backend-free.
from scconsensus_tpu.config import env_flag  # noqa: E402
from scconsensus_tpu.obs.export import build_run_record  # noqa: E402

BASELINE_SECONDS = 30.0


def _vsb(secs, extra) -> "float | None":
    """vs_baseline against the 30 s bar — or None (JSON null) whenever the
    record is degraded or size-reduced. A 2k-cell degraded-CPU run scored
    against the 26k-cell TPU target reads as a fake 8x 'beat' (VERDICT r4
    weak #1); a null ratio cannot mislead."""
    if not secs or secs <= 0:
        return None
    if extra.get("degraded") or extra.get("size_reduced"):
        return None
    return round(BASELINE_SECONDS / secs, 3)
# Shared persistent XLA compile cache: reused across workers, attempts, AND
# tunnel windows (a window that dies mid-compile still banks its programs).
# The stall watchdog also reads it as a liveness signal — keep both in sync.
_JAX_CACHE_DIR = "/tmp/scc_jax_cache"
# v5e peak is 197 bf16 TFLOP/s per chip; our kernels run f32, so MFU quoted
# against the bf16 peak is a conservative lower bound.
TPU_PEAK_FLOPS = 197e12

# Orchestrator timeouts (seconds). TPU backend init through the axon tunnel
# has been observed to hang for >15 min, hence the generous first window.
ATTEMPT_PLANS = {
    # (label, env overrides, timeout_s)
    "default": [
        ("primary", {}, 2700),
        ("retry", {}, 1500),
        ("cpu-degraded", {"SCC_BENCH_PLATFORM": "cpu",
                          "SCC_BENCH_DEGRADED": "1"}, 2400),
    ],
    "quick": [
        ("quick-cpu", {"SCC_BENCH_PLATFORM": "cpu"}, 900),
    ],
}
# test hook: scales every attempt timeout (e.g. 0.01 to exercise the
# timeout/fallback path without waiting out real windows)
_TIMEOUT_SCALE = float(env_flag("SCC_BENCH_TIMEOUT_SCALE"))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# Truncation caps keeping the final JSON line under the driver's tail window.
_TAIL_CHARS = 300
_MAX_FAILURES = 3


def _trim_line(parsed: dict) -> str:
    """Serialize the final record, dropping the least important parts until
    the line fits a driver that only sees the last ~2 KB of output.
    Operates on a copy: callers re-emit cumulative records; the untrimmed
    record (full span tree included) lives in the checkpoint file."""
    parsed = json.loads(json.dumps(parsed, default=str))
    line = json.dumps(parsed)
    # spans first: the tree is the biggest block and belongs in the
    # checkpoint/evidence file, not the stdout tail
    if len(line) > 1500 and parsed.get("spans"):
        parsed["spans"] = []
        parsed.setdefault("extra", {})["truncated"] = True
        line = json.dumps(parsed)
    # residency/kernels sections next: they live whole in the checkpoint
    # + ledger record; the tail keeps one-line summaries (total transfer
    # bytes + any enforce-mode violation count — the facts a driver must
    # see)
    if len(line) > 1500 and parsed.get("residency"):
        res = parsed.pop("residency")
        ex = parsed.setdefault("extra", {})
        ex["transfer_bytes"] = (
            (res.get("to_host") or {}).get("bytes", 0)
            + (res.get("to_device") or {}).get("bytes", 0)
        )
        if res.get("violations"):
            ex["residency_violations"] = len(res["violations"])
        ex["truncated"] = True
        line = json.dumps(parsed)
    if len(line) > 1500 and parsed.get("kernels"):
        kern = parsed.pop("kernels")
        ex = parsed.setdefault("extra", {})
        if kern.get("total_device_time_s") is not None:
            ex["device_time_s"] = kern["total_device_time_s"]
        ex["truncated"] = True
        line = json.dumps(parsed)
    # derived attribution sections (profile / residency_burndown): both
    # recompute losslessly from the checkpoint record's own sections, so
    # the tail keeps only the burn-down headline (the ratchet number a
    # driver should see) and drops the tables
    if len(line) > 1500 and parsed.get("profile"):
        parsed.pop("profile")
        parsed.setdefault("extra", {})["truncated"] = True
        line = json.dumps(parsed)
    if len(line) > 1500 and parsed.get("residency_burndown"):
        bd = parsed.pop("residency_burndown")
        ex = parsed.setdefault("extra", {})
        ex["burndown_total_bytes"] = bd.get("total_bytes")
        ex["burndown_item2_bytes"] = bd.get("todo_item2_bytes")
        ex["truncated"] = True
        line = json.dumps(parsed)
    # host-observatory sections (round 19): sample tables and timelines
    # live whole in the checkpoint + ledger record; the tail keeps the
    # two facts a driver must see (GC pause total, retrace count)
    if len(line) > 1500 and parsed.get("host_profile"):
        hp = parsed.pop("host_profile")
        ex = parsed.setdefault("extra", {})
        pause = (hp.get("gc") or {}).get("pause_s")
        if pause:
            ex["gc_pause_s"] = pause
        ex["truncated"] = True
        line = json.dumps(parsed)
    if len(line) > 1500 and parsed.get("compile"):
        comp = parsed.pop("compile")
        ex = parsed.setdefault("extra", {})
        if comp.get("retraces"):
            ex["retraces"] = comp["retraces"]
        ex["truncated"] = True
        line = json.dumps(parsed)
    # graph passports (round 24): the full per-program censuses live in
    # the checkpoint + ledger record; the tail keeps the ratchet facts a
    # driver must see (program count + static host-crossing totals)
    if len(line) > 1500 and parsed.get("graphs"):
        gr = parsed.pop("graphs")
        ex = parsed.setdefault("extra", {})
        tot = gr.get("totals") or {}
        ex["graph_programs"] = tot.get("programs", 0)
        if tot.get("transfer_ops") or tot.get("host_callbacks"):
            ex["graph_crossings"] = (tot.get("transfer_ops", 0)
                                     + tot.get("host_callbacks", 0))
        ex["truncated"] = True
        line = json.dumps(parsed)
    if len(line) > 1500 and parsed.get("memory_timeline"):
        mt = parsed.pop("memory_timeline")
        ex = parsed.setdefault("extra", {})
        if mt.get("rss_peak_bytes"):
            ex["rss_peak_bytes"] = mt["rss_peak_bytes"]
        ex["truncated"] = True
        line = json.dumps(parsed)
    # integrity section: the tail keeps the verification facts a driver
    # must see (checks passed/run + detection counts); the full catalog
    # lives in the checkpoint + ledger record
    if len(line) > 1500 and parsed.get("integrity"):
        ig = parsed.pop("integrity")
        ex = parsed.setdefault("extra", {})
        ch = ig.get("checks") or {}
        ex["integrity_checks"] = (f"{ch.get('passed', 0)}"
                                  f"/{ch.get('run', 0)}")
        det = (len(ig.get("violations") or [])
               + len((ig.get("ghost") or {}).get("mismatches") or []))
        if det:
            ex["integrity_detections"] = det
        ex["truncated"] = True
        line = json.dumps(parsed)
    # robustness section: the tail keeps the survival facts a driver must
    # see (retry/fault counts + whether the run recovered); the full
    # trail lives in the checkpoint + ledger record
    if len(line) > 1500 and parsed.get("robustness"):
        rb = parsed.pop("robustness")
        ex = parsed.setdefault("extra", {})
        for k in ("retries", "degradations", "faults_injected",
                  "resume_points", "mesh_transitions"):
            if rb.get(k):
                ex[f"robust_{k}"] = len(rb[k])
        if rb.get("mesh_transitions"):
            # the elastic headline a driver must see: where the mesh
            # ended up (the full from/to trail lives in the checkpoint)
            ex["robust_mesh_devices"] = len(
                rb["mesh_transitions"][-1].get("to_devices") or []
            )
        if rb.get("recovered"):
            ex["robust_recovered"] = True
        ex["truncated"] = True
        line = json.dumps(parsed)
    # streaming section: the tail keeps the bounded-memory facts a
    # driver must see (chunk completion + peak RSS vs budget); the full
    # section lives in the checkpoint + ledger record
    if len(line) > 1500 and parsed.get("streaming"):
        sm = parsed.pop("streaming")
        ex = parsed.setdefault("extra", {})
        ch = sm.get("chunks") or {}
        ex["stream_chunks"] = (f"{ch.get('completed')}"
                               f"/{ch.get('planned')}")
        bud = sm.get("budget") or {}
        ex["peak_rss_mb"] = bud.get("peak_rss_mb")
        ex["within_budget"] = bud.get("within_budget")
        ex["truncated"] = True
        line = json.dumps(parsed)
    # quality section next (funnel per-pair lists scale with K²): it
    # lives whole in the checkpoint + ledger record; the tail keeps only
    # the sentinel-trip count, the one quality fact a driver must see
    if len(line) > 1500 and parsed.get("quality"):
        trips = (parsed["quality"].get("numeric_health") or {}).get(
            "trips") or []
        parsed.pop("quality")
        if trips:
            parsed.setdefault("extra", {})["sentinel_trips"] = len(trips)
        parsed.setdefault("extra", {})["truncated"] = True
        line = json.dumps(parsed)
    drop_order = ("wilcox_occupancy", "stage_throughput",
                  "numeric_fingerprint", "prior_failures", "pallas_vs_xla",
                  "mfu", "edger_error", "wilcox_error", "wilcox_stages",
                  "edger_stages", "best_partial")
    for key in drop_order:
        if len(line) <= 1500:
            break
        if parsed.get("extra", {}).pop(key, None) is not None:
            parsed["extra"]["truncated"] = True
            line = json.dumps(parsed)
    # failures are the LAST thing to sacrifice (an all-attempts-failed
    # record without them is unactionable): first shrink each failure's
    # stderr tail — three 300-char tails alone can breach the budget
    fails = parsed.get("extra", {}).get("failures")
    if len(line) > 1500 and fails:
        for f in fails:
            if isinstance(f, dict) and len(f.get("stderr_tail", "")) > 100:
                f["stderr_tail"] = f["stderr_tail"][-100:]
        parsed["extra"]["truncated"] = True
        line = json.dumps(parsed)
    if len(line) > 1500 and parsed.get("extra", {}).pop(
            "failures", None) is not None:
        parsed["extra"]["truncated"] = True
        line = json.dumps(parsed)
    return line


# --------------------------------------------------------------------------
# checkpoint file (VERDICT r3 #1: a timeout must still leave a record)
# --------------------------------------------------------------------------

def _evidence_dir() -> str:
    """The ledger directory bench writes into: SCC_EVIDENCE_DIR when set
    (the test suite points it at a tmp dir), else <repo>/evidence."""
    from scconsensus_tpu.obs.ledger import default_evidence_dir

    return default_evidence_dir(os.path.dirname(os.path.abspath(__file__)))


def _ckpt_path() -> str:
    """Per-config checkpoint path, so quick-config test runs can never
    clobber flagship TPU evidence. Checkpoints live under evidence/ now
    (the root-level BENCH_CHECKPOINT_* files were relocated there); they
    are working files, indexed into MANIFEST.json only via the final
    ledger ingest."""
    override = env_flag("SCC_BENCH_CKPT")
    if override:
        return override
    name = env_flag("SCC_BENCH_CONFIG")
    return os.path.join(_evidence_dir(), f"BENCH_CHECKPOINT_{name}.json")


def _live_base() -> str:
    """Flight-recorder path base: the checkpoint path minus extension, so
    `<ckpt>_heartbeat.jsonl` / `<ckpt>_partial.json` sit next to the
    checkpoint and the orchestrator can derive the stream path without a
    side channel."""
    return os.path.splitext(_ckpt_path())[0]


# the worker's flight recorder (obs.live); None until worker() starts it
_LIVE = None


def _flush_live(cause: str) -> None:
    """Best-effort partial-record flush of the worker's recorder — called
    from the SIGTERM path, so it must never raise."""
    try:
        from scconsensus_tpu.obs.live import flush_active

        flush_active(cause)
    except Exception:
        pass


def _write_ckpt(record: dict) -> None:
    try:
        path = _ckpt_path()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1)
        os.replace(tmp, path)
    except Exception as e:  # checkpointing must never kill the measurement
        # broad on purpose: a numpy scalar in extra raises TypeError from
        # json.dump, and the SIGTERM handler must still reach its print
        log(f"[bench] checkpoint write failed: {e!r}")


def _read_ckpt(min_mtime: float | None = None) -> dict | None:
    try:
        path = _ckpt_path()
        if min_mtime is not None and os.path.getmtime(path) < min_mtime:
            return None  # stale: predates this orchestrator run
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _finalize(record: dict) -> dict:
    """Final-record stamp, applied to every record kind: per-stage
    achieved-vs-cost-model throughput (obs.cost.stage_cost_summary over
    the span tree), then the derived attribution sections — the unified
    ``profile`` join and the ``residency_burndown`` ledger
    (obs.profile) — and the accelerator-tunnel health stamp. Each part
    is present only when its inputs are: an empty summary / profile is
    omitted, never zeros."""
    try:
        from scconsensus_tpu.obs.cost import stage_cost_summary

        summ = stage_cost_summary(record.get("spans") or [])
        if summ:
            record.setdefault("extra", {})["stage_throughput"] = summ
    except Exception as e:
        log(f"[bench] stage-throughput summary failed: {e!r}")
    try:
        from scconsensus_tpu.obs.profile import profile_sections_of

        derived = profile_sections_of(record)
        for key in ("profile", "residency_burndown"):
            if derived.get(key) is not None:
                record[key] = derived[key]
    except Exception as e:
        log(f"[bench] profile/burndown derivation failed: {e!r}")
    try:
        from scconsensus_tpu.obs import hostprof

        prof = hostprof.active_profiler()
        if prof is not None:
            secs = prof.sections()
            for key in ("host_profile", "memory_timeline"):
                if secs.get(key) is not None:
                    record[key] = secs[key]
    except Exception as e:
        log(f"[bench] host-profile stamp failed: {e!r}")
    try:
        from scconsensus_tpu.obs import compilelog

        comp = compilelog.snapshot()
        if comp is not None:
            record["compile"] = comp
    except Exception as e:
        log(f"[bench] compile-log stamp failed: {e!r}")
    try:
        from scconsensus_tpu.obs import graphs

        sec = graphs.snapshot()
        if sec is not None and sec.get("programs"):
            record["graphs"] = sec
            _stamp_graph_ratchet_ack(record)
    except Exception as e:
        log(f"[bench] graph-passport stamp failed: {e!r}")
    _stamp_tunnel(record)
    return record


def _stamp_graph_ratchet_ack(record: dict) -> None:
    """Stamp ``extra.graph_ratchet_ack`` — a digest of the
    NUMERIC_PINS.json ``graph_ratchet`` entry this record's dataset is
    gated against — so committed bench evidence names exactly which
    transfer-op debt snapshot it acknowledged (the committed-evidence
    lint requires it on new bench records carrying a graphs section)."""
    try:
        from scconsensus_tpu.obs.graphs import ratchet_ack
        from scconsensus_tpu.obs.ledger import run_key
        from scconsensus_tpu.obs.regress import PINS_NAME

        pins_path = os.path.join(_evidence_dir(), PINS_NAME)
        with open(pins_path) as f:
            doc = json.load(f)
        ratchet = doc.get("graph_ratchet")
        if not isinstance(ratchet, dict):
            return
        entry = ratchet.get(run_key(record)["dataset"])
        if isinstance(entry, dict):
            record.setdefault("extra", {})["graph_ratchet_ack"] = \
                ratchet_ack(entry)
    except Exception as e:
        log(f"[bench] graph-ratchet ack stamp failed: {e!r}")


def _stamp_tunnel(record: dict) -> None:
    """Stamp ``tunnel`` on a record whose accelerator evidence is
    expected but absent (satellite: explicit `tunnel: stale` instead of
    silent omission). A record that ran on a real accelerator, or a CPU
    run outside no-cpu-fallback mode (CPU was the *intent*), carries no
    stamp; every other case names the tunnel's last known state from
    TUNNEL_LOG.jsonl so "no TPU numbers" is a recorded, typed fact."""
    try:
        plat = (record.get("extra") or {}).get("platform") \
            or (record.get("run") or {}).get("platform")
        expected = bool(env_flag("SCC_BENCH_NO_CPU_FALLBACK"))
        if (plat not in (None, "cpu")) or not expected:
            return
        tools_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tools"
        )
        sys.path.insert(0, tools_dir)
        try:
            from tunnel_probe import tunnel_status
        finally:
            sys.path.pop(0)
        st = tunnel_status()
        record["tunnel"] = {k: st[k] for k in
                           ("state", "age_s", "last_outcome", "log")
                           if k in st}
    except Exception as e:
        log(f"[bench] tunnel stamp failed: {e!r}")


def _ingest_evidence(record: dict) -> None:
    """Best-effort ledger ingest of the final record into evidence/
    (SCC_BENCH_LEDGER=0 disables). The perf gate reads its baselines from
    this history. Must never kill the bench — the record already printed."""
    try:
        if not env_flag("SCC_BENCH_LEDGER"):
            return
        from scconsensus_tpu.obs.ledger import Ledger

        record = json.loads(json.dumps(record, default=str))
        entry = Ledger(_evidence_dir()).ingest(record, source="bench")
        log(f"[bench] evidence: ingested {entry['file']}")
    except Exception as e:
        log(f"[bench] evidence ingest failed: {e!r}")


def _emit_partial(record: dict) -> None:
    """Checkpoint a cumulative record: write the file and print a partial
    line (the driver parses the LAST JSON line of the tail, so cumulative
    re-emits are safe and make even a SIGKILL leave the newest numbers).
    Must never kill the measurement: a non-serializable extra (numpy
    scalar) degrades to str instead of raising mid-pipeline."""
    try:
        record = json.loads(json.dumps(record, default=str))
        record.setdefault("extra", {})["partial"] = True
        _write_ckpt(record)
        print(_trim_line(record), flush=True)
    except Exception as e:  # pragma: no cover - defensive
        log(f"[bench] partial emit failed: {e!r}")


def _robust_section() -> "dict | None":
    """The worker's in-process robustness trail (robust.record) for the
    run record — None on healthy unfaulted runs, so the section's very
    presence means something happened."""
    try:
        from scconsensus_tpu.robust import record as robust_record

        return robust_record.section()
    except Exception:
        return None


def _integrity_section() -> "dict | None":
    """The worker's in-process computation-integrity trail
    (robust.integrity) — None with SCC_INTEGRITY=off, so the section's
    very presence means the run audited its own arithmetic."""
    try:
        from scconsensus_tpu.robust import integrity as robust_integrity

        return robust_integrity.section()
    except Exception:
        return None


def _adapt_from_failure(failure: dict | None) -> "tuple[dict, str] | None":
    """Cause-aware attempt adaptation (robust round): read the dead
    attempt's termination cause + stderr signature and shape the NEXT
    attempt — stall -> retry with a profiler capture armed (the r9 stall
    watchdog then leaves a trace, not just a stack dump); resource
    exhaustion -> retry degraded rather than re-OOM at full size.
    Returns (env updates, reason) or None."""
    if not failure:
        return None
    if failure.get("outcome") == "stall":
        return ({"SCC_OBS_STALL_TRACE": "/tmp/scc_stall_capture"},
                "stall -> retry with stall-capture armed")
    try:
        from scconsensus_tpu.robust.retry import classify_text

        cls = classify_text(failure.get("stderr_tail"))
    except Exception:
        cls = None
    if cls == "resource":
        return ({"SCC_BENCH_DEGRADED": "1"},
                "resource exhaustion -> retry degraded")
    return None


def _poison_path() -> str:
    name = env_flag("SCC_BENCH_CONFIG")
    return os.path.join(_evidence_dir(), f"POISON_{name}.json")


def _poison_config(failures: list) -> dict:
    """Repeated-crash poisoning: two crash-class attempt failures mean
    the config itself is broken (not the box, not the tunnel) — record a
    named reason in evidence/ so operators and the next orchestrator see
    WHY the ladder stopped early, instead of burning every window
    re-crashing."""
    reason = {
        "config": env_flag("SCC_BENCH_CONFIG"),
        "reason": "repeated crash: "
                  + "; ".join(
                      f"{f.get('attempt')}: rc={f.get('rc')}"
                      for f in failures if f.get("outcome") == "error"
                  ),
        "failures": failures[-_MAX_FAILURES:],
        "poisoned_unix": round(time.time(), 1),
    }
    try:
        os.makedirs(_evidence_dir(), exist_ok=True)
        from scconsensus_tpu.obs.export import write_json_atomic

        write_json_atomic(_poison_path(), reason)
        log(f"[bench] config POISONED: {reason['reason']} "
            f"({_poison_path()})")
    except Exception as e:
        log(f"[bench] poison write failed: {e!r}")
    return reason


def _record_value(record: dict | None) -> float:
    try:
        return float(record.get("value", -1))
    except (AttributeError, TypeError, ValueError):
        return -1.0


def _best_partial(stdout: str, min_mtime: float) -> dict | None:
    """Best recovered evidence from a dead attempt: the worker's stdout
    partial lines or the checkpoint written during this attempt — prefer
    whichever carries a real headline value (a stale value<=0 startup
    partial on stdout must not mask a value>0 checkpoint on disk)."""
    cands = [_last_json_line(stdout), _read_ckpt(min_mtime)]
    best = next((c for c in cands if _record_value(c) > 0), None)
    return best or next((c for c in cands if c is not None), None)


def _section(extra: dict, name: str, fn):
    """Run one flagship section; on failure record a truncated error and
    keep going (VERDICT r2 #3: sections must not couple). Returns the
    section's value or None."""
    if env_flag("SCC_BENCH_CRASH") == name:
        extra[f"{name}_error"] = "injected crash (SCC_BENCH_CRASH)"
        log(f"[bench] section '{name}': injected crash")
        return None
    try:
        return fn()
    except Exception as e:  # never let one section kill the others
        extra[f"{name}_error"] = repr(e)[:_TAIL_CHARS]
        log(f"[bench] section '{name}' failed: {repr(e)[:500]}")
        return None


# --------------------------------------------------------------------------
# workload builders (worker side)
# --------------------------------------------------------------------------

def _consensus(*labelings):
    """Chain plot_contingency_table across 2+ labelings (3-way consensus is
    consensus(consensus(l1, l2), l3) — the README's multi-tool workflow)."""
    from scconsensus_tpu import plot_contingency_table

    out = labelings[0]
    for nxt in labelings[1:]:
        out = plot_contingency_table(out, nxt, filename=None)
    return out


_GEN_CACHE = {}


_DEVICE_GEN_BROKEN = False  # set after a device-gen failure (see _gen)


def _device_gen() -> bool:
    """Generate the synthetic matrix on device when running on an
    accelerator (opt out: SCC_BENCH_HOST_GEN=1; force on anywhere:
    SCC_BENCH_DEVICE_GEN=1). Host generation costs ~130 s of numpy at
    flagship scale plus a ~1.5 GB upload — over the remote-TPU tunnel the
    upload alone can outlast a tunnel window, which is how round 3's
    capture died. On-device gen moves only KBs."""
    if _DEVICE_GEN_BROKEN or env_flag("SCC_BENCH_HOST_GEN"):
        return False
    if env_flag("SCC_BENCH_DEVICE_GEN"):
        return True
    import jax

    return jax.devices()[0].platform != "cpu"


def _gen(n_cells, n_genes, n_clusters, seed=7):
    """Synthetic dataset, memoized: the edgeR and wilcox flagship sections
    use the identical dataset, and regenerating it costs ~130 s of host
    time at 26k × 15k (measured) — pure waste inside the bench wall."""
    from scconsensus_tpu.utils.synthetic import (
        synthetic_scrna,
        synthetic_scrna_device,
    )

    dev = _device_gen()
    key = (n_cells, n_genes, n_clusters, seed, dev)
    if key not in _GEN_CACHE:
        _GEN_CACHE.clear()  # at most one flagship-sized dataset resident
        kw = dict(
            n_genes=n_genes,
            n_cells=n_cells,
            n_clusters=n_clusters,
            n_markers_per_cluster=min(40, n_genes // n_clusters),
            seed=seed,
        )
        if dev:
            try:
                import jax

                out = synthetic_scrna_device(**kw)
                # force materialization NOW: async dispatch would otherwise
                # surface a device-side failure (e.g. HBM OOM) later inside
                # the timed section, past this try
                jax.block_until_ready(out[0])
                _GEN_CACHE[key] = out
            except Exception as e:
                # Untested-backend insurance: losing the upload saving is
                # better than losing the whole measurement section. The
                # flag makes every later _gen call go straight to host gen
                # instead of re-failing (and re-clearing the cache).
                global _DEVICE_GEN_BROKEN
                _DEVICE_GEN_BROKEN = True
                log(f"[bench] device gen failed ({repr(e)[:200]}); "
                    "falling back to host gen + upload")
                key = (n_cells, n_genes, n_clusters, seed, False)
                _GEN_CACHE[key] = synthetic_scrna(**kw)
        else:
            _GEN_CACHE[key] = synthetic_scrna(**kw)
    return _GEN_CACHE[key]


def _labelings(truth, n_clusters, n_way=2):
    """Input-labeling construction lives in the workload zoo now
    (workloads.labelings): the historical recipe is the named
    ``truth_perturb`` strategy among several, moved VERBATIM — seeds,
    flip fractions, coarsening, prefixes — so the existing bench keys'
    numeric-fingerprint pins (evidence/NUMERIC_PINS.json) stay
    byte-stable across the move."""
    from scconsensus_tpu.workloads.labelings import truth_perturb

    return truth_perturb(truth, n_clusters, n_way)


def run_refine_config(n_cells, n_genes, n_clusters, n_way=2, method="wilcox",
                      **refine_kw):
    from scconsensus_tpu import (
        recluster_de_consensus,
        recluster_de_consensus_fast,
    )

    data, truth, _ = _gen(n_cells, n_genes, n_clusters)
    labelings = _labelings(truth, n_clusters, n_way)

    def once():
        t0 = time.perf_counter()
        consensus = _consensus(*labelings)
        if method == "edgeR":
            # the literal north-star workload: slow path, edgeR NB engine
            # (reference R/reclusterDEConsensus.R:20 with method="edgeR")
            result = recluster_de_consensus(
                data, consensus, method="edgeR", q_val_thrs=0.01, fc_thrs=2.0,
                mean_scaling_factor=2.0, deep_split_values=(1, 2, 3, 4),
                **refine_kw,
            )
        else:
            result = recluster_de_consensus_fast(
                data, consensus, method="wilcox",
                deep_split_values=(1, 2, 3, 4), **refine_kw,
            )
        elapsed = time.perf_counter() - t0
        try:
            # the pipeline scored ARI vs the CONSENSUS it was handed; the
            # bench additionally scores the final cut against BOTH raw
            # input labelings (quality.ari_final_vs — the same
            # implementation cluster_structure uses)
            from scconsensus_tpu.obs import quality as obs_quality

            cs = (result.metrics.get("quality") or {}).get(
                "cluster_structure")
            if cs is not None:
                refs = obs_quality.ari_final_vs(
                    result.dynamic_labels,
                    {f"input_{i}": lab for i, lab in enumerate(labelings)},
                )
                if refs:
                    cs["ari_final_vs"] = refs
        except Exception as e:
            log(f"[bench] ari_final_vs failed: {e!r}")
        return elapsed, result

    return once


def run_brain1m(n_cells=1_000_000, n_pcs=15, n_clusters=24):
    """1M-cell scale config: landmark recluster (r7 — sketch-fitted Lloyd,
    Ward on k ≪ N landmarks, device nearest-landmark cut propagation) +
    ring silhouette over a synthetic embedding (the 'pod-sharded distance
    + approx hierarchical' configuration; metric is cells/sec)."""
    import numpy as np

    from scconsensus_tpu.ops.pooling import landmark_ward_linkage
    from scconsensus_tpu.ops.silhouette import mean_cluster_silhouette
    from scconsensus_tpu.ops.treecut import cutree_hybrid

    rng = np.random.default_rng(3)
    centers = rng.normal(scale=6.0, size=(n_clusters, n_pcs))
    lab = rng.integers(0, n_clusters, n_cells)
    if _device_gen():
        # Draw the embedding on device (same planted structure): avoids a
        # 60 MB x upload through the tunnel; only labels (4 MB) cross.
        import jax
        import jax.numpy as jnp

        key = jax.random.PRNGKey(3)
        x = (
            jnp.take(jnp.asarray(centers, jnp.float32),
                     jnp.asarray(lab.astype(np.int32)), axis=0)
            + jax.random.normal(key, (n_cells, n_pcs), jnp.float32)
        )
    else:
        x = (centers[lab] + rng.normal(size=(n_cells, n_pcs))).astype(
            np.float32
        )

    def once():
        from scconsensus_tpu.obs.trace import Tracer

        tracer = Tracer()
        t0 = time.perf_counter()
        with tracer.span("landmark_ward", n_cells=n_cells):
            tree, assign, cents, lm_info = landmark_ward_linkage(x, seed=1)
        with tracer.span("cut"):
            w = np.bincount(assign, minlength=cents.shape[0]).astype(
                np.float64
            )
            # cell-unit floor equivalent to the old "2 centroids" minimum
            # at the old average occupancy (min_cluster_size=2 on 4096
            # pools of N cells)
            cut = cutree_hybrid(
                tree, cents, deep_split=1,
                min_cluster_size=max(2, round(2 * n_cells / 4096)),
                weights=w,
            )
            cells = cut[assign]
        with tracer.span("silhouette"):
            sub = rng.choice(n_cells, size=50_000, replace=False)  # sampled
            si, _ = mean_cluster_silhouette(x[sub], cells[sub])
        dt = time.perf_counter() - t0
        return dt, {"clusters": len(set(cells[cells > 0].tolist())),
                    "silhouette": round(si, 3),
                    "landmark": lm_info,
                    }, tracer.span_records()

    return once


# --------------------------------------------------------------------------
# FLOPs / MFU probes
# --------------------------------------------------------------------------

def _cost_flops(compiled) -> float:
    """XLA's flop estimate from a compiled computation (version-tolerant)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return float(ca.get("flops", 0.0))


def _time_reps(fn, args, min_reps=3) -> float:
    """Median wall-clock of jitted fn over a few reps (post-warmup)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(min_reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def mfu_probes(platform: str) -> dict:
    """Achieved-FLOPs probes for the two hot DE kernels (VERDICT r1 #1,
    retargeted to the round-3 engines): the all-pairs sorted-cumsum rank-sum
    chunk and the NB node-table contraction (the rewritten edgeR engine's
    pass-2 equivalent — it prices every tagwise/common grid evaluation), at
    flagship-representative shapes. FLOPs are XLA cost-analysis estimates;
    MFU is quoted against the 197 TFLOP/s bf16 peak (conservative: the
    kernels run f32)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scconsensus_tpu.de.edger import _table_chunk, _NODE_COUNT
    from scconsensus_tpu.ops.ranksum_allpairs import allpairs_ranksum_chunk

    rng = np.random.default_rng(0)
    out = {}

    # all-pairs rank-sum chunk: Gc genes × N cells × K clusters, all pairs
    Gc, N, K = 256, 26000, 44
    P = K * (K - 1) // 2
    chunk = jnp.asarray(rng.gamma(2.0, size=(Gc, N)).astype(np.float32))
    cid = jnp.asarray(rng.integers(0, K, N).astype(np.int32))
    n_of = jnp.asarray(np.bincount(np.asarray(cid), minlength=K).astype(np.int32))
    pi, pj = np.triu_indices(K, k=1)
    args = (chunk, cid, n_of, jnp.asarray(pi.astype(np.int32)),
            jnp.asarray(pj.astype(np.int32)))
    try:
        compiled = allpairs_ranksum_chunk.lower(*args, n_clusters=K).compile()
        flops = _cost_flops(compiled)
        f = lambda *a: allpairs_ranksum_chunk(*a, n_clusters=K)
        sec = _time_reps(f, args)
        out["ranksum"] = {
            "chunk": [Gc, N, K],
            "gene_pairs_per_s": round(Gc * P / sec),
            "achieved_tflops": round(flops / sec / 1e12, 3),
        }
        if platform == "tpu":
            out["ranksum"]["mfu_vs_bf16_peak"] = round(
                flops / sec / TPU_PEAK_FLOPS, 4
            )
    except Exception as e:  # pragma: no cover - probe must never kill bench
        out["ranksum"] = {"error": repr(e)[:200]}

    # NB node-table build: the edgeR-equivalent engine's hot kernel. After
    # the round-3 rewrite the engine is no longer FLOP-dominated — the
    # (Gc, Ns, R) lgamma sweep feeding one MXU contraction prices every
    # common/tagwise grid evaluation for every pair at once, so the honest
    # throughput number is lgamma-site evaluations per second (an MFU quoted
    # against the matmul peak undercounts transcendental work by design).
    try:
        Gt, Ns = 1024, K * 64
        psub = jnp.asarray(rng.gamma(2.0, size=(Gt, Ns)).astype(np.float32))
        sub_onehot = jnp.asarray(
            np.eye(K, dtype=np.float32)[rng.integers(0, K, Ns)]
        )
        r_nodes = jnp.asarray(
            np.exp(np.linspace(-4.0, 9.0, _NODE_COUNT)).astype(np.float32)
        )
        nb_args = (psub, sub_onehot, r_nodes)
        compiled = _table_chunk.lower(*nb_args).compile()
        flops = _cost_flops(compiled)
        sec = _time_reps(_table_chunk, nb_args)
        out["nb_table"] = {
            "kernel": "lgamma_node_table+contraction",
            "chunk": [Gt, Ns, _NODE_COUNT],
            "lgamma_evals_per_s": round(Gt * Ns * _NODE_COUNT / sec),
            "grid_points_priced_per_s": round(Gt * _NODE_COUNT / sec),
            "achieved_tflops": round(flops / sec / 1e12, 3),
        }
        if platform == "tpu":
            out["nb_table"]["mfu_vs_bf16_peak"] = round(
                flops / sec / TPU_PEAK_FLOPS, 4
            )
    except Exception as e:  # pragma: no cover
        out["nb_table"] = {"error": repr(e)[:200]}
    return out


def pallas_vs_xla_probe() -> dict:
    """Fused Pallas distance+cluster-sums vs the XLA fallback. Two shapes:
    the flagship silhouette (26k × 15, K=22 — where round-3 measured XLA
    ahead and demoted Pallas from auto) and the fat-K pooled-centroid
    geometry (100k × 15, K=4096 — the brain1m assignment shape, VERDICT r3
    #8's candidate for a Pallas win). TPU only."""
    import numpy as np

    from scconsensus_tpu.ops.pallas_kernels import distance_cluster_sums

    rng = np.random.default_rng(1)
    shapes = {
        "flagship_26k_k22": (26_000, 15, 22),
        "pooled_100k_k4096": (100_000, 15, 4096),
    }
    out = {}
    for name, (n, d, k) in shapes.items():
        x = rng.normal(size=(n, d)).astype(np.float32)
        lab = rng.integers(0, k, size=n)
        onehot = np.eye(k, dtype=np.float32)[lab]
        rec = {}
        try:
            import jax
            import jax.numpy as jnp

            # upload ONCE and keep results on device: at the fat-K shape the
            # one-hot + result are ~3.2 GB — timing transfers instead of the
            # kernels would push pallas_speedup to a meaningless ~1.0
            jx = jnp.asarray(x)
            joh = jnp.asarray(onehot)
            stats = {}
            results = {}
            for backend in ("xla", "pallas"):
                r = distance_cluster_sums(
                    jx, joh, backend=backend, device_out=True
                )
                jax.block_until_ready(r)
                t0 = time.perf_counter()
                r = distance_cluster_sums(
                    jx, joh, backend=backend, device_out=True
                )
                jax.block_until_ready(r)
                stats[backend] = time.perf_counter() - t0
                results[backend] = r
                rec[f"{backend}_s"] = round(stats[backend], 4)
            rec["pallas_speedup"] = round(stats["xla"] / stats["pallas"], 3)
            diff = float(jnp.max(jnp.abs(results["xla"] - results["pallas"])))
            scale = max(1.0, float(jnp.max(jnp.abs(results["xla"]))))
            rec["max_rel_diff"] = diff / scale
        except Exception as e:
            rec["error"] = repr(e)[:300]
        out[name] = rec
    return out


# --------------------------------------------------------------------------
# worker
# --------------------------------------------------------------------------

def _install_term_handler(record_fn) -> None:
    """On SIGTERM (the driver's `timeout` signal), checkpoint and print the
    best cumulative record before dying, so rc=124 still leaves a parseable
    line in the tail (VERDICT r3 #1: r03's rc=124 left nothing)."""
    import signal

    def _on_term(signum, frame):  # pragma: no cover - signal path
        try:
            # the flight recorder's partial record first: it carries the
            # open-span stack of the moment the TERM landed
            _flush_live("signal")
            rec = record_fn()
            rec.setdefault("extra", {})["partial"] = True
            rec["extra"]["terminated"] = True
            _write_ckpt(rec)  # never raises (broad except inside)
            try:
                print(_trim_line(rec), flush=True)
            except Exception:
                # non-serializable extra: still leave SOMETHING in the tail
                print(json.dumps({
                    "metric": rec.get("metric", "terminated"),
                    "value": rec.get("value", -1), "unit": "seconds",
                    "vs_baseline": None,
                    "extra": {"partial": True, "terminated": True},
                }, default=str), flush=True)
        finally:
            os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass


CONFIGS = {
    "flagship": dict(kind="flagship", n_cells=26000, n_genes=15000,
                     n_clusters=22),
    "pbmc68k": dict(kind="refine", n_cells=68000, n_genes=15000, n_clusters=12,
                    n_way=3),
    "cite8k": dict(kind="refine", n_cells=8000, n_genes=10000, n_clusters=8),
    "tm100k": dict(kind="refine", n_cells=100000, n_genes=12000, n_clusters=40,
                   refine_kw=dict(approx_threshold=50000)),
    "brain1m": dict(kind="brain1m"),
    # 10M cells, out-of-core (ROADMAP item 5): the FULL refine pipeline
    # over a disk-resident ChunkedCSRStore with a hard host-memory
    # budget (stream.runner) — the order-of-magnitude jump past brain1m
    # that no in-memory stage survives. Cold = synthetic chunk ingest +
    # compiles; steady re-runs the streaming refine against the durable
    # chunk store with a fresh stage dir. The record carries the
    # validated `streaming` section (chunk counters + peak-RSS-vs-budget
    # evidence) and its peak RSS rides the streaming memory gate.
    "brain10m": dict(kind="stream10m", n_cells=10_000_000, n_genes=2000,
                     n_clusters=16, density=0.02,
                     refine_kw=dict(approx_threshold=100_000,
                                    landmark_threshold=100_000,
                                    silhouette_sample=50_000)),
    "quick": dict(kind="flagship", n_cells=800, n_genes=300, n_clusters=3),
    # atlas→query label transfer: the serve path exercised as a BATCH
    # workload (ROADMAP item 4 crossover) — a frozen gaussian atlas is
    # exported as a consensus model and queried through the wire front
    # over a replica fleet; the record carries the validated serving
    # section (wire + fleet accounting) and its p99/throughput baselines
    # ride the replica-keyed serving gate.
    "atlas_query": dict(kind="atlas_query", n_genes=2000, n_clusters=12,
                        n_train=20000, n_queries=300, cells_per=64,
                        n_ood=8),
    # Workload zoo (round 19, ROADMAP item 4): four scenario configs
    # dispatched through scconsensus_tpu.workloads.run_scenario — each a
    # registered bench key with its own ledger baseline and a validated
    # top-level `scenario` record section. The DEGRADED / CPU fallback
    # for a scenario is its ≤5k-cell `smoke` shape (the same shape the
    # tier-1 pytest lane runs), so the attempt ladder never reruns a
    # full-size scenario on a 2-core box.
    "multi_sample": dict(kind="scenario", scenario="multi_sample"),
    "cite_dual": dict(kind="scenario", scenario="cite_dual"),
    "atlas_transfer": dict(kind="scenario", scenario="atlas_transfer"),
    "topo_inputs": dict(kind="scenario", scenario="topo_inputs"),
}

# Degraded CPU-fallback sizes: small enough to finish on host in minutes.
# The NB engine is transcendental-bound (LL grids over pairs × genes ×
# cells × dispersions) — sized for TPU VPU throughput, so the CPU fallback
# must stay small to bound the edgeR headline.
DEGRADED = {
    "flagship": dict(n_cells=2000, n_genes=800, n_clusters=4),
    "pbmc68k": dict(n_cells=8000, n_genes=3000, n_clusters=6),
    "cite8k": dict(n_cells=3000, n_genes=2000, n_clusters=5),
    "tm100k": dict(n_cells=20000, n_genes=3000, n_clusters=12),
    "atlas_query": dict(n_genes=400, n_clusters=6, n_train=4000,
                        n_queries=80, cells_per=32, n_ood=4),
    # 2-core-box shape that still crosses the landmark threshold so the
    # streaming tree path exercises the sketch-fit/blocked-assign split
    "brain10m": dict(n_cells=150_000, n_genes=500, n_clusters=8,
                     density=0.05),
}


def _stamp_fingerprint(extra: dict, result) -> None:
    """Numeric-drift sentinel payload on the run record: DE log-p
    quantiles, NB tagwise-dispersion quantiles (edgeR runs), and the
    final-label ARI vs the input consensus (from the pipeline's quality
    section). Stamped on EVERY run; the ledger copies it onto the
    manifest entry, so the perf gate can flag quality drift on any
    dataset — against evidence/NUMERIC_PINS.json when the dataset is
    pinned, else against the key's previous clean run — with the
    DRIFT_LEDGER.jsonl acknowledgement flow either way."""
    try:
        from scconsensus_tpu.obs.regress import drift_fingerprint

        aux = result.de.aux or {}
        fp = drift_fingerprint(
            log_p=result.de.log_p,
            dispersions=aux.get("tagwise_dispersion"),
        )
        q = (result.metrics or {}).get("quality") or {}
        ari = (q.get("cluster_structure") or {}).get("ari_vs_input") or {}
        if ari:
            # the LAST deepSplit cut's agreement with the input labeling:
            # a quality shift here is exactly the silent-recut failure
            # mode the drift ledger exists to force into the open
            fp["label_ari_vs_input"] = list(ari.values())[-1]
        extra["numeric_fingerprint"] = fp
    except Exception as e:
        log(f"[bench] fingerprint failed: {e!r}")


def worker() -> None:
    """Measurement entry, wrapped in the live flight recorder (obs.live):
    heartbeats + the incrementally flushed partial record cover the whole
    worker life INCLUDING backend init (the historical hang site), and the
    orchestrator watchdog reads the stream as its primary liveness
    signal."""
    global _LIVE
    # test hook: simulate a hung backend init (worker dies having produced
    # nothing — not even heartbeats — so the orchestrator must catch it
    # through the no-heartbeat fallback signals)
    hang = float(env_flag("SCC_BENCH_HANG"))
    if hang:
        time.sleep(hang)
    # heartbeats default ON for bench workers (like SCC_OBS_COST below);
    # the in-process stall watchdog dumps stacks at half the orchestrator
    # window, so the stream holds the wedged stack before the reap
    os.environ.setdefault("SCC_OBS_HEARTBEAT", "5")
    os.environ.setdefault("SCC_OBS_STALL_S", str(
        max(60.0, float(env_flag("SCC_BENCH_STALL_S")) / 2)
    ))
    from scconsensus_tpu.obs.live import LiveRecorder

    _LIVE = LiveRecorder(
        _live_base(), metric="bench flight record",
        extra={"config": env_flag("SCC_BENCH_CONFIG")},
    ).start()
    ok = False
    try:
        _worker_body()
        ok = True
    finally:
        # a clean pass overwrites the standing crash-stamped partial; an
        # exception leaves cause="crash" with the open-span stack
        _LIVE.stop("clean" if ok else "crash")


def _worker_body() -> None:
    # cost attribution on by default for bench workers: the run record's
    # stages carry XLA cost_analysis flops/bytes, so the ledger can report
    # achieved vs. cost-model throughput (one memoized AOT compile per
    # kernel shape; steady-state walls are unaffected)
    os.environ.setdefault("SCC_OBS_COST", "1")
    # numeric-health sentinels on by default too (obs.quality): a NaN mid-
    # pipeline must land span-attributed on the run record, not in labels
    os.environ.setdefault("SCC_OBS_NUMERIC", "1")
    # residency audit on by default (obs.residency): every bench record
    # carries span-attributed transfer accounting, so the perf gate can
    # baseline per-stage transfer bytes alongside walls. Audit, not
    # enforce: a bench must measure a violation, not die of it.
    os.environ.setdefault("SCC_OBS_RESIDENCY", "audit")
    # host observatory on by default (round 19): sampled host stacks +
    # GC pauses + memory timeline (obs.hostprof) and compile/retrace
    # telemetry (obs.compilelog) land on every bench record; overhead is
    # pinned under the perf gate's noise floor by test
    os.environ.setdefault("SCC_HOSTPROF", "1")
    os.environ.setdefault("SCC_COMPILELOG", "1")
    # compiled-program observatory on by default (round 24): every bench
    # record carries per-program graph passports (obs.graphs) so the
    # transfer-op ratchet has a candidate side. serve never sets this.
    os.environ.setdefault("SCC_GRAPHS", "1")

    import jax

    plat = env_flag("SCC_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update(
        "jax_compilation_cache_dir",
        env_flag("SCC_JAX_CACHE_DIR") or _JAX_CACHE_DIR,
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    try:
        # arm AFTER jax is importable: the jax.monitoring listener
        # install is deferred until jax is in sys.modules
        from scconsensus_tpu.obs import compilelog

        compilelog.install_and_mark()
    except Exception as e:
        log(f"[bench] compile-log arm failed: {e!r}")
    try:
        # same deferral: passport capture lowers+compiles through jax
        from scconsensus_tpu.obs import graphs

        graphs.install_and_mark()
    except Exception as e:
        log(f"[bench] graph-passport arm failed: {e!r}")
    try:
        # start AFTER jax finishes importing: the sampler thread probes
        # sys.modules for the xla bridge every tick, and launching it
        # mid-import would race the interpreter's partially-initialized
        # jax module graph
        from scconsensus_tpu.obs import hostprof

        hostprof.start_if_enabled()
    except Exception as e:
        log(f"[bench] hostprof start failed: {e!r}")

    name = env_flag("SCC_BENCH_CONFIG")
    degraded = bool(env_flag("SCC_BENCH_DEGRADED"))
    cfg = dict(CONFIGS[name])
    if degraded and name in DEGRADED:
        cfg.update(DEGRADED[name])
    kind = cfg.pop("kind")
    t_init = time.perf_counter()
    platform = jax.devices()[0].platform
    init_s = time.perf_counter() - t_init
    log(f"[bench] config={name} platform={platform} init={init_s:.1f}s"
        f" degraded={degraded}")
    extra = {"platform": platform, "config": name, "degraded": degraded,
             "backend_init_s": round(init_s, 1)}
    if _LIVE is not None:  # refine the stream's run key now the backend
        _LIVE.annotate(platform=platform, degraded=degraded)  # answered

    if kind == "brain1m":
        bn = 100_000 if degraded else 1_000_000  # CPU fallback stays bounded
        extra["size_reduced"] = bn < 1_000_000

        def _b1m_record(secs):
            # nominal target: 1M cells through the approx-hierarchical path
            # in 300 s (no published reference numbers exist, SURVEY.md §6).
            # This is the clustering tail only (r7: landmark recluster —
            # sketch Lloyd+weighted Ward+device assignment — +cut+
            # silhouette on an embedding), NOT consensus+DE at 1M — the
            # metric string says exactly what ran (VERDICT r4 weak #5).
            reduced = extra.get("degraded") or extra.get("size_reduced")
            cold = b1m_state.get("phase") == "cold"
            return build_run_record(
                metric=f"{bn // 1000}k-cell landmark recluster+cut+"
                       "silhouette throughput (clustering tail only)"
                       + (" COLD (incl. XLA compiles)" if cold else ""),
                value=round(bn / secs) if secs else -1.0,
                unit="cells/sec",
                vs_baseline=(round((bn / secs) / (1_000_000 / 300.0), 3)
                             if secs and not reduced else None),
                extra=extra,
                spans=b1m_state.get("spans") or [],
                robustness=_robust_section(),
                integrity=_integrity_section(),
            )

        b1m_state = {"secs": None, "phase": "cold", "spans": None}
        _install_term_handler(lambda: _b1m_record(b1m_state["secs"]))
        if _LIVE is not None:
            _LIVE.record_fn = lambda: _b1m_record(b1m_state["secs"])
        once = run_brain1m(n_cells=bn)
        cold_s, cold_info, cold_spans = once()
        log(f"[bench] cold run: {cold_s:.2f}s {cold_info}")
        extra["cold_s"] = round(cold_s, 3)
        b1m_state["secs"] = cold_s
        b1m_state["spans"] = cold_spans
        extra.update(cold_info)
        if env_flag("SCC_BENCH_COLD"):
            elapsed, info = cold_s, cold_info
        else:
            _emit_partial(_b1m_record(cold_s))
            elapsed, info, steady_spans = once()
            # secs BEFORE phase: a SIGTERM between the two must not emit
            # the cold number under a steady-labeled metric
            b1m_state["secs"] = elapsed
            b1m_state["spans"] = steady_spans
            b1m_state["phase"] = "steady"
        log(f"[bench] steady: {elapsed:.2f}s {info}")
        extra.update(info)
        final = _finalize(_b1m_record(elapsed))
        _write_ckpt(final)
        print(json.dumps(final))
        if env_flag("SCC_BENCH_NO_FORK"):
            _ingest_evidence(final)
        return

    if kind == "stream10m":
        # out-of-core streaming refine against a disk-resident chunked
        # CSR store: the measurement the `streaming` section evidences.
        import shutil as _shutil
        import tempfile as _tempfile

        from scconsensus_tpu.stream.budget import HostBudgetAccountant
        from scconsensus_tpu.stream.runner import streaming_refine
        from scconsensus_tpu.stream.soak import (
            chunk_generator,
            consensus_input,
        )
        from scconsensus_tpu.stream.store import ChunkedCSRStore
        from scconsensus_tpu.config import ReclusterConfig

        sn, sg, sk = cfg["n_cells"], cfg["n_genes"], cfg["n_clusters"]
        density = cfg.get("density", 0.02)
        refine_kw = dict(cfg.get("refine_kw") or {})
        stream_root = env_flag("SCC_STREAM_DIR") or _tempfile.mkdtemp(
            prefix="scc-brain10m-"
        )
        ephemeral = not env_flag("SCC_STREAM_DIR")
        window = int(env_flag("SCC_STREAM_WINDOW"))
        extra["n_cells"], extra["n_genes"] = sn, sg
        extra["row_window"] = window
        s10_state = {"secs": None, "phase": "cold", "spans": None,
                     "streaming": None, "robustness": None}

        def _s10_record(secs):
            cold = s10_state["phase"] == "cold"
            return build_run_record(
                metric=(f"{sn // 1000}k-cell OUT-OF-CORE streaming "
                        "refine (disk-chunked CSR, bounded host memory)"
                        + (" COLD (incl. chunk ingest + XLA compiles)"
                           if cold else "")),
                value=round(sn / secs) if secs else -1.0,
                unit="cells/sec",
                extra=extra,
                spans=s10_state.get("spans") or [],
                streaming=s10_state.get("streaming"),
                robustness=(s10_state.get("robustness")
                            or _robust_section()),
                integrity=_integrity_section(),
            )

        _install_term_handler(lambda: _s10_record(s10_state["secs"]))
        if _LIVE is not None:
            _LIVE.record_fn = lambda: _s10_record(s10_state["secs"])
        gen = chunk_generator(sg, sn, sk, seed=11, density=density)
        labels = consensus_input(sn, sk, seed=11)
        chunks_dir = os.path.join(stream_root, "chunks")
        config = ReclusterConfig(
            method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25,
            min_pct=5.0, deep_split_values=(1, 2), min_cluster_size=10,
            n_top_de_genes=20, random_seed=11, **refine_kw,
        )

        def _s10_once(tag):
            # fresh stage dir per measurement: steady prices the full
            # streaming refine against the DURABLE chunk store (the
            # ingest is the cold run's cost), never a stage-artifact
            # short-circuit
            stage_dir = os.path.join(stream_root, f"stages-{tag}")
            _shutil.rmtree(stage_dir, ignore_errors=True)
            # fresh store INSTANCE per measurement: chunk counters are
            # per-run, so the steady record honestly reports its chunks
            # as resumed (adopted from the cold run's durable ingest)
            chunks = ChunkedCSRStore.create(chunks_dir, sg, sn, window)
            acct = HostBudgetAccountant()
            t0 = time.perf_counter()
            result = streaming_refine(
                chunks, labels, config, stage_dir=stage_dir,
                accountant=acct, regen=gen,
            )
            elapsed = time.perf_counter() - t0
            return elapsed, result

        try:
            cold_s, cold_res = _s10_once("cold")
            sm = cold_res.metrics["streaming"]
            log(f"[bench] brain10m cold (ingest + compiles): "
                f"{cold_s:.2f}s  chunks={sm['chunks']}  "
                f"peak_rss={sm['budget']['peak_rss_mb']:.0f}MB"
                f"/{sm['budget']['limit_mb']:.0f}MB")
            extra["cold_s"] = round(cold_s, 3)
            s10_state.update(secs=cold_s,
                             spans=cold_res.metrics.get("spans"),
                             streaming=sm,
                             robustness=cold_res.metrics.get(
                                 "robustness"))
            del cold_res
            if env_flag("SCC_BENCH_COLD"):
                elapsed = cold_s
            else:
                _emit_partial(_s10_record(cold_s))
                elapsed, res = _s10_once("steady")
                sm = res.metrics["streaming"]
                s10_state.update(secs=elapsed,
                                 spans=res.metrics.get("spans"),
                                 streaming=sm,
                                 robustness=res.metrics.get(
                                     "robustness"))
                s10_state["phase"] = "steady"
                extra["clusters"] = {
                    f"ds{d['deep_split']}": d["n_clusters"]
                    for d in res.deep_split_info
                }
                extra["silhouette"] = res.deep_split_info[-1].get(
                    "silhouette")
                del res
            extra["peak_rss_mb"] = sm["budget"]["peak_rss_mb"]
            extra["within_budget"] = sm["budget"]["within_budget"]
            if not sm["budget"]["within_budget"]:
                # the bounded-memory contract is the config's POINT: an
                # over-budget run still records honestly (the validator
                # only rejects CLAIMING within_budget), but the driver
                # tail must say so
                log(f"[bench] brain10m peak RSS "
                    f"{sm['budget']['peak_rss_mb']:.0f}MB OVER the "
                    f"{sm['budget']['limit_mb']:.0f}MB budget")
            log(f"[bench] brain10m steady: {elapsed:.2f}s "
                f"({round(sn / elapsed)} cells/sec)")
        finally:
            if ephemeral:
                _shutil.rmtree(stream_root, ignore_errors=True)
        final = _finalize(_s10_record(elapsed))
        _write_ckpt(final)
        print(json.dumps(final))
        if env_flag("SCC_BENCH_NO_FORK"):
            _ingest_evidence(final)
        return

    if kind == "atlas_query":
        # the serve path as a batch label-transfer workload: seeded
        # gaussian atlas → frozen consensus-model artifact → a replica
        # fleet behind the wire front → a replayable query pump over
        # HTTP. The headline is steady query throughput; the record's
        # serving section (wire + fleet accounting, p99) rides the
        # replica-keyed serving gate like any other baseline.
        import shutil as _shutil
        import tempfile as _tempfile

        from scconsensus_tpu.serve.fleet.soak import run_fleet_soak

        replicas = int(env_flag("SCC_FLEET_REPLICAS"))
        extra["replicas"] = replicas
        extra["size_reduced"] = degraded
        n_query_cells = cfg["n_queries"] * cfg["cells_per"]
        aq_state = {"secs": None, "serving": None, "phase": "cold"}

        def _aq_record(secs):
            cold = aq_state["phase"] == "cold"
            return build_run_record(
                metric=(f"atlas→query label transfer over the wire "
                        f"front ({cfg['n_queries']} batches × "
                        f"{cfg['cells_per']} cells, {replicas} "
                        f"replica(s))"
                        + (" COLD (incl. atlas build + XLA compiles)"
                           if cold else "")),
                value=round(n_query_cells / secs) if secs else -1.0,
                unit="cells/sec",
                extra=extra,
                serving=aq_state["serving"],
                robustness=_robust_section(),
                integrity=_integrity_section(),
            )

        _install_term_handler(lambda: _aq_record(aq_state["secs"]))
        if _LIVE is not None:
            _LIVE.record_fn = lambda: _aq_record(aq_state["secs"])
        workdir = _tempfile.mkdtemp(prefix="scc-atlas-query-")
        try:
            def _aq_once(fresh):
                t0 = time.perf_counter()
                summary = run_fleet_soak(
                    workdir, n_requests=cfg["n_queries"],
                    cells_per=cfg["cells_per"], seed=7,
                    replicas=replicas, n_ood=cfg["n_ood"],
                    n_genes=cfg["n_genes"],
                    n_clusters=cfg["n_clusters"],
                    n_train=cfg["n_train"], fresh=fresh,
                )
                if not summary["ok"]:
                    raise RuntimeError(
                        "atlas_query wire soak broke the accounting "
                        f"contract: {summary['outcome_counts']}"
                    )
                return time.perf_counter() - t0, summary

            cold_s, cold_sum = _aq_once(fresh=True)
            log(f"[bench] atlas_query cold (atlas build + compiles): "
                f"{cold_s:.2f}s")
            extra["cold_s"] = round(cold_s, 3)
            extra["model_fp"] = cold_sum["fp_v1"]
            aq_state["secs"] = cold_s
            aq_state["serving"] = (cold_sum.get("record")
                                   or {}).get("serving")
            if env_flag("SCC_BENCH_COLD"):
                elapsed = cold_s
            else:
                _emit_partial(_aq_record(cold_s))
                elapsed, steady_sum = _aq_once(fresh=False)
                aq_state["secs"] = elapsed
                aq_state["serving"] = (steady_sum.get("record")
                                       or {}).get("serving")
                aq_state["phase"] = "steady"
                sv = aq_state["serving"] or {}
                extra["serve_p99_ms"] = (sv.get("latency_ms")
                                         or {}).get("p99")
                extra["serve_throughput_rps"] = sv.get("throughput_rps")
                extra["outcome_counts"] = steady_sum["outcome_counts"]
                log(f"[bench] atlas_query steady: {elapsed:.2f}s "
                    f"p99={extra['serve_p99_ms']}ms "
                    f"outcomes={extra['outcome_counts']}")
        finally:
            _shutil.rmtree(workdir, ignore_errors=True)
        final = _finalize(_aq_record(elapsed))
        _write_ckpt(final)
        print(json.dumps(final))
        if env_flag("SCC_BENCH_NO_FORK"):
            _ingest_evidence(final)
        return

    if kind == "scenario":
        # workload-zoo scenario (workloads/): the runner owns dataset
        # generation, input-labeling construction, and scenario scoring;
        # bench owns the cold/steady protocol, the record assembly, and
        # the ledger ingest — so a scenario is gated and baselined like
        # any other config.
        from scconsensus_tpu.workloads import get_scenario, run_scenario

        sc_name = cfg["scenario"]
        sc = get_scenario(sc_name)
        smoke = degraded  # degraded attempts run the ≤5k smoke shape
        extra["size_reduced"] = smoke
        sc_state = {"outcome": None, "phase": "cold"}

        def _sc_record():
            out = sc_state["outcome"]
            cold = sc_state["phase"] == "cold"
            if out is None:
                return build_run_record(
                    metric=(f"workload-zoo scenario {sc_name}: no run "
                            "finished"),
                    value=-1.0, unit=sc.unit, extra=extra,
                    robustness=_robust_section(),
                    integrity=_integrity_section(),
                )
            return build_run_record(
                metric=out.metric
                + (" COLD (incl. XLA compiles)" if cold else ""),
                value=out.value, unit=out.unit, extra=extra,
                spans=out.spans,
                quality=out.quality,
                serving=out.serving,
                scenario=out.scenario,
                residency=out.residency,
                kernels=out.kernels,
                robustness=out.robustness or _robust_section(),
                integrity=out.integrity or _integrity_section(),
            )

        _install_term_handler(_sc_record)
        if _LIVE is not None:
            _LIVE.record_fn = _sc_record
        out_cold = run_scenario(sc_name, smoke=smoke)
        extra["cold_s"] = out_cold.extra.get("elapsed_s")
        sc_state["outcome"] = out_cold
        log(f"[bench] scenario {sc_name} cold: "
            f"{out_cold.value} {out_cold.unit}")
        if not env_flag("SCC_BENCH_COLD"):
            _emit_partial(_sc_record())
            out_steady = run_scenario(sc_name, smoke=smoke)
            # outcome BEFORE phase: a SIGTERM between the two must not
            # emit the cold outcome under a steady-labeled metric
            sc_state["outcome"] = out_steady
            sc_state["phase"] = "steady"
            log(f"[bench] scenario {sc_name} steady: "
                f"{out_steady.value} {out_steady.unit}")
        # the winning run's scalar extras ride the record (headline
        # scores land in quality.scenario; these are the tail facts)
        extra.update({
            k: v for k, v in sc_state["outcome"].extra.items()
            if isinstance(v, (int, float, str, bool))
        })
        final = _finalize(_sc_record())
        _write_ckpt(final)
        print(_trim_line(final))
        if env_flag("SCC_BENCH_NO_FORK"):
            _ingest_evidence(final)
        return

    if name == "flagship":  # env overrides for ad-hoc scaling runs
        cfg["n_cells"] = int(env_flag("SCC_BENCH_CELLS") or cfg["n_cells"])
        cfg["n_genes"] = int(env_flag("SCC_BENCH_GENES") or cfg["n_genes"])
        cfg["n_clusters"] = int(
            env_flag("SCC_BENCH_CLUSTERS") or cfg["n_clusters"]
        )
    refine_kw = cfg.pop("refine_kw", {})
    log(f"[bench] generating synthetic data: {cfg}")
    # The 30 s bar prices the FULL-SIZE workload: anything smaller (the
    # quick config, DEGRADED shrinks, env-var shrinks) must record
    # vs_baseline=null, not a flattering ratio.
    nominal = CONFIGS["flagship" if kind == "flagship" else name]
    extra["size_reduced"] = any(
        cfg.get(k, 0) < v for k, v in nominal.items()
        if k in ("n_cells", "n_genes", "n_clusters", "n_way")
    )

    if kind == "flagship":
        n_cells = cfg["n_cells"]
        size = f"{n_cells // 1000}k" if n_cells >= 1000 else str(n_cells)
        state = {"edger": None, "wilcox": None, "spans": None,
                 "quality": None, "residency": None, "kernels": None,
                 "robustness": None, "integrity": None}

        def _record():
            """Cumulative flagship record from whatever has finished."""
            elapsed, wilcox_s = state["edger"], state["wilcox"]
            if elapsed is not None:
                metric = (f"{size}-cell reclusterDEConsensus(edgeR) "
                          "end-to-end wall-clock")
                value = round(elapsed, 3)
                vsb = _vsb(value, extra)
            elif extra.get("edger_cold_s"):
                # Steady-state never ran (e.g. the tunnel window closed
                # right after the cold run): the cold number is still a
                # real end-to-end measurement on the platform — record it
                # rather than value=-1. vs_baseline stays honest (computed
                # against the same 30 s bar; compile time included).
                metric = (f"{size}-cell reclusterDEConsensus(edgeR) "
                          "end-to-end COLD (incl. XLA compiles)")
                value = float(extra["edger_cold_s"])
                vsb = _vsb(value, extra)
            elif wilcox_s is not None:
                # edgeR missing/failed: fall back to the wilcox flagship so
                # the driver still records a real number. vs_baseline is
                # null: the 30 s baseline prices the edgeR workload, not
                # the fast path — dividing it by the wilcox time would
                # report an inflated speedup masking the regression.
                metric = (f"{size}-cell reclusterDEConsensusFast(wilcox) "
                          "wall-clock")
                value = round(wilcox_s, 3)
                vsb = None
            else:
                metric = f"{size}-cell flagship: no section finished (see extra)"
                value = -1.0
                vsb = None
            return build_run_record(
                metric=metric, value=value, unit="seconds",
                vs_baseline=vsb, extra=extra,
                spans=state.get("spans") or [],
                quality=state.get("quality"),
                residency=state.get("residency"),
                kernels=state.get("kernels"),
                # completed run's trail, else the LIVE trail (a SIGTERM
                # partial must carry the faults/retries of the run it
                # interrupted, not of the previous one)
                robustness=state.get("robustness") or _robust_section(),
                integrity=(state.get("integrity")
                           or _integrity_section()),
            )

        def _ckpt():
            _emit_partial(_record())

        def _stage_dict(result):
            return {
                s["stage"]: round(s["wall_s"], 3)
                for s in result.metrics.get("stages", [])
                if "wall_s" in s
            }

        _install_term_handler(_record)
        if _LIVE is not None:  # partial flushes carry the cumulative record
            _LIVE.record_fn = _record
        _ckpt()  # records platform + backend init before any heavy work

        # headline: the literal north-star workload — slow-path edgeR
        def _edger():
            once_edger = run_refine_config(**cfg, method="edgeR", **refine_kw)
            cold_s, cold_res = once_edger()
            log(f"[bench] edgeR cold (incl. XLA compiles): {cold_s:.2f}s")
            extra["edger_cold_s"] = round(cold_s, 3)
            # cold spans so a COLD record (or a SIGTERM before steady-state
            # lands) still carries a span tree; steady overwrites below.
            # Keep only the spans + robustness trail — the full cold
            # result must not stay resident through the measured steady
            # run. Cold-run recovery evidence matters: a one-shot fault
            # plan usually fires (and is survived) in the cold run only.
            state["spans"] = cold_res.metrics.get("spans")
            state["robustness"] = cold_res.metrics.get("robustness")
            del cold_res
            if env_flag("SCC_BENCH_COLD"):
                return cold_s
            _ckpt()  # the cold number survives even if steady-state dies
            if env_flag("SCC_BENCH_CRASH") == "edger_steady":
                raise RuntimeError("injected crash (SCC_BENCH_CRASH)")
            elapsed, result = once_edger()
            log(f"[bench] edgeR steady-state: {elapsed:.2f}s")
            extra["edger_stages"] = _stage_dict(result)
            extra["union_size"] = int(result.de_gene_union_idx.size)
            _stamp_fingerprint(extra, result)
            # the headline workload's span tree + quality/residency/
            # kernels sections ride the run record
            state["spans"] = result.metrics.get("spans") or state["spans"]
            state["quality"] = result.metrics.get("quality")
            state["residency"] = result.metrics.get("residency")
            state["kernels"] = result.metrics.get("kernels")
            # a healthy steady run (None) must not erase the cold run's
            # recovery evidence
            state["robustness"] = (result.metrics.get("robustness")
                                   or state["robustness"])
            state["integrity"] = (result.metrics.get("integrity")
                                  or state["integrity"])
            return elapsed

        state["edger"] = _section(extra, "edger", _edger)
        _ckpt()

        # secondary: fast-path wilcox at the same scale
        def _wilcox():
            once_fast = run_refine_config(**cfg, method="wilcox", **refine_kw)
            fast_cold, cold_fast_res = once_fast()
            extra["wilcox_cold_s"] = round(fast_cold, 3)
            if not state["robustness"]:
                state["robustness"] = cold_fast_res.metrics.get(
                    "robustness")
            del cold_fast_res
            _ckpt()
            fast_s, fast_res = once_fast()
            log(f"[bench] wilcox fast-path steady-state: {fast_s:.2f}s")
            extra["wilcox_s"] = round(fast_s, 3)
            extra["wilcox_stages"] = _stage_dict(fast_res)
            # the migrated occupancy metrics (window ladder) ride the
            # flagship record too, not just the refine configs
            occ = next(
                (s["occupancy"] for s in fast_res.metrics.get("stages", [])
                 if "occupancy" in s), None,
            )
            if occ is not None:
                extra["wilcox_occupancy"] = occ
            if not state["spans"]:  # edgeR section died: wilcox spans stand in
                state["spans"] = fast_res.metrics.get("spans")
            if not state["quality"]:
                state["quality"] = fast_res.metrics.get("quality")
            if not state["residency"]:
                state["residency"] = fast_res.metrics.get("residency")
            if not state["kernels"]:
                state["kernels"] = fast_res.metrics.get("kernels")
            if not state["robustness"]:
                state["robustness"] = fast_res.metrics.get("robustness")
            return fast_s

        state["wilcox"] = _section(extra, "wilcox", _wilcox)
        _ckpt()
        _GEN_CACHE.clear()  # both consumers done; free ~1.5 GB before probes

        if not degraded and name != "quick":
            mfu = _section(extra, "mfu", lambda: mfu_probes(platform))
            if mfu is not None:
                extra["mfu"] = mfu
            _ckpt()
        if platform == "tpu" or env_flag("SCC_BENCH_PALLAS"):
            pv = _section(extra, "pallas", pallas_vs_xla_probe)
            if pv is not None:
                extra["pallas_vs_xla"] = pv

        final = _finalize(_record())
        _write_ckpt(final)  # final checkpoint is the complete record
        print(_trim_line(final))
        if env_flag("SCC_BENCH_NO_FORK"):
            _ingest_evidence(final)
        return

    n_cells = cfg["n_cells"]

    def _refine_record(secs):
        cold = refine_state.get("phase") == "cold"
        return build_run_record(
            metric=(
                f"{n_cells // 1000}k" if n_cells >= 1000 else str(n_cells)
            ) + f"-cell end-to-end consensus+recluster wall-clock ({name})"
            + (" COLD (incl. XLA compiles)" if cold else ""),
            value=round(secs, 3) if secs else -1.0,
            unit="seconds",
            vs_baseline=_vsb(secs, extra),
            extra=extra,
            spans=refine_state.get("spans") or [],
            quality=refine_state.get("quality"),
            residency=refine_state.get("residency"),
            kernels=refine_state.get("kernels"),
            robustness=(refine_state.get("robustness")
                        or _robust_section()),
        )

    refine_state = {"secs": None, "phase": "cold", "spans": None,
                    "quality": None, "residency": None, "kernels": None,
                    "robustness": None}
    _install_term_handler(lambda: _refine_record(refine_state["secs"]))
    if _LIVE is not None:
        _LIVE.record_fn = lambda: _refine_record(refine_state["secs"])
    once = run_refine_config(**cfg, **refine_kw)
    cold_s, cold_res = once()
    log(f"[bench] cold run (includes XLA compiles): {cold_s:.2f}s")
    extra["cold_s"] = round(cold_s, 3)
    refine_state["secs"] = cold_s
    # spans + quality only; drop the cold result before the measured
    # steady run
    refine_state["spans"] = cold_res.metrics.get("spans")
    refine_state["quality"] = cold_res.metrics.get("quality")
    refine_state["robustness"] = cold_res.metrics.get("robustness")
    del cold_res
    if env_flag("SCC_BENCH_COLD"):
        elapsed = cold_s
    else:
        _emit_partial(_refine_record(cold_s))
        elapsed, result = once()
        # secs BEFORE phase: a SIGTERM between the two must not emit the
        # cold number under a steady-labeled metric
        refine_state["secs"] = elapsed
        refine_state["spans"] = result.metrics.get("spans")
        refine_state["quality"] = result.metrics.get("quality")
        refine_state["residency"] = result.metrics.get("residency")
        refine_state["kernels"] = result.metrics.get("kernels")
        # a healthy steady run (None) must not erase the cold run's
        # recovery evidence (one-shot fault plans fire in the cold run)
        refine_state["robustness"] = (result.metrics.get("robustness")
                                      or refine_state["robustness"])
        refine_state["phase"] = "steady"
        log(f"[bench] steady-state run: {elapsed:.2f}s; union="
            f"{result.de_gene_union_idx.size} genes; "
            f"deep_split_info={result.deep_split_info}")
        _stamp_fingerprint(extra, result)
        extra["stages"] = {
            s["stage"]: round(s["wall_s"], 3)
            for s in result.metrics.get("stages", [])
            if "wall_s" in s
        }
        # the rank-sum window-ladder occupancy probe (engine r6) rides the
        # stage records; committing it makes every refine artifact carry
        # its own ladder diagnosis
        occ = next(
            (s["occupancy"] for s in result.metrics.get("stages", [])
             if "occupancy" in s), None,
        )
        if occ is not None:
            extra["wilcox_occupancy"] = occ
        sil = [
            {k: d[k] for k in ("deep_split", "silhouette",
                               "silhouette_method") if k in d}
            for d in result.deep_split_info
        ]
        if any("silhouette" in d for d in sil):
            extra["silhouette"] = sil
    final = _finalize(_refine_record(elapsed))
    _write_ckpt(final)
    print(json.dumps(final))
    if env_flag("SCC_BENCH_NO_FORK"):
        _ingest_evidence(final)


# --------------------------------------------------------------------------
# orchestrator
# --------------------------------------------------------------------------

# handle of the currently-running worker, for the SIGTERM emergency path
_CURRENT_WORKER: "subprocess.Popen | None" = None


def _heartbeat_progress(hb_path: str,
                        min_unix: float) -> "tuple[float, float] | None":
    """(progress_unix, line_ts) from the flight-recorder stream's tail, or
    None when no stream fresh for THIS attempt exists. This is the
    watchdog's PRIMARY liveness signal: unlike cache-dir mtimes it cannot
    be faked by an unrelated JAX process, and unlike raw file mtime it
    distinguishes "sampler thread alive" from "run thread making
    progress" — a worker wedged inside a dead device RPC keeps
    heartbeating (the C++ wait releases the GIL) with a frozen
    ``progress_unix``, which is exactly a stall. ``line_ts`` lets the
    caller notice the STREAM itself going quiet (sampler dead, disk
    full), which re-engages the fallback signals."""
    try:
        from scconsensus_tpu.obs.live import read_heartbeat_tail

        tail = read_heartbeat_tail(hb_path)
    except Exception:
        return None
    if not tail:
        return None
    ts = float(tail.get("ts") or 0.0)
    if ts < min_unix:
        return None  # stale stream from a previous attempt/run
    kind = tail.get("t")
    if kind == "hb":
        return float(tail.get("progress_unix") or ts), ts
    if kind == "stall":
        # the stall event's own ts is NOT progress; back out the moment
        # progress actually stopped
        return ts - float(tail.get("since_progress_s") or 0.0), ts
    # header / annotate / end: the line itself is fresh worker activity
    return ts, ts


# How quiet the heartbeat stream may go before the orchestrator stops
# trusting it as the sole liveness signal and re-engages the fallbacks.
_HB_QUIET_S = 60.0


def _last_json_line(text: str) -> dict | None:
    """Newest parseable JSON line. Keeps scanning past decode errors: a
    SIGKILL mid-print truncates the final line, but the cumulative partial
    printed just before it is complete and is the evidence we want."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _sweep_attempt_caches() -> None:
    """Bank per-attempt compile caches back into the shared dir, then remove
    attempt dirs owned by this process or by dead ones. New entries
    hardlink into the shared cache so cross-window compile banking survives
    the per-attempt cache isolation the stall watchdog needs (ADVICE r4: a
    concurrent JAX process writing the shared dir must not read as worker
    liveness). Live foreign orchestrators keep theirs."""
    import re
    import shutil

    base = os.path.dirname(_JAX_CACHE_DIR) or "/tmp"
    prefix = os.path.basename(_JAX_CACHE_DIR) + "_att"
    try:
        entries = list(os.scandir(base))
    except OSError:
        return
    for d in entries:
        if not d.name.startswith(prefix) or not d.is_dir():
            continue
        m = re.match(re.escape(prefix) + r"(\d+)_", d.name)
        pid = int(m.group(1)) if m else 0
        if pid and pid != os.getpid():
            try:
                os.kill(pid, 0)
                continue  # owning orchestrator still alive — leave it be
            except ProcessLookupError:
                pass  # truly dead (ESRCH) — safe to bank + remove
            except OSError:
                continue  # EPERM etc.: alive but unsignalable — keep it
        try:
            os.makedirs(_JAX_CACHE_DIR, exist_ok=True)
            for e in os.scandir(d.path):
                dst = os.path.join(_JAX_CACHE_DIR, e.name)
                if not os.path.exists(dst):
                    try:
                        os.link(e.path, dst)
                    except OSError:
                        pass
            shutil.rmtree(d.path, ignore_errors=True)
        except OSError:
            pass


def _run_attempt(label: str, env_over: dict, timeout_s: int):
    """One worker subprocess attempt. Returns (parsed_json | None, failure).

    Worker stderr streams into a temp file (not a pipe) so a timed-out or
    killed worker still leaves its progress log behind for the failure
    record — a pipe's buffer dies with the process. A timed-out worker's
    checkpoint file (and its partial stdout lines) are recovered: a partial
    with a real headline value becomes the attempt's result.

    Stall watchdog: a remote-TPU tunnel can die MID-RUN, leaving the worker
    blocked forever inside a device RPC (zero CPU, no signal delivery into
    the C++ wait — observed as a 35-min dead hang). The orchestrator
    therefore tracks worker liveness (new stdout lines or a fresher
    checkpoint file) and aborts the attempt after SCC_BENCH_STALL_S
    (default 1200 s) without progress, so the ladder reaches its retry /
    CPU fallback while there is still wall-clock to use them."""
    import tempfile
    import threading

    global _CURRENT_WORKER
    env = dict(os.environ)
    env.update(env_over)
    timeout_s = max(1, int(timeout_s * _TIMEOUT_SCALE))
    cmd = [sys.executable, os.path.abspath(__file__), "--worker"]
    log(f"[bench] attempt '{label}' timeout={timeout_s}s env={env_over}")
    # Private per-attempt compile-cache dir, warm-started by hardlinking the
    # shared cache in: the watchdog's cache-liveness signal then counts ONLY
    # this worker's compiles (an unrelated concurrent JAX process writing
    # the shared dir can no longer keep a dead attempt alive — ADVICE r4),
    # while banked programs from earlier windows still hit. New entries are
    # linked back to the shared dir after the attempt.
    attempt_cache = env.get("SCC_JAX_CACHE_DIR")
    if not attempt_cache:
        import re

        _sweep_attempt_caches()  # bank + drop finished/dead dirs first
        tag = re.sub(r"[^A-Za-z0-9_-]", "_", label)
        attempt_cache = f"{_JAX_CACHE_DIR}_att{os.getpid()}_{tag}"
        try:
            os.makedirs(attempt_cache, exist_ok=True)
            os.makedirs(_JAX_CACHE_DIR, exist_ok=True)
            for e in os.scandir(_JAX_CACHE_DIR):
                try:
                    os.link(e.path, os.path.join(attempt_cache, e.name))
                except OSError:
                    pass
            env["SCC_JAX_CACHE_DIR"] = attempt_cache
        except OSError:
            attempt_cache = _JAX_CACHE_DIR  # degraded: shared-dir liveness
    t0 = time.perf_counter()
    t0_wall = time.time()
    with tempfile.NamedTemporaryFile("w+", suffix=".log", delete=True) as errf:
        def _err_tail(n=_TAIL_CHARS):
            errf.flush()
            errf.seek(0, os.SEEK_END)
            size = errf.tell()
            errf.seek(max(0, size - n))
            return errf.read()

        try:
            proc = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
                errors="replace",  # stray non-UTF-8 must not kill the drain
            )
            _CURRENT_WORKER = proc
            lines: list = []
            last_line_wall = [time.time()]

            def _drain(pipe):
                try:
                    for ln in pipe:
                        lines.append(ln)
                        last_line_wall[0] = time.time()
                except Exception as e:  # pipe closed on kill
                    log(f"[bench] stdout drain ended early: {e!r}")

            reader = threading.Thread(
                target=_drain, args=(proc.stdout,), daemon=True
            )
            reader.start()
            stall_s = float(env_flag("SCC_BENCH_STALL_S"))
            deadline = t0 + timeout_s
            outcome = None
            err_size = [0]
            err_grew = [0.0]
            from scconsensus_tpu.obs.live import heartbeat_path

            hb_path = heartbeat_path(_live_base())
            while proc.poll() is None:
                if time.perf_counter() >= deadline:
                    outcome = "timeout"
                    break
                activity = last_line_wall[0]
                try:
                    activity = max(activity, os.path.getmtime(_ckpt_path()))
                except OSError:
                    pass
                # PRIMARY liveness signal: the worker's flight-recorder
                # heartbeat stream (progress_unix = span transitions +
                # compile events, sampled in-process by obs.live). While
                # the stream is actively written, the indirect fallbacks
                # below are demoted; if it goes quiet (> _HB_QUIET_S —
                # sampler dead, stream unwritable) or never appeared
                # (SCC_OBS_HEARTBEAT=0, hung interpreter startup), they
                # re-engage so a silent stream cannot get a live worker
                # reaped.
                hb = _heartbeat_progress(hb_path, t0_wall)
                if hb is not None:
                    activity = max(activity, hb[0])
                hb_fresh = (hb is not None
                            and time.time() - hb[1] < _HB_QUIET_S)
                if not hb_fresh:
                    # FALLBACK: fresh persistent-cache entries + stderr
                    # growth. The cache dir is private to this attempt
                    # (hardlink-warmed above), so only THIS worker's
                    # compiles count; entries older than the attempt (the
                    # warm-start links keep their source mtimes) are not
                    # life either.
                    try:
                        activity = max(activity, max(
                            (m for m in (
                                e.stat().st_mtime
                                for e in os.scandir(attempt_cache)
                            ) if m >= t0_wall),
                            default=0.0,
                        ))
                    except OSError:
                        pass
                    try:
                        sz = os.fstat(errf.fileno()).st_size
                        if sz != err_size[0]:
                            err_size[0] = sz
                            err_grew[0] = time.time()
                        activity = max(activity, err_grew[0])
                    except OSError:
                        pass
                if time.time() - activity > stall_s:
                    outcome = "stall"
                    break
                try:  # wakes instantly on worker exit, unlike a flat sleep
                    proc.wait(timeout=min(
                        5.0, max(0.05, deadline - time.perf_counter())
                    ))
                except subprocess.TimeoutExpired:
                    pass
            if outcome is not None:
                if outcome == "stall":
                    log(f"[bench] attempt '{label}': no worker progress for "
                        f"{stall_s:.0f}s — aborting (tunnel stall?)")
                proc.terminate()  # gives the worker its SIGTERM checkpoint
                try:
                    proc.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    try:
                        proc.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        # uninterruptible sleep inside a dead device RPC:
                        # even SIGKILL is deferred. Abandon the zombie and
                        # let the ladder proceed — waiting forever here
                        # would burn the window the watchdog exists to save.
                        log(f"[bench] attempt '{label}': worker ignores "
                            "SIGKILL (uninterruptible RPC wait) — abandoning")
                reader.join(timeout=5)
                stdout = "".join(lines)
                partial = _best_partial(stdout, t0_wall)
                failure = {"attempt": label, "outcome": outcome,
                           "timeout_s": timeout_s, "stderr_tail": _err_tail()}
                hb_tail = None
                try:
                    from scconsensus_tpu.obs.live import read_heartbeat_tail

                    hb_tail = read_heartbeat_tail(hb_path)
                except Exception:
                    pass
                if hb_tail and float(hb_tail.get("ts") or 0) >= t0_wall:
                    # post-mortem: where the worker was when it was reaped
                    opens = hb_tail.get("open_spans") or []
                    failure["heartbeat"] = {
                        "last_t": hb_tail.get("t"),
                        "age_s": round(
                            time.time() - float(hb_tail.get("ts") or 0), 1
                        ),
                        "since_progress_s": hb_tail.get("since_progress_s"),
                        "last_span": opens[-1]["name"] if opens else None,
                        "stalls": hb_tail.get("stalls"),
                    }
                if _record_value(partial) > 0:
                    partial.setdefault("extra", {})["attempt"] = label
                    partial["extra"]["partial"] = True
                    partial["extra"]["attempt_outcome"] = outcome
                    return partial, None
                if partial is not None:
                    failure["partial"] = True
                return None, failure
            reader.join(timeout=10)
            stdout = "".join(lines)
        finally:
            _CURRENT_WORKER = None
        wall = time.perf_counter() - t0
        errf.flush()
        errf.seek(0)
        for line in errf.read().splitlines():
            log(f"[worker] {line}")
        if proc.returncode == 0:
            parsed = _last_json_line(stdout)
            if parsed is not None:
                parsed.setdefault("extra", {})["attempt"] = label
                parsed["extra"]["attempt_wall_s"] = round(wall, 1)
                return parsed, None
            return None, {"attempt": label, "outcome": "no-json",
                          "rc": 0,
                          "stdout_tail": (stdout or "")[-_TAIL_CHARS:]}
        # crashed worker: partial lines printed before death still count,
        # as does a checkpoint written during this attempt
        partial = _best_partial(stdout, t0_wall)
        if _record_value(partial) > 0:
            partial.setdefault("extra", {})["attempt"] = label
            partial["extra"]["partial"] = True
            partial["extra"]["attempt_outcome"] = f"rc={proc.returncode}"
            return partial, None
        return None, {"attempt": label, "outcome": "error",
                      "rc": proc.returncode, "stderr_tail": _err_tail()}


def _probe_backend(timeout_s: int = 420) -> str:
    """Cheap subprocess probe of backend health before committing to the
    long primary attempt: a dead axon tunnel hangs backend init for >15 min
    (r03's rc=124), so a hung probe reroutes straight to the CPU fallback."""
    timeout_s = max(1, int(timeout_s * _TIMEOUT_SCALE))
    code = "import jax; print(jax.devices()[0].platform)"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return "hang"
    if proc.returncode != 0:
        return "error"
    return (proc.stdout or "").strip().splitlines()[-1] if proc.stdout else "?"


def _orchestrator_term_handler(t_start: float):
    """The driver's outer `timeout` TERMs the orchestrator, not the worker:
    forward the signal (triggering the worker's own checkpoint emit), then
    print the freshest checkpoint so rc=124 still parses."""
    import signal

    def _on_term(signum, frame):  # pragma: no cover - signal path
        try:
            proc = _CURRENT_WORKER
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
            rec = _read_ckpt(t_start)
            if rec is None:
                rec = build_run_record(
                    metric="bench terminated before any checkpoint",
                    value=-1, extra={"terminated": True},
                )
            rec.setdefault("extra", {})["partial"] = True
            rec["extra"]["terminated"] = True
            print(_trim_line(rec), flush=True)
        finally:
            os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass


def main() -> None:
    args = set(sys.argv[1:])
    if "--worker" in args:
        worker()
        return
    if "--quick" in args:
        os.environ.setdefault("SCC_BENCH_CONFIG", "quick")
        plan = ATTEMPT_PLANS["quick"]
    elif env_flag("SCC_BENCH_PLATFORM") == "cpu":
        # caller already pinned CPU: a single bounded attempt, no fallback
        plan = [("cpu", {}, 2400)]
    else:
        plan = ATTEMPT_PLANS["default"]
    if env_flag("SCC_BENCH_NO_FORK"):
        worker()
        return

    t_start = time.time()
    _orchestrator_term_handler(t_start)
    probe = None

    def _is_cpu_attempt(env_over: dict) -> bool:
        """An attempt is CPU-bound if its override pins CPU — or if the
        ambient env does and the override doesn't reclaim it."""
        return env_over.get(
            "SCC_BENCH_PLATFORM", env_flag("SCC_BENCH_PLATFORM")
        ) == "cpu"

    def _probe_disqualified(p: str, no_cpu_mode: bool) -> bool:
        """Shared rule for the initial probe and the post-stall re-probe:
        dead backends always disqualify; in no-cpu (accelerator-evidence)
        mode a probe that silently resolved to CPU disqualifies too."""
        return p in ("hang", "error") or (no_cpu_mode and p == "cpu")

    # SCC_BENCH_NO_CPU_FALLBACK=1: an accelerator-evidence run (the tunnel
    # watcher) — a CPU-degraded record must never overwrite TPU evidence,
    # so a dead tunnel fails fast instead of rerouting to CPU.
    no_cpu = bool(env_flag("SCC_BENCH_NO_CPU_FALLBACK"))
    if no_cpu:
        # an attempt is CPU-bound if its override pins CPU — or if the
        # ambient env does and the override doesn't reclaim it
        plan = [(l, e, t) for l, e, t in plan if not _is_cpu_attempt(e)]
        if not plan:  # e.g. --quick, whose only attempt is CPU-pinned
            rec = build_run_record(
                metric="no accelerator attempt in plan "
                       "(no-cpu-fallback mode)",
                value=-1,
            )
            _stamp_tunnel(rec)
            print(json.dumps(rec))
            return
    if plan is ATTEMPT_PLANS["default"] or no_cpu:
        probe = _probe_backend()
        log(f"[bench] backend probe: {probe}")
        # no-cpu mode also rejects a probe that silently resolved to the
        # CPU backend: the run exists to produce accelerator evidence.
        if _probe_disqualified(probe, no_cpu):
            if no_cpu:
                rec = build_run_record(
                    metric="backend probe failed (no-cpu-fallback mode)",
                    value=-1,
                    extra={"backend_probe": probe},
                )
                _stamp_tunnel(rec)
                print(json.dumps(rec))
                return
            # tunnel down: don't burn the primary/retry windows on a hung
            # backend init — go straight to the bounded CPU fallback
            plan = [("cpu-degraded", {"SCC_BENCH_PLATFORM": "cpu",
                                      "SCC_BENCH_DEGRADED": "1"}, 2400)]

    failures = []
    adaptations: list = []
    adapt_env: dict = {}
    poison = None
    for label, env_over, timeout_s in plan:
        # cause-aware ladder (robust round): adaptations earned by earlier
        # failures ride every later attempt (stall capture, degraded size)
        env_over = {**env_over, **adapt_env}
        accel_attempt = not _is_cpu_attempt(env_over)
        if (failures and accel_attempt
                and failures[-1].get("outcome") == "stall"):
            # The previous accelerator attempt STALLED — the dead-tunnel
            # signature (a plain timeout means slow-but-alive). Re-probe
            # before burning another accelerator window; if the backend is
            # dead now, fall through to whatever CPU attempt the plan still
            # holds (or fail fast in no-cpu mode) instead of stalling again.
            p2 = _probe_backend()
            log(f"[bench] re-probe after {failures[-1]['outcome']}: {p2}")
            if _probe_disqualified(p2, no_cpu):
                failures.append({"attempt": label,
                                 "outcome": "skipped-dead-backend",
                                 "reprobe": p2})
                continue
        parsed, failure = _run_attempt(label, env_over, timeout_s)
        # bank this attempt's fresh compiles into the shared cache NOW —
        # deferring to the next run risks stranding them behind a recycled
        # pid (the sweep would read the new owner as a live orchestrator)
        _sweep_attempt_caches()
        if parsed is not None and float(parsed.get("value", -1)) < 0:
            # A worker that swallowed every section's failure still exits
            # rc=0 with value=-1; treat that as a failed attempt so the
            # retry / cpu-degraded fallbacks get their turn.
            ex = parsed.get("extra", {})
            failure = {"attempt": label, "outcome": "all-sections-failed",
                       **{k: v for k, v in ex.items() if k.endswith("_error")}}
            parsed = None
        if parsed is not None:
            if failures:
                parsed["extra"]["prior_failures"] = failures[-_MAX_FAILURES:]
            if probe is not None:
                parsed["extra"]["backend_probe"] = probe
            # The stdout line `parsed` came from may already be trimmed
            # (the worker trims for the tail window); the worker's final
            # on-disk checkpoint is untrimmed. Merge so the evidence file
            # keeps the full extras (mfu/stages) AND the span tree plus
            # orchestrator stamps.
            disk = _read_ckpt(t_start)
            if disk is not None and disk.get("value") == parsed.get("value"):
                parsed["extra"] = {**disk.get("extra", {}),
                                   **parsed.get("extra", {})}
                if not parsed.get("spans") and disk.get("spans"):
                    parsed["spans"] = disk["spans"]
                # every section the worker's tail-trim can drop comes
                # back from the checkpoint — the evidence record must be
                # the full story (the round-22 profile/burn-down
                # sections ride or the attribution plane goes blind)
                for sec in ("robustness", "residency", "kernels",
                            "quality", "integrity", "serving", "loadgen",
                            "profile", "residency_burndown", "tunnel"):
                    if not parsed.get(sec) and disk.get(sec):
                        parsed[sec] = disk[sec]
            if failures or adaptations:
                # the attempt ladder's own recovery story rides the
                # validated robustness section (orchestration sub-object)
                rb = parsed.get("robustness") or {}
                rb["orchestration"] = {
                    "attempts": [
                        {"attempt": f.get("attempt"),
                         "outcome": f.get("outcome")} for f in failures
                    ] + [{"attempt": label, "outcome": "ok"}],
                    "adaptations": adaptations,
                }
                parsed["robustness"] = rb
            _write_ckpt(parsed)
            print(_trim_line(parsed))
            _ingest_evidence(parsed)
            return
        failures.append(failure)
        log(f"[bench] attempt '{label}' failed: {failure['outcome']}")
        if len([f for f in failures if f.get("outcome") == "error"]) >= 2:
            # two crash-class failures: the config is broken, not the
            # box — poison it with a named reason instead of burning the
            # remaining windows re-crashing
            poison = _poison_config(failures)
            break
        adapt = _adapt_from_failure(failure)
        if adapt is not None:
            adapt_env.update(adapt[0])
            adaptations.append({"after": label, "reason": adapt[1],
                                "env": adapt[0]})
            log(f"[bench] cause-aware adaptation: {adapt[1]}")

    # Every attempt failed. If any attempt left a value<=0 partial, surface
    # the freshest checkpoint's extras (platform, cold numbers) in the
    # failure record; then emit a structured line, never a traceback.
    rec = build_run_record(
        metric=("bench config poisoned after repeated crashes "
                "(see extra.poisoned)" if poison is not None
                else "bench failed on every attempt (see extra.failures)"),
        value=-1,
        extra={"failures": failures[-_MAX_FAILURES:]},
    )
    if poison is not None:
        rec["extra"]["poisoned"] = {"config": poison["config"],
                                    "reason": poison["reason"]}
    if failures or adaptations:
        rec["robustness"] = {"orchestration": {
            "attempts": [{"attempt": f.get("attempt"),
                          "outcome": f.get("outcome")} for f in failures],
            "adaptations": adaptations,
        }}
    if probe is not None:
        rec["extra"]["backend_probe"] = probe
    best = _read_ckpt(t_start)
    if best is not None:
        rec["extra"]["best_partial"] = {
            "metric": best.get("metric"), "value": best.get("value"),
            "extra": {k: v for k, v in best.get("extra", {}).items()
                      if isinstance(v, (int, float, str, bool))},
        }
    _stamp_tunnel(rec)
    print(_trim_line(rec))


if __name__ == "__main__":
    main()
