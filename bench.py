"""Benchmark harness: 26k-cell end-to-end refinement (the north-star config).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: BASELINE.json north star — 26k PBMC reclusterDEConsensus end-to-end
in < 30 s (vs_baseline = 30 / measured_seconds; > 1.0 beats the target).

Synthetic NB data with planted clusters stands in for the Zenodo 26k-PBMC
dataset (no network egress). Scale knobs via env: SCC_BENCH_CELLS,
SCC_BENCH_GENES, SCC_BENCH_CLUSTERS, SCC_BENCH_COLD=1 to report the
cold-compile run instead of steady state.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SECONDS = 30.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def run_once(data, labels1, labels2):
    from scconsensus_tpu import plot_contingency_table, recluster_de_consensus_fast

    t0 = time.perf_counter()
    consensus = plot_contingency_table(
        labels1, labels2, automate_consensus=True, filename=None
    )
    result = recluster_de_consensus_fast(
        data,
        consensus,
        method="wilcox",
        deep_split_values=(1, 2, 3, 4),
    )
    t1 = time.perf_counter()
    return t1 - t0, result


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/scc_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    n_cells = int(os.environ.get("SCC_BENCH_CELLS", 26000))
    n_genes = int(os.environ.get("SCC_BENCH_GENES", 15000))
    n_clusters = int(os.environ.get("SCC_BENCH_CLUSTERS", 22))

    from scconsensus_tpu.utils.synthetic import noisy_labeling, synthetic_scrna

    log(f"[bench] generating synthetic data: {n_genes} genes x {n_cells} cells, "
        f"{n_clusters} planted clusters on {jax.devices()[0].platform}")
    data, true_labels, _ = synthetic_scrna(
        n_genes=n_genes,
        n_cells=n_cells,
        n_clusters=n_clusters,
        n_markers_per_cluster=min(40, n_genes // n_clusters),
        seed=7,
    )
    labels1 = noisy_labeling(true_labels, 0.05, seed=1, prefix="sup")
    labels2 = noisy_labeling(
        true_labels, 0.10, n_out_clusters=max(2, n_clusters - 4), seed=2, prefix="unsup"
    )

    cold_s, _ = run_once(data, labels1, labels2)
    log(f"[bench] cold run (includes XLA compiles): {cold_s:.2f}s")
    if os.environ.get("SCC_BENCH_COLD"):
        elapsed = cold_s
    else:
        elapsed, result = run_once(data, labels1, labels2)
        log(f"[bench] steady-state run: {elapsed:.2f}s; union="
            f"{result.de_gene_union_idx.size} genes; "
            f"deep_split_info={result.deep_split_info}")

    print(json.dumps({
        "metric": (
            f"{n_cells // 1000}k" if n_cells >= 1000 else str(n_cells)
        ) + "-cell end-to-end consensus+recluster wall-clock",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
