"""Benchmark harness over the BASELINE.json configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Default config is the north star — 26k PBMC-scale consensus+recluster
end-to-end in < 30 s on one chip (vs_baseline = 30 / measured_seconds;
> 1.0 beats the target).

Select a config with SCC_BENCH_CONFIG:
  flagship  26k cells × 15k genes, K=22, fast Wilcoxon, exact Ward tree
  pbmc68k   68k cells × 15k genes, 3-way consensus (chained), fast Wilcoxon
  cite8k    8k cells, ADT-style coarse supervised × RNA unsupervised
  tm100k    100k cells, 40 clusters, centroid-pooled approximate tree
  brain1m   1M-cell embedding → pooled Ward + dynamic cut + ring silhouette
            (reports cells/sec; DE is out of scope for this config)

Synthetic NB data with planted clusters stands in for the public datasets
(no network egress). Extra knobs: SCC_BENCH_CELLS / _GENES / _CLUSTERS
override the flagship sizes; SCC_BENCH_COLD=1 reports the cold-compile run.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_SECONDS = 30.0


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _consensus(*labelings):
    """Chain plot_contingency_table across 2+ labelings (3-way consensus is
    consensus(consensus(l1, l2), l3) — the README's multi-tool workflow)."""
    from scconsensus_tpu import plot_contingency_table

    out = labelings[0]
    for nxt in labelings[1:]:
        out = plot_contingency_table(out, nxt, filename=None)
    return out


def _gen(n_cells, n_genes, n_clusters, seed=7):
    from scconsensus_tpu.utils.synthetic import synthetic_scrna

    return synthetic_scrna(
        n_genes=n_genes,
        n_cells=n_cells,
        n_clusters=n_clusters,
        n_markers_per_cluster=min(40, n_genes // n_clusters),
        seed=seed,
    )


def run_refine_config(n_cells, n_genes, n_clusters, n_way=2, **refine_kw):
    from scconsensus_tpu import recluster_de_consensus_fast
    from scconsensus_tpu.utils.synthetic import noisy_labeling

    data, truth, _ = _gen(n_cells, n_genes, n_clusters)
    labelings = [noisy_labeling(truth, 0.05, seed=1, prefix="sup")]
    labelings.append(noisy_labeling(
        truth, 0.10, n_out_clusters=max(2, n_clusters - 4), seed=2, prefix="uns"
    ))
    for i in range(n_way - 2):
        labelings.append(noisy_labeling(truth, 0.08, seed=3 + i, prefix=f"t{i}"))

    def once():
        t0 = time.perf_counter()
        consensus = _consensus(*labelings)
        result = recluster_de_consensus_fast(
            data, consensus, method="wilcox",
            deep_split_values=(1, 2, 3, 4), **refine_kw,
        )
        return time.perf_counter() - t0, result

    return once


def run_brain1m(n_cells=1_000_000, n_pcs=15, n_clusters=24):
    """1M-cell scale config: pooled Ward + cut + ring silhouette over a
    synthetic embedding (the 'pod-sharded distance + approx hierarchical'
    configuration; metric is cells/sec)."""
    import numpy as np

    from scconsensus_tpu.ops.pooling import pooled_ward_linkage
    from scconsensus_tpu.ops.silhouette import mean_cluster_silhouette
    from scconsensus_tpu.ops.treecut import cutree_hybrid

    rng = np.random.default_rng(3)
    centers = rng.normal(scale=6.0, size=(n_clusters, n_pcs))
    lab = rng.integers(0, n_clusters, n_cells)
    x = (centers[lab] + rng.normal(size=(n_cells, n_pcs))).astype(np.float32)

    def once():
        t0 = time.perf_counter()
        tree, assign, cents = pooled_ward_linkage(x, n_centroids=4096, seed=1)
        cut = cutree_hybrid(tree, cents, deep_split=1, min_cluster_size=2)
        cells = cut[assign]
        sub = rng.choice(n_cells, size=50_000, replace=False)  # SI on a sample
        si, _ = mean_cluster_silhouette(x[sub], cells[sub])
        dt = time.perf_counter() - t0
        return dt, {"clusters": len(set(cells[cells > 0].tolist())),
                    "silhouette": round(si, 3)}

    return once


CONFIGS = {
    "flagship": dict(kind="refine", n_cells=26000, n_genes=15000, n_clusters=22),
    "pbmc68k": dict(kind="refine", n_cells=68000, n_genes=15000, n_clusters=12,
                    n_way=3),
    "cite8k": dict(kind="refine", n_cells=8000, n_genes=10000, n_clusters=8),
    "tm100k": dict(kind="refine", n_cells=100000, n_genes=12000, n_clusters=40,
                   refine_kw=dict(approx_threshold=50000)),
    "brain1m": dict(kind="brain1m"),
}


def main() -> None:
    import jax

    # SCC_BENCH_PLATFORM=cpu pins the backend before first init (the env var
    # JAX_PLATFORMS alone is overridden by site-level TPU plugin config).
    plat = os.environ.get("SCC_BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_compilation_cache_dir", "/tmp/scc_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    name = os.environ.get("SCC_BENCH_CONFIG", "flagship")
    cfg = dict(CONFIGS[name])
    kind = cfg.pop("kind")
    log(f"[bench] config={name} on {jax.devices()[0].platform}")

    if kind == "brain1m":
        once = run_brain1m()
        cold_s, cold_info = once()
        log(f"[bench] cold run: {cold_s:.2f}s {cold_info}")
        if os.environ.get("SCC_BENCH_COLD"):
            elapsed, info = cold_s, cold_info
        else:
            elapsed, info = once()
        log(f"[bench] steady: {elapsed:.2f}s {info}")
        # nominal target: 1M cells through the approx-hierarchical path in
        # 300 s (no published reference numbers exist, SURVEY.md §6)
        print(json.dumps({
            "metric": "1M-cell pooled distance+linkage+cut+silhouette throughput",
            "value": round(1_000_000 / elapsed),
            "unit": "cells/sec",
            "vs_baseline": round((1_000_000 / elapsed) / (1_000_000 / 300.0), 3),
        }))
        return

    cfg.setdefault("n_cells", 26000)
    if name == "flagship":  # env overrides for ad-hoc scaling runs
        cfg["n_cells"] = int(os.environ.get("SCC_BENCH_CELLS", cfg["n_cells"]))
        cfg["n_genes"] = int(os.environ.get("SCC_BENCH_GENES", cfg["n_genes"]))
        cfg["n_clusters"] = int(
            os.environ.get("SCC_BENCH_CLUSTERS", cfg["n_clusters"])
        )
    refine_kw = cfg.pop("refine_kw", {})
    log(f"[bench] generating synthetic data: {cfg}")
    once = run_refine_config(**cfg, **refine_kw)

    cold_s, _ = once()
    log(f"[bench] cold run (includes XLA compiles): {cold_s:.2f}s")
    if os.environ.get("SCC_BENCH_COLD"):
        elapsed = cold_s
    else:
        elapsed, result = once()
        log(f"[bench] steady-state run: {elapsed:.2f}s; union="
            f"{result.de_gene_union_idx.size} genes; "
            f"deep_split_info={result.deep_split_info}")

    n_cells = cfg["n_cells"]
    print(json.dumps({
        "metric": (
            f"{n_cells // 1000}k" if n_cells >= 1000 else str(n_cells)
        ) + f"-cell end-to-end consensus+recluster wall-clock ({name})",
        "value": round(elapsed, 3),
        "unit": "seconds",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 3),
    }))


if __name__ == "__main__":
    main()
