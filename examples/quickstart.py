"""Worked scConsensus session — the TPU-native mirror of the reference's
README workflow (reference README.md:38-162), runnable end to end on CPU or
a TPU chip with no external data (synthetic 26k-PBMC-shaped input stands in
for the Zenodo dataset; no network egress in this environment).

Steps, in the reference's order:
  1. load a (genes × cells) log-normalized matrix + two labelings
     (supervised "celltype" names × unsupervised cluster ids — the
     Seurat × RCA pair of the reference),
  2. gene filter  rowSums(data > 0) > threshold      (README.md:116),
  3. plot_contingency_table → automated consensus    (README.md:85),
  4. MANUAL consensus override — the user-in-the-loop relabeling step the
     reference performs between consensus and refinement (README.md:91-101),
  5. recluster_de_consensus(method="edgeR", ...)     (README.md:118) — the
     flagship slow path — and the fast Wilcoxon path,
  6. per-deepSplit colors → cell-type annotation     (README.md:127-138),
  7. both plots (contingency heatmap + DE heatmap PDFs),
  8. resume: re-running refine() with an artifact_dir skips completed
     stages (the capability the reference's write-only saveRDS dumps never
     had, SURVEY.md §5.4).

Run:  python examples/quickstart.py [--cells 2000] [--genes 800] [--outdir .]
"""

from __future__ import annotations

import argparse
import os
import pathlib
import sys
import tempfile

import numpy as np

if os.environ.get("JAX_PLATFORMS"):
    # Honor JAX_PLATFORMS even where a site plugin force-registers an
    # accelerator backend (the env var alone loses that race; the config
    # update must land before the first backend init).
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

try:
    import scconsensus_tpu as scc
except ModuleNotFoundError:  # running from a checkout without installation
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import scconsensus_tpu as scc
from scconsensus_tpu.utils.synthetic import noisy_labeling, synthetic_scrna


def main(n_cells: int = 2000, n_genes: int = 800, outdir: str = ".") -> dict:
    out = pathlib.Path(outdir)
    out.mkdir(parents=True, exist_ok=True)

    # -- 1. inputs: matrix + two labelings ------------------------------
    data, truth, _ = synthetic_scrna(
        n_genes=n_genes, n_cells=n_cells, n_clusters=6,
        n_markers_per_cluster=min(40, n_genes // 8), seed=7,
    )
    gene_names = np.array([f"gene{i}" for i in range(data.shape[0])])
    celltypes = ["T_Naive", "T_Cytotoxic", "B_Cells", "NK_Cells",
                 "Monocytes", "pDC"]
    supervised = np.array([celltypes[v] for v in noisy_labeling(
        truth, 0.05, seed=1, prefix=""
    ).astype(int)])
    unsupervised = noisy_labeling(truth, 0.10, seed=2, prefix="uns")

    # -- 2. gene filter: rowSums(data > 0) > threshold ------------------
    keep = (data > 0).sum(axis=1) > max(10, n_cells // 250)
    data, gene_names = data[keep], gene_names[keep]
    print(f"[quickstart] gene filter kept {keep.sum()}/{keep.size} genes")

    # -- 3. contingency table + automated consensus ---------------------
    consensus = scc.plot_contingency_table(
        supervised, unsupervised,
        filename=str(out / "Contingency_Table.pdf"),
    )
    print(f"[quickstart] consensus labels: {len(set(consensus))} clusters")

    # -- 4. manual consensus override (user-in-the-loop) ----------------
    # The reference hand-merges labels after inspecting the table
    # (README.md:91-101). Consensus labels are a plain vector — override
    # them with ordinary numpy indexing:
    consensus = np.asarray(consensus, dtype=object)
    rare = [lab for lab in set(consensus)
            if (consensus == lab).sum() < max(20, n_cells // 100)]
    for lab in rare:
        base = str(lab).split("_")[0]
        consensus[consensus == lab] = base
    consensus = consensus.astype(str)
    print(f"[quickstart] after manual override: {len(set(consensus))} clusters")

    # -- 5. DE refinement: flagship edgeR slow path + fast Wilcoxon -----
    de_obj = scc.recluster_de_consensus(
        data, consensus,
        method="edgeR", q_val_thrs=0.01, fc_thrs=2.0,
        mean_scaling_factor=0.5, deep_split_values=(1, 2, 3, 4),
        min_cluster_size=10, gene_names=gene_names,
        plot_name=str(out / "Reclustered_DE_edgeR_Heatmap.pdf"),
    )
    print(f"[quickstart] edgeR DE union: {de_obj.de_gene_union.size} genes; "
          f"deep_split_info: {de_obj.deep_split_info}")

    fast_obj = scc.recluster_de_consensus_fast(
        data, consensus, method="wilcox", q_val_thrs=0.1,
        deep_split_values=(1, 2), gene_names=gene_names,
    )
    print(f"[quickstart] wilcox DE union: {fast_obj.de_gene_union.size} genes")

    # -- 6. annotate refined clusters by color --------------------------
    # (README.md:127-138: map per-deepSplit colors to cell-type names)
    colors = de_obj.dynamic_colors["deepsplit: 3"]
    annotation = {}
    for color in dict.fromkeys(colors):        # stable order
        members = colors == color
        if color == "grey":
            annotation[color] = "Unknown"
            continue
        vals, counts = np.unique(consensus[members], return_counts=True)
        annotation[color] = str(vals[np.argmax(counts)])
    de_celltypes = np.array([annotation[c] for c in colors])
    print(f"[quickstart] annotated {len(annotation)} refined clusters: "
          f"{sorted(set(de_celltypes))}")

    # -- 8. resume from the artifact store ------------------------------
    with tempfile.TemporaryDirectory() as tmp:
        kw = dict(
            method="wilcox", q_val_thrs=0.1, deep_split_values=(1, 2),
            artifact_dir=tmp,
        )
        scc.recluster_de_consensus_fast(data, consensus, **kw)
        resumed = scc.recluster_de_consensus_fast(data, consensus, **kw)
        stages = [s["stage"] for s in resumed.metrics.get("stages", [])]
        assert "wilcox_test" not in stages, "resume should skip the DE stage"
        print("[quickstart] resume: DE stage skipped via artifact store")

    return {
        "consensus_k": len(set(consensus)),
        "edger_union": int(de_obj.de_gene_union.size),
        "wilcox_union": int(fast_obj.de_gene_union.size),
        "annotation": annotation,
        "outputs": sorted(p.name for p in out.glob("*.pdf")),
    }


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=2000)
    ap.add_argument("--genes", type=int, default=800)
    ap.add_argument("--outdir", default=".")
    args = ap.parse_args()
    summary = main(args.cells, args.genes, args.outdir)
    print(f"[quickstart] done: {summary}")
