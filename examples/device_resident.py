"""Device-resident data path: run the full refinement without the matrix
ever crossing the host↔device link as a dense block.

Two entry routes (both end in the same `refine()` call the quickstart uses):

  1. A sparse load → ``io.csr_to_device`` ships only the CSR triplet
     (data + indices + indptr ≈ nnz·8 bytes — ~10× smaller than the dense
     matrix at typical scRNA sparsity) and densifies in HBM on device.
  2. Synthetic/benchmark data → ``utils.synthetic.synthetic_scrna_device``
     draws the gamma–Poisson matrix directly on device via ``jax.random``.

Either way the pipeline (`recluster_de_consensus[_fast]`) detects the
``jax.Array`` input and keeps every stage on device, fetching only
O(N)-sized results (embedding scores, labels, NODG). This matters whenever
the accelerator sits behind a thin link — a 26k × 15k f32 matrix is
~1.5 GB of transfer avoided — and costs nothing locally.

Run:  python examples/device_resident.py [--cells 1200] [--genes 400]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS"):
    # Honor JAX_PLATFORMS even where a site plugin force-registers an
    # accelerator backend (same shim as examples/quickstart.py).
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=1200)
    ap.add_argument("--genes", type=int, default=400)
    args = ap.parse_args()

    import numpy as np
    import scipy.sparse as sp

    import scconsensus_tpu as scc
    from scconsensus_tpu.io import csr_to_device, is_jax
    from scconsensus_tpu.utils.synthetic import (
        noisy_labeling,
        synthetic_scrna_device,
    )

    # Route 2: draw the matrix on device (route 1 shown below).
    t0 = time.perf_counter()
    data, truth, _ = synthetic_scrna_device(
        n_genes=args.genes, n_cells=args.cells, n_clusters=5,
        n_markers_per_cluster=min(30, args.genes // 5), seed=7,
    )
    print(f"on-device gen: {data.shape} in {time.perf_counter() - t0:.2f}s "
          f"(device-resident: {is_jax(data)})")

    sup = noisy_labeling(truth, 0.05, n_out_clusters=3, seed=1, prefix="T")
    uns = noisy_labeling(truth, 0.10, seed=2, prefix="L")
    consensus = scc.plot_contingency_table(sup, uns, filename=None)

    t0 = time.perf_counter()
    res = scc.recluster_de_consensus_fast(data, consensus, q_val_thrs=0.05)
    print(f"refine over device matrix: {time.perf_counter() - t0:.2f}s, "
          f"union={res.de_gene_union_idx.size}, "
          f"clusters per deepSplit="
          f"{ {k: len(set(v)) for k, v in res.dynamic_colors.items()} }")

    # Route 1: the same pipeline fed from a sparse load staged into HBM.
    host = np.array(data)  # writable host copy
    host[host < 0.4] = 0.0  # sparsify for the demo
    dev2 = csr_to_device(sp.csr_matrix(host))
    res2 = scc.recluster_de_consensus_fast(dev2, consensus, q_val_thrs=0.05)
    print(f"refine over csr_to_device matrix: "
          f"union={res2.de_gene_union_idx.size}")


if __name__ == "__main__":
    main()
