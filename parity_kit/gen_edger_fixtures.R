# Golden-fixture generator for the edgeR NB parity tests.
# Run anywhere R + Bioconductor edgeR exist:
#   Rscript parity_kit/gen_edger_fixtures.R > tests/fixtures/edger_golden.json
#
# Replicates the reference call sequence per cluster pair
# (/root/reference analog: R/reclusterDEConsensus.R:133-156):
#   DGEList(group) -> estimateCommonDisp -> estimateTagwiseDisp ->
#   calcNormFactors("none") -> exactTest
# on deterministic synthetic NB counts with planted DE blocks.
# JSON is written by hand (no jsonlite dependency).

suppressMessages(library(edgeR))

set.seed(7)

G <- 150
sizes <- c(60, 45, 30)
K <- length(sizes)
N <- sum(sizes)
phi_true <- 0.4

# per-cluster mean profiles: shared baseline + a planted 4x block per cluster
base <- runif(G, 1, 12)
mu <- matrix(rep(base, K), nrow = G)
block <- 30
for (k in seq_len(K)) {
  rows <- ((k - 1) * block + 1):(k * block)
  mu[rows, k] <- mu[rows, k] * 4
}

group <- rep(seq_len(K), sizes)
counts <- matrix(0L, nrow = G, ncol = N)
for (n in seq_len(N)) {
  depth <- runif(1, 0.6, 1.6)           # per-cell library variation
  m <- mu[, group[n]] * depth
  counts[, n] <- rnbinom(G, size = 1 / phi_true, mu = m)
}

pairs <- t(combn(seq_len(K), 2))

# ---- hand-rolled JSON helpers (no dependencies) ----------------------------
jnum <- function(x) {
  s <- formatC(x, digits = 10, format = "g")
  s[!is.finite(x)] <- "null"
  paste0("[", paste(s, collapse = ","), "]")
}
jint <- function(x) paste0("[", paste(as.integer(x), collapse = ","), "]")

res_chunks <- character(nrow(pairs))
for (p in seq_len(nrow(pairs))) {
  i <- pairs[p, 1]; j <- pairs[p, 2]
  sel <- group %in% c(i, j)
  g <- factor(group[sel], levels = c(i, j))
  y <- DGEList(counts = counts[, sel], group = g)
  y <- estimateCommonDisp(y)
  y <- estimateTagwiseDisp(y)
  y <- calcNormFactors(y, method = "none")   # reference order: after disp
  et <- exactTest(y, pair = as.character(c(j, i)))  # logFC of i over j
  res_chunks[p] <- paste0(
    '{"common_disp":', formatC(y$common.dispersion, digits = 10, format = "g"),
    ',"tagwise_disp":', jnum(y$tagwise.dispersion),
    ',"p_value":', jnum(et$table$PValue),
    ',"logfc_log2":', jnum(et$table$logFC), "}"
  )
}

cat(
  '{"schema_version":1',
  ',"n_genes":', G,
  ',"n_cells":', N,
  ',"n_clusters":', K,
  ',"counts":', jint(as.vector(t(counts))),      # row-major (gene-major)
  ',"group":', jint(group - 1L),                 # 0-based
  ',"pairs":[', paste(
    apply(pairs - 1L, 1, function(r) paste0("[", r[1], ",", r[2], "]")),
    collapse = ","), "]",
  ',"results":[', paste(res_chunks, collapse = ","), "]}",
  sep = ""
)
