# Golden-fixture generator for the dynamicTreeCut parity tests.
# Run anywhere R + dynamicTreeCut (CRAN) exist:
#   Rscript parity_kit/gen_treecut_fixtures.R > tests/fixtures/treecut_golden.json
#
# Replicates the reference call sequence (/root/reference analog:
# R/reclusterDEConsensus.R:254-260): hclust(dist(x), "ward.D2") then
# cutreeHybrid at deepSplit 0..4 on deterministic planted Gaussian clusters.
# JSON is written by hand (no jsonlite dependency).

suppressMessages(library(dynamicTreeCut))

set.seed(11)

n_per <- 30
d <- 4
centers <- matrix(c(
   0,  0,  0,  0,
   8,  0,  0,  0,
   0,  8,  0,  0,
   0,  0,  8,  0,
   5,  5,  5,  5,
  -6,  4, -4,  6
), ncol = d, byrow = TRUE)
k0 <- nrow(centers)
n <- n_per * k0

x <- matrix(0, nrow = n, ncol = d)
for (k in seq_len(k0)) {
  rows <- ((k - 1) * n_per + 1):(k * n_per)
  x[rows, ] <- sweep(
    matrix(rnorm(n_per * d, sd = 1.2), ncol = d), 2, centers[k, ], `+`
  )
}

dm <- dist(x)
hc <- hclust(dm, method = "ward.D2")
dmat <- as.matrix(dm)

jnum <- function(v) {
  s <- formatC(v, digits = 10, format = "g")
  s[!is.finite(v)] <- "null"
  paste0("[", paste(s, collapse = ","), "]")
}
jint <- function(v) paste0("[", paste(as.integer(v), collapse = ","), "]")

lab_chunks <- character(5)
for (ds in 0:4) {
  ct <- cutreeHybrid(
    dendro = hc, distM = dmat, deepSplit = ds,
    minClusterSize = 5, pamStage = TRUE, verbose = 0
  )
  lab_chunks[ds + 1] <- paste0('"', ds, '":', jint(ct$labels))
}

cat(
  '{"schema_version":1',
  ',"n_points":', n,
  ',"n_dims":', d,
  ',"points":', jnum(as.vector(t(x))),           # row-major
  ',"merge":', jint(as.vector(t(hc$merge))),     # row-major (n-1) x 2
  ',"height":', jnum(hc$height),
  ',"labels":{', paste(lab_chunks, collapse = ","), "}}",
  sep = ""
)
