"""Serving stats + the validated ``serving`` run-record section.

One :class:`ServingStats` per driver (the driver registers it as the
process's active stats so the heartbeat sampler can feed ``tail_run``'s
serving panel live). The section's load-bearing rule, enforced by
:func:`validate_serving` exactly like the robustness section's
recovery-needs-evidence rule: **every submitted request must be accounted
for** — ``requests.submitted`` must equal the sum of the outcome
counters. A serving record that lost track of even one request is
rejected, because "silently dropped" is the failure mode the whole
guarded path exists to make impossible.

Import discipline: stdlib only (``validate_run_record`` and the chaos
harness load this without jax).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

from scconsensus_tpu.serve import slo as serve_slo

__all__ = [
    "OUTCOMES",
    "BREAKER_STATES",
    "BREAKER_SEVERITY",
    "STAGE_HIST_STAGES",
    "ServingStats",
    "WireStats",
    "merge_serving_sections",
    "active_stats",
    "set_active_fleet",
    "live_summary",
    "validate_serving",
]

# Every way a request can leave the system. submit-time rejections
# (queue-full, invalid, closed) never reach a batch; the rest resolve
# from one.
OUTCOMES = (
    "ok",                 # labels returned, device path, breaker closed
    "degraded",           # labels returned by the HOST fallback, flagged
    "quarantined",        # drift gate refused confident labels; ledgered
    "rejected_queue",     # bounded-admission backpressure (retry-after)
    "rejected_invalid",   # malformed request, refused at admission
    "rejected_closed",    # typed ServerClosed (shutdown / undrained stop)
    "deadline_exceeded",  # typed late failure (queue wait or compute)
    "failed",             # fatal batch error, typed RequestFailed
)

BREAKER_STATES = ("closed", "open", "half_open")

# Rolling latency reservoir size: enough for a stable p99 (the live panel
# and the section both read it), bounded so a soak cannot grow the record.
_LATENCY_RING = 4096

# The per-stage latency histogram vocabulary (serve.slo fixed-bucket
# grids): queue_wait is dequeue-minus-enqueue per request, compute is the
# batch classify wall — the two halves a p99 decomposes into.
STAGE_HIST_STAGES = ("queue_wait", "compute")

# Recent-request telemetry ring per stats object: the heartbeat stream's
# trace-id evidence (tools/postmortem.py joins heartbeat lines to wire/
# ledger rows through these ids) — bounded so a tick stays small.
_RECENT_RING = 8


class ServingStats:
    """Thread-safe counters for one serving driver's lifetime."""

    def __init__(self, queue_capacity: int = 0):
        self.queue_capacity = int(queue_capacity)
        self.counts: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self.submitted = 0
        self.queue_depth = 0
        self.queue_peak = 0
        self.batches = 0
        self.batch_cells = 0
        self.batch_max = 0
        self.breaker_state = "closed"
        self.breaker_trips = 0
        self.drift_batches = 0
        self.quarantine_entries = 0
        self.consumed_s = 0.0       # self-measured driver bookkeeping
        self.classify_wall_s = 0.0  # cumulative classify-call wall
        self.started_unix = time.time()
        self._lat_ms: List[float] = []
        self._lat_i = 0             # ring cursor
        self._lat_n = 0
        self._lat_sum = 0.0
        self._lat_max = 0.0
        # telemetry plane (round 20): per-outcome + per-stage fixed-
        # bucket histograms (mergeable across replicas by construction),
        # the multi-window SLO tracker, and the recent-trace ring the
        # heartbeat stream carries
        self.lat_hist: Dict[str, serve_slo.LatencyHistogram] = {
            o: serve_slo.LatencyHistogram() for o in OUTCOMES
        }
        self.stage_hist: Dict[str, serve_slo.LatencyHistogram] = {
            s: serve_slo.LatencyHistogram() for s in STAGE_HIST_STAGES
        }
        self.slo_track = serve_slo.SLOTracker()
        self.recent: "collections.deque" = collections.deque(
            maxlen=_RECENT_RING
        )
        # running availability counters (good+bad=total, client-fault
        # excluded): kept incrementally so the per-request note is O(1)
        # — this path sits inside the <2% driver overhead guard
        self._av_bad = 0
        self._av_total = 0
        self._lock = threading.Lock()

    # -- notes -------------------------------------------------------------
    def note_submit(self, depth: int) -> None:
        with self._lock:
            self.submitted += 1
            self.queue_depth = int(depth)
            self.queue_peak = max(self.queue_peak, int(depth))

    def note_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.queue_peak = max(self.queue_peak, int(depth))

    def note_outcome(self, outcome: str,
                     latency_s: Optional[float] = None,
                     trace_id: Optional[str] = None) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown serving outcome {outcome!r}")
        with self._lock:
            self.counts[outcome] += 1
            if latency_s is not None:
                ms = max(float(latency_s), 0.0) * 1e3
                if len(self._lat_ms) < _LATENCY_RING:
                    self._lat_ms.append(ms)
                else:
                    self._lat_ms[self._lat_i] = ms
                    self._lat_i = (self._lat_i + 1) % _LATENCY_RING
                self._lat_n += 1
                self._lat_sum += ms
                self._lat_max = max(self._lat_max, ms)
                self.lat_hist[outcome].observe(ms)
            cls = serve_slo.OUTCOME_CLASS.get(outcome)
            if cls == "good":
                self._av_total += 1
            elif cls == "bad":
                self._av_bad += 1
                self._av_total += 1
            self.slo_track.note(self._av_bad, self._av_total)
            if trace_id:
                self.recent.append({
                    "trace_id": trace_id, "outcome": outcome,
                    "latency_ms": (round(float(latency_s) * 1e3, 3)
                                   if latency_s is not None else None),
                    "ts": round(time.time(), 3),
                })

    def note_stage_latency(self, stage: str, seconds: float) -> None:
        """Observe one per-stage latency (queue_wait / compute) into the
        stage's fixed-bucket histogram."""
        if stage not in STAGE_HIST_STAGES:
            raise ValueError(f"unknown latency stage {stage!r}")
        with self._lock:
            self.stage_hist[stage].observe(max(float(seconds), 0.0) * 1e3)

    def note_batch(self, n_requests: int, n_cells: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_cells += int(n_cells)
            self.batch_max = max(self.batch_max, int(n_cells))

    def note_breaker(self, state: str, tripped: bool = False) -> None:
        if state not in BREAKER_STATES:
            raise ValueError(f"unknown breaker state {state!r}")
        with self._lock:
            self.breaker_state = state
            if tripped:
                self.breaker_trips += 1

    def note_drift_batch(self, quarantined: int = 0) -> None:
        with self._lock:
            self.drift_batches += 1
            self.quarantine_entries += int(quarantined)

    def add_consumed(self, dt: float) -> None:
        with self._lock:
            self.consumed_s += max(float(dt), 0.0)

    def add_classify_wall(self, dt: float) -> None:
        with self._lock:
            self.classify_wall_s += max(float(dt), 0.0)

    def latency_samples(self) -> List[float]:
        """Copy of the raw latency ring (ms) — the fleet aggregator merges
        per-replica rings so pool quantiles come from real samples, not
        from averaging quantiles (which is statistically meaningless)."""
        with self._lock:
            return list(self._lat_ms)

    def expo_snapshot(self) -> Dict[str, Any]:
        """One internally consistent exposition snapshot (counters,
        gauges, serialized histograms, the recent-trace ring, and the
        SLO window deltas) taken under this stats object's lock — the
        unit the pool's swap-lock snapshot and the wire's exposition
        are assembled from."""
        with self._lock:
            av = serve_slo.classify_counts(self.counts)
            return {
                "counts": dict(self.counts),
                "submitted": self.submitted,
                "queue_depth": self.queue_depth,
                "queue_cap": self.queue_capacity,
                "breaker": self.breaker_state,
                "trips": self.breaker_trips,
                "latency_hist": {o: h.to_dict()
                                 for o, h in self.lat_hist.items()},
                "stage_hist": {s: h.to_dict()
                               for s, h in self.stage_hist.items()},
                "recent": list(self.recent),
                "window_deltas": self.slo_track.window_deltas(
                    av["bad"], av["total"]
                ),
            }

    def slo_section(self, obs_overhead: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """The validated ``slo`` run-record section for this driver's
        lifetime (single-driver case; a fleet builds the merged twin via
        ``ReplicaPool.slo_section``)."""
        snap = self.expo_snapshot()
        p99 = self.latency_ms().get("p99")
        return serve_slo.build_slo_section(
            snap["counts"], p99, snap["window_deltas"],
            latency_hist=snap["latency_hist"],
            stage_hist=snap["stage_hist"],
            obs_overhead=obs_overhead or serve_slo.obs_overhead(),
        )

    # -- reads -------------------------------------------------------------
    def latency_ms(self) -> Dict[str, Any]:
        with self._lock:
            if self._lat_n == 0:
                return {"n": 0}
            # ONE sort for both quantiles: this runs on every heartbeat
            # tick under the same lock the submit/resolve hot path takes
            s = sorted(self._lat_ms)
            return {
                "n": self._lat_n,
                "p50": round(s[min(int(0.50 * len(s)), len(s) - 1)], 4),
                "p99": round(s[min(int(0.99 * len(s)), len(s) - 1)], 4),
                "max": round(self._lat_max, 4),
                "mean": round(self._lat_sum / self._lat_n, 4),
            }

    def section(self) -> Dict[str, Any]:
        """The run record's ``serving`` section (always present once a
        driver ran — unlike robustness, an all-healthy serving window is
        itself the evidence: N requests in, N outcomes out)."""
        lat = self.latency_ms()
        with self._lock:
            wall = max(time.time() - self.started_unix, 0.0)
            served = sum(self.counts[o]
                         for o in ("ok", "degraded", "quarantined"))
            return {
                "requests": {"submitted": self.submitted,
                             **dict(self.counts)},
                "latency_ms": lat,
                "throughput_rps": round(served / wall, 4) if wall else 0.0,
                "batches": {
                    "count": self.batches,
                    "cells": self.batch_cells,
                    "max_cells": self.batch_max,
                    "mean_cells": (round(self.batch_cells / self.batches, 2)
                                   if self.batches else 0.0),
                },
                "queue": {"depth_peak": self.queue_peak,
                          "capacity": self.queue_capacity},
                "breaker": {"state": self.breaker_state,
                            "trips": self.breaker_trips},
                "drift": {"batches_flagged": self.drift_batches,
                          "quarantine_entries": self.quarantine_entries},
                "consumed_s": round(self.consumed_s, 4),
                "classify_wall_s": round(self.classify_wall_s, 4),
                "window_s": round(wall, 4),
            }


# -- wire-front accounting --------------------------------------------------

class WireStats:
    """HTTP-layer accounting for the fleet's wire front: every wire
    request resolves to exactly ONE typed outcome (the same OUTCOMES
    vocabulary the driver uses) mapped to exactly one status code. The
    r15 accounting rule holds at the wire layer too — a wire request
    that got a socket but no counted outcome is the dropped-request
    failure mode all over again, one layer up."""

    def __init__(self):
        self.submitted = 0
        self.counts: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self.status_codes: Dict[str, int] = {}
        # wire-level telemetry (round 20): the front is the one place
        # every request of the whole fleet passes, so the formal SLO
        # (availability + burn windows) and the end-to-end per-outcome
        # latency histograms anchor HERE; replicas keep their own for
        # the per-replica exposition and the merge proof
        self.lat_hist: Dict[str, serve_slo.LatencyHistogram] = {
            o: serve_slo.LatencyHistogram() for o in OUTCOMES
        }
        self.slo_track = serve_slo.SLOTracker()
        self.recent: "collections.deque" = collections.deque(
            maxlen=_RECENT_RING
        )
        self._av_bad = 0
        self._av_total = 0
        self._lock = threading.Lock()

    def note(self, outcome: str, status: int,
             latency_s: Optional[float] = None,
             trace_id: Optional[str] = None) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown wire outcome {outcome!r}")
        with self._lock:
            self.submitted += 1
            self.counts[outcome] += 1
            key = str(int(status))
            self.status_codes[key] = self.status_codes.get(key, 0) + 1
            if latency_s is not None:
                self.lat_hist[outcome].observe(
                    max(float(latency_s), 0.0) * 1e3
                )
            cls = serve_slo.OUTCOME_CLASS.get(outcome)
            if cls == "good":
                self._av_total += 1
            elif cls == "bad":
                self._av_bad += 1
                self._av_total += 1
            self.slo_track.note(self._av_bad, self._av_total)
            if trace_id:
                self.recent.append({
                    "trace_id": trace_id, "outcome": outcome,
                    "status": int(status),
                    "ts": round(time.time(), 3),
                })

    def section(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "requests": {"submitted": self.submitted,
                             **dict(self.counts)},
                "status_codes": dict(self.status_codes),
            }

    def expo_snapshot(self) -> Dict[str, Any]:
        """Wire-scope exposition snapshot (counters + status codes +
        end-to-end histograms + SLO window deltas), one lock hold."""
        with self._lock:
            av = serve_slo.classify_counts(self.counts)
            return {
                "counts": dict(self.counts),
                "submitted": self.submitted,
                "status_codes": dict(self.status_codes),
                "latency_hist": {o: h.to_dict()
                                 for o, h in self.lat_hist.items()},
                "recent": list(self.recent),
                "window_deltas": self.slo_track.window_deltas(
                    av["bad"], av["total"]
                ),
            }


# -- fleet aggregation ------------------------------------------------------

# one severity order for every consumer (pool routing, live-panel
# worst-state fold, merged-section breaker) — two copies of this map
BREAKER_SEVERITY = {"closed": 0, "half_open": 1, "open": 2}


def _quantile_summary(samples: List[float], n_total: int,
                      total_sum: float, mx: float) -> Dict[str, Any]:
    if not samples or n_total <= 0:
        return {"n": 0}
    s = sorted(samples)
    return {
        "n": int(n_total),
        "p50": round(s[min(int(0.50 * len(s)), len(s) - 1)], 4),
        "p99": round(s[min(int(0.99 * len(s)), len(s) - 1)], 4),
        "max": round(mx, 4),
        "mean": round(total_sum / n_total, 4),
    }


def merge_serving_sections(
    sections: List[Dict[str, Any]],
    latency_samples: List[List[float]],
    window_s: float,
) -> Dict[str, Any]:
    """Fold per-replica serving sections (live + retired + the pool's own
    boundary stats) into ONE pool-level section the accounting rule still
    holds over: counters sum, latency quantiles come from the merged raw
    sample rings, the breaker reports the worst live state, and drift /
    batch / queue evidence aggregates. Sum-of-valid-sections is valid by
    construction: submitted and the outcome counters sum on both sides of
    the accounting equation."""
    req: Dict[str, int] = {"submitted": 0, **{o: 0 for o in OUTCOMES}}
    batches = {"count": 0, "cells": 0, "max_cells": 0}
    queue = {"depth_peak": 0, "capacity": 0}
    breaker = {"state": "closed", "trips": 0}
    drift = {"batches_flagged": 0, "quarantine_entries": 0}
    consumed = classify_wall = 0.0
    lat_n = 0
    lat_sum = 0.0
    lat_max = 0.0
    for sec in sections:
        r = sec.get("requests") or {}
        req["submitted"] += int(r.get("submitted", 0))
        for o in OUTCOMES:
            req[o] += int(r.get(o, 0))
        b = sec.get("batches") or {}
        batches["count"] += int(b.get("count", 0))
        batches["cells"] += int(b.get("cells", 0))
        batches["max_cells"] = max(batches["max_cells"],
                                   int(b.get("max_cells", 0)))
        q = sec.get("queue") or {}
        queue["depth_peak"] = max(queue["depth_peak"],
                                  int(q.get("depth_peak", 0)))
        queue["capacity"] += int(q.get("capacity", 0))
        br = sec.get("breaker") or {}
        if (BREAKER_SEVERITY.get(br.get("state"), 0)
                > BREAKER_SEVERITY[breaker["state"]]):
            breaker["state"] = br.get("state")
        breaker["trips"] += int(br.get("trips", 0))
        d = sec.get("drift") or {}
        drift["batches_flagged"] += int(d.get("batches_flagged", 0))
        drift["quarantine_entries"] += int(d.get("quarantine_entries", 0))
        consumed += float(sec.get("consumed_s", 0.0))
        classify_wall += float(sec.get("classify_wall_s", 0.0))
        lat = sec.get("latency_ms") or {}
        n = int(lat.get("n", 0))
        lat_n += n
        lat_sum += float(lat.get("mean", 0.0)) * n
        lat_max = max(lat_max, float(lat.get("max", 0.0)))
    merged = [ms for ring in latency_samples for ms in ring]
    served = sum(req[o] for o in ("ok", "degraded", "quarantined"))
    window_s = max(float(window_s), 0.0)
    batches["mean_cells"] = (round(batches["cells"] / batches["count"], 2)
                             if batches["count"] else 0.0)
    return {
        "requests": req,
        "latency_ms": _quantile_summary(merged, lat_n, lat_sum, lat_max),
        "throughput_rps": (round(served / window_s, 4)
                           if window_s else 0.0),
        "batches": batches,
        "queue": queue,
        "breaker": breaker,
        "drift": drift,
        "consumed_s": round(consumed, 4),
        "classify_wall_s": round(classify_wall, 4),
        "window_s": round(window_s, 4),
    }


# -- the process's active stats (heartbeat feed) ----------------------------

_ACTIVE: Optional[ServingStats] = None
_ACTIVE_FLEET = None  # () -> live-summary dict; a ReplicaPool registers it
_ACTIVE_LOCK = threading.Lock()


def set_active(stats: Optional[ServingStats]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = stats


def set_active_fleet(summary_fn) -> None:
    """Register (or clear, with None) the process's fleet live feed: a
    zero-arg callable returning the pool-aggregated live summary. A fleet
    wins over a single active driver in :func:`live_summary` — with a
    pool running, per-replica stats are panel rows, not the headline."""
    global _ACTIVE_FLEET
    with _ACTIVE_LOCK:
        _ACTIVE_FLEET = summary_fn


def active_stats() -> Optional[ServingStats]:
    return _ACTIVE


def live_summary() -> Optional[Dict[str, Any]]:
    """Compact serving counters for one heartbeat tick (None = no driver
    running) — queue depth, rolling p99, breaker state, and the
    degraded/quarantined/rejected tallies tail_run's panel renders. With
    a fleet registered, the pool's aggregated summary (plus its
    per-replica ``fleet`` panel) is the tick."""
    fleet = _ACTIVE_FLEET
    if fleet is not None:
        try:
            return fleet()
        except Exception:
            return None
    st = _ACTIVE
    if st is None:
        return None
    lat = st.latency_ms()
    with st._lock:
        out: Dict[str, Any] = {
            "queue_depth": st.queue_depth,
            "queue_cap": st.queue_capacity,
            "breaker": st.breaker_state,
            "ok": st.counts["ok"],
        }
        for key in ("degraded", "quarantined", "deadline_exceeded",
                    "failed"):
            if st.counts[key]:
                out[key] = st.counts[key]
        rejected = (st.counts["rejected_queue"]
                    + st.counts["rejected_invalid"]
                    + st.counts["rejected_closed"])
        if rejected:
            out["rejected"] = rejected
        if st.breaker_trips:
            out["breaker_trips"] = st.breaker_trips
        # telemetry-plane panel feed (round 20): per-outcome histogram
        # counts, the live SLO (availability + burn per window), and the
        # recent-trace ring — tail_run renders these instead of raw
        # counter deltas, and the postmortem joins heartbeats on the ids
        av = serve_slo.classify_counts(st.counts)
        deltas = st.slo_track.window_deltas(av["bad"], av["total"])
        hist = {o: {"n": h.n, "buckets": list(h.counts)}
                for o, h in st.lat_hist.items() if h.n}
        recent = list(st.recent)
    out["slo"] = slo_summary(av, deltas)
    if hist:
        out["lat_hist"] = hist
    if recent:
        out["recent"] = recent
    if lat.get("p99") is not None:
        out["p99_ms"] = lat["p99"]
    return out


def slo_summary(avail: Dict[str, int],
                window_deltas: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Compact live-SLO tick: availability ratio + burn per window —
    the heartbeat-sized view of the full slo section (one formula with
    build_slo_section, shared via classify_counts/window_deltas)."""
    budget = max(1.0 - float(env_or_default_avail()), 1e-9)
    ratio = ((avail["good"] / avail["total"]) if avail["total"] else 1.0)
    burns = {}
    for wd in window_deltas:
        err = (wd["bad"] / wd["total"]) if wd["total"] else 0.0
        # %g keying: int() would collide the sub-second test-scale
        # windows ("0.1" and "0.5" both -> "0")
        burns[f"{float(wd['window_s']):g}"] = round(err / budget, 3)
    return {"availability": round(ratio, 6), "burn": burns}


def env_or_default_avail() -> float:
    from scconsensus_tpu.config import env_flag

    return float(env_flag("SCC_SLO_AVAIL_TARGET"))


# -- schema validation ------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"serving section: {msg}")


def validate_serving(sv: Dict[str, Any]) -> None:
    """Structural validation of a record's ``serving`` section
    (``export.validate_run_record`` dispatches here). Load-bearing rules:

    * accounting — ``requests.submitted == sum(outcome counters)``; a
      record that lost a request is rejected;
    * latency sanity — ``0 <= p50 <= p99 <= max`` whenever latencies
      were measured;
    * evidence coupling — degraded responses require a tripped breaker,
      quarantined responses require drift-flagged batches, queue
      rejections require a bounded queue (capacity > 0);
    * wire accounting (fleet round, when a ``wire`` subsection is
      present) — the SAME rule one layer up: every wire request must
      end as exactly one typed outcome, and every outcome must have
      produced exactly one status code;
    * fleet coherence (when a ``fleet`` subsection is present) —
      replicas >= 1, an active fingerprint, and the submitted-by-owner
      split (live replicas + retired replicas + pool boundary) must sum
      to ``requests.submitted``: a request the fleet cannot attribute to
      an owner is a lost request wearing a disguise.
    """
    _require(isinstance(sv, dict), "must be an object")
    req = sv.get("requests")
    _require(isinstance(req, dict), "requests must be an object")
    sub = req.get("submitted")
    _require(isinstance(sub, int) and sub >= 0,
             "requests.submitted must be an int >= 0")
    total = 0
    for o in OUTCOMES:
        v = req.get(o, 0)
        _require(isinstance(v, int) and v >= 0,
                 f"requests.{o} must be an int >= 0")
        total += v
    _require(
        total == sub,
        f"request accounting broken: submitted={sub} but outcomes sum to "
        f"{total} — every request must end as exactly one of {OUTCOMES}",
    )
    lat = sv.get("latency_ms")
    _require(isinstance(lat, dict), "latency_ms must be an object")
    n = lat.get("n", 0)
    _require(isinstance(n, int) and n >= 0,
             "latency_ms.n must be an int >= 0")
    if n > 0:
        p50, p99, mx = lat.get("p50"), lat.get("p99"), lat.get("max")
        for name, v in (("p50", p50), ("p99", p99), ("max", mx)):
            _require(isinstance(v, (int, float)) and v >= 0,
                     f"latency_ms.{name} must be a number >= 0")
        _require(p50 <= p99 <= mx,
                 f"latency ordering broken: p50={p50} p99={p99} max={mx}")
    br = sv.get("breaker")
    _require(isinstance(br, dict), "breaker must be an object")
    _require(br.get("state") in BREAKER_STATES,
             f"breaker.state must be one of {BREAKER_STATES}, "
             f"got {br.get('state')!r}")
    trips = br.get("trips", 0)
    _require(isinstance(trips, int) and trips >= 0,
             "breaker.trips must be an int >= 0")
    if req.get("degraded", 0) > 0:
        _require(
            trips >= 1,
            "degraded responses claimed with breaker.trips == 0 — the "
            "host fallback only serves behind a tripped breaker",
        )
    drift = sv.get("drift") or {}
    _require(isinstance(drift, dict), "drift must be an object")
    if req.get("quarantined", 0) > 0:
        _require(
            int(drift.get("batches_flagged", 0)) >= 1
            and int(drift.get("quarantine_entries", 0)) >= 1,
            "quarantined responses claimed without drift evidence "
            "(drift.batches_flagged / quarantine_entries)",
        )
    q = sv.get("queue") or {}
    if req.get("rejected_queue", 0) > 0:
        _require(
            int(q.get("capacity", 0)) > 0,
            "queue rejections claimed with no bounded queue "
            "(queue.capacity must be > 0)",
        )
    tp = sv.get("throughput_rps")
    if tp is not None:
        _require(isinstance(tp, (int, float)) and tp >= 0,
                 "throughput_rps must be a number >= 0")
    wire = sv.get("wire")
    if wire is not None:
        _require(isinstance(wire, dict), "wire must be an object")
        wreq = wire.get("requests") or {}
        wsub = wreq.get("submitted")
        _require(isinstance(wsub, int) and wsub >= 0,
                 "wire.requests.submitted must be an int >= 0")
        wtotal = 0
        for o in OUTCOMES:
            v = wreq.get(o, 0)
            _require(isinstance(v, int) and v >= 0,
                     f"wire.requests.{o} must be an int >= 0")
            wtotal += v
        _require(
            wtotal == wsub,
            f"wire accounting broken: submitted={wsub} but outcomes sum "
            f"to {wtotal} — every wire request must end as exactly one "
            f"typed outcome",
        )
        codes = wire.get("status_codes") or {}
        _require(isinstance(codes, dict),
                 "wire.status_codes must be an object")
        ctotal = sum(int(v) for v in codes.values())
        _require(
            ctotal == wsub,
            f"wire status-code accounting broken: submitted={wsub} but "
            f"status codes sum to {ctotal} — every typed outcome maps to "
            f"exactly one status code",
        )
        # NOTE: wire submitted may legitimately EXCEED serving
        # submitted — a malformed body (422) is refused before it can
        # reach admission accounting; both layers stay internally
        # consistent, which is the rule that matters.
    fleet = sv.get("fleet")
    if fleet is not None:
        _require(isinstance(fleet, dict), "fleet must be an object")
        nrep = fleet.get("replicas")
        _require(isinstance(nrep, int) and nrep >= 1,
                 "fleet.replicas (configured width) must be an "
                 "int >= 1")
        _require(isinstance(fleet.get("active_fp"), str)
                 and fleet["active_fp"],
                 "fleet.active_fp must be a non-empty string")
        live = fleet.get("live_replicas")
        _require(isinstance(live, int) and live >= 0,
                 "fleet.live_replicas must be an int >= 0")
        per = fleet.get("per_replica")
        _require(isinstance(per, list) and len(per) == live,
                 "fleet.per_replica must list exactly "
                 "fleet.live_replicas entries")
        owners = fleet.get("submitted_by_owner")
        _require(isinstance(owners, dict),
                 "fleet.submitted_by_owner must be an object")
        osum = 0
        for part in ("replicas", "retired", "pool"):
            v = owners.get(part, 0)
            _require(isinstance(v, int) and v >= 0,
                     f"fleet.submitted_by_owner.{part} must be an "
                     f"int >= 0")
            osum += v
        _require(
            osum == sub,
            f"fleet ownership accounting broken: submitted={sub} but "
            f"owners (replicas+retired+pool) sum to {osum} — every "
            f"request must be attributable to exactly one owner",
        )
        swaps = fleet.get("swaps", [])
        _require(isinstance(swaps, list), "fleet.swaps must be a list")
        for i, sw in enumerate(swaps):
            _require(isinstance(sw, dict) and sw.get("from_fp")
                     and sw.get("to_fp"),
                     f"fleet.swaps[{i}] must carry from_fp and to_fp")
            _require(sw.get("from_fp") != sw.get("to_fp"),
                     f"fleet.swaps[{i}]: a swap onto the SAME "
                     f"fingerprint is not a swap")
        scales = fleet.get("scales", [])
        _require(isinstance(scales, list),
                 "fleet.scales must be a list")
        for i, sc in enumerate(scales):
            _require(isinstance(sc, dict),
                     f"fleet.scales[{i}] must be an object")
            frm, to = sc.get("from"), sc.get("to")
            _require(isinstance(frm, int) and frm >= 0
                     and isinstance(to, int) and to >= 1,
                     f"fleet.scales[{i}] must carry int from >= 0 "
                     f"and to >= 1")
            _require(frm != to,
                     f"fleet.scales[{i}]: a resize to the SAME width "
                     f"is not a scale action (no-ops are un-stamped)")
            _require(isinstance(sc.get("ts"), (int, float)),
                     f"fleet.scales[{i}].ts must be a number")
