"""Serving stats + the validated ``serving`` run-record section.

One :class:`ServingStats` per driver (the driver registers it as the
process's active stats so the heartbeat sampler can feed ``tail_run``'s
serving panel live). The section's load-bearing rule, enforced by
:func:`validate_serving` exactly like the robustness section's
recovery-needs-evidence rule: **every submitted request must be accounted
for** — ``requests.submitted`` must equal the sum of the outcome
counters. A serving record that lost track of even one request is
rejected, because "silently dropped" is the failure mode the whole
guarded path exists to make impossible.

Import discipline: stdlib only (``validate_run_record`` and the chaos
harness load this without jax).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "OUTCOMES",
    "BREAKER_STATES",
    "ServingStats",
    "active_stats",
    "live_summary",
    "validate_serving",
]

# Every way a request can leave the system. submit-time rejections
# (queue-full, invalid, closed) never reach a batch; the rest resolve
# from one.
OUTCOMES = (
    "ok",                 # labels returned, device path, breaker closed
    "degraded",           # labels returned by the HOST fallback, flagged
    "quarantined",        # drift gate refused confident labels; ledgered
    "rejected_queue",     # bounded-admission backpressure (retry-after)
    "rejected_invalid",   # malformed request, refused at admission
    "rejected_closed",    # typed ServerClosed (shutdown / undrained stop)
    "deadline_exceeded",  # typed late failure (queue wait or compute)
    "failed",             # fatal batch error, typed RequestFailed
)

BREAKER_STATES = ("closed", "open", "half_open")

# Rolling latency reservoir size: enough for a stable p99 (the live panel
# and the section both read it), bounded so a soak cannot grow the record.
_LATENCY_RING = 4096


class ServingStats:
    """Thread-safe counters for one serving driver's lifetime."""

    def __init__(self, queue_capacity: int = 0):
        self.queue_capacity = int(queue_capacity)
        self.counts: Dict[str, int] = {o: 0 for o in OUTCOMES}
        self.submitted = 0
        self.queue_depth = 0
        self.queue_peak = 0
        self.batches = 0
        self.batch_cells = 0
        self.batch_max = 0
        self.breaker_state = "closed"
        self.breaker_trips = 0
        self.drift_batches = 0
        self.quarantine_entries = 0
        self.consumed_s = 0.0       # self-measured driver bookkeeping
        self.classify_wall_s = 0.0  # cumulative classify-call wall
        self.started_unix = time.time()
        self._lat_ms: List[float] = []
        self._lat_i = 0             # ring cursor
        self._lat_n = 0
        self._lat_sum = 0.0
        self._lat_max = 0.0
        self._lock = threading.Lock()

    # -- notes -------------------------------------------------------------
    def note_submit(self, depth: int) -> None:
        with self._lock:
            self.submitted += 1
            self.queue_depth = int(depth)
            self.queue_peak = max(self.queue_peak, int(depth))

    def note_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.queue_peak = max(self.queue_peak, int(depth))

    def note_outcome(self, outcome: str,
                     latency_s: Optional[float] = None) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown serving outcome {outcome!r}")
        with self._lock:
            self.counts[outcome] += 1
            if latency_s is not None:
                ms = max(float(latency_s), 0.0) * 1e3
                if len(self._lat_ms) < _LATENCY_RING:
                    self._lat_ms.append(ms)
                else:
                    self._lat_ms[self._lat_i] = ms
                    self._lat_i = (self._lat_i + 1) % _LATENCY_RING
                self._lat_n += 1
                self._lat_sum += ms
                self._lat_max = max(self._lat_max, ms)

    def note_batch(self, n_requests: int, n_cells: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_cells += int(n_cells)
            self.batch_max = max(self.batch_max, int(n_cells))

    def note_breaker(self, state: str, tripped: bool = False) -> None:
        if state not in BREAKER_STATES:
            raise ValueError(f"unknown breaker state {state!r}")
        with self._lock:
            self.breaker_state = state
            if tripped:
                self.breaker_trips += 1

    def note_drift_batch(self, quarantined: int = 0) -> None:
        with self._lock:
            self.drift_batches += 1
            self.quarantine_entries += int(quarantined)

    def add_consumed(self, dt: float) -> None:
        with self._lock:
            self.consumed_s += max(float(dt), 0.0)

    def add_classify_wall(self, dt: float) -> None:
        with self._lock:
            self.classify_wall_s += max(float(dt), 0.0)

    # -- reads -------------------------------------------------------------
    def latency_ms(self) -> Dict[str, Any]:
        with self._lock:
            if self._lat_n == 0:
                return {"n": 0}
            # ONE sort for both quantiles: this runs on every heartbeat
            # tick under the same lock the submit/resolve hot path takes
            s = sorted(self._lat_ms)
            return {
                "n": self._lat_n,
                "p50": round(s[min(int(0.50 * len(s)), len(s) - 1)], 4),
                "p99": round(s[min(int(0.99 * len(s)), len(s) - 1)], 4),
                "max": round(self._lat_max, 4),
                "mean": round(self._lat_sum / self._lat_n, 4),
            }

    def section(self) -> Dict[str, Any]:
        """The run record's ``serving`` section (always present once a
        driver ran — unlike robustness, an all-healthy serving window is
        itself the evidence: N requests in, N outcomes out)."""
        lat = self.latency_ms()
        with self._lock:
            wall = max(time.time() - self.started_unix, 0.0)
            served = sum(self.counts[o]
                         for o in ("ok", "degraded", "quarantined"))
            return {
                "requests": {"submitted": self.submitted,
                             **dict(self.counts)},
                "latency_ms": lat,
                "throughput_rps": round(served / wall, 4) if wall else 0.0,
                "batches": {
                    "count": self.batches,
                    "cells": self.batch_cells,
                    "max_cells": self.batch_max,
                    "mean_cells": (round(self.batch_cells / self.batches, 2)
                                   if self.batches else 0.0),
                },
                "queue": {"depth_peak": self.queue_peak,
                          "capacity": self.queue_capacity},
                "breaker": {"state": self.breaker_state,
                            "trips": self.breaker_trips},
                "drift": {"batches_flagged": self.drift_batches,
                          "quarantine_entries": self.quarantine_entries},
                "consumed_s": round(self.consumed_s, 4),
                "classify_wall_s": round(self.classify_wall_s, 4),
                "window_s": round(wall, 4),
            }


# -- the process's active stats (heartbeat feed) ----------------------------

_ACTIVE: Optional[ServingStats] = None
_ACTIVE_LOCK = threading.Lock()


def set_active(stats: Optional[ServingStats]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = stats


def active_stats() -> Optional[ServingStats]:
    return _ACTIVE


def live_summary() -> Optional[Dict[str, Any]]:
    """Compact serving counters for one heartbeat tick (None = no driver
    running) — queue depth, rolling p99, breaker state, and the
    degraded/quarantined/rejected tallies tail_run's panel renders."""
    st = _ACTIVE
    if st is None:
        return None
    lat = st.latency_ms()
    with st._lock:
        out: Dict[str, Any] = {
            "queue_depth": st.queue_depth,
            "queue_cap": st.queue_capacity,
            "breaker": st.breaker_state,
            "ok": st.counts["ok"],
        }
        for key in ("degraded", "quarantined", "deadline_exceeded",
                    "failed"):
            if st.counts[key]:
                out[key] = st.counts[key]
        rejected = (st.counts["rejected_queue"]
                    + st.counts["rejected_invalid"]
                    + st.counts["rejected_closed"])
        if rejected:
            out["rejected"] = rejected
        if st.breaker_trips:
            out["breaker_trips"] = st.breaker_trips
    if lat.get("p99") is not None:
        out["p99_ms"] = lat["p99"]
    return out


# -- schema validation ------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"serving section: {msg}")


def validate_serving(sv: Dict[str, Any]) -> None:
    """Structural validation of a record's ``serving`` section
    (``export.validate_run_record`` dispatches here). Load-bearing rules:

    * accounting — ``requests.submitted == sum(outcome counters)``; a
      record that lost a request is rejected;
    * latency sanity — ``0 <= p50 <= p99 <= max`` whenever latencies
      were measured;
    * evidence coupling — degraded responses require a tripped breaker,
      quarantined responses require drift-flagged batches, queue
      rejections require a bounded queue (capacity > 0).
    """
    _require(isinstance(sv, dict), "must be an object")
    req = sv.get("requests")
    _require(isinstance(req, dict), "requests must be an object")
    sub = req.get("submitted")
    _require(isinstance(sub, int) and sub >= 0,
             "requests.submitted must be an int >= 0")
    total = 0
    for o in OUTCOMES:
        v = req.get(o, 0)
        _require(isinstance(v, int) and v >= 0,
                 f"requests.{o} must be an int >= 0")
        total += v
    _require(
        total == sub,
        f"request accounting broken: submitted={sub} but outcomes sum to "
        f"{total} — every request must end as exactly one of {OUTCOMES}",
    )
    lat = sv.get("latency_ms")
    _require(isinstance(lat, dict), "latency_ms must be an object")
    n = lat.get("n", 0)
    _require(isinstance(n, int) and n >= 0,
             "latency_ms.n must be an int >= 0")
    if n > 0:
        p50, p99, mx = lat.get("p50"), lat.get("p99"), lat.get("max")
        for name, v in (("p50", p50), ("p99", p99), ("max", mx)):
            _require(isinstance(v, (int, float)) and v >= 0,
                     f"latency_ms.{name} must be a number >= 0")
        _require(p50 <= p99 <= mx,
                 f"latency ordering broken: p50={p50} p99={p99} max={mx}")
    br = sv.get("breaker")
    _require(isinstance(br, dict), "breaker must be an object")
    _require(br.get("state") in BREAKER_STATES,
             f"breaker.state must be one of {BREAKER_STATES}, "
             f"got {br.get('state')!r}")
    trips = br.get("trips", 0)
    _require(isinstance(trips, int) and trips >= 0,
             "breaker.trips must be an int >= 0")
    if req.get("degraded", 0) > 0:
        _require(
            trips >= 1,
            "degraded responses claimed with breaker.trips == 0 — the "
            "host fallback only serves behind a tripped breaker",
        )
    drift = sv.get("drift") or {}
    _require(isinstance(drift, dict), "drift must be an object")
    if req.get("quarantined", 0) > 0:
        _require(
            int(drift.get("batches_flagged", 0)) >= 1
            and int(drift.get("quarantine_entries", 0)) >= 1,
            "quarantined responses claimed without drift evidence "
            "(drift.batches_flagged / quarantine_entries)",
        )
    q = sv.get("queue") or {}
    if req.get("rejected_queue", 0) > 0:
        _require(
            int(q.get("capacity", 0)) > 0,
            "queue rejections claimed with no bounded queue "
            "(queue.capacity must be > 0)",
        )
    tp = sv.get("throughput_rps")
    if tp is not None:
        _require(isinstance(tp, (int, float)) and tp >= 0,
                 "throughput_rps must be a number >= 0")
