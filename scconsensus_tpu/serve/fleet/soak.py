"""Runnable fleet-soak worker: the chaos harness's fleet workload.

    python -m scconsensus_tpu.serve.fleet.soak --dir DIR [--replicas N]
        [--requests N] [--cells M] [--seed S] [--swap-after K]
        [--ood-requests K] [--genes G] [--clusters C] [--train T]
        [--summary PATH] [--fresh] [--no-wire]

Builds (or loads) a deterministic demo atlas model under ``DIR/model_v1``
(and, with ``--swap-after``, a same-distribution variant under
``DIR/model_v2`` — same training data, reseeded landmarks, different
fingerprint), drives a replayable request set through the WIRE front
over a :class:`ReplicaPool`, optionally hot-swaps v1→v2 mid-traffic, and
writes one summary JSON. The exit code IS the chaos contract:

  0  every wire request ended as exactly one typed outcome, the serving
     section (wire + fleet accounting included) validates, and — in swap
     mode — every post-swap response was served by v2 only;
  1  the contract broke (a request vanished, validation failed, a
     response crossed models).

Round 20 (the telemetry plane's proving ground): ``--kill-after K``
hard-kills one replica (no drain) once K requests resolved — its queued
requests resolve typed ``rejected_closed`` and the pumps RETRY them with
the SAME trace id (``X-SCC-Trace-Id``), so the summary's per-attempt
evidence shows both attempts under one trace and the postmortem bundle
(tools/postmortem.py) can prove trace continuity across the kill →
respawn → retry arc. ``--heartbeat S`` arms an obs.live flight recorder
over the soak (heartbeat stream + partial record — the bundle's other
inputs), and the quarantine ledger lands under ``DIR/ledger`` so its
rows are trace-joinable too. ``--obs-overhead M`` measures the plane's
own cost (median wire latency over M requests, tracing+scrapes on vs
off) and stamps the gauge onto the record's validated ``slo`` section.

Because the atlas build, the request set, and classify are all seeded,
the per-request labels are a pure function of (model, request): the
``replay-across-replicas`` chaos plan runs the same set through 1 and N
replicas and pins ``sha(labels)`` equal — routing must never change an
answer.

This module also owns the **atlas→query generator** the ``atlas_query``
bench config drives (a bench config with a ledger baseline, not a
one-off script): :func:`build_atlas_model` / :func:`make_query_batches`
scale the same seeded gaussian-atlas shape to bench sizes.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "build_atlas_model",
    "make_query_batches",
    "run_fleet_soak",
    "main",
]


# --------------------------------------------------------------------------
# the atlas→query generator (bench + soak share it)
# --------------------------------------------------------------------------

def _gaussian_atlas(n_genes: int, n_clusters: int, n_train: int,
                    seed: int):
    """Seeded well-separated gaussian atlas: (N, G) training cells,
    per-cell labels 1..K, and the (K, G) centers queries draw from."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0.0, 4.0, size=(n_clusters, n_genes))
    per = max(n_train // n_clusters, 1)
    cells = np.concatenate([
        centers[c] + rng.normal(0.0, 0.6, size=(per, n_genes))
        for c in range(n_clusters)
    ]).astype(np.float32)
    labels = np.repeat(np.arange(1, n_clusters + 1), per)
    return cells, labels, centers


def build_atlas_model(model_dir: str, n_genes: int = 120,
                      n_clusters: int = 4, n_train: int = 360,
                      n_landmarks: Optional[int] = None, n_pcs: int = 8,
                      seed: int = 7,
                      landmark_seed: Optional[int] = None):
    """Freeze a seeded gaussian atlas into a servable consensus model
    through the REAL export pieces (pca_basis → landmark_ward_linkage →
    the shared ``freeze_model_arrays`` assembly → ArtifactStore save).
    ``landmark_seed`` reseeds only the landmark fit: same distribution,
    different fingerprint — the hot-swap soak's v2."""
    import jax.numpy as jnp

    from scconsensus_tpu.ops.pca import pca_basis
    from scconsensus_tpu.ops.pooling import landmark_ward_linkage
    from scconsensus_tpu.serve.model import (
        MODEL_STAGE,
        _assemble,
        freeze_model_arrays,
    )
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    cells, labels, _ = _gaussian_atlas(n_genes, n_clusters, n_train, seed)
    panel = np.arange(n_genes, dtype=np.int64)
    mean, comps = pca_basis(jnp.asarray(cells), min(n_pcs, n_genes))
    mean = np.asarray(mean, np.float32)
    comps = np.asarray(comps, np.float32)
    emb = (cells - mean) @ comps.T
    k = int(n_landmarks if n_landmarks
            else np.clip(round(2.0 * np.sqrt(cells.shape[0])), 16, 512))
    tree, assign, cents, _info = landmark_ward_linkage(
        emb, n_landmarks=min(k, cells.shape[0]),
        seed=seed if landmark_seed is None else int(landmark_seed),
    )
    arrays, meta = freeze_model_arrays(
        panel, mean, comps, emb, cents, assign, labels, tree,
        n_genes=n_genes, drift_margin=1.5,
        meta_extra={"deep_split": 2, "config_fp": "fleet-atlas",
                    "atlas": {"n_clusters": int(n_clusters),
                              "n_train": int(cells.shape[0]),
                              "seed": int(seed)}},
    )
    ArtifactStore(model_dir).save(MODEL_STAGE, arrays, meta)
    return _assemble(arrays, meta)


def make_query_batches(n_requests: int, cells_per: int, seed: int,
                       n_genes: int = 120, n_clusters: int = 4,
                       n_ood: int = 0) -> List[np.ndarray]:
    """Replayable query workload: batches drawn around the atlas centers
    (label transfer), the last ``n_ood`` drawn far outside (drift
    targets). Each batch also returns with a planted majority cluster so
    the bench can score transfer accuracy."""
    rng = np.random.default_rng(seed + 1)
    _, _, centers = _gaussian_atlas(n_genes, n_clusters, 4, seed)
    out: List[np.ndarray] = []
    for i in range(n_requests):
        if i >= n_requests - n_ood:
            x = rng.normal(40.0, 1.0, size=(cells_per, n_genes))
        else:
            c = centers[rng.integers(0, n_clusters)]
            x = c + rng.normal(0.0, 0.6, size=(cells_per, n_genes))
        out.append(np.asarray(x, np.float32))
    return out


# --------------------------------------------------------------------------
# the soak
# --------------------------------------------------------------------------

def _fast_cfg(deadline_s: Optional[float], ledger_dir: Optional[str],
              batch_window_s: float = 0.001):
    from scconsensus_tpu.serve.driver import ServeConfig

    return ServeConfig(
        batch_window_s=batch_window_s,
        default_deadline_s=deadline_s,
        ledger_dir=ledger_dir,
    )


def _measure_overhead(port: int, batch: np.ndarray, m: int,
                      concurrency: int = 4) -> Dict[str, Any]:
    """The plane accounting for itself: mean per-request WALL over a
    concurrent burst of ``m`` identical requests with the telemetry
    plane ON (trace minting + one /metrics scrape per ~8 requests — the
    always-on cost profile) vs OFF (SCC_OBS_TRACE=0, no scrapes). A
    burst, not sequential pings: sequential latency phase-locks with
    the batch window (bimodal by ± one window), while burst throughput
    amortizes batching and isolates the plane's own cost. Returns the
    gauge dict the ``slo`` section carries; BASELINE.md pins the
    ratio's noise band."""
    import http.client

    body = json.dumps({"cells": batch.tolist()})

    def _pump_n(n: int, scrape: bool) -> None:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        for i in range(n):
            conn.request("POST", "/classify", body=body,
                         headers={"Content-Type": "application/json"})
            conn.getresponse().read()
            if scrape and i % 16 == 0:
                conn.request("GET", "/metrics")
                conn.getresponse().read()
        conn.close()

    def _run(scrape: bool) -> float:
        _pump_n(2, scrape=False)  # settle caches outside the clock
        per = max(m // concurrency, 1)
        threads = [threading.Thread(target=_pump_n,
                                    args=(per, scrape), daemon=True)
                   for _ in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        return (time.perf_counter() - t0) * 1e3 / (per * concurrency)

    prev = os.environ.get("SCC_OBS_TRACE")
    try:
        os.environ["SCC_OBS_TRACE"] = "0"
        off_ms = _run(scrape=False)
        os.environ["SCC_OBS_TRACE"] = "1"
        on_ms = _run(scrape=True)
    finally:
        if prev is None:
            os.environ.pop("SCC_OBS_TRACE", None)
        else:
            os.environ["SCC_OBS_TRACE"] = prev
    return {"on_ms": round(on_ms, 4), "off_ms": round(off_ms, 4),
            "ratio": round(on_ms / off_ms, 4) if off_ms else None,
            "n": int(m)}


def run_fleet_soak(workdir: str, n_requests: int = 24,
                   cells_per: int = 16, seed: int = 7,
                   replicas: Optional[int] = None,
                   swap_after: Optional[int] = None,
                   kill_after: Optional[int] = None,
                   n_ood: int = 0, n_genes: int = 120,
                   n_clusters: int = 4, n_train: int = 360,
                   fresh: bool = False, concurrency: int = 4,
                   deadline_s: Optional[float] = None,
                   heartbeat_s: Optional[float] = None,
                   obs_overhead_requests: int = 0,
                   batch_window_s: float = 0.001) -> Dict[str, Any]:
    """Drive the request set through the wire front over a replica pool;
    returns the summary dict (see module doc). With ``swap_after``, the
    fleet hot-swaps to the v2 model once that many requests have
    resolved — mid-traffic, while the pumps keep pumping. With
    ``kill_after``, one replica is hard-killed (and respawned) once that
    many requests have resolved; refused requests are retried with the
    SAME trace id."""
    import http.client

    from scconsensus_tpu.obs import trace as obs_trace
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.obs.live import LiveRecorder
    from scconsensus_tpu.serve import slo as serve_slo
    from scconsensus_tpu.serve.fleet.pool import ReplicaPool
    from scconsensus_tpu.serve.fleet.wire import TRACE_HEADER, WireFront
    from scconsensus_tpu.serve.model import MODEL_STAGE
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    v1_dir = os.path.join(workdir, "model_v1")
    v2_dir = os.path.join(workdir, "model_v2")
    built = False
    if fresh or not ArtifactStore(v1_dir).has(MODEL_STAGE):
        build_atlas_model(v1_dir, n_genes=n_genes, n_clusters=n_clusters,
                          n_train=n_train, seed=seed)
        built = True
    if swap_after is not None and (
            fresh or not ArtifactStore(v2_dir).has(MODEL_STAGE)):
        build_atlas_model(v2_dir, n_genes=n_genes, n_clusters=n_clusters,
                          n_train=n_train, seed=seed,
                          landmark_seed=seed + 1000)

    requests = make_query_batches(n_requests, cells_per, seed,
                                  n_genes=n_genes, n_clusters=n_clusters,
                                  n_ood=n_ood)
    outcomes: List[Optional[Dict[str, Any]]] = [None] * len(requests)
    attempts: List[Dict[str, Any]] = []
    label_blobs: List[bytes] = [b""] * len(requests)
    resolved = [0]
    swap_state: Dict[str, Any] = {"done": False, "to_fp": None}
    kill_state: Dict[str, Any] = {"done": False, "kills": []}
    lock = threading.Lock()
    next_i = [0]
    # swap mode reserves a TAIL of the request set until the cutover
    # lands: "hot-swap mid-traffic" must actually observe post-swap
    # traffic, not just in-flight survivors (the swap can outlast a small
    # request set on a fast box)
    swap_gate = (max(min(swap_after, len(requests)),
                     len(requests) - max(len(requests) // 3, 2))
                 if swap_after is not None else None)

    # flight recorder over the soak (round 20): the tracer catches each
    # replica's serve_request spans (trace ids included), the recorder
    # streams heartbeats whose serving panel carries the recent-trace
    # ring — the postmortem bundle's per-process inputs. Ledger rows
    # land under DIR/ledger, trace-keyed.
    tracer = obs_trace.Tracer(sync="off")
    recorder = LiveRecorder(
        os.path.join(workdir, "FLEET_SOAK"),
        metric="fleet soak flight record",
        extra={"config": "fleet-soak", "platform": "cpu"},
        heartbeat_s=heartbeat_s,
    )
    recorder.start(install_signals=False)
    ledger_dir = os.path.join(workdir, "ledger")

    pool = ReplicaPool(v1_dir, n_replicas=replicas,
                       config=_fast_cfg(deadline_s, ledger_dir,
                                        batch_window_s=batch_window_s))
    fp1 = pool.active_fingerprint()
    front = WireFront(pool)
    obs_overhead: Optional[Dict[str, Any]] = None
    try:
      with pool, front:
        port = front.port

        def _pump():
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            while True:
                with lock:
                    if next_i[0] >= len(requests):
                        conn.close()
                        return
                    i = next_i[0]
                    if (swap_gate is not None and i >= swap_gate
                            and not swap_state["done"]):
                        i = None  # tail held back until the swap lands
                    else:
                        next_i[0] += 1
                if i is None:
                    time.sleep(0.002)
                    continue
                body = json.dumps({"cells": requests[i].tolist()})
                trace_id: Optional[str] = None
                attempt = 0
                while True:
                    attempt += 1
                    post_swap = bool(swap_state["done"])
                    headers = {"Content-Type": "application/json"}
                    if trace_id:
                        # the retry carries the SAME id: both attempts
                        # tell one story under one trace
                        headers[TRACE_HEADER] = trace_id
                    try:
                        conn.request("POST", "/classify", body=body,
                                     headers=headers)
                        r = conn.getresponse()
                        doc = json.loads(r.read())
                        tid = (doc.get("trace_id")
                               or r.getheader(TRACE_HEADER))
                        out = {
                            "i": i, "status": r.status,
                            "outcome": doc.get("outcome"),
                            "model_fp": doc.get("model_fp"),
                            "post_swap": post_swap,
                            "trace_id": tid,
                            "attempt": attempt,
                            "ts": round(time.time(), 3),
                        }
                        if doc.get("labels") is not None:
                            label_blobs[i] = np.asarray(
                                doc["labels"], np.int64
                            ).tobytes()
                    except (OSError, http.client.HTTPException,
                            json.JSONDecodeError) as e:
                        out = {"i": i, "status": None,
                               "outcome": "wire-error",
                               "error": str(e)[:200],
                               "post_swap": post_swap,
                               "trace_id": trace_id,
                               "attempt": attempt,
                               "ts": round(time.time(), 3)}
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=60)
                    with lock:
                        attempts.append(out)
                    trace_id = out.get("trace_id") or trace_id
                    if (kill_after is not None and attempt < 5
                            and out["outcome"] in ("rejected_queue",
                                                   "rejected_closed")):
                        # a kill-refused request is resubmitted under
                        # its original trace id — the respawned replica
                        # serves attempt 2
                        time.sleep(0.05)
                        continue
                    outcomes[i] = out
                    break
                with lock:
                    resolved[0] += 1

        threads = [threading.Thread(target=_pump, daemon=True)
                   for _ in range(max(1, concurrency))]
        for t in threads:
            t.start()
        if swap_after is not None:
            # mid-traffic hot-swap: wait for the trigger count, cut over
            # while the pumps keep pumping
            while True:
                with lock:
                    if resolved[0] >= min(swap_after, len(requests)):
                        break
                time.sleep(0.002)
            to_fp = pool.hot_swap(v2_dir)
            swap_state["to_fp"] = to_fp
            swap_state["done"] = True
        if kill_after is not None:
            # hard-kill one replica mid-traffic (no drain: its queued
            # requests refuse typed and the pumps retry them). Up to 3
            # kills until one actually catches queued requests — each
            # kill respawns, so the fleet is back at width either way.
            gate = min(int(kill_after), len(requests) - 1)
            while True:
                with lock:
                    if resolved[0] >= gate:
                        break
                time.sleep(0.002)
            for _ in range(3):
                kill = pool.kill_replica()
                kill_state["kills"].append(kill)
                with lock:
                    remaining = len(requests) - resolved[0]
                if kill["refused"] or remaining <= 2:
                    break
                time.sleep(0.01)
            kill_state["done"] = True
        for t in threads:
            t.join(timeout=180.0)
        # sections FIRST: the record's p99/availability/burn describe
        # the soak under test, not the synthetic overhead burst (which
        # also toggles tracing off for half its requests)
        section = front.serving_section()
        slo_section = front.slo_section()
        if obs_overhead_requests > 0:
            obs_overhead = _measure_overhead(port, requests[0],
                                             obs_overhead_requests)
            serve_slo.set_obs_overhead(obs_overhead)
            slo_section["obs_overhead"] = dict(obs_overhead)
    except BaseException:
        # the postmortem's own input must not lie: a soak that died
        # mid-run leaves a crash-stamped partial, never a clean one
        recorder.stop("crash")
        serve_slo.set_obs_overhead(None)
        raise
    else:
        recorder.stop("clean")
        serve_slo.set_obs_overhead(None)

    rec = build_run_record(
        metric="fleet soak wire p99 latency",
        value=(section.get("latency_ms") or {}).get("p99"),
        unit="ms",
        extra={"config": "fleet-soak", "platform": "cpu"},
        spans=tracer.live_span_records(),
        serving=section,
        slo=slo_section,
    )
    accounting_ok = True
    try:
        validate_run_record(rec)
    except ValueError as e:
        accounting_ok = False
        rec = {"invalid": str(e)}

    done = [o for o in outcomes if o is not None]
    fps_seen = sorted({o["model_fp"] for o in done if o.get("model_fp")})
    post = [o for o in done
            if o.get("post_swap") and o.get("model_fp")]
    post_swap_pure = all(o["model_fp"] == swap_state["to_fp"]
                         for o in post) if swap_state["done"] else None
    h = hashlib.sha256()
    for blob in label_blobs:
        h.update(blob)
    counts: Dict[str, int] = {}
    for o in done:
        counts[str(o["outcome"])] = counts.get(str(o["outcome"]), 0) + 1
    # trace evidence (round 20): every attempt carries a trace id; a
    # request that took >1 attempt must have kept ONE id across them —
    # the continuity contract the postmortem bundle proves end to end
    by_req: Dict[int, List[Dict[str, Any]]] = {}
    for a in attempts:
        by_req.setdefault(int(a["i"]), []).append(a)
    retried = {
        i: [{"attempt": a["attempt"], "outcome": a["outcome"],
             "status": a["status"], "trace_id": a["trace_id"],
             "ts": a["ts"]} for a in sorted(atts,
                                            key=lambda x: x["attempt"])]
        for i, atts in by_req.items() if len(atts) > 1
    }
    trace_continuity = all(
        len({a["trace_id"] for a in atts if a["trace_id"]}) == 1
        for atts in retried.values()
    ) if retried else None
    traced = [o for o in done if o.get("trace_id")]
    ok = (len(done) == len(requests)
          and accounting_ok
          and not any(o["outcome"] == "wire-error" for o in done)
          and (post_swap_pure is not False)
          and (trace_continuity is not False))
    if kill_after is not None:
        # the kill contract: the kill landed, the fleet respawned back
        # to width, and every request STILL ended served (retries
        # rescued the refused ones) — zero lost requests across a
        # replica death
        ok = (ok and kill_state["done"]
              and all(o["outcome"] in ("ok", "degraded", "quarantined")
                      for o in done))
    summary: Dict[str, Any] = {
        "ok": ok,
        "requests": len(requests),
        "resolved": len(done),
        "replicas": pool.n_default,
        "model_built": built,
        "fp_v1": fp1,
        "fp_v2": swap_state["to_fp"],
        "swapped": bool(swap_state["done"]),
        "post_swap_pure": post_swap_pure,
        "post_swap_responses": len(post),
        "fps_seen": fps_seen,
        "labels_sha": h.hexdigest(),
        "outcome_counts": counts,
        "accounting_ok": accounting_ok,
        "traced_responses": len(traced),
        "trace_continuity": trace_continuity,
        "retried": retried,
        "kills": list(kill_state["kills"]),
        "spans_done": len(tracer.spans),
        "outcomes": done,
        "attempts": attempts,
        "record": rec,
    }
    if obs_overhead is not None:
        summary["obs_overhead"] = obs_overhead
    if recorder.enabled:
        summary["heartbeat_stream"] = os.path.basename(recorder.hb_path)
        summary["partial_record"] = os.path.basename(
            recorder.partial_path)
    return summary


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="fleet soak worker")
    ap.add_argument("--dir", required=True, help="work directory")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--cells", type=int, default=16)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--swap-after", type=int, default=None,
                    help="hot-swap to the v2 model once this many "
                         "requests resolved (mid-traffic)")
    ap.add_argument("--kill-after", type=int, default=None,
                    help="hard-kill (and respawn) one replica once this "
                         "many requests resolved; refused requests are "
                         "retried under their original trace id")
    ap.add_argument("--heartbeat", type=float, default=None,
                    help="flight-recorder heartbeat cadence in seconds "
                         "(default: SCC_OBS_HEARTBEAT; 0 disables)")
    ap.add_argument("--obs-overhead", type=int, default=0,
                    help="measure the telemetry plane's own cost over "
                         "this many extra requests (plane on vs off) "
                         "and stamp the gauge onto the slo section")
    ap.add_argument("--window", type=float, default=0.001,
                    help="replica batch window (s)")
    ap.add_argument("--concurrency", type=int, default=4,
                    help="client pump threads")
    ap.add_argument("--ood-requests", type=int, default=0)
    ap.add_argument("--genes", type=int, default=120)
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--train", type=int, default=360)
    ap.add_argument("--summary", default=None)
    ap.add_argument("--fresh", action="store_true")
    ap.add_argument("--deadline", type=float, default=None)
    args = ap.parse_args(argv)

    summary_path = args.summary or os.path.join(args.dir,
                                                "FLEET_SOAK_SUMMARY.json")
    os.makedirs(args.dir, exist_ok=True)
    summary = run_fleet_soak(
        args.dir, n_requests=args.requests, cells_per=args.cells,
        seed=args.seed, replicas=args.replicas,
        swap_after=args.swap_after, kill_after=args.kill_after,
        n_ood=args.ood_requests,
        n_genes=args.genes, n_clusters=args.clusters, n_train=args.train,
        fresh=args.fresh, concurrency=args.concurrency,
        deadline_s=args.deadline,
        heartbeat_s=args.heartbeat,
        obs_overhead_requests=args.obs_overhead,
        batch_window_s=args.window,
    )
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "requests": summary["requests"],
        "resolved": summary["resolved"],
        "replicas": summary["replicas"],
        "swapped": summary["swapped"],
        "post_swap_pure": summary["post_swap_pure"],
        "kills": len(summary["kills"]),
        "retried": len(summary["retried"]),
        "trace_continuity": summary["trace_continuity"],
        "outcome_counts": summary["outcome_counts"],
        "labels_sha": summary["labels_sha"][:16],
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
