"""The drift-to-reconsensus loop: quarantined cells back into consensus.

Round 15's drift gate refuses to label what no longer fits the frozen
model and ledgers the evidence; this module closes the loop the ledger
opened (ROADMAP item 3c, the Secuer argument: landmark-sketch clustering
is cheap enough to re-run incrementally on small batches):

1. **Accumulate** — :func:`read_quarantine_batch` folds the ledger dir's
   persisted cell payloads (``quarantine_cells/*.npy``, written by the
   driver alongside each ledger line) into one batch.
2. **Classify against landmarks** — every quarantined cell is projected
   through the frozen PCA basis and scored against the existing
   landmarks; cells back inside the calibrated drift threshold CONFORM
   (a batch can quarantine on a fraction — the conforming rest needs no
   new structure).
3. **Mini-refine the spill** — non-conforming cells get a landmark
   mini-recluster (sketch Lloyd → occupancy-weighted Ward → dynamic
   cut), exactly the r12 engine at quarantine-batch scale.
4. **Merge via the contingency heuristic** — the frozen model's
   nearest-cluster claim vs the mini-refine's cut run through the
   paper's ``automated_consensus`` merge grammar: overlap keeps the old
   label, genuine novelty becomes new clusters numbered past the
   existing label space.
5. **Export + hot-swap** — the combined landmark set (old centroids,
   old labels, old occupancy + the new ones) freezes into a new
   sha256-verified model artifact whose fingerprint differs, and
   :func:`run_reconsensus` hot-swaps it into the fleet through the
   verified load path. The consumed ledger is renamed aside
   (``*.consumed-N``), so the next accumulation starts clean.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.serve.driver import (
    QUARANTINE_CELLS_DIR,
    QUARANTINE_LEDGER_NAME,
)
from scconsensus_tpu.serve.model import (
    MODEL_STAGE,
    _CALIB_QS,
    ConsensusModel,
    _assemble,
)

__all__ = [
    "read_quarantine_batch",
    "reconsensus_update",
    "run_reconsensus",
]


# --------------------------------------------------------------------------
# accumulate
# --------------------------------------------------------------------------

def _read_ledger_file(path: str, cells_dir: str
                      ) -> Tuple[np.ndarray, List[Dict[str, Any]]]:
    """Fold one ledger file + payload dir into ``(cells (M, G) float32,
    entries)``. ``cells_file`` entries resolve by basename into
    ``cells_dir`` (payloads live flat there), so a snapshotted ledger
    reads against its snapshotted payload dir. Entries without a
    persisted payload (cap reached, write failed) are kept in the entry
    list — they are evidence — but contribute no cells. Unreadable
    payloads are skipped, never fatal: the ledger is an append-only
    audit trail a crashed server may have left mid-write."""
    entries: List[Dict[str, Any]] = []
    blocks: List[np.ndarray] = []
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return np.zeros((0, 0), np.float32), entries
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            e = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(e, dict):
            continue
        entries.append(e)
        rel = e.get("cells_file")
        if not rel:
            continue
        try:
            blocks.append(np.asarray(
                np.load(os.path.join(cells_dir, os.path.basename(rel)),
                        allow_pickle=False),
                np.float32,
            ))
        except (OSError, ValueError):
            continue
    if not blocks:
        return np.zeros((0, 0), np.float32), entries
    return np.concatenate(blocks, axis=0), entries


def read_quarantine_batch(ledger_dir: str
                          ) -> Tuple[np.ndarray, List[Dict[str, Any]]]:
    """Fold a live ledger dir into ``(cells, entries)`` — see
    :func:`_read_ledger_file`."""
    return _read_ledger_file(
        os.path.join(ledger_dir, QUARANTINE_LEDGER_NAME),
        os.path.join(ledger_dir, QUARANTINE_CELLS_DIR),
    )


# --------------------------------------------------------------------------
# the update
# --------------------------------------------------------------------------

def _host_embed(model: ConsensusModel, cells: np.ndarray) -> np.ndarray:
    """Project (n, G) cells through the frozen panel + PCA basis — the
    same float64 math as ``classify_host``, shared so the loop scores
    drift exactly the way the serving driver did."""
    xp = model._gather_panel(cells).astype(np.float64)
    return ((xp - model.pca_mean.astype(np.float64))
            @ model.pca_components.astype(np.float64).T)


def _nearest(proj: np.ndarray, cents: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
    c = np.asarray(cents, np.float64)
    d2 = (np.sum(proj * proj, axis=1, keepdims=True)
          - 2.0 * proj @ c.T
          + np.sum(c * c, axis=1)[None, :])
    j = np.argmin(d2, axis=1)
    dist = np.sqrt(np.maximum(d2[np.arange(j.size), j], 0.0))
    return j, dist


def reconsensus_update(
    model: ConsensusModel,
    cells: np.ndarray,
    seed: int = 0,
    deep_split: int = 2,
    min_cluster_size: int = 4,
    drift_margin: Optional[float] = None,
) -> Tuple[Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]],
           Dict[str, Any]]:
    """One incremental consensus update from a quarantine batch.

    Returns ``((arrays, meta) | None, summary)`` — the arrays+meta of the
    updated model artifact (None when the batch holds no recoverable new
    structure; the summary says why). The updated model keeps every old
    landmark (centroid, label, occupancy) untouched: cells that still
    conform keep classifying identically — the update only ADDS decision
    surface, it never rewrites the frozen atlas.
    """
    from scconsensus_tpu.consensus.contingency import automated_consensus
    from scconsensus_tpu.ops.linkage import ward_linkage
    from scconsensus_tpu.ops.pooling import (
        centroid_majority_labels,
        landmark_ward_linkage,
    )
    from scconsensus_tpu.ops.treecut import cutree_hybrid

    m = int(cells.shape[0]) if cells.size else 0
    summary: Dict[str, Any] = {
        "parent_fp": model.fingerprint(),
        "n_batch": m,
        "updated": False,
    }
    if m == 0:
        summary["reason"] = "empty quarantine batch"
        return None, summary

    proj = _host_embed(model, cells)
    j_old, dist_old = _nearest(proj, model.centroids)
    labels_old = model.centroid_labels[j_old].astype(np.int64)
    conform = dist_old <= model.drift_threshold
    nc = ~conform
    n_nc = int(nc.sum())
    summary["n_conforming"] = int(conform.sum())
    summary["n_nonconforming"] = n_nc
    if n_nc < max(2 * min_cluster_size, 8):
        summary["reason"] = (
            f"only {n_nc} non-conforming cells — no recoverable new "
            f"structure (conforming cells need no reconsensus)"
        )
        return None, summary

    # (3) landmark mini-refine on the spill: the r12 engine at batch scale
    k_mini = int(np.clip(round(2.0 * np.sqrt(n_nc)), 8, 256))
    k_mini = min(k_mini, n_nc)
    tree_nc, assign_nc, cents_nc, info = landmark_ward_linkage(
        np.asarray(proj[nc], np.float32), n_landmarks=k_mini, seed=seed,
    )
    counts_nc = np.bincount(
        assign_nc, minlength=cents_nc.shape[0]
    ).astype(np.int64)
    cut = cutree_hybrid(
        tree_nc, cents_nc, deep_split=deep_split,
        min_cluster_size=min_cluster_size,
        weights=counts_nc.astype(np.float64),
    )
    mini_labels = np.asarray(cut, np.int64)[assign_nc]  # per nc cell

    # (4) the paper's merge grammar over the spill: the frozen model's
    # nearest-cluster claim vs the drift view. The mini labels are
    # namespaced ("n<k>") so a mini cluster id can never collide with an
    # existing label value. Overlapping mass keeps the old label;
    # compound/new labels become clusters numbered past the existing
    # label space; anything touching the mini unassigned bucket ("n0")
    # stays unassigned — noise must not found a cluster.
    consensus = automated_consensus(
        labels_old[nc].astype(str),
        np.array([f"n{v}" for v in mini_labels]),
        min_clust_size=min_cluster_size,
    )
    existing = set(int(v) for v in np.unique(model.centroid_labels)
                   if int(v) > 0)
    existing |= set(int(v) for v in model.meta.get("label_values", []))
    next_id = max(existing | {0}) + 1
    mapping: Dict[str, int] = {}
    for s in sorted(np.unique(consensus)):
        if s.isdigit() and int(s) in existing:
            mapping[s] = int(s)  # merged back into an existing cluster
        elif s == "0" or "n0" in s.split("_"):
            mapping[s] = 0  # unassigned noise, never a new cluster
        else:
            mapping[s] = next_id  # genuinely new structure
            next_id += 1
    merged_nc = np.array([mapping[s] for s in consensus], np.int64)
    new_ids = sorted(set(mapping.values()) - existing - {0})
    summary["merge_table"] = {s: int(v) for s, v in mapping.items()}
    summary["n_new_clusters"] = len(new_ids)
    if not new_ids:
        summary["reason"] = (
            "contingency merge folded every non-conforming cell back "
            "into existing clusters — drift without new structure"
        )
        return None, summary

    # (5) additive landmark set: new centroids labeled by majority vote
    # of the merged consensus (unlabeled mini-landmarks are noise and
    # are dropped — a landmark that would serve label 0 serves nothing)
    votes = centroid_majority_labels(assign_nc, merged_nc,
                                     cents_nc.shape[0])
    keep = (votes > 0) & (counts_nc > 0)
    if not keep.any():
        summary["reason"] = "every mini-landmark voted unassigned"
        return None, summary
    centroids = np.vstack([
        model.centroids.astype(np.float32),
        np.asarray(cents_nc[keep], np.float32),
    ])
    centroid_labels = np.concatenate([model.centroid_labels,
                                      votes[keep]]).astype(np.int64)
    centroid_counts = np.concatenate([model.centroid_counts,
                                      counts_nc[keep]]).astype(np.int64)
    tree = ward_linkage(centroids.astype(np.float64),
                        weights=centroid_counts.astype(np.float64))

    # recalibrate drift on the combined surface: the batch's distances to
    # the combined centroids can only widen the calibration (max-merge) —
    # the updated model must keep admitting everything the old one did
    _, dist_new = _nearest(proj, centroids)
    batch_q = (np.quantile(dist_new, _CALIB_QS) if dist_new.size
               else np.zeros(len(_CALIB_QS)))
    calib_q = np.maximum(model.calib_q, batch_q)
    margin = float(drift_margin if drift_margin is not None
                   else model.meta.get("drift_margin")
                   or env_flag("SCC_SERVE_DRIFT_MARGIN"))
    threshold = float(max(model.drift_threshold,
                          batch_q[_CALIB_QS.index(0.99)] * margin))

    label_values = sorted(existing | set(new_ids))
    meta: Dict[str, Any] = dict(model.meta)
    meta.update({
        "created_unix": round(time.time(), 3),
        "n_cells": int(meta.get("n_cells", 0)) + m,
        "k": int(centroids.shape[0]),
        "drift_margin": margin,
        "drift_threshold": threshold,
        "label_values": [int(v) for v in label_values],
        "reconsensus": {
            "parent_fp": model.fingerprint(),
            "round": int((model.meta.get("reconsensus") or {})
                         .get("round", 0)) + 1,
            "n_batch": m,
            "n_nonconforming": n_nc,
            "n_new_clusters": len(new_ids),
            "new_labels": [int(v) for v in new_ids],
            "mini_landmarks": int(keep.sum()),
        },
    })
    arrays = {
        "panel_idx": np.asarray(model.panel_idx, np.int64),
        "pca_mean": np.asarray(model.pca_mean, np.float32),
        "pca_components": np.asarray(model.pca_components, np.float32),
        "centroids": centroids,
        "centroid_labels": centroid_labels,
        "centroid_counts": centroid_counts,
        "tree_merge": np.asarray(tree.merge),
        "tree_height": np.asarray(tree.height),
        "tree_order": np.asarray(tree.order),
        "calib_q": np.asarray(calib_q, np.float64),
    }
    summary["updated"] = True
    summary["new_labels"] = [int(v) for v in new_ids]
    summary["mini_info"] = {k: v for k, v in info.items()
                            if isinstance(v, (int, float, str))}
    return (arrays, meta), summary


# --------------------------------------------------------------------------
# the loop
# --------------------------------------------------------------------------

def run_reconsensus(
    ledger_dir: str,
    out_dir: str,
    model: Optional[ConsensusModel] = None,
    pool=None,
    min_cells: Optional[int] = None,
    seed: int = 0,
    deep_split: int = 2,
    min_cluster_size: int = 4,
    consume: bool = True,
) -> Dict[str, Any]:
    """One turn of the drift-to-reconsensus loop: accumulate → update →
    export → hot-swap. ``model`` defaults to the pool's active model.
    Returns the summary (``updated`` False with a named reason when the
    evidence is insufficient — the ledger keeps accumulating).

    ``consume=True`` snapshots the ledger (+ its cell payload dir) aside
    as ``*.consumed-N`` BEFORE processing — evidence appended by live
    replicas while the mini-refine runs lands in a fresh ledger and is
    never swallowed unread — and restores the snapshot back into the
    live ledger (merge-append if new evidence arrived meanwhile) when no
    update lands, so evidence is never double-counted, never destroyed,
    and never starved out of a future loop turn.
    """
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    if model is None:
        if pool is None:
            raise ValueError("run_reconsensus needs a model or a pool")
        model = pool.active_model()
    floor = int(min_cells if min_cells is not None
                else env_flag("SCC_FLEET_RECON_MIN_CELLS"))
    snap = _snapshot_ledger(ledger_dir) if consume else None
    committed = False
    try:
        if consume:
            cells, entries = (_read_ledger_file(*snap) if snap
                              else (np.zeros((0, 0), np.float32), []))
        else:
            cells, entries = read_quarantine_batch(ledger_dir)
        n = int(cells.shape[0]) if cells.size else 0
        if n < floor:
            return {
                "updated": False,
                "parent_fp": model.fingerprint(),
                "n_batch": n,
                "n_entries": len(entries),
                "reason": f"{n} accumulated cells < the {floor}-cell "
                          f"floor (SCC_FLEET_RECON_MIN_CELLS)",
            }
        built, summary = reconsensus_update(
            model, cells, seed=seed, deep_split=deep_split,
            min_cluster_size=min_cluster_size,
        )
        summary["n_entries"] = len(entries)
        if built is None:
            return summary
        arrays, meta = built
        ArtifactStore(out_dir).save(MODEL_STAGE, arrays, meta)
        new_model = _assemble(arrays, meta)
        summary["new_fp"] = new_model.fingerprint()
        summary["model_dir"] = out_dir
        if pool is not None:
            # back into the fleet through the VERIFIED load path: the
            # swap reads the artifact we just wrote, sha256 and all —
            # the loop never injects an unverified in-memory model
            summary["swapped_fp"] = pool.hot_swap(out_dir)
        committed = True
        summary["ledger_consumed"] = bool(snap)
        return summary
    finally:
        if snap and not committed:
            # no model landed (insufficient evidence, no new structure,
            # or a crash): the snapshot flows BACK into the live ledger
            # so the evidence keeps accumulating toward a future turn
            _restore_snapshot(ledger_dir, snap)


def _snapshot_ledger(ledger_dir: str
                     ) -> Optional[Tuple[str, str]]:
    """Move the live ledger + payload dir aside as ``*.consumed-N``
    BEFORE reading (evidence appended during processing lands in a fresh
    live ledger, never consumed unread). Returns the snapshot's
    ``(ledger_path, cells_dir)`` or None when there is no ledger."""
    path = os.path.join(ledger_dir, QUARANTINE_LEDGER_NAME)
    cdir = os.path.join(ledger_dir, QUARANTINE_CELLS_DIR)
    if not os.path.exists(path):
        return None
    n = 1
    while (os.path.exists(f"{path}.consumed-{n}")
           or os.path.exists(f"{cdir}.consumed-{n}")):
        n += 1
    try:
        os.replace(path, f"{path}.consumed-{n}")
        if os.path.exists(cdir):
            os.replace(cdir, f"{cdir}.consumed-{n}")
    except OSError:
        return None
    return f"{path}.consumed-{n}", f"{cdir}.consumed-{n}"


def _restore_snapshot(ledger_dir: str, snap: Tuple[str, str]) -> None:
    """Fold a snapshot back into the live ledger: plain rename when
    nothing new arrived, merge-append otherwise (snapshot lines prepend
    into the live file; payloads move back into the live dir — names
    are unique per (pid, seq), so collisions don't occur in practice
    and a collider is left in the snapshot rather than clobbered)."""
    snap_ledger, snap_cells = snap
    path = os.path.join(ledger_dir, QUARANTINE_LEDGER_NAME)
    cdir = os.path.join(ledger_dir, QUARANTINE_CELLS_DIR)
    try:
        if not os.path.exists(path) and not os.path.exists(cdir):
            os.replace(snap_ledger, path)
            if os.path.exists(snap_cells):
                os.replace(snap_cells, cdir)
            return
        with open(snap_ledger) as f:
            old_lines = f.read()
        with open(path, "a") as f:
            f.write(old_lines)
        os.remove(snap_ledger)
        if os.path.exists(snap_cells):
            os.makedirs(cdir, exist_ok=True)
            for name in os.listdir(snap_cells):
                dst = os.path.join(cdir, name)
                if not os.path.exists(dst):
                    os.replace(os.path.join(snap_cells, name), dst)
            if not os.listdir(snap_cells):
                os.rmdir(snap_cells)
    except OSError:
        pass  # best-effort: the snapshot stays on disk as the audit copy
