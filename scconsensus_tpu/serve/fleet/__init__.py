"""Serving fleet: wire front, multi-replica hot-swap, reconsensus loop.

The round-15 ``ConsensusServer`` is an in-process driver; this package is
what stands between it and real traffic (ROADMAP item 3):

* ``fleet.pool`` — :class:`ReplicaPool`: N ``ConsensusServer`` workers
  behind ONE shared admission layer with least-depth routing, per-replica
  circuit breakers, **model hot-swap by artifact fingerprint** (load v2
  through the readonly sha256 path, atomic cutover, drain v1's in-flight
  batches — a request is never split across models), and multi-model
  routing keyed on model fingerprint for atlas-per-tissue deployments.
* ``fleet.wire`` — :class:`WireFront`: a stdlib-only threaded HTTP front
  where every wire request resolves to exactly one typed outcome mapped
  to exactly one status code, plus ``/healthz`` and ``/metrics`` fed from
  ``serve.metrics.live_summary``. The r15 accounting rule (submitted ==
  Σ outcomes) holds at the wire layer and is validated in the run record.
* ``fleet.reconsensus`` — the drift-to-reconsensus loop: accumulated
  quarantine-ledger cells → classify against the frozen landmarks →
  spill non-conforming cells into a landmark mini-refine → merge via the
  paper's contingency heuristic → export → hot-swap back into the fleet.
  Closes the loop the r15 quarantine ledger opened.
* ``fleet.loadgen`` — the open-loop load generator (round 21): seeded
  Poisson/burst arrival schedules over diurnal/spike/ramp rate
  profiles, traffic mixes drawn from registered workload-zoo
  scenarios, driven through the REAL wire front; emits a wire-side run
  record whose headline is sustained RPS at SLO.
* ``fleet.autoscale`` — the burn-rate fleet autoscaler (round 21): a
  pure table-testable control policy (streak + cooldown hysteresis)
  over the r20 multi-window burn rates and queue pressure, actuating
  replica width (``ReplicaPool.scale_to``), admission tightening, and
  explicit degraded-mode entry/exit — every action a typed
  ``actuation`` record on the trace/ledger plane.

Import discipline: this module is import-light; the heavy pieces load
lazily (the chaos harness imports the package root without jax).
"""

__all__ = ["ReplicaPool", "WireFront", "Autoscaler", "AutoscalePolicy",
           "run_load", "run_reconsensus",
           "reconsensus_update", "read_quarantine_batch"]


def __getattr__(name):
    if name == "ReplicaPool":
        from scconsensus_tpu.serve.fleet.pool import ReplicaPool

        return ReplicaPool
    if name == "WireFront":
        from scconsensus_tpu.serve.fleet.wire import WireFront

        return WireFront
    if name in ("Autoscaler", "AutoscalePolicy"):
        from scconsensus_tpu.serve.fleet import autoscale

        return getattr(autoscale, name)
    if name == "run_load":
        from scconsensus_tpu.serve.fleet.loadgen import run_load

        return run_load
    if name in ("run_reconsensus", "reconsensus_update",
                "read_quarantine_batch"):
        from scconsensus_tpu.serve.fleet import reconsensus

        return getattr(reconsensus, name)
    raise AttributeError(name)
