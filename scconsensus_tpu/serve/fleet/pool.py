"""Replica fleet: N guarded servers behind one admission layer.

:class:`ReplicaPool` owns a set of :class:`ConsensusServer` replicas
grouped by model fingerprint. The design invariants, in the order they
matter:

* **One owner per request.** Admission routes each request to exactly
  one replica (least queue depth among the target model's replicas,
  preferring closed breakers); from there the r15 driver's accounting
  covers it. Requests the pool itself refuses (unknown model, closed
  fleet) ride the pool's own boundary stats, so the merged section's
  ``submitted_by_owner`` split always sums — a request the fleet cannot
  attribute to an owner is a lost request wearing a disguise
  (``serve.metrics.validate_serving`` rejects it).
* **Hot-swap by fingerprint, never a half-loaded model.** ``hot_swap``
  loads v2 through the readonly sha256 path, builds AND starts v2's
  replicas first, then performs the atomic cutover under the routing
  lock, then drains v1's in-flight batches (bounded by
  ``SCC_FLEET_SWAP_DRAIN_S``). Because admission holds the same lock the
  cutover takes, every request either enqueued on v1 before the flip
  (and drains to completion there) or routes to v2 after it — no request
  is ever split across models, and no request ever reaches a model whose
  replicas are not fully up. Retired replicas' stats are snapshotted
  into the pool's lifetime accounting: a swap loses zero requests AND
  zero evidence.
* **Multi-model routing.** ``add_model`` registers additional frozen
  models (atlas-per-tissue deployments) addressable per request by
  fingerprint; the active fingerprint serves unaddressed requests.

Fault sites (``robust.faults``): ``fleet_route`` fires at admission,
``fleet_swap`` at the start of a hot-swap — the chaos soak matrix drives
both.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.serve import metrics as serve_metrics
from scconsensus_tpu.serve.driver import (
    ConsensusServer,
    RequestHandle,
    ServeConfig,
    ServeResponse,
)
from scconsensus_tpu.serve.errors import RequestInvalid, ServerClosed
from scconsensus_tpu.serve.model import ConsensusModel, load_consensus_model

__all__ = ["Replica", "ReplicaPool"]

_BREAKER_RANK = serve_metrics.BREAKER_SEVERITY


@dataclasses.dataclass
class Replica:
    index: int
    model_fp: str
    server: ConsensusServer


class ReplicaPool:
    """N ``ConsensusServer`` replicas behind one shared admission layer.
    Use as a context manager or call :meth:`start`/:meth:`stop`."""

    def __init__(self, model: Union[ConsensusModel, str],
                 n_replicas: Optional[int] = None,
                 config: Optional[ServeConfig] = None,
                 readonly: bool = False,
                 register_live: bool = True):
        self.config = (config or ServeConfig()).resolved()
        self.n_default = int(n_replicas if n_replicas is not None
                             else env_flag("SCC_FLEET_REPLICAS"))
        if self.n_default < 1:
            raise ValueError("a fleet needs at least one replica")
        self._register_live = bool(register_live)
        self._lock = threading.Lock()
        self._closed = True
        self._rep_seq = 0
        # pool-boundary accounting: refusals that never reach a replica
        self._pool_stats = serve_metrics.ServingStats(queue_capacity=0)
        self._retired_sections: List[Dict[str, Any]] = []
        self._retired_samples: List[List[float]] = []
        self._retired_expo: List[Dict[str, Any]] = []
        # replicas removed from routing but not yet banked (stop() can
        # take seconds): the telemetry snapshot still counts them, so
        # fleet-aggregate counters never dip and rebound mid-retire —
        # a scraper would read the dip as a counter reset
        self._dying: List[Replica] = []
        self._swaps: List[Dict[str, Any]] = []
        self._kills: List[Dict[str, Any]] = []
        self._scales: List[Dict[str, Any]] = []
        self._started_unix = time.time()
        first = self._load(model, readonly)
        self._models: Dict[str, ConsensusModel] = {
            first.fingerprint(): first
        }
        self._active_fp = first.fingerprint()
        self._groups: Dict[str, List[Replica]] = {
            first.fingerprint(): self._build_group(first, self.n_default)
        }

    # -- construction ------------------------------------------------------
    def _load(self, model: Union[ConsensusModel, str],
              readonly: bool) -> ConsensusModel:
        if isinstance(model, str):
            # the readonly sha256 path: every model entering the fleet is
            # verified intact, and a frozen mount is never written
            return load_consensus_model(model, readonly=readonly)
        return model

    def _build_group(self, model: ConsensusModel,
                     n: int) -> List[Replica]:
        group = []
        for _ in range(max(int(n), 1)):
            srv = ConsensusServer(model, self.config, register_live=False)
            group.append(Replica(index=self._rep_seq,
                                 model_fp=model.fingerprint(),
                                 server=srv))
            self._rep_seq += 1
        return group

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaPool":
        with self._lock:
            if not self._closed:
                return self
            self._closed = False
            self._started_unix = time.time()
            reps = [r for g in self._groups.values() for r in g]
        for rep in reps:
            rep.server.start()
        if self._register_live:
            serve_metrics.set_active_fleet(self._live_summary)
        return self

    def stop(self, drain: bool = True) -> None:
        with self._lock:
            if self._closed and not any(self._groups.values()):
                return
            self._closed = True
            groups = self._groups
            self._groups = {fp: [] for fp in groups}
            # dying registration happens under the SAME lock hold that
            # removes the replicas from routing (here and in every
            # retire caller): a telemetry snapshot can never catch a
            # replica in neither the live nor the retired bucket
            for g in groups.values():
                self._dying.extend(g)
        for group in groups.values():
            self._retire_group(group, drain=drain)
        if self._register_live:
            serve_metrics.set_active_fleet(None)

    def __enter__(self) -> "ReplicaPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission ---------------------------------------------------------
    def _pool_refuse(self, outcome: str) -> None:
        # keep the boundary stats internally consistent: one submit, one
        # outcome — the merged section's accounting rule depends on it
        self._pool_stats.note_submit(0)
        self._pool_stats.note_outcome(outcome)

    def submit(self, cells: np.ndarray,
               deadline_s: Optional[float] = None,
               model_fp: Optional[str] = None,
               trace_id: Optional[str] = None) -> RequestHandle:
        """Route one request to exactly one replica of the addressed
        model (default: the active fingerprint). Typed refusals:
        ServerClosed (fleet closed), RequestInvalid (unknown model),
        plus everything the replica's own admission can raise.
        ``trace_id`` (from the wire front) rides through routing to the
        owning replica's admission unchanged — admission must never
        re-mint an id the front already issued."""
        from scconsensus_tpu.robust import faults

        faults.fault_point("fleet_route")
        with self._lock:
            if self._closed:
                self._pool_refuse("rejected_closed")
                raise ServerClosed("fleet is not accepting requests")
            fp = model_fp or self._active_fp
            group = self._groups.get(fp)
            if not group:
                self._pool_refuse("rejected_invalid")
                raise RequestInvalid(
                    f"no model {fp!r} in the fleet "
                    f"(have {sorted(self._groups)})"
                )
            rep = self._least_depth(group)
            # enqueue UNDER the pool lock: hot_swap's cutover takes the
            # same lock, so a request either lands on v1 before the flip
            # (the drain serves it) or routes to v2 after — never to a
            # replica already marked for draining
            return rep.server.submit(cells, deadline_s=deadline_s,
                                     trace_id=trace_id)

    @staticmethod
    def _least_depth(group: List[Replica]) -> Replica:
        """Least-depth routing, preferring replicas whose breaker is
        closest to closed: a healthy shallow queue beats a degraded
        one — but a fully-open fleet still serves (degraded beats
        down)."""
        return min(
            group,
            key=lambda rep: (
                _BREAKER_RANK.get(rep.server.breaker.state, 0),
                len(rep.server._queue),
            ),
        )

    def classify(self, cells: np.ndarray,
                 deadline_s: Optional[float] = None,
                 model_fp: Optional[str] = None,
                 timeout: Optional[float] = None) -> ServeResponse:
        return self.submit(cells, deadline_s=deadline_s,
                           model_fp=model_fp).result(timeout=timeout)

    # -- hot-swap + multi-model routing ------------------------------------
    def hot_swap(self, model: Union[ConsensusModel, str],
                 readonly: bool = False,
                 n_replicas: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None) -> str:
        """Atomic cutover of the ACTIVE model: load v2 (sha256-verified),
        start its replicas, flip the routing pointer under the admission
        lock, then drain v1. Returns the new active fingerprint.
        Swapping to the already-active fingerprint is a no-op (idempotent
        — a retried swap must not restart the fleet); swapping to a
        model already routed via ``add_model`` PROMOTES its running
        group rather than replacing it (its replicas and their
        accounting survive)."""
        from scconsensus_tpu.robust import faults

        faults.fault_point("fleet_swap")
        new_model = self._load(model, readonly)
        new_fp = new_model.fingerprint()
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting a swap")
            if new_fp == self._active_fp:
                return new_fp
            build = new_fp not in self._groups
        group: List[Replica] = []
        if build:
            # build AND start v2 before any routing change: no request
            # is ever admitted toward a half-loaded model
            group = self._build_group(new_model,
                                      n_replicas or self.n_default)
            for rep in group:
                rep.server.start()
        redundant: List[Replica] = []
        with self._lock:
            if self._closed:
                # a stop() raced the swap: the new group never routed
                for rep in group:
                    rep.server.stop(drain=False)
                raise ServerClosed("fleet stopped during hot-swap")
            # re-read EVERYTHING under the cutover lock: a concurrent
            # swap may have flipped the pointer (or installed this very
            # fingerprint) since the first check
            old_fp = self._active_fp
            if old_fp == new_fp:
                redundant, group = group, []  # lost a race to an
                old_group: List[Replica] = []  # identical swap — done
                swap = None
            else:
                if new_fp in self._groups:
                    # promote the already-routed group (add_model, or a
                    # racing swap's install): a freshly built twin group
                    # must not overwrite live replicas
                    redundant, group = group, []
                else:
                    self._groups[new_fp] = group
                    self._models[new_fp] = new_model
                old_group = self._groups.pop(old_fp, [])
                self._dying.extend(old_group)
                self._active_fp = new_fp
                swap = {"from_fp": old_fp, "to_fp": new_fp,
                        "ts": round(time.time(), 3)}
        if redundant:
            # never-routed servers: stop without banking (zero traffic)
            for rep in redundant:
                rep.server.stop(drain=False)
        if swap is None:
            return new_fp
        # v1 drains OUTSIDE the lock: in-flight batches finish on v1 (a
        # request is never split across models), new traffic is already
        # routing to v2
        drained = self._retire_group(old_group, drain=True,
                                     timeout_s=drain_timeout_s)
        swap["drained_requests"] = drained
        with self._lock:
            self._swaps.append(swap)
            self._models.pop(old_fp, None)
        return new_fp

    def add_model(self, model: Union[ConsensusModel, str],
                  n_replicas: int = 1,
                  readonly: bool = False) -> str:
        """Register an additional routed model (atlas-per-tissue):
        requests addressed to its fingerprint route to its replicas; the
        active model keeps serving unaddressed traffic."""
        m = self._load(model, readonly)
        fp = m.fingerprint()
        group = self._build_group(m, n_replicas)
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting models")
            if fp in self._groups:
                raise ValueError(f"model {fp!r} is already in the fleet")
            self._groups[fp] = group
            self._models[fp] = m
        for rep in group:
            rep.server.start()
        return fp

    def retire_model(self, fp: str,
                     drain_timeout_s: Optional[float] = None) -> None:
        """Drain and remove a routed model (refuses the active one —
        hot-swap first)."""
        with self._lock:
            if fp == self._active_fp:
                raise ValueError(
                    f"cannot retire the active model {fp!r}; hot_swap a "
                    "replacement first"
                )
            group = self._groups.pop(fp, None)
            self._models.pop(fp, None)
            if group:
                self._dying.extend(group)
        if group:
            self._retire_group(group, drain=True,
                               timeout_s=drain_timeout_s)

    def kill_replica(self, index: Optional[int] = None,
                     respawn: bool = True) -> Dict[str, Any]:
        """Hard-kill one live replica of the ACTIVE model (no drain —
        its queued requests resolve as typed ServerClosed, exactly what
        a process death looks like one layer up) and, by default,
        respawn a fresh replica of the same model so the fleet returns
        to width. The killed replica's stats are banked into the
        retired accounting — a kill loses zero requests AND zero
        evidence — and the kill is stamped into ``fleet.kills``.
        Returns the kill record. The soak's replica-kill plan drives
        this; a client that retries its refused request with the SAME
        trace id produces the two-attempts-one-trace story the
        postmortem bundle proves."""
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting a kill")
            group = self._groups.get(self._active_fp) or []
            if not group:
                raise ValueError("no live replica of the active model "
                                 "to kill")
            if index is None:
                # default to the DEEPEST queue: a kill exists to prove
                # queued requests refuse typed and retry clean, so aim
                # it where the requests are
                rep = max(group,
                          key=lambda r: r.server.stats.queue_depth)
            else:
                matches = [r for r in group if r.index == int(index)]
                if not matches:
                    raise ValueError(
                        f"no live replica {index!r} in the active group "
                        f"(have {[r.index for r in group]})"
                    )
                rep = matches[0]
            group.remove(rep)
            self._dying.append(rep)
            model = self._models[self._active_fp]
            fp = self._active_fp
        # stop OUTSIDE the lock, without drain: queued requests resolve
        # typed rejected_closed on the dead replica's own stats
        rep.server.stop(drain=False, timeout_s=5.0)
        sec = rep.server.stats.section()
        with self._lock:
            self._retired_sections.append(sec)
            self._retired_samples.append(
                rep.server.stats.latency_samples()
            )
            self._retired_expo.append(rep.server.stats.expo_snapshot())
            self._dying.remove(rep)
        kill: Dict[str, Any] = {
            "replica": rep.index,
            "model_fp": fp,
            "refused": int(sec["requests"]["rejected_closed"]),
            "ts": round(time.time(), 3),
        }
        if respawn:
            new_group = self._build_group(model, 1)
            for nr in new_group:
                nr.server.start()
            with self._lock:
                if self._closed or self._active_fp != fp:
                    # the fleet moved on mid-respawn: the fresh replica
                    # never routed, stop it without banking
                    for nr in new_group:
                        nr.server.stop(drain=False)
                else:
                    self._groups[fp].extend(new_group)
                    kill["respawned"] = new_group[0].index
        with self._lock:
            self._kills.append(kill)
        return kill

    def scale_to(self, n: int,
                 drain_timeout_s: Optional[float] = None,
                 reason: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        """Resize the ACTIVE model's replica group to ``n`` (the
        autoscaler's actuator). Scale-up mirrors the kill/respawn path:
        fresh replicas are built AND started outside the lock, then
        joined to routing only if the fleet has not moved on. Scale-down
        drains the removed replicas (shallowest queues first) and banks
        their stats — a scale action loses zero requests and zero
        evidence. The action is stamped into ``fleet.scales``; a no-op
        resize is returned un-stamped. Returns the scale record."""
        n = int(n)
        if n < 1:
            raise ValueError("a fleet needs at least one replica")
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting a resize")
            fp = self._active_fp
            model = self._models[fp]
            group = self._groups.get(fp) or []
            cur = len(group)
            victims: List[Replica] = []
            if n < cur:
                # shed the SHALLOWEST queues: a scale-down exists to
                # trim idle width, so aim it away from queued work
                by_depth = sorted(group,
                                  key=lambda r: r.server.stats.queue_depth)
                victims = by_depth[:cur - n]
                for rep in victims:
                    group.remove(rep)
                # dying registration under the SAME lock hold that
                # unroutes them (the stop()/kill discipline)
                self._dying.extend(victims)
        rec: Dict[str, Any] = {"from": cur, "to": n,
                               "ts": round(time.time(), 3)}
        if reason:
            rec["reason"] = dict(reason)
        if n == cur:
            rec["noop"] = True
            return rec
        if victims:
            drained = self._retire_group(victims, drain=True,
                                         timeout_s=drain_timeout_s)
            rec["drained_requests"] = drained
        elif n > cur:
            new_group = self._build_group(model, n - cur)
            for nr in new_group:
                nr.server.start()
            with self._lock:
                if self._closed or self._active_fp != fp:
                    # the fleet moved on mid-build: the fresh replicas
                    # never routed, stop them without banking
                    for nr in new_group:
                        nr.server.stop(drain=False)
                    rec["aborted"] = True
                    return rec
                self._groups[fp].extend(new_group)
                rec["added"] = [r.index for r in new_group]
        with self._lock:
            self._scales.append(rec)
        return rec

    def _retire_group(self, group: List[Replica], drain: bool,
                      timeout_s: Optional[float] = None) -> int:
        """Stop a group's servers and bank their stats into the pool's
        lifetime accounting (a swap loses zero evidence). Returns the
        group's total submitted count."""
        budget = float(timeout_s if timeout_s is not None
                       else env_flag("SCC_FLEET_SWAP_DRAIN_S"))
        deadline = time.monotonic() + max(budget, 0.1)
        total = 0
        for rep in group:
            left = max(deadline - time.monotonic(), 0.1)
            rep.server.stop(drain=drain, timeout_s=left)
            sec = rep.server.stats.section()
            samples = rep.server.stats.latency_samples()
            expo = rep.server.stats.expo_snapshot()
            total += int(sec["requests"]["submitted"])
            with self._lock:
                self._retired_sections.append(sec)
                self._retired_samples.append(samples)
                # histograms survive retirement too: the fleet-merged
                # exposition/slo series must not lose a killed or
                # swapped-out replica's observations
                self._retired_expo.append(expo)
                # the caller registered the group as dying under the
                # lock that unrouted it; banking supersedes that
                if rep in self._dying:
                    self._dying.remove(rep)
        return total

    # -- introspection -----------------------------------------------------
    def active_fingerprint(self) -> str:
        return self._active_fp

    def active_model(self) -> ConsensusModel:
        with self._lock:
            return self._models[self._active_fp]

    def fingerprints(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)

    def replicas(self) -> List[Replica]:
        with self._lock:
            return [r for g in self._groups.values() for r in g]

    # -- the validated section + the heartbeat feed ------------------------
    def serving_section(self) -> Dict[str, Any]:
        """The pool-level ``serving`` run-record section: per-replica
        sections (live + retired + pool boundary) merged so the
        accounting rule holds fleet-wide, plus the ``fleet`` subsection
        (replica table, swap history, submitted-by-owner split). Like the
        r15 driver, read it quiescent — mid-flight requests are counted
        submitted but not yet resolved."""
        with self._lock:
            live = [r for g in self._groups.values() for r in g]
            retired_secs = list(self._retired_sections)
            retired_samps = list(self._retired_samples)
            swaps = [dict(s) for s in self._swaps]
            active = self._active_fp
            models = {fp: len(g) for fp, g in self._groups.items() if g}
        live_secs = [rep.server.stats.section() for rep in live]
        live_samps = [rep.server.stats.latency_samples() for rep in live]
        pool_sec = self._pool_stats.section()
        sec = serve_metrics.merge_serving_sections(
            live_secs + retired_secs + [pool_sec],
            live_samps + retired_samps
            + [self._pool_stats.latency_samples()],
            window_s=time.time() - self._started_unix,
        )
        with self._lock:
            kills = [dict(k) for k in self._kills]
            scales = [dict(s) for s in self._scales]
        sec["fleet"] = {
            # configured fleet width — the replica-keyed baseline key (a
            # workload property, stable across stop/drain)...
            "replicas": self.n_default,
            # ...vs the replicas alive RIGHT NOW (0 after stop; the
            # per_replica table below describes exactly these)
            "live_replicas": len(live),
            "active_fp": active,
            "models": models,
            "swaps": swaps,
            "kills": kills,
            "scales": scales,
            "submitted_by_owner": {
                "replicas": sum(s["requests"]["submitted"]
                                for s in live_secs),
                "retired": sum(s["requests"]["submitted"]
                               for s in retired_secs),
                "pool": pool_sec["requests"]["submitted"],
            },
            "per_replica": [
                {
                    "replica": rep.index,
                    "model_fp": rep.model_fp,
                    "submitted": s["requests"]["submitted"],
                    "ok": s["requests"]["ok"],
                    "breaker": s["breaker"]["state"],
                    "trips": s["breaker"]["trips"],
                    "queue_depth_peak": s["queue"]["depth_peak"],
                    "p99_ms": (s["latency_ms"] or {}).get("p99"),
                }
                for rep, s in zip(live, live_secs)
            ],
        }
        return sec

    # -- the shared telemetry snapshot (round 20) --------------------------
    def telemetry_snapshot(self) -> Dict[str, Any]:
        """One internally consistent fleet telemetry snapshot, taken
        UNDER the admission/swap lock: the replica table and every
        per-replica stats snapshot are read while no hot-swap cutover
        (or kill/respawn) can flip the groups mid-read. Both consumers
        — the ``/metrics`` OpenMetrics exposition and the JSON
        ``live_summary`` panel — assemble from THIS one structure, so
        the two can never disagree on per-replica keys while a swap is
        in flight (the pre-r20 exposition read the replica list under
        the lock but the stats after releasing it — torn exactly when
        a scrape races a cutover)."""
        with self._lock:
            live = [r for g in self._groups.values() for r in g]
            reps = [{
                "replica": rep.index,
                "model_fp": rep.model_fp,
                "expo": rep.server.stats.expo_snapshot(),
                "lat": rep.server.stats.latency_ms(),
                "samples": rep.server.stats.latency_samples(),
            } for rep in live]
            # mid-retire replicas (removed from routing, stop() still
            # running) count as already-retired evidence: aggregate
            # counters stay monotonic through a kill or swap
            dying_expo = [r.server.stats.expo_snapshot()
                          for r in self._dying]
            dying_samples = [r.server.stats.latency_samples()
                             for r in self._dying]
            return {
                "active_fp": self._active_fp,
                "replicas": reps,
                "retired_expo": [dict(e) for e in self._retired_expo]
                + dying_expo,
                "retired_samples": [list(s)
                                    for s in self._retired_samples]
                + dying_samples,
                "pool_expo": self._pool_stats.expo_snapshot(),
                "kills": [dict(k) for k in self._kills],
                "scales": [dict(s) for s in self._scales],
            }

    def expo_scopes(self, snap: Optional[Dict[str, Any]] = None
                    ) -> List[Dict[str, Any]]:
        """Exposition scopes for ``serve.slo.render_openmetrics``: one
        per live replica plus the ``replica="fleet"`` aggregate whose
        counters are exact sums (live + retired + pool boundary) and
        whose histograms are per-bucket merges — mergeable by the frozen
        bucket grid."""
        from scconsensus_tpu.serve import slo as serve_slo

        snap = snap or self.telemetry_snapshot()
        scopes: List[Dict[str, Any]] = []
        for r in snap["replicas"]:
            e = r["expo"]
            scopes.append({
                "labels": {"replica": str(r["replica"]),
                           "model": r["model_fp"][:8]},
                "counts": e["counts"],
                "queue_depth": e["queue_depth"],
                "queue_cap": e["queue_cap"],
                "breaker": e["breaker"],
                "trips": e["trips"],
                "latency_hist": e["latency_hist"],
                "stage_hist": e["stage_hist"],
            })
        all_expo = ([r["expo"] for r in snap["replicas"]]
                    + snap["retired_expo"] + [snap["pool_expo"]])
        counts: Dict[str, int] = {o: 0 for o in serve_metrics.OUTCOMES}
        for e in all_expo:
            for o in serve_metrics.OUTCOMES:
                counts[o] += int((e.get("counts") or {}).get(o, 0))
        lat_hist = {
            o: serve_slo.merge_histogram_dicts([
                (e.get("latency_hist") or {}).get(o)
                or serve_slo.LatencyHistogram().to_dict()
                for e in all_expo
            ]) for o in serve_metrics.OUTCOMES
        }
        stage_hist = {
            s: serve_slo.merge_histogram_dicts([
                (e.get("stage_hist") or {}).get(s)
                or serve_slo.LatencyHistogram().to_dict()
                for e in all_expo
            ]) for s in serve_metrics.STAGE_HIST_STAGES
        }
        live_expo = [r["expo"] for r in snap["replicas"]]
        worst = "closed"
        for e in live_expo:
            if (_BREAKER_RANK.get(e["breaker"], 0)
                    > _BREAKER_RANK[worst]):
                worst = e["breaker"]
        scopes.append({
            "labels": {"replica": "fleet"},
            "counts": counts,
            "queue_depth": sum(e["queue_depth"] for e in live_expo),
            "queue_cap": sum(e["queue_cap"] for e in live_expo),
            "breaker": worst,
            "trips": sum(e["trips"] for e in all_expo),
            "latency_hist": lat_hist,
            "stage_hist": stage_hist,
        })
        return scopes

    def slo_section(self, snap: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """The fleet-level validated ``slo`` run-record section:
        availability over the SAME cumulative counters the accounting
        rule validates (live + retired + pool boundary — a killed
        replica's refusals still burn the budget), p99 from the merged
        raw sample rings, burn windows from the live replicas' + pool
        boundary's summed window deltas."""
        from scconsensus_tpu.serve import slo as serve_slo

        snap = snap or self.telemetry_snapshot()
        scopes = self.expo_scopes(snap)
        fleet = scopes[-1]
        # retired/killed replicas' raw samples stay in the gated tail:
        # a kill must lose zero latency evidence, or the record's p99
        # understates exactly the incident it should report
        merged = [ms for r in snap["replicas"] for ms in r["samples"]]
        for samples in snap.get("retired_samples") or []:
            merged.extend(samples)
        p99 = serve_slo.p99_ms(merged)
        # live + RETIRED trackers both burn: a killed replica's typed
        # refusals must show in the burn windows, not just availability
        live_deltas = ([r["expo"]["window_deltas"]
                        for r in snap["replicas"]]
                       + [e.get("window_deltas") or []
                          for e in snap.get("retired_expo") or []]
                       + [snap["pool_expo"]["window_deltas"]])
        # window order follows the trackers' declared objectives order
        # (first-seen), NOT numeric sort: validate_slo pins burn_rates
        # positionally against objectives.windows_s
        order: List[float] = []
        windows: Dict[float, Dict[str, int]] = {}
        for deltas in live_deltas:
            for wd in deltas:
                w = float(wd["window_s"])
                agg = windows.get(w)
                if agg is None:
                    agg = windows[w] = {"bad": 0, "total": 0}
                    order.append(w)
                agg["bad"] += int(wd["bad"])
                agg["total"] += int(wd["total"])
        window_deltas = [
            {"window_s": w, **windows[w]} for w in order
        ]
        return serve_slo.build_slo_section(
            fleet["counts"], p99, window_deltas,
            latency_hist=fleet["latency_hist"],
            stage_hist=fleet["stage_hist"],
            obs_overhead=serve_slo.obs_overhead(),
        )

    def _live_summary(self) -> Dict[str, Any]:
        """One heartbeat tick (``serve.metrics.live_summary`` delegates
        here while the pool is registered): aggregated vitals plus the
        per-replica fleet panel tail_run renders — assembled from the
        same swap-lock snapshot the exposition reads."""
        from scconsensus_tpu.serve import slo as serve_slo

        snap = self.telemetry_snapshot()
        out: Dict[str, Any] = {"queue_depth": 0, "queue_cap": 0,
                               "breaker": "closed", "ok": 0}
        agg: Dict[str, int] = {}
        trips_total = 0
        merged: List[float] = []
        reps: List[Dict[str, Any]] = []
        recent: List[Dict[str, Any]] = []
        hist_src: Dict[str, List[Dict[str, Any]]] = {}
        counts_sum: Dict[str, int] = {o: 0
                                      for o in serve_metrics.OUTCOMES}
        window_order: List[float] = []
        window_sum: Dict[float, Dict[str, int]] = {}
        for r in snap["replicas"]:
            e = r["expo"]
            counts = e["counts"]
            out["queue_depth"] += e["queue_depth"]
            out["queue_cap"] += e["queue_cap"]
            out["ok"] += counts["ok"]
            if (_BREAKER_RANK.get(e["breaker"], 0)
                    > _BREAKER_RANK[out["breaker"]]):
                out["breaker"] = e["breaker"]
            trips_total += e["trips"]
            for key in ("degraded", "quarantined", "deadline_exceeded",
                        "failed"):
                agg[key] = agg.get(key, 0) + counts[key]
            agg["rejected"] = (agg.get("rejected", 0)
                               + counts["rejected_queue"]
                               + counts["rejected_invalid"]
                               + counts["rejected_closed"])
            merged.extend(r["samples"])
            recent.extend(e.get("recent") or [])
            for o in serve_metrics.OUTCOMES:
                counts_sum[o] += int(counts.get(o, 0))
                h = (e.get("latency_hist") or {}).get(o)
                if h and h.get("count"):
                    hist_src.setdefault(o, []).append(h)
            for wd in e.get("window_deltas") or []:
                w = float(wd["window_s"])
                a = window_sum.get(w)
                if a is None:
                    a = window_sum[w] = {"bad": 0, "total": 0}
                    window_order.append(w)
                a["bad"] += int(wd["bad"])
                a["total"] += int(wd["total"])
            entry: Dict[str, Any] = {
                "replica": r["replica"],
                "model_fp": r["model_fp"][:8],
                "queue_depth": e["queue_depth"],
                "breaker": e["breaker"],
            }
            if e["trips"]:
                entry["trips"] = e["trips"]
            if r["lat"].get("p99") is not None:
                entry["p99_ms"] = r["lat"]["p99"]
            reps.append(entry)
        for key, v in agg.items():
            if v:
                out[key] = v
        if trips_total:
            out["breaker_trips"] = trips_total
        p99 = serve_slo.p99_ms(merged)
        if p99 is not None:
            out["p99_ms"] = round(p99, 4)
        av = serve_slo.classify_counts(counts_sum)
        out["slo"] = serve_metrics.slo_summary(av, [
            {"window_s": w, **window_sum[w]} for w in window_order
        ])
        # panel histograms through the ONE merge implementation (the
        # exposition's), reshaped to the heartbeat's compact {n,
        # buckets} form
        hist = {
            o: {"n": m["count"], "buckets": list(m["buckets"])}
            for o, m in ((o, serve_slo.merge_histogram_dicts(hs))
                         for o, hs in hist_src.items())
        }
        if hist:
            out["lat_hist"] = hist
        if recent:
            recent.sort(key=lambda x: x.get("ts") or 0)
            out["recent"] = recent[-8:]
        out["fleet"] = {"active_fp": snap["active_fp"][:8],
                        "replicas": reps}
        if snap.get("scales"):
            # the heartbeat panel's autoscale tail: tail_run renders it
            out["fleet"]["scales"] = [dict(s)
                                      for s in snap["scales"][-3:]]
        return out
