"""Replica fleet: N guarded servers behind one admission layer.

:class:`ReplicaPool` owns a set of :class:`ConsensusServer` replicas
grouped by model fingerprint. The design invariants, in the order they
matter:

* **One owner per request.** Admission routes each request to exactly
  one replica (least queue depth among the target model's replicas,
  preferring closed breakers); from there the r15 driver's accounting
  covers it. Requests the pool itself refuses (unknown model, closed
  fleet) ride the pool's own boundary stats, so the merged section's
  ``submitted_by_owner`` split always sums — a request the fleet cannot
  attribute to an owner is a lost request wearing a disguise
  (``serve.metrics.validate_serving`` rejects it).
* **Hot-swap by fingerprint, never a half-loaded model.** ``hot_swap``
  loads v2 through the readonly sha256 path, builds AND starts v2's
  replicas first, then performs the atomic cutover under the routing
  lock, then drains v1's in-flight batches (bounded by
  ``SCC_FLEET_SWAP_DRAIN_S``). Because admission holds the same lock the
  cutover takes, every request either enqueued on v1 before the flip
  (and drains to completion there) or routes to v2 after it — no request
  is ever split across models, and no request ever reaches a model whose
  replicas are not fully up. Retired replicas' stats are snapshotted
  into the pool's lifetime accounting: a swap loses zero requests AND
  zero evidence.
* **Multi-model routing.** ``add_model`` registers additional frozen
  models (atlas-per-tissue deployments) addressable per request by
  fingerprint; the active fingerprint serves unaddressed requests.

Fault sites (``robust.faults``): ``fleet_route`` fires at admission,
``fleet_swap`` at the start of a hot-swap — the chaos soak matrix drives
both.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.serve import metrics as serve_metrics
from scconsensus_tpu.serve.driver import (
    ConsensusServer,
    RequestHandle,
    ServeConfig,
    ServeResponse,
)
from scconsensus_tpu.serve.errors import RequestInvalid, ServerClosed
from scconsensus_tpu.serve.model import ConsensusModel, load_consensus_model

__all__ = ["Replica", "ReplicaPool"]

_BREAKER_RANK = serve_metrics.BREAKER_SEVERITY


@dataclasses.dataclass
class Replica:
    index: int
    model_fp: str
    server: ConsensusServer


class ReplicaPool:
    """N ``ConsensusServer`` replicas behind one shared admission layer.
    Use as a context manager or call :meth:`start`/:meth:`stop`."""

    def __init__(self, model: Union[ConsensusModel, str],
                 n_replicas: Optional[int] = None,
                 config: Optional[ServeConfig] = None,
                 readonly: bool = False,
                 register_live: bool = True):
        self.config = (config or ServeConfig()).resolved()
        self.n_default = int(n_replicas if n_replicas is not None
                             else env_flag("SCC_FLEET_REPLICAS"))
        if self.n_default < 1:
            raise ValueError("a fleet needs at least one replica")
        self._register_live = bool(register_live)
        self._lock = threading.Lock()
        self._closed = True
        self._rep_seq = 0
        # pool-boundary accounting: refusals that never reach a replica
        self._pool_stats = serve_metrics.ServingStats(queue_capacity=0)
        self._retired_sections: List[Dict[str, Any]] = []
        self._retired_samples: List[List[float]] = []
        self._swaps: List[Dict[str, Any]] = []
        self._started_unix = time.time()
        first = self._load(model, readonly)
        self._models: Dict[str, ConsensusModel] = {
            first.fingerprint(): first
        }
        self._active_fp = first.fingerprint()
        self._groups: Dict[str, List[Replica]] = {
            first.fingerprint(): self._build_group(first, self.n_default)
        }

    # -- construction ------------------------------------------------------
    def _load(self, model: Union[ConsensusModel, str],
              readonly: bool) -> ConsensusModel:
        if isinstance(model, str):
            # the readonly sha256 path: every model entering the fleet is
            # verified intact, and a frozen mount is never written
            return load_consensus_model(model, readonly=readonly)
        return model

    def _build_group(self, model: ConsensusModel,
                     n: int) -> List[Replica]:
        group = []
        for _ in range(max(int(n), 1)):
            srv = ConsensusServer(model, self.config, register_live=False)
            group.append(Replica(index=self._rep_seq,
                                 model_fp=model.fingerprint(),
                                 server=srv))
            self._rep_seq += 1
        return group

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaPool":
        with self._lock:
            if not self._closed:
                return self
            self._closed = False
            self._started_unix = time.time()
            reps = [r for g in self._groups.values() for r in g]
        for rep in reps:
            rep.server.start()
        if self._register_live:
            serve_metrics.set_active_fleet(self._live_summary)
        return self

    def stop(self, drain: bool = True) -> None:
        with self._lock:
            if self._closed and not any(self._groups.values()):
                return
            self._closed = True
            groups = self._groups
            self._groups = {fp: [] for fp in groups}
        for group in groups.values():
            self._retire_group(group, drain=drain)
        if self._register_live:
            serve_metrics.set_active_fleet(None)

    def __enter__(self) -> "ReplicaPool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission ---------------------------------------------------------
    def _pool_refuse(self, outcome: str) -> None:
        # keep the boundary stats internally consistent: one submit, one
        # outcome — the merged section's accounting rule depends on it
        self._pool_stats.note_submit(0)
        self._pool_stats.note_outcome(outcome)

    def submit(self, cells: np.ndarray,
               deadline_s: Optional[float] = None,
               model_fp: Optional[str] = None) -> RequestHandle:
        """Route one request to exactly one replica of the addressed
        model (default: the active fingerprint). Typed refusals:
        ServerClosed (fleet closed), RequestInvalid (unknown model),
        plus everything the replica's own admission can raise."""
        from scconsensus_tpu.robust import faults

        faults.fault_point("fleet_route")
        with self._lock:
            if self._closed:
                self._pool_refuse("rejected_closed")
                raise ServerClosed("fleet is not accepting requests")
            fp = model_fp or self._active_fp
            group = self._groups.get(fp)
            if not group:
                self._pool_refuse("rejected_invalid")
                raise RequestInvalid(
                    f"no model {fp!r} in the fleet "
                    f"(have {sorted(self._groups)})"
                )
            rep = self._least_depth(group)
            # enqueue UNDER the pool lock: hot_swap's cutover takes the
            # same lock, so a request either lands on v1 before the flip
            # (the drain serves it) or routes to v2 after — never to a
            # replica already marked for draining
            return rep.server.submit(cells, deadline_s=deadline_s)

    @staticmethod
    def _least_depth(group: List[Replica]) -> Replica:
        """Least-depth routing, preferring replicas whose breaker is
        closest to closed: a healthy shallow queue beats a degraded
        one — but a fully-open fleet still serves (degraded beats
        down)."""
        return min(
            group,
            key=lambda rep: (
                _BREAKER_RANK.get(rep.server.breaker.state, 0),
                len(rep.server._queue),
            ),
        )

    def classify(self, cells: np.ndarray,
                 deadline_s: Optional[float] = None,
                 model_fp: Optional[str] = None,
                 timeout: Optional[float] = None) -> ServeResponse:
        return self.submit(cells, deadline_s=deadline_s,
                           model_fp=model_fp).result(timeout=timeout)

    # -- hot-swap + multi-model routing ------------------------------------
    def hot_swap(self, model: Union[ConsensusModel, str],
                 readonly: bool = False,
                 n_replicas: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None) -> str:
        """Atomic cutover of the ACTIVE model: load v2 (sha256-verified),
        start its replicas, flip the routing pointer under the admission
        lock, then drain v1. Returns the new active fingerprint.
        Swapping to the already-active fingerprint is a no-op (idempotent
        — a retried swap must not restart the fleet); swapping to a
        model already routed via ``add_model`` PROMOTES its running
        group rather than replacing it (its replicas and their
        accounting survive)."""
        from scconsensus_tpu.robust import faults

        faults.fault_point("fleet_swap")
        new_model = self._load(model, readonly)
        new_fp = new_model.fingerprint()
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting a swap")
            if new_fp == self._active_fp:
                return new_fp
            build = new_fp not in self._groups
        group: List[Replica] = []
        if build:
            # build AND start v2 before any routing change: no request
            # is ever admitted toward a half-loaded model
            group = self._build_group(new_model,
                                      n_replicas or self.n_default)
            for rep in group:
                rep.server.start()
        redundant: List[Replica] = []
        with self._lock:
            if self._closed:
                # a stop() raced the swap: the new group never routed
                for rep in group:
                    rep.server.stop(drain=False)
                raise ServerClosed("fleet stopped during hot-swap")
            # re-read EVERYTHING under the cutover lock: a concurrent
            # swap may have flipped the pointer (or installed this very
            # fingerprint) since the first check
            old_fp = self._active_fp
            if old_fp == new_fp:
                redundant, group = group, []  # lost a race to an
                old_group: List[Replica] = []  # identical swap — done
                swap = None
            else:
                if new_fp in self._groups:
                    # promote the already-routed group (add_model, or a
                    # racing swap's install): a freshly built twin group
                    # must not overwrite live replicas
                    redundant, group = group, []
                else:
                    self._groups[new_fp] = group
                    self._models[new_fp] = new_model
                old_group = self._groups.pop(old_fp, [])
                self._active_fp = new_fp
                swap = {"from_fp": old_fp, "to_fp": new_fp,
                        "ts": round(time.time(), 3)}
        if redundant:
            # never-routed servers: stop without banking (zero traffic)
            for rep in redundant:
                rep.server.stop(drain=False)
        if swap is None:
            return new_fp
        # v1 drains OUTSIDE the lock: in-flight batches finish on v1 (a
        # request is never split across models), new traffic is already
        # routing to v2
        drained = self._retire_group(old_group, drain=True,
                                     timeout_s=drain_timeout_s)
        swap["drained_requests"] = drained
        with self._lock:
            self._swaps.append(swap)
            self._models.pop(old_fp, None)
        return new_fp

    def add_model(self, model: Union[ConsensusModel, str],
                  n_replicas: int = 1,
                  readonly: bool = False) -> str:
        """Register an additional routed model (atlas-per-tissue):
        requests addressed to its fingerprint route to its replicas; the
        active model keeps serving unaddressed traffic."""
        m = self._load(model, readonly)
        fp = m.fingerprint()
        group = self._build_group(m, n_replicas)
        with self._lock:
            if self._closed:
                raise ServerClosed("fleet is not accepting models")
            if fp in self._groups:
                raise ValueError(f"model {fp!r} is already in the fleet")
            self._groups[fp] = group
            self._models[fp] = m
        for rep in group:
            rep.server.start()
        return fp

    def retire_model(self, fp: str,
                     drain_timeout_s: Optional[float] = None) -> None:
        """Drain and remove a routed model (refuses the active one —
        hot-swap first)."""
        with self._lock:
            if fp == self._active_fp:
                raise ValueError(
                    f"cannot retire the active model {fp!r}; hot_swap a "
                    "replacement first"
                )
            group = self._groups.pop(fp, None)
            self._models.pop(fp, None)
        if group:
            self._retire_group(group, drain=True,
                               timeout_s=drain_timeout_s)

    def _retire_group(self, group: List[Replica], drain: bool,
                      timeout_s: Optional[float] = None) -> int:
        """Stop a group's servers and bank their stats into the pool's
        lifetime accounting (a swap loses zero evidence). Returns the
        group's total submitted count."""
        budget = float(timeout_s if timeout_s is not None
                       else env_flag("SCC_FLEET_SWAP_DRAIN_S"))
        deadline = time.monotonic() + max(budget, 0.1)
        total = 0
        for rep in group:
            left = max(deadline - time.monotonic(), 0.1)
            rep.server.stop(drain=drain, timeout_s=left)
            sec = rep.server.stats.section()
            samples = rep.server.stats.latency_samples()
            total += int(sec["requests"]["submitted"])
            with self._lock:
                self._retired_sections.append(sec)
                self._retired_samples.append(samples)
        return total

    # -- introspection -----------------------------------------------------
    def active_fingerprint(self) -> str:
        return self._active_fp

    def active_model(self) -> ConsensusModel:
        with self._lock:
            return self._models[self._active_fp]

    def fingerprints(self) -> List[str]:
        with self._lock:
            return sorted(self._groups)

    def replicas(self) -> List[Replica]:
        with self._lock:
            return [r for g in self._groups.values() for r in g]

    # -- the validated section + the heartbeat feed ------------------------
    def serving_section(self) -> Dict[str, Any]:
        """The pool-level ``serving`` run-record section: per-replica
        sections (live + retired + pool boundary) merged so the
        accounting rule holds fleet-wide, plus the ``fleet`` subsection
        (replica table, swap history, submitted-by-owner split). Like the
        r15 driver, read it quiescent — mid-flight requests are counted
        submitted but not yet resolved."""
        with self._lock:
            live = [r for g in self._groups.values() for r in g]
            retired_secs = list(self._retired_sections)
            retired_samps = list(self._retired_samples)
            swaps = [dict(s) for s in self._swaps]
            active = self._active_fp
            models = {fp: len(g) for fp, g in self._groups.items() if g}
        live_secs = [rep.server.stats.section() for rep in live]
        live_samps = [rep.server.stats.latency_samples() for rep in live]
        pool_sec = self._pool_stats.section()
        sec = serve_metrics.merge_serving_sections(
            live_secs + retired_secs + [pool_sec],
            live_samps + retired_samps
            + [self._pool_stats.latency_samples()],
            window_s=time.time() - self._started_unix,
        )
        sec["fleet"] = {
            # configured fleet width — the replica-keyed baseline key (a
            # workload property, stable across stop/drain)...
            "replicas": self.n_default,
            # ...vs the replicas alive RIGHT NOW (0 after stop; the
            # per_replica table below describes exactly these)
            "live_replicas": len(live),
            "active_fp": active,
            "models": models,
            "swaps": swaps,
            "submitted_by_owner": {
                "replicas": sum(s["requests"]["submitted"]
                                for s in live_secs),
                "retired": sum(s["requests"]["submitted"]
                               for s in retired_secs),
                "pool": pool_sec["requests"]["submitted"],
            },
            "per_replica": [
                {
                    "replica": rep.index,
                    "model_fp": rep.model_fp,
                    "submitted": s["requests"]["submitted"],
                    "ok": s["requests"]["ok"],
                    "breaker": s["breaker"]["state"],
                    "trips": s["breaker"]["trips"],
                    "queue_depth_peak": s["queue"]["depth_peak"],
                    "p99_ms": (s["latency_ms"] or {}).get("p99"),
                }
                for rep, s in zip(live, live_secs)
            ],
        }
        return sec

    def _live_summary(self) -> Dict[str, Any]:
        """One heartbeat tick (``serve.metrics.live_summary`` delegates
        here while the pool is registered): aggregated vitals plus the
        per-replica fleet panel tail_run renders."""
        with self._lock:
            live = [r for g in self._groups.values() for r in g]
            active = self._active_fp
        out: Dict[str, Any] = {"queue_depth": 0, "queue_cap": 0,
                               "breaker": "closed", "ok": 0}
        agg: Dict[str, int] = {}
        trips_total = 0
        merged: List[float] = []
        reps: List[Dict[str, Any]] = []
        for rep in live:
            st = rep.server.stats
            lat = st.latency_ms()
            with st._lock:
                depth = st.queue_depth
                cap = st.queue_capacity
                counts = dict(st.counts)
                bstate = st.breaker_state
                trips = st.breaker_trips
            out["queue_depth"] += depth
            out["queue_cap"] += cap
            out["ok"] += counts["ok"]
            if (_BREAKER_RANK.get(bstate, 0)
                    > _BREAKER_RANK[out["breaker"]]):
                out["breaker"] = bstate
            trips_total += trips
            for key in ("degraded", "quarantined", "deadline_exceeded",
                        "failed"):
                agg[key] = agg.get(key, 0) + counts[key]
            agg["rejected"] = (agg.get("rejected", 0)
                               + counts["rejected_queue"]
                               + counts["rejected_invalid"]
                               + counts["rejected_closed"])
            merged.extend(st.latency_samples())
            entry: Dict[str, Any] = {
                "replica": rep.index,
                "model_fp": rep.model_fp[:8],
                "queue_depth": depth,
                "breaker": bstate,
            }
            if trips:
                entry["trips"] = trips
            if lat.get("p99") is not None:
                entry["p99_ms"] = lat["p99"]
            reps.append(entry)
        for key, v in agg.items():
            if v:
                out[key] = v
        if trips_total:
            out["breaker_trips"] = trips_total
        if merged:
            s = sorted(merged)
            out["p99_ms"] = round(s[min(int(0.99 * len(s)),
                                        len(s) - 1)], 4)
        out["fleet"] = {"active_fp": active[:8], "replicas": reps}
        return out
