"""Wire front: a stdlib-only threaded HTTP server over the fleet.

Every wire request resolves to **exactly one typed outcome mapped to
exactly one status code** — the r15 accounting contract raised to the
network layer (``serve.metrics.WireStats`` counts both sides; the run
record's ``serving.wire`` subsection is validated):

    ==================  ======  =======================================
    outcome             status  meaning
    ==================  ======  =======================================
    ok                  200     labels returned (device path)
    degraded            200     labels returned, ``degraded: true``
                                (host fallback behind a tripped breaker)
    quarantined         409     drift gate refused confident labels;
                                ledgered for the reconsensus loop
    rejected_queue      429     bounded-admission backpressure;
                                ``Retry-After`` carries the EWMA hint
    rejected_invalid    422     malformed body / wrong gene dimension /
                                oversized / non-finite cells / unknown
                                model fingerprint
    rejected_closed     503     fleet closed or draining
    deadline_exceeded   504     queue wait or compute overran the
                                request deadline
    failed              500     fatal batch error (typed RequestFailed)
    ==================  ======  =======================================

``GET /healthz`` answers 200 while the backend accepts traffic and 503
once it is closed/unhealthy; ``GET /metrics`` returns the OpenMetrics
text exposition (round 20: per-outcome counters, per-stage fixed-bucket
latency histograms, queue/breaker gauges — per replica and
fleet-aggregated from ONE swap-lock snapshot, plus the wire counters and
the live SLO); ``GET /metrics.json`` keeps the pre-r20 JSON live summary
(``serve.metrics.live_summary`` — the same feed the heartbeat panel
reads, fleet panel included).

Every classify response (success or typed refusal) carries the request's
trace id in ``X-SCC-Trace-Id`` and the JSON body: minted here at the
front (``SCC_OBS_TRACE``), or adopted from the client's header — which
is how a retried request keeps its id and the postmortem bundle shows
both attempts under one trace.

``POST /classify`` accepts two bodies:

* ``application/json`` — ``{"cells": [[...], ...], "deadline_s"?: s,
  "model_fp"?: fp}`` (fp addresses a routed model in a multi-model
  fleet);
* ``application/x-npy`` — a raw ``.npy`` float matrix (the bulk path:
  no JSON float inflation on big batches), with ``X-SCC-Deadline-S`` /
  ``X-SCC-Model-FP`` headers for the extras.

Responses are JSON either way; every served response carries
``model_fp`` — the fingerprint of the model that answered, the hot-swap
purity check's evidence.

Fault site (``robust.faults``): ``wire_request`` fires on every classify
request before admission.
"""

from __future__ import annotations

import io
import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.serve import metrics as serve_metrics
from scconsensus_tpu.serve import slo as serve_slo
from scconsensus_tpu.serve.driver import ServeResponse
from scconsensus_tpu.serve.errors import (
    DeadlineExceeded,
    QueueFull,
    RequestFailed,
    RequestInvalid,
    ServerClosed,
)

__all__ = ["OUTCOME_STATUS", "TRACE_HEADER", "WireFront"]

# THE mapping (BASELINE.md "Fleet policy"): one outcome, one status code.
# One copy, owned by serve.slo so the exposition and the availability
# classification can never drift from the wire's table (re-exported here
# because this is where callers historically import it from).
OUTCOME_STATUS: Dict[str, int] = serve_slo.OUTCOME_STATUS

# The trace-id header, both directions: a client (or a retrying client —
# the resubmit keeps its id) sends it; every response echoes the id that
# actually traced the request.
TRACE_HEADER = "X-SCC-Trace-Id"

# Adopted (client-supplied) ids must look like ids: bounded length,
# header-safe charset. The id is echoed into a response header and
# appended to the shared quarantine ledger / heartbeat ring, so an
# unvalidated value would let one client split responses (CRLF) or
# bloat cross-request evidence. Anything else is ignored and a fresh
# id is minted.
_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9_-]{1,64}$")


def _clean_trace_id(raw) -> Optional[str]:
    if not raw:
        return None
    raw = str(raw).strip()
    return raw if _TRACE_ID_RE.match(raw) else None

# Extra margin past the request deadline before the wire gives up on the
# handle: the backend resolves typed DeadlineExceeded itself; this only
# bounds a driver-bug hang so the socket never waits forever.
_RESULT_SLACK_S = 30.0


class WireFront:
    """Threaded HTTP front over a ``ReplicaPool`` or a bare
    ``ConsensusServer``. Use as a context manager or
    :meth:`start`/:meth:`stop`."""

    def __init__(self, backend, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.backend = backend
        self.host = host
        self.port_requested = int(port if port is not None
                                  else env_flag("SCC_FLEET_WIRE_PORT"))
        self.wire_stats = serve_metrics.WireStats()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "WireFront":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.port_requested),
                                    _WireHandler)
        httpd.daemon_threads = True
        httpd.front = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self._thread = threading.Thread(target=httpd.serve_forever,
                                        name="scc-wire", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self) -> "WireFront":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("wire front is not started")
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- record ------------------------------------------------------------
    def serving_section(self) -> Dict[str, Any]:
        """The backend's validated serving section with the wire-layer
        accounting attached (``serving.wire`` — submitted == Σ outcomes
        == Σ status codes, enforced by ``validate_serving``)."""
        sec = self.backend.serving_section()
        sec["wire"] = self.wire_stats.section()
        return sec

    def slo_section(self, snap: Optional[Dict[str, Any]] = None,
                    wire_expo: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """The validated ``slo`` run-record section, anchored at the
        wire: availability and burn windows over the WIRE outcome
        counters (the one stream every fleet request passes), end-to-end
        per-outcome latency histograms from the wire's observations,
        per-stage histograms from the backend's merged replicas, p99
        from the backend's merged raw sample rings. ``snap``/
        ``wire_expo`` let telemetry_text build counters, gauges, and
        SLO from the SAME instant."""
        we = wire_expo or self.wire_stats.expo_snapshot()
        b = self.backend
        stage_hist = None
        p99 = None
        if hasattr(b, "telemetry_snapshot"):
            snap = snap or b.telemetry_snapshot()
            merged = [ms for r in snap["replicas"] for ms in r["samples"]]
            for samples in snap.get("retired_samples") or []:
                # killed/swapped-out replicas' tails stay in the gated
                # p99 — retirement must lose zero latency evidence
                merged.extend(samples)
            p99 = serve_slo.p99_ms(merged)
            stage_hist = b.expo_scopes(snap)[-1]["stage_hist"]
        else:
            p99 = b.stats.latency_ms().get("p99")
            stage_hist = b.stats.expo_snapshot()["stage_hist"]
        return serve_slo.build_slo_section(
            we["counts"], p99, we["window_deltas"],
            latency_hist=we["latency_hist"],
            stage_hist=stage_hist,
            obs_overhead=serve_slo.obs_overhead(),
        )

    def telemetry_text(self) -> str:
        """The OpenMetrics exposition, assembled from ONE backend
        telemetry snapshot (taken under the pool's swap lock) and ONE
        wire snapshot, both shared with the SLO gauges — a scrape
        racing a hot-swap can never see a torn replica table, and a
        scrape's SLO gauges can never disagree with its own counters."""
        b = self.backend
        we = self.wire_stats.expo_snapshot()
        snap = None
        if hasattr(b, "telemetry_snapshot"):
            snap = b.telemetry_snapshot()
            scopes = b.expo_scopes(snap)
        else:
            e = b.stats.expo_snapshot()
            scope = {
                "labels": {"replica": "0",
                           "model": b.model.fingerprint()[:8]},
                "counts": e["counts"], "queue_depth": e["queue_depth"],
                "queue_cap": e["queue_cap"], "breaker": e["breaker"],
                "trips": e["trips"], "latency_hist": e["latency_hist"],
                "stage_hist": e["stage_hist"],
            }
            scopes = [scope, {**scope, "labels": {"replica": "fleet"}}]
        return serve_slo.render_openmetrics({
            "scopes": scopes,
            "wire": we,
            "slo": self.slo_section(snap=snap, wire_expo=we),
        })

    # -- backend adapter ---------------------------------------------------
    def _submit(self, cells: np.ndarray, deadline_s: Optional[float],
                model_fp: Optional[str],
                trace_id: Optional[str] = None):
        b = self.backend
        if hasattr(b, "hot_swap"):  # a ReplicaPool routes by fingerprint
            return b.submit(cells, deadline_s=deadline_s,
                            model_fp=model_fp, trace_id=trace_id)
        if model_fp and model_fp != b.model.fingerprint():
            raise RequestInvalid(
                f"this server holds model {b.model.fingerprint()!r}, "
                f"not {model_fp!r}"
            )
        return b.submit(cells, deadline_s=deadline_s, trace_id=trace_id)


def _parse_deadline(dl) -> Optional[float]:
    """A malformed deadline is a malformed REQUEST (422), not a driver
    failure (500) — parse errors must stay in the rejected_invalid
    bucket the status table promises."""
    if dl is None or dl == "":
        return None
    try:
        return float(dl)
    except (TypeError, ValueError):
        raise RequestInvalid(f"deadline_s is not a number: {dl!r}")


def _response_body(resp: ServeResponse) -> Dict[str, Any]:
    return {
        "req_id": resp.req_id,
        "outcome": resp.outcome,
        "labels": (None if resp.labels is None
                   else [int(v) for v in resp.labels]),
        "degraded": bool(resp.degraded),
        "quarantined": bool(resp.quarantined),
        "drift_fraction": round(float(resp.drift_fraction), 6),
        "latency_s": round(float(resp.latency_s), 6),
        "model_fp": resp.model_fp,
        "trace_id": resp.trace_id,
    }


class _WireHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"  # keep-alive: bulk clients reuse sockets
    server: ThreadingHTTPServer

    # one request, one accounting entry — never stderr spam per hit
    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        pass

    @property
    def front(self) -> WireFront:
        return self.server.front  # type: ignore[attr-defined]

    def _send_json(self, status: int, body: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        payload = json.dumps(body).encode()
        self.send_response(int(status))
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client gone; the outcome is already accounted

    def _send_text(self, status: int, text: str, ctype: str) -> None:
        payload = text.encode("utf-8")
        self.send_response(int(status))
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        try:
            self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # -- GET: health + metrics ---------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?")[0]
        if path == "/healthz":
            closed = bool(getattr(self.front.backend, "closed", False))
            live = serve_metrics.live_summary() or {}
            body = {"status": "unhealthy" if closed else "ok",
                    "breaker": live.get("breaker"),
                    "queue_depth": live.get("queue_depth")}
            self._send_json(503 if closed else 200, body)
        elif path == "/metrics":
            # OpenMetrics text exposition (round 20) — per-replica and
            # fleet-aggregated series from ONE swap-lock snapshot; the
            # pre-r20 ad-hoc JSON summary moved to /metrics.json
            try:
                text = self.front.telemetry_text()
            except Exception as e:  # noqa: BLE001 - scrape must answer
                self._send_json(500,
                                {"error": f"{type(e).__name__}: {e}"})
                return
            self._send_text(
                200, text,
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8",
            )
        elif path == "/metrics.json":
            live = serve_metrics.live_summary()
            self._send_json(200, live if live is not None
                            else {"serving": "idle"})
        else:
            self._send_json(404, {"error": f"unknown path {path!r}"})

    # -- POST: classify ----------------------------------------------------
    def _finish_wire(self, outcome: str, status: int,
                     body: Dict[str, Any],
                     headers: Optional[Dict[str, str]] = None,
                     trace_id: Optional[str] = None,
                     t0: Optional[float] = None) -> None:
        if trace_id is None and env_flag("SCC_OBS_TRACE"):
            # refusal paths (including a body that never parsed) still
            # get a traceable typed response
            from scconsensus_tpu.obs.trace import new_trace_id

            trace_id = new_trace_id()
        latency = (time.monotonic() - t0) if t0 is not None else None
        self.front.wire_stats.note(outcome, status, latency_s=latency,
                                   trace_id=trace_id)
        body.setdefault("outcome", outcome)
        if trace_id:
            # the response carries the id BOTH ways (header for bulk
            # clients that drop the body, body for everyone else)
            body.setdefault("trace_id", trace_id)
            headers = {**(headers or {}), TRACE_HEADER: trace_id}
        self._send_json(status, body, headers)

    def _parse_body(self) -> Tuple[np.ndarray, Optional[float],
                                   Optional[str], Optional[str]]:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            raise RequestInvalid("empty request body")
        raw = self.rfile.read(n)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype == "application/x-npy":
            try:
                cells = np.load(io.BytesIO(raw), allow_pickle=False)
            except ValueError as e:
                raise RequestInvalid(f"unparseable npy payload: {e}")
            dl = self.headers.get("X-SCC-Deadline-S")
            fp = self.headers.get("X-SCC-Model-FP")
            return cells, _parse_deadline(dl), (fp or None), None
        try:
            doc = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise RequestInvalid(f"unparseable JSON body: {e}")
        if not isinstance(doc, dict) or "cells" not in doc:
            raise RequestInvalid('body must be {"cells": [[...], ...]}')
        try:
            cells = np.asarray(doc["cells"], np.float32)
        except (TypeError, ValueError) as e:
            raise RequestInvalid(f"cells is not a numeric matrix: {e}")
        return cells, _parse_deadline(doc.get("deadline_s")), (
            doc.get("model_fp") or None
        ), (str(doc["trace_id"]) if doc.get("trace_id") else None)

    def do_POST(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?")[0]
        if path != "/classify":
            self._send_json(404, {"error": f"unknown path {path!r}"})
            return
        from scconsensus_tpu.robust import faults

        front = self.front
        t0 = time.monotonic()
        # adoption order: header, then JSON-body trace_id, then mint —
        # a client-supplied id wins either way (that is how a retry
        # keeps its id across attempts; the postmortem bundle shows
        # both under one trace). Minting waits until after the body
        # parse so a body-supplied id is never shadowed; _finish_wire
        # mints for the refusal paths, so even a malformed request
        # still gets a traceable response.
        trace_id = _clean_trace_id(self.headers.get(TRACE_HEADER))
        try:
            faults.fault_point("wire_request")
            cells, deadline_s, model_fp, body_trace = self._parse_body()
            if trace_id is None:
                trace_id = _clean_trace_id(body_trace)
            if trace_id is None and env_flag("SCC_OBS_TRACE"):
                from scconsensus_tpu.obs.trace import new_trace_id

                trace_id = new_trace_id()
            handle = front._submit(cells, deadline_s, model_fp,
                                   trace_id=trace_id)
            wait = ((deadline_s
                     if deadline_s is not None
                     else getattr(front.backend, "config", None)
                     and front.backend.config.default_deadline_s) or 30.0)
            resp = handle.result(timeout=float(wait) + _RESULT_SLACK_S)
            self._finish_wire(resp.outcome, OUTCOME_STATUS[resp.outcome],
                              _response_body(resp),
                              trace_id=resp.trace_id or trace_id, t0=t0)
        except QueueFull as e:
            self._finish_wire(
                "rejected_queue", 429,
                {"error": str(e),
                 "retry_after_s": round(e.retry_after_s, 4)},
                headers={"Retry-After":
                         str(max(1, math.ceil(e.retry_after_s)))},
                trace_id=trace_id, t0=t0,
            )
        except RequestInvalid as e:
            self._finish_wire("rejected_invalid", 422, {"error": str(e)},
                              trace_id=trace_id, t0=t0)
        except ServerClosed as e:
            self._finish_wire("rejected_closed", 503, {"error": str(e)},
                              trace_id=trace_id, t0=t0)
        except DeadlineExceeded as e:
            self._finish_wire(
                "deadline_exceeded", 504,
                {"error": str(e), "late_by_s": round(e.late_by_s, 4)},
                trace_id=trace_id, t0=t0,
            )
        except RequestFailed as e:
            self._finish_wire("failed", 500,
                              {"error": str(e),
                               "error_class": e.error_class},
                              trace_id=trace_id, t0=t0)
        except Exception as e:  # noqa: BLE001
            # the last-ditch guard: even a wire/driver bug resolves as a
            # counted typed outcome — a socket that dies uncounted is the
            # dropped-request failure mode one layer up
            self._finish_wire("failed", 500,
                              {"error": f"{type(e).__name__}: {e}"},
                              trace_id=trace_id, t0=t0)
