"""Burn-rate-driven fleet autoscaler: the loop that ACTS on telemetry.

Rounds 16-20 built the defensive machinery — replica pool, hot-swap,
multi-window burn rates, replica-merged histograms — and nothing
consumed them. This module closes the loop:

* :func:`decide` is the PURE control policy — ``(state, observation,
  policy) -> (state', actions)`` with no clock, no threads, no pool —
  so every hysteresis rule (consecutive-tick streaks, post-actuation
  cooldown, the no-flap guarantee under an oscillating burn series) is
  table-testable without standing up a fleet.
* :class:`Autoscaler` runs it on a cadence against a live
  :class:`~scconsensus_tpu.serve.fleet.pool.ReplicaPool`: one
  internally consistent telemetry snapshot per tick (the same
  swap-lock snapshot the exposition reads), one decision, then
  actuation through the EXISTING machinery — replica resize via
  ``pool.scale_to`` (the hot-swap path's build/start/bank discipline),
  admission tightening by shrinking each live replica's queue
  capacity (429s are client-class: shed load never burns the SLO
  budget), and explicit degraded-mode entry/exit by forcing the
  per-replica breakers open/closed.

Every action lands as a typed ``actuation`` record in three places:
the in-memory list (the run record's ``loadgen.actuations``), one
JSONL row in ``ACTUATION_LEDGER.jsonl`` (``tools/postmortem.py``
auto-collects ``*LEDGER*.jsonl`` and renders the rows on the incident
timeline), and — through the pool — ``serving.fleet.scales`` on the
validated serving section. Each record carries its own trace id, so an
actuation joins the request-trace plane like any other event.

Module-level imports stay jax-free (the export validators and the
jax-free tools import this)."""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from scconsensus_tpu.config import env_flag

__all__ = [
    "ACTUATION_LEDGER_NAME",
    "ACTUATION_KINDS",
    "AutoscalePolicy",
    "ControlState",
    "Observation",
    "decide",
    "validate_actuation",
    "Autoscaler",
]

ACTUATION_LEDGER_NAME = "ACTUATION_LEDGER.jsonl"

ACTUATION_KINDS = (
    "scale_up", "scale_down",
    "tighten_admission", "relax_admission",
    "enter_degraded", "exit_degraded",
)


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The control policy's knobs. ``from_env()`` resolves the scale
    thresholds from the registered autoscale env flags; the
    admission/degraded levels default relative to the burn thresholds
    (tighten fires between scale-up pressure and degraded entry)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # scale pressure: worst multi-window burn OR queue fill fraction
    burn_up: float = 2.0
    burn_down: float = 0.25
    queue_high: float = 0.5
    queue_low: float = 0.05
    # hysteresis: consecutive pressured/idle ticks before acting, then
    # a cooldown during which no further scale action fires
    up_ticks: int = 2
    down_ticks: int = 8
    cooldown_ticks: int = 4
    # admission tightening: above tighten_burn the queue capacity
    # shrinks by tighten_factor (shed as client-class 429s); at or
    # below relax_burn it is restored
    tighten_burn: float = 6.0
    relax_burn: float = 1.0
    tighten_factor: float = 0.5
    # degraded mode: sustained burn past degrade_burn forces the
    # breakers open (flagged host-fallback service); sustained calm
    # below recover_burn lifts it
    degrade_burn: float = 14.4
    recover_burn: float = 1.0
    degrade_ticks: int = 3
    recover_ticks: int = 6

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if not (0.0 < self.tighten_factor <= 1.0):
            raise ValueError("tighten_factor must be in (0, 1]")
        # every paired threshold is a dead band: the hysteresis
        # guarantees collapse if the enter level is not above the exit
        for hi, lo, what in (
                (self.burn_up, self.burn_down, "burn_up/burn_down"),
                (self.queue_high, self.queue_low,
                 "queue_high/queue_low"),
                (self.tighten_burn, self.relax_burn,
                 "tighten_burn/relax_burn"),
                (self.degrade_burn, self.recover_burn,
                 "degrade_burn/recover_burn")):
            if hi <= lo:
                raise ValueError(
                    f"{what} must form a dead band (enter > exit)")

    @classmethod
    def from_env(cls, **overrides: Any) -> "AutoscalePolicy":
        kw: Dict[str, Any] = dict(
            min_replicas=int(env_flag("SCC_AUTOSCALE_MIN")),
            max_replicas=int(env_flag("SCC_AUTOSCALE_MAX")),
            burn_up=float(env_flag("SCC_AUTOSCALE_BURN_UP")),
            burn_down=float(env_flag("SCC_AUTOSCALE_BURN_DOWN")),
            up_ticks=int(env_flag("SCC_AUTOSCALE_UP_TICKS")),
            down_ticks=int(env_flag("SCC_AUTOSCALE_DOWN_TICKS")),
            cooldown_ticks=int(env_flag("SCC_AUTOSCALE_COOLDOWN_TICKS")),
        )
        kw.update(overrides)
        return cls(**kw)


@dataclasses.dataclass(frozen=True)
class Observation:
    """One tick's view of the fleet, reduced to the control inputs:
    the worst burn across the record-validated multi-window burn rates
    (live + retired + pool-boundary trackers — the same counters the
    slo section carries), the merged-sample p99, the fleet queue fill
    fraction, and the live width."""

    worst_burn: float
    p99_ms: Optional[float]
    queue_frac: float
    live_replicas: int


@dataclasses.dataclass
class ControlState:
    """The controller's memory between ticks. ``target`` is the width
    the controller wants; streaks and cooldown implement hysteresis;
    the admission/degraded booleans make those actions edge-triggered
    (an actuation happens on the transition, never re-fired while the
    condition holds)."""

    target: int
    up_streak: int = 0
    down_streak: int = 0
    cooldown: int = 0
    tightened: bool = False
    degraded: bool = False
    degrade_streak: int = 0
    recover_streak: int = 0


def decide(state: ControlState, obs: Observation,
           policy: AutoscalePolicy
           ) -> Tuple[ControlState, List[Dict[str, Any]]]:
    """One control step: pure, deterministic, clock-free.

    Hysteresis rules (the no-flap guarantee):

    * scale pressure must hold for ``up_ticks`` (``down_ticks``)
      CONSECUTIVE ticks — a flip to the opposite pressure resets the
      streak, so an oscillating burn series (above ``burn_up`` one
      tick, below ``burn_down`` the next) never accumulates a streak
      and never actuates;
    * after any scale action, ``cooldown_ticks`` ticks must pass
      before the next one — two actions are always at least the
      cooldown apart;
    * admission tightening and degraded mode are edge-triggered
      transitions with their own enter/exit thresholds separated by a
      dead band (``tighten_burn`` > ``relax_burn``, ``degrade_burn`` >
      ``recover_burn``).

    Returns the new state and the ordered action list; each action is
    a dict ``{"kind", "from", "to", "reason"}`` (``from``/``to`` are
    replica widths for scale actions, booleans for mode actions).
    """
    s = dataclasses.replace(state)  # shallow copy; fields are scalars
    actions: List[Dict[str, Any]] = []
    reason = {
        "worst_burn": round(float(obs.worst_burn), 4),
        "queue_frac": round(float(obs.queue_frac), 4),
    }
    if obs.p99_ms is not None:
        reason["p99_ms"] = round(float(obs.p99_ms), 4)

    # -- scale streaks -----------------------------------------------------
    pressure_up = (obs.worst_burn >= policy.burn_up
                   or obs.queue_frac >= policy.queue_high)
    pressure_down = (obs.worst_burn <= policy.burn_down
                     and obs.queue_frac <= policy.queue_low)
    if pressure_up:
        s.up_streak += 1
        s.down_streak = 0
    elif pressure_down:
        s.down_streak += 1
        s.up_streak = 0
    else:
        s.up_streak = 0
        s.down_streak = 0

    if s.cooldown > 0:
        s.cooldown -= 1
    elif (s.up_streak >= policy.up_ticks
            and s.target < policy.max_replicas):
        frm, s.target = s.target, s.target + 1
        s.up_streak = 0
        s.cooldown = policy.cooldown_ticks
        actions.append({"kind": "scale_up", "from": frm,
                        "to": s.target, "reason": dict(reason)})
    elif (s.down_streak >= policy.down_ticks
            and s.target > policy.min_replicas):
        frm, s.target = s.target, s.target - 1
        s.down_streak = 0
        s.cooldown = policy.cooldown_ticks
        actions.append({"kind": "scale_down", "from": frm,
                        "to": s.target, "reason": dict(reason)})

    # -- admission tightening (edge-triggered, burn dead band) -------------
    if not s.tightened and obs.worst_burn >= policy.tighten_burn:
        s.tightened = True
        actions.append({"kind": "tighten_admission", "from": False,
                        "to": True, "reason": dict(reason)})
    elif s.tightened and obs.worst_burn <= policy.relax_burn:
        s.tightened = False
        actions.append({"kind": "relax_admission", "from": True,
                        "to": False, "reason": dict(reason)})

    # -- degraded mode (sustained-burn entry, sustained-calm exit) ---------
    if not s.degraded:
        s.degrade_streak = (s.degrade_streak + 1
                            if obs.worst_burn >= policy.degrade_burn
                            else 0)
        if s.degrade_streak >= policy.degrade_ticks:
            s.degraded = True
            s.degrade_streak = 0
            actions.append({"kind": "enter_degraded", "from": False,
                            "to": True, "reason": dict(reason)})
    else:
        s.recover_streak = (s.recover_streak + 1
                            if obs.worst_burn <= policy.recover_burn
                            else 0)
        if s.recover_streak >= policy.recover_ticks:
            s.degraded = False
            s.recover_streak = 0
            actions.append({"kind": "exit_degraded", "from": True,
                            "to": False, "reason": dict(reason)})
    return s, actions


def validate_actuation(a: Dict[str, Any]) -> None:
    """Structural validation of one typed actuation record (the loadgen
    section validator and the ledger-row reader share it)."""
    if not isinstance(a, dict):
        raise ValueError("actuation must be an object")
    if a.get("kind") not in ACTUATION_KINDS:
        raise ValueError(
            f"actuation.kind must be one of {ACTUATION_KINDS}, "
            f"got {a.get('kind')!r}"
        )
    if not isinstance(a.get("ts"), (int, float)):
        raise ValueError("actuation.ts must be a number")
    if not isinstance(a.get("reason"), dict):
        raise ValueError("actuation.reason must be an object")
    if a["kind"] in ("scale_up", "scale_down"):
        frm, to = a.get("from"), a.get("to")
        if not (isinstance(frm, int) and isinstance(to, int)):
            raise ValueError("scale actuation needs int from/to widths")
        if (to > frm) != (a["kind"] == "scale_up"):
            raise ValueError(
                f"actuation kind {a['kind']!r} contradicts its own "
                f"from={frm} to={to}"
            )


class Autoscaler:
    """The control loop over a live pool. ``tick()`` is one observe →
    decide → actuate step (call it directly for deterministic tests);
    ``start()``/``stop()`` run it on the ``SCC_AUTOSCALE_TICK_S``
    cadence in a daemon thread. Every actuation is appended to
    ``self.actuations`` and one JSONL row to ``ledger_dir/``
    ``ACTUATION_LEDGER.jsonl`` (when a ledger dir is given)."""

    def __init__(self, pool: Any,
                 policy: Optional[AutoscalePolicy] = None,
                 ledger_dir: Optional[str] = None,
                 tick_s: Optional[float] = None):
        self.pool = pool
        self.policy = policy or AutoscalePolicy.from_env()
        self.tick_s = float(tick_s if tick_s is not None
                            else env_flag("SCC_AUTOSCALE_TICK_S"))
        self.ledger_path = (os.path.join(ledger_dir,
                                         ACTUATION_LEDGER_NAME)
                            if ledger_dir else None)
        start_width = max(min(pool.n_default,
                              self.policy.max_replicas),
                          self.policy.min_replicas)
        self.state = ControlState(target=start_width)
        self.actuations: List[Dict[str, Any]] = []
        self.ticks = 0
        # the untightened per-replica queue capacity (the pool config's
        # resolved value — each server holds its own mutable copy)
        self._base_queue_cap = int(pool.config.queue_capacity)
        from scconsensus_tpu.serve import slo as serve_slo

        self._objectives = serve_slo.resolve_objectives()
        self._budget = max(1.0 - float(self._objectives["availability"]),
                           1e-9)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- observe -----------------------------------------------------------
    def observe(self) -> Observation:
        """Reduce one swap-lock telemetry snapshot to the control
        inputs. Burn is computed from the SAME window deltas the
        record's slo section carries (live + retired + pool boundary),
        with the budget from this process's declared objectives."""
        from scconsensus_tpu.serve import slo as serve_slo

        snap = self.pool.telemetry_snapshot()
        live = snap["replicas"]
        all_deltas = ([r["expo"]["window_deltas"] for r in live]
                      + [e.get("window_deltas") or []
                         for e in snap.get("retired_expo") or []]
                      + [snap["pool_expo"]["window_deltas"]])
        windows: Dict[float, Dict[str, int]] = {}
        for deltas in all_deltas:
            for wd in deltas:
                w = float(wd["window_s"])
                agg = windows.setdefault(w, {"bad": 0, "total": 0})
                agg["bad"] += int(wd["bad"])
                agg["total"] += int(wd["total"])
        worst = 0.0
        for agg in windows.values():
            if agg["total"]:
                err = agg["bad"] / agg["total"]
                worst = max(worst, err / self._budget)
        depth = sum(int(r["expo"]["queue_depth"]) for r in live)
        cap = sum(int(r["expo"]["queue_cap"]) for r in live)
        merged = [ms for r in live for ms in r["samples"]]
        return Observation(
            worst_burn=worst,
            p99_ms=serve_slo.p99_ms(merged),
            queue_frac=(depth / cap) if cap else 0.0,
            live_replicas=len(live),
        )

    # -- actuate -----------------------------------------------------------
    def _stamp(self, action: Dict[str, Any]) -> Dict[str, Any]:
        rec = {
            "kind": action["kind"],
            "from": action["from"],
            "to": action["to"],
            "reason": dict(action.get("reason") or {}),
            "ts": round(time.time(), 3),
        }
        if env_flag("SCC_OBS_TRACE"):
            from scconsensus_tpu.obs.trace import new_trace_id

            rec["trace_id"] = new_trace_id()
        with self._lock:
            self.actuations.append(rec)
        if self.ledger_path:
            try:
                os.makedirs(os.path.dirname(self.ledger_path),
                            exist_ok=True)
                # ledger rows discriminate on "kind" (the quarantine
                # rows own the legacy shape), so the action name moves
                # to "action" in the on-disk twin
                row = dict(rec)
                row["action"] = row.pop("kind")
                row["kind"] = "actuation"
                with open(self.ledger_path, "a") as f:
                    f.write(json.dumps(row) + "\n")
            except OSError:
                pass  # actuation must not die on a full disk
        return rec

    def _actuate(self, action: Dict[str, Any]) -> None:
        kind = action["kind"]
        if kind in ("scale_up", "scale_down"):
            self.pool.scale_to(int(action["to"]),
                               reason=action.get("reason"))
        elif kind == "tighten_admission":
            cap = max(int(self._base_queue_cap
                          * self.policy.tighten_factor), 1)
            for rep in self.pool.replicas():
                rep.server.config.queue_capacity = cap
        elif kind == "relax_admission":
            for rep in self.pool.replicas():
                rep.server.config.queue_capacity = self._base_queue_cap
        elif kind == "enter_degraded":
            for rep in self.pool.replicas():
                rep.server.breaker.force_open()
        elif kind == "exit_degraded":
            for rep in self.pool.replicas():
                rep.server.breaker.force_close()
        self._stamp(action)

    def tick(self) -> List[Dict[str, Any]]:
        """One observe → decide → actuate step; returns the actions it
        took (possibly empty)."""
        obs = self.observe()
        self.state, actions = decide(self.state, obs, self.policy)
        for action in actions:
            self._actuate(action)
        self.ticks += 1
        # newly scaled-up replicas start with the BASE capacity; while
        # tightened, pull them down to the tightened one
        if self.state.tightened and any(
                a["kind"] == "scale_up" for a in actions):
            cap = max(int(self._base_queue_cap
                          * self.policy.tighten_factor), 1)
            for rep in self.pool.replicas():
                rep.server.config.queue_capacity = cap
        return actions

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(self.tick_s):
                try:
                    self.tick()
                except Exception:
                    # the control loop must outlive a torn snapshot
                    # mid-shutdown; the next tick observes fresh
                    continue

        self._thread = threading.Thread(target=_loop,
                                        name="scc-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def section(self) -> Dict[str, Any]:
        """The autoscaler's summary block (rides the run record's
        ``loadgen`` section): policy, final state, every actuation."""
        with self._lock:
            acts = [dict(a) for a in self.actuations]
        return {
            "policy": dataclasses.asdict(self.policy),
            "ticks": int(self.ticks),
            "final_target": int(self.state.target),
            "degraded": bool(self.state.degraded),
            "tightened": bool(self.state.tightened),
            "actuations": acts,
        }
