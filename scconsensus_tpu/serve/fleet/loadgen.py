"""Open-loop load generator: seeded traffic against the real wire front.

The soak (``serve.fleet.soak``) is CLOSED-loop: N pump threads each
wait for a response before sending the next request, so offered load
self-throttles to whatever the fleet can absorb and a degraded fleet
quietly receives less traffic — precisely the signal an autoscaler must
not train on. This module is the OPEN-loop twin: the arrival schedule
is computed up front (seeded Poisson thinning over a rate profile, or
bursty trains), every request fires at its scheduled offset whether or
not earlier ones have answered, and what the fleet cannot absorb shows
up as queue backpressure (typed 429s — client-class, so shed load never
burns the SLO budget), latency, or burn. That is the substrate the
burn-rate autoscaler (:mod:`.autoscale`) is exercised against.

Everything is deterministic given the seed: the rate profile, the
thinned arrival offsets, the per-request scenario assignment (traffic
mixes are drawn over REGISTERED workload-zoo scenarios — a mix naming
an unregistered scenario is rejected the same way a bench config would
be), and the per-scenario query batches (``soak.make_query_batches``,
the same generator the atlas bench replays).

The run produces a wire-side run record keyed like any bench
(``extra.config = "loadgen-<profile>"``) whose headline is **sustained
RPS at SLO**: good responses per second IF the record's own slo section
holds (worst burn within its declared burn limit AND p99 within its
declared target), else 0.0 — a fleet that answered fast but breached
its SLO sustains nothing. The validated ``loadgen`` section carries the
schedule, the mix, the accounting (offered == sent, open-loop lateness)
and every autoscaler actuation; ``tools/perf_gate.py`` gates the
headline against the ledger's noise band.

Module-level imports stay jax-free (the export validators and jax-free
tools import this); the run path lazy-imports its compute.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.workloads import scenario_names

__all__ = [
    "PROFILES",
    "ARRIVALS",
    "DEFAULT_MIX",
    "rate_profile",
    "arrival_offsets",
    "resolve_mix",
    "assign_scenarios",
    "build_loadgen_section",
    "validate_loadgen",
    "slo_breaches",
    "run_load",
]

# rate profiles: base_rps modulated over the run's duration
#   steady   flat at base_rps
#   diurnal  one sinusoidal day compressed into the run (trough 0.6x,
#            crest 1.4x of base — peak_rps is ignored)
#   spike    base_rps with a flat peak_rps plateau in the middle third
#   ramp     linear base_rps -> peak_rps
PROFILES = ("steady", "diurnal", "spike", "ramp")

ARRIVALS = ("poisson", "burst")

# open-loop honesty gauge: a request fired later than this past its
# scheduled offset counts late (the generator, not the fleet, fell
# behind — late_fraction near 1 means the measurement is closed-loop
# in disguise and the record says so)
LATE_TOLERANCE_S = 0.050

# relative batch geometry per registered scenario: the mix models the
# zoo's request-size diversity (atlas_transfer is the bulk batch
# workload; cite_dual's per-request matrices are smaller than the
# RNA-only shapes). Scaled onto the run's --cells.
_SCENARIO_CELL_FACTOR = {
    "multi_sample": 1.0,
    "cite_dual": 0.5,
    "atlas_transfer": 2.0,
    "topo_inputs": 0.75,
}


# --------------------------------------------------------------------------
# the schedule (pure, seeded)
# --------------------------------------------------------------------------

def rate_profile(profile: str, t: float, duration_s: float,
                 base_rps: float, peak_rps: float) -> float:
    """Instantaneous arrival rate (req/s) at offset ``t``."""
    if profile not in PROFILES:
        raise ValueError(
            f"unknown profile {profile!r} (known: {PROFILES})"
        )
    if profile == "steady":
        return base_rps
    if profile == "diurnal":
        # one compressed day: trough at the endpoints, crest mid-run
        frac = (t / duration_s) if duration_s > 0 else 0.0
        return base_rps * (1.0 + 0.4 * math.sin(2.0 * math.pi * frac
                                                - math.pi / 2.0))
    if profile == "spike":
        third = duration_s / 3.0
        return peak_rps if third <= t < 2.0 * third else base_rps
    # ramp
    frac = (t / duration_s) if duration_s > 0 else 0.0
    return base_rps + (peak_rps - base_rps) * frac


def arrival_offsets(profile: str, base_rps: float, peak_rps: float,
                    duration_s: float, seed: int,
                    arrival: str = "poisson",
                    burst_size: int = 4) -> List[float]:
    """The full arrival schedule as sorted offsets from t0, seeded and
    deterministic.

    ``poisson`` draws an inhomogeneous Poisson process by Lewis
    thinning: homogeneous exponential gaps at the profile's max rate,
    each candidate kept with probability rate(t)/max_rate. ``burst``
    keeps every thinned arrival but replaces it with a back-to-back
    train of ``burst_size`` requests (the base rate is divided by the
    burst size so the OFFERED volume matches poisson in expectation —
    same load, burstier arrivals)."""
    if arrival not in ARRIVALS:
        raise ValueError(
            f"unknown arrival {arrival!r} (known: {ARRIVALS})"
        )
    if duration_s <= 0 or base_rps <= 0:
        raise ValueError("duration_s and base_rps must be > 0")
    peak_rps = max(float(peak_rps), float(base_rps))
    train = max(int(burst_size), 1) if arrival == "burst" else 1
    rng = np.random.default_rng(int(seed))
    max_rate = max(
        rate_profile(profile, t, duration_s, base_rps, peak_rps)
        for t in np.linspace(0.0, duration_s, 257)
    ) / train
    out: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / max_rate))
        if t >= duration_s:
            break
        rate = rate_profile(profile, t, duration_s, base_rps,
                            peak_rps) / train
        if float(rng.random()) <= rate / max_rate:
            for j in range(train):
                tj = t + j * 1e-3  # back-to-back, 1ms spaced
                if tj < duration_s:
                    out.append(round(tj, 6))
    return sorted(out)


def resolve_mix(mix: Optional[Dict[str, float]]
                ) -> Dict[str, float]:
    """Validate and normalize a traffic mix over REGISTERED scenarios.
    ``None`` means the default mix (every registered scenario, equal
    weight)."""
    if mix is None:
        names = scenario_names()
        return {n: round(1.0 / len(names), 6) for n in names}
    if not isinstance(mix, dict) or not mix:
        raise ValueError("mix must be a non-empty "
                         "{scenario_name: weight} object")
    known = set(scenario_names())
    total = 0.0
    for name, w in mix.items():
        if name not in known:
            raise ValueError(
                f"mix names unregistered scenario {name!r} "
                f"(registered: {sorted(known)})"
            )
        if not isinstance(w, (int, float)) or w <= 0:
            raise ValueError(f"mix[{name!r}] must be a number > 0")
        total += float(w)
    return {n: round(float(w) / total, 6) for n, w in mix.items()}


# canonical default for docs/CLI help
DEFAULT_MIX = "all registered scenarios, equal weight"


def assign_scenarios(n: int, mix: Dict[str, float],
                     seed: int) -> List[str]:
    """Seeded per-request scenario assignment drawn from the mix."""
    names = sorted(mix)
    probs = np.asarray([mix[k] for k in names], np.float64)
    probs = probs / probs.sum()
    rng = np.random.default_rng(int(seed) + 17)
    idx = rng.choice(len(names), size=max(int(n), 0), p=probs)
    return [names[int(i)] for i in idx]


# --------------------------------------------------------------------------
# the record section
# --------------------------------------------------------------------------

def build_loadgen_section(profile: str, arrival: str, base_rps: float,
                          peak_rps: float, duration_s: float, seed: int,
                          mix: Dict[str, float], offered: int,
                          sent: int, completed: int, good: int,
                          late_fraction: float, achieved_rps: float,
                          breaches: List[str],
                          autoscale: Optional[Dict[str, Any]] = None,
                          ) -> Dict[str, Any]:
    slo_held = not breaches
    sec: Dict[str, Any] = {
        "profile": profile,
        "arrival": arrival,
        "base_rps": round(float(base_rps), 4),
        "peak_rps": round(max(float(peak_rps), float(base_rps)), 4),
        "duration_s": round(float(duration_s), 4),
        "seed": int(seed),
        "mix": {k: round(float(v), 6) for k, v in mix.items()},
        "offered": int(offered),
        "sent": int(sent),
        "completed": int(completed),
        "good": int(good),
        "late_fraction": round(float(late_fraction), 6),
        "achieved_rps": round(float(achieved_rps), 4),
        "slo_held": slo_held,
        "breaches": list(breaches),
        "rps_at_slo": round(float(achieved_rps), 4) if slo_held else 0.0,
    }
    if autoscale is not None:
        sec["autoscale"] = autoscale
    return sec


def slo_breaches(slo: Dict[str, Any]) -> List[str]:
    """Judge a record's slo section against its OWN declared objectives
    (the SLOVerdict rule, history-free): a worst burn past the declared
    burn limit and a missed latency target are each one breach."""
    out: List[str] = []
    obj = slo.get("objectives") or {}
    worst = slo.get("worst_burn")
    limit = obj.get("burn_limit")
    if (isinstance(worst, (int, float)) and isinstance(limit, (int, float))
            and worst > limit):
        out.append(f"burn: worst_burn {worst} > limit {limit}")
    lat = slo.get("latency") or {}
    if lat.get("met") is False:
        out.append(f"latency: p99 {lat.get('p99_ms')}ms > target "
                   f"{lat.get('target_ms')}ms")
    return out


def _lg_require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"loadgen section: {msg}")


def validate_loadgen(lg: Dict[str, Any]) -> None:
    """Structural validation of a record's ``loadgen`` section (jax-free;
    ``obs.export.validate_run_record`` dispatches here). Load-bearing
    rules: the mix must name only registered scenarios with positive
    weights summing to 1, the open-loop accounting must hold (offered >=
    sent >= completed >= good), and the headline must be consistent with
    the record's own SLO verdict — ``rps_at_slo`` is ``achieved_rps``
    when the SLO held and exactly 0.0 when it did not (a breached run
    sustains nothing)."""
    _lg_require(isinstance(lg, dict), "must be an object")
    _lg_require(lg.get("profile") in PROFILES,
                f"profile must be one of {PROFILES}, "
                f"got {lg.get('profile')!r}")
    _lg_require(lg.get("arrival") in ARRIVALS,
                f"arrival must be one of {ARRIVALS}, "
                f"got {lg.get('arrival')!r}")
    for k in ("base_rps", "peak_rps", "duration_s"):
        v = lg.get(k)
        _lg_require(isinstance(v, (int, float)) and v > 0,
                    f"{k} must be a number > 0")
    _lg_require(lg["peak_rps"] >= lg["base_rps"],
                "peak_rps must be >= base_rps")
    _lg_require(isinstance(lg.get("seed"), int), "seed must be an int")
    mix = lg.get("mix")
    _lg_require(isinstance(mix, dict) and mix,
                "mix must be a non-empty object")
    known = set(scenario_names())
    for name, w in mix.items():
        _lg_require(name in known,
                    f"mix names unregistered scenario {name!r}")
        _lg_require(isinstance(w, (int, float)) and w > 0,
                    f"mix[{name!r}] must be a number > 0")
    _lg_require(abs(sum(float(w) for w in mix.values()) - 1.0) < 1e-3,
                "mix weights must sum to 1")
    counts = [lg.get(k) for k in ("offered", "sent", "completed", "good")]
    _lg_require(all(isinstance(c, int) and c >= 0 for c in counts),
                "offered/sent/completed/good must be ints >= 0")
    _lg_require(counts[0] >= counts[1] >= counts[2] >= counts[3],
                f"open-loop accounting must hold: offered >= sent >= "
                f"completed >= good, got {counts}")
    lf = lg.get("late_fraction")
    _lg_require(isinstance(lf, (int, float)) and 0.0 <= lf <= 1.0,
                "late_fraction must be in [0, 1]")
    ar = lg.get("achieved_rps")
    _lg_require(isinstance(ar, (int, float)) and ar >= 0,
                "achieved_rps must be a number >= 0")
    breaches = lg.get("breaches")
    _lg_require(isinstance(breaches, list)
                and all(isinstance(b, str) for b in breaches),
                "breaches must be a list of strings")
    _lg_require(lg.get("slo_held") == (len(breaches) == 0),
                "slo_held must equal breaches == []")
    rps = lg.get("rps_at_slo")
    _lg_require(isinstance(rps, (int, float)), "rps_at_slo must be a "
                "number")
    if lg["slo_held"]:
        _lg_require(abs(float(rps) - float(ar)) <= 0.01 + 1e-6,
                    f"rps_at_slo ({rps}) must equal achieved_rps "
                    f"({ar}) when the SLO held")
    else:
        _lg_require(float(rps) == 0.0,
                    "rps_at_slo must be 0.0 when the SLO was breached")
    auto = lg.get("autoscale")
    if auto is not None:
        from scconsensus_tpu.serve.fleet.autoscale import (
            validate_actuation,
        )

        _lg_require(isinstance(auto, dict), "autoscale must be an object")
        acts = auto.get("actuations")
        _lg_require(isinstance(acts, list),
                    "autoscale.actuations must be a list")
        for a in acts:
            validate_actuation(a)
        for k in ("ticks", "final_target"):
            _lg_require(isinstance(auto.get(k), int) and auto[k] >= 0,
                        f"autoscale.{k} must be an int >= 0")


# --------------------------------------------------------------------------
# the run
# --------------------------------------------------------------------------

def _build_request_bodies(offsets: List[float], mix: Dict[str, float],
                          cells_per: int, n_genes: int, n_clusters: int,
                          seed: int) -> Tuple[List[bytes], List[str]]:
    """Per-arrival request bodies: seeded scenario assignment over the
    mix, per-scenario batch geometry, batches from the same replayable
    generator the atlas bench drives."""
    from scconsensus_tpu.serve.fleet.soak import make_query_batches

    scen = assign_scenarios(len(offsets), mix, seed)
    by_scen: Dict[str, List[int]] = {}
    for i, name in enumerate(scen):
        by_scen.setdefault(name, []).append(i)
    bodies: List[bytes] = [b""] * len(offsets)
    for name, idxs in sorted(by_scen.items()):
        cells = max(int(round(cells_per
                              * _SCENARIO_CELL_FACTOR.get(name, 1.0))), 1)
        # the generator's seed ALSO seeds the atlas centers the cells
        # are drawn around — it must match the model build seed or every
        # request reads as drift and the fleet (correctly) quarantines
        # the whole run; scenarios still differ by batch geometry
        batches = make_query_batches(len(idxs), cells, seed,
                                     n_genes=n_genes,
                                     n_clusters=n_clusters)
        for i, batch in zip(idxs, batches):
            bodies[i] = json.dumps(
                {"cells": batch.tolist()}).encode()
    return bodies, scen


def run_load(workdir: str, profile: Optional[str] = None,
             base_rps: Optional[float] = None,
             peak_rps: Optional[float] = None,
             duration_s: Optional[float] = None,
             seed: Optional[int] = None,
             mix: Optional[Dict[str, float]] = None,
             arrival: str = "poisson",
             replicas: Optional[int] = None,
             cells_per: int = 8, n_genes: int = 120,
             n_clusters: int = 4, n_train: int = 360,
             queue_capacity: Optional[int] = None,
             deadline_s: Optional[float] = None,
             autoscale: bool = True,
             policy: Optional[Any] = None,
             pumps: int = 8,
             heartbeat_s: Optional[float] = None,
             fresh: bool = False) -> Dict[str, Any]:
    """One open-loop load run against a real fleet behind the real wire
    front; returns the summary dict with the validated run record.

    ``replicas`` is the pool's configured width — the autoscale FLOOR
    and the replica-keyed baseline key. With ``autoscale`` the
    burn-rate controller runs over the pool for the run's duration and
    its every actuation lands in the record and the actuation ledger
    (``ACTUATION_LEDGER.jsonl`` under ``workdir/ledger`` — the
    postmortem bundle auto-collects it)."""
    import http.client

    from scconsensus_tpu.obs import trace as obs_trace
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.obs.live import LiveRecorder
    from scconsensus_tpu.serve.driver import ServeConfig
    from scconsensus_tpu.serve.fleet.autoscale import Autoscaler
    from scconsensus_tpu.serve.fleet.pool import ReplicaPool
    from scconsensus_tpu.serve.fleet.soak import build_atlas_model
    from scconsensus_tpu.serve.fleet.wire import WireFront
    from scconsensus_tpu.serve.model import MODEL_STAGE
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    profile = profile or str(env_flag("SCC_LOADGEN_PROFILE"))
    base_rps = float(base_rps if base_rps is not None
                     else env_flag("SCC_LOADGEN_RPS"))
    peak_rps = float(peak_rps if peak_rps is not None
                     else 4.0 * base_rps)
    duration_s = float(duration_s if duration_s is not None
                       else env_flag("SCC_LOADGEN_DURATION_S"))
    seed = int(seed if seed is not None
               else env_flag("SCC_LOADGEN_SEED"))
    norm_mix = resolve_mix(mix)

    model_dir = os.path.join(workdir, "model_v1")
    if fresh or not ArtifactStore(model_dir).has(MODEL_STAGE):
        build_atlas_model(model_dir, n_genes=n_genes,
                          n_clusters=n_clusters, n_train=n_train,
                          seed=seed)

    offsets = arrival_offsets(profile, base_rps, peak_rps, duration_s,
                              seed, arrival=arrival)
    bodies, scen = _build_request_bodies(offsets, norm_mix, cells_per,
                                         n_genes, n_clusters, seed)

    ledger_dir = os.path.join(workdir, "ledger")
    cfg = ServeConfig(batch_window_s=0.001,
                      default_deadline_s=deadline_s,
                      ledger_dir=ledger_dir,
                      queue_capacity=(int(queue_capacity)
                                      if queue_capacity is not None
                                      else None))

    tracer = obs_trace.Tracer(sync="off")
    recorder = LiveRecorder(
        os.path.join(workdir, "LOAD_RUN"),
        metric="open-loop load run flight record",
        extra={"config": f"loadgen-{profile}", "platform": "cpu"},
        heartbeat_s=heartbeat_s,
    )
    recorder.start(install_signals=False)

    pool = ReplicaPool(model_dir, n_replicas=replicas, config=cfg)
    front = WireFront(pool)
    scaler: Optional[Autoscaler] = None
    results: List[Optional[Dict[str, Any]]] = [None] * len(offsets)
    next_i = [0]
    lock = threading.Lock()
    try:
      with pool, front:
        port = front.port
        if autoscale:
            scaler = Autoscaler(pool, policy=policy,
                                ledger_dir=ledger_dir).start()

        t0 = time.monotonic()

        def _pump():
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            while True:
                with lock:
                    if next_i[0] >= len(offsets):
                        conn.close()
                        return
                    i = next_i[0]
                    next_i[0] += 1
                # open loop: fire at the SCHEDULED offset, never gated
                # on earlier responses
                delay = (t0 + offsets[i]) - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                fired = time.monotonic() - t0
                try:
                    conn.request(
                        "POST", "/classify", body=bodies[i],
                        headers={"Content-Type": "application/json"})
                    r = conn.getresponse()
                    outcome = json.loads(r.read()).get("outcome")
                    out = {"i": i, "status": r.status,
                           "outcome": outcome,
                           "scenario": scen[i],
                           "late_s": round(max(fired - offsets[i],
                                               0.0), 6),
                           "latency_s": round(
                               time.monotonic() - t0 - fired, 6)}
                except (OSError, http.client.HTTPException,
                        ValueError) as e:
                    out = {"i": i, "status": None,
                           "outcome": "wire-error",
                           "scenario": scen[i],
                           "late_s": round(max(fired - offsets[i],
                                               0.0), 6),
                           "error": str(e)[:200]}
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=60)
                results[i] = out

        threads = [threading.Thread(target=_pump, daemon=True)
                   for _ in range(max(1, int(pumps)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + 120.0)
        elapsed = max(time.monotonic() - t0, duration_s)
        if scaler is not None:
            scaler.stop()
        section = front.serving_section()
        slo_section = front.slo_section()
    except BaseException:
        recorder.stop("crash")
        raise
    else:
        recorder.stop("clean")

    done = [r for r in results if r is not None]
    good = sum(1 for r in done if r["status"] == 200)
    completed = sum(1 for r in done if r["status"] is not None)
    late = sum(1 for r in done if r["late_s"] > LATE_TOLERANCE_S)
    breaches = slo_breaches(slo_section)
    achieved = good / elapsed if elapsed > 0 else 0.0
    lg = build_loadgen_section(
        profile, arrival, base_rps, peak_rps, duration_s, seed,
        norm_mix, offered=len(offsets), sent=len(done),
        completed=completed, good=good,
        late_fraction=(late / len(done)) if done else 0.0,
        achieved_rps=achieved, breaches=breaches,
        autoscale=scaler.section() if scaler is not None else None,
    )
    rec = build_run_record(
        metric="sustained RPS at SLO",
        value=lg["rps_at_slo"],
        unit="rps",
        extra={"config": f"loadgen-{profile}", "platform": "cpu"},
        spans=tracer.live_span_records(),
        serving=section,
        slo=slo_section,
        loadgen=lg,
    )
    accounting_ok = True
    try:
        validate_run_record(rec)
    except ValueError as e:
        accounting_ok = False
        rec = {"invalid": str(e)}

    counts: Dict[str, int] = {}
    for r in done:
        counts[str(r["outcome"])] = counts.get(str(r["outcome"]), 0) + 1
    by_scenario: Dict[str, int] = {}
    for name in scen:
        by_scenario[name] = by_scenario.get(name, 0) + 1
    ok = (len(done) == len(offsets)
          and accounting_ok
          and not any(r["outcome"] == "wire-error" for r in done))
    summary: Dict[str, Any] = {
        "ok": ok,
        "profile": profile,
        "arrival": arrival,
        "offered": len(offsets),
        "sent": len(done),
        "completed": completed,
        "good": good,
        "achieved_rps": round(achieved, 4),
        "rps_at_slo": lg["rps_at_slo"],
        "slo_held": lg["slo_held"],
        "breaches": breaches,
        "late_fraction": lg["late_fraction"],
        "outcome_counts": counts,
        "mix_counts": by_scenario,
        "replicas_floor": pool.n_default,
        "accounting_ok": accounting_ok,
        "actuations": (list(scaler.actuations)
                       if scaler is not None else []),
        "scales": [dict(s) for s in pool.telemetry_snapshot()["scales"]],
        "record": rec,
    }
    if recorder.enabled:
        summary["heartbeat_stream"] = os.path.basename(recorder.hb_path)
        summary["partial_record"] = os.path.basename(
            recorder.partial_path)
    return summary
