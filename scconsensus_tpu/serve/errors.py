"""Typed serving errors — the online path's whole failure vocabulary.

At millions-of-users scale a request that fails MUST fail loudly and
specifically: the client's retry policy branches on the type (a
``QueueFull`` is retryable after ``retry_after_s``; a ``ModelLoadError``
is not retryable at all until an operator replaces the artifact), and the
serving stats account every one of them — no request ever just
disappears. Import discipline: stdlib only (the chaos harness and the
jax-free orchestrator load these to classify worker outcomes).
"""

from __future__ import annotations

__all__ = [
    "ServeError",
    "ModelLoadError",
    "RequestInvalid",
    "QueueFull",
    "DeadlineExceeded",
    "ServerClosed",
    "RequestFailed",
]


class ServeError(Exception):
    """Base of every typed serving failure."""


class ModelLoadError(ServeError):
    """The frozen consensus-model artifact could not be loaded: missing,
    wrong schema/version, incoherent shapes, or corrupt (in which case the
    store has already QUARANTINED the files — ``quarantined`` says so).
    A server must refuse to start on this; serving garbage labels is the
    one failure mode worse than downtime."""

    def __init__(self, msg: str, quarantined: bool = False):
        super().__init__(msg)
        self.quarantined = bool(quarantined)


class RequestInvalid(ServeError, ValueError):
    """The request is malformed (wrong gene dimension, empty, non-finite
    cells, oversized) — rejected at admission, never enqueued."""


class QueueFull(ServeError):
    """Bounded-admission backpressure: the queue is at capacity, the
    request was NOT enqueued, and the client should retry after
    ``retry_after_s`` — the explicit alternative to unbounded growth."""

    def __init__(self, depth: int, capacity: int, retry_after_s: float):
        super().__init__(
            f"admission queue full ({depth}/{capacity}); "
            f"retry after {retry_after_s:.3f}s"
        )
        self.depth = int(depth)
        self.capacity = int(capacity)
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(ServeError):
    """The request's deadline passed before a result could be returned
    (queue wait or compute overran it). The typed promise: late answers
    are an error, never a silently stale success."""

    def __init__(self, msg: str, late_by_s: float = 0.0):
        super().__init__(msg)
        self.late_by_s = float(late_by_s)


class ServerClosed(ServeError):
    """submit() after stop(): the driver is draining or gone."""


class RequestFailed(ServeError):
    """A fatal (non-retryable, non-degradable) error killed this request's
    batch — carries the underlying class/message for the client log."""

    def __init__(self, msg: str, error_class: str = "fatal"):
        super().__init__(msg)
        self.error_class = str(error_class)
