"""Async micro-batching serving driver — born hardened.

``ConsensusServer`` serves ``classify(new_cells)`` against a frozen
:class:`~scconsensus_tpu.serve.model.ConsensusModel`. The robustness is
the headline, not the batching:

  * **Bounded admission** — the queue has a hard capacity; a submit at
    capacity raises typed :class:`QueueFull` carrying ``retry_after_s``
    (reject-with-retry-after, never unbounded growth).
  * **Per-request deadlines** — checked at dequeue AND after compute;
    an overrun resolves as typed :class:`DeadlineExceeded`, never a
    silently late answer.
  * **Circuit breaker over the device path** — failures classified by
    ``robust.retry.classify_exception`` (the same classifier real
    XlaRuntimeError text and injected faults share) count toward the
    trip threshold; a tripped breaker routes batches to the HOST
    nearest-centroid fallback with every response explicitly flagged
    ``degraded=True``, then half-open-probes the device after a
    cooldown. Fatal-class errors never trip it — they resolve each
    request as typed :class:`RequestFailed` (a bug must not read as an
    outage).
  * **Drift quarantine** — each request's batch slice is scored against
    the model's calibrated foreign-cell threshold; a request past the
    quarantine fraction gets NO labels: it is appended to the quarantine
    ledger (JSONL, with a distance-quantile fingerprint in the r10
    mold) and resolved ``quarantined=True`` — refusing to confidently
    mislabel what no longer fits the frozen model.
  * **Accounting** — every request ends as exactly one
    ``serve.metrics.OUTCOMES`` entry; ``validate_serving`` rejects a
    record whose outcomes don't sum to its submissions.

Fault-injection sites (``robust.faults``): ``serve_load`` (model load),
``serve_batch`` (micro-batch assembly — kill/stall land here),
``serve_device`` (inside the device call) — ``tools/chaos_run.py``'s
serve soak matrix drives all three.

Every batch rides a ``serve_batch`` span and every request a back-dated
``serve_request`` span (``Tracer.add_completed_span``) stamped with its
outcome and latency, so serving shows up in run records, Chrome traces,
and the heartbeat stream like any pipeline stage.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.serve import metrics as serve_metrics
from scconsensus_tpu.serve.errors import (
    DeadlineExceeded,
    ModelLoadError,
    QueueFull,
    RequestFailed,
    RequestInvalid,
    ServeError,
    ServerClosed,
)
from scconsensus_tpu.serve.model import ConsensusModel, load_consensus_model

__all__ = [
    "ServeConfig",
    "ServeResponse",
    "RequestHandle",
    "CircuitBreaker",
    "ConsensusServer",
    "QUARANTINE_LEDGER_NAME",
    "QUARANTINE_CELLS_DIR",
]

QUARANTINE_LEDGER_NAME = "QUARANTINE_LEDGER.jsonl"
# sibling dir for the persisted quarantined-cell payloads — the writer
# (this driver) and the reader (serve.fleet.reconsensus) share this ONE
# name
QUARANTINE_CELLS_DIR = "quarantine_cells"


@dataclasses.dataclass
class ServeConfig:
    """Driver knobs; ``None`` fields resolve from the registered serve
    env flags (config.ENV_FLAGS) at construction."""

    max_batch_cells: Optional[int] = None     # SCC_SERVE_MAX_BATCH
    queue_capacity: Optional[int] = None      # SCC_SERVE_QUEUE_CAP
    batch_window_s: Optional[float] = None    # SCC_SERVE_BATCH_WINDOW_S
    default_deadline_s: Optional[float] = None  # SCC_SERVE_DEADLINE_S
    breaker_threshold: Optional[int] = None   # SCC_SERVE_BREAKER_THRESHOLD
    breaker_cooldown_s: Optional[float] = None  # SCC_SERVE_BREAKER_COOLDOWN_S
    drift_quarantine_frac: Optional[float] = None  # SCC_SERVE_DRIFT_FRAC
    quarantine_path: Optional[str] = None     # default <model_dir>/ledger
    ledger_dir: Optional[str] = None          # SCC_SERVE_LEDGER_DIR

    def resolved(self) -> "ServeConfig":
        def _r(v, flag):
            return env_flag(flag) if v is None else v

        return ServeConfig(
            max_batch_cells=int(_r(self.max_batch_cells,
                                   "SCC_SERVE_MAX_BATCH")),
            queue_capacity=int(_r(self.queue_capacity,
                                  "SCC_SERVE_QUEUE_CAP")),
            batch_window_s=float(_r(self.batch_window_s,
                                    "SCC_SERVE_BATCH_WINDOW_S")),
            default_deadline_s=float(_r(self.default_deadline_s,
                                        "SCC_SERVE_DEADLINE_S")),
            breaker_threshold=int(_r(self.breaker_threshold,
                                     "SCC_SERVE_BREAKER_THRESHOLD")),
            breaker_cooldown_s=float(_r(self.breaker_cooldown_s,
                                        "SCC_SERVE_BREAKER_COOLDOWN_S")),
            drift_quarantine_frac=float(_r(self.drift_quarantine_frac,
                                           "SCC_SERVE_DRIFT_FRAC")),
            quarantine_path=self.quarantine_path,
            ledger_dir=_r(self.ledger_dir, "SCC_SERVE_LEDGER_DIR"),
        )


@dataclasses.dataclass
class ServeResponse:
    """One request's terminal answer. ``labels`` is None exactly when the
    drift gate quarantined the request (``outcome == "quarantined"``)."""

    req_id: int
    outcome: str                       # "ok" | "degraded" | "quarantined"
    labels: Optional[np.ndarray]
    distances: Optional[np.ndarray]
    degraded: bool
    quarantined: bool
    drift_fraction: float
    latency_s: float
    batch_seq: int
    # fingerprint of the model that answered — the fleet's hot-swap
    # purity check reads it off every response (a request is never split
    # across models, and this proves WHICH model served it)
    model_fp: Optional[str] = None
    # the request's trace id (round 20): minted at the wire front (or
    # here at admission), carried through the span/ledger/heartbeat so
    # one id recovers the request's whole story
    trace_id: Optional[str] = None


class RequestHandle:
    """Future-style handle returned by :meth:`ConsensusServer.submit`.
    ``result()`` returns the :class:`ServeResponse` or raises the
    request's typed error."""

    __slots__ = ("req_id", "cells", "n", "deadline_mono", "enqueued_mono",
                 "trace_id", "_event", "_response", "_error")

    def __init__(self, req_id: int, cells: np.ndarray,
                 deadline_mono: float, trace_id: Optional[str] = None):
        # monotonic stamps: deadlines and latencies are DURATIONS, and a
        # wall-clock step (NTP) must not expire a queue or stretch a p99
        self.req_id = req_id
        self.cells = cells
        self.n = int(cells.shape[0])
        self.deadline_mono = float(deadline_mono)
        self.trace_id = trace_id
        self.enqueued_mono = time.monotonic()
        self._event = threading.Event()
        self._response: Optional[ServeResponse] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, response: Optional[ServeResponse] = None,
                 error: Optional[BaseException] = None) -> None:
        self._response = response
        self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> ServeResponse:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.req_id} not resolved within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        assert self._response is not None
        return self._response


class CircuitBreaker:
    """closed → (threshold consecutive device-class failures) → open →
    (cooldown) → half_open probe → closed on success / open on failure.
    Device-class = resource/transient/device_lost per the shared
    classifier; fatal never counts."""

    def __init__(self, threshold: int, cooldown_s: float,
                 stats: serve_metrics.ServingStats):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self.stats = stats
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.trips = 0
        # operator override (the autoscaler's degraded-mode lever): a
        # forced-open breaker routes every batch to the fallback with NO
        # half-open probing until force_close() lifts it
        self.forced = False
        self._lock = threading.Lock()

    def route(self, now: Optional[float] = None) -> str:
        """'device' or 'fallback' for the next batch."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.forced:
                return "fallback"
            if self.state == "closed":
                return "device"
            if self.state == "open":
                if now - self.opened_at >= self.cooldown_s:
                    self.state = "half_open"
                    self.stats.note_breaker("half_open")
                    return "device"  # the probe
                return "fallback"
            return "device"  # half_open: keep probing

    def force_open(self, now: Optional[float] = None) -> None:
        """Explicit degraded-mode entry: pin the breaker open (every
        batch serves flagged-degraded from the fallback, no probing).
        Counts as a trip — a record claiming degraded service must show
        a tripped breaker, and a forced entry is exactly that."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.forced:
                return
            self.forced = True
            if self.state != "open":
                self.state = "open"
                self.opened_at = now
                self.trips += 1
                self.stats.note_breaker("open", tripped=True)

    def force_close(self) -> None:
        """Lift the forced-open override (degraded-mode exit): the
        breaker returns to closed and normal failure counting resumes."""
        with self._lock:
            if not self.forced:
                return
            self.forced = False
            if self.state != "closed":
                self.state = "closed"
                self.stats.note_breaker("closed")
            self.failures = 0

    def record_success(self) -> None:
        with self._lock:
            if self.forced:
                # a fallback success must not close a forced-open
                # breaker: only force_close() ends degraded mode
                self.failures = 0
                return
            if self.state != "closed":
                self.state = "closed"
                self.stats.note_breaker("closed")
            self.failures = 0

    def record_failure(self, err_class: str,
                       now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self.failures += 1
            if self.state == "half_open" or (
                self.state == "closed" and self.failures >= self.threshold
            ):
                self.state = "open"
                self.opened_at = now
                self.trips += 1
                self.stats.note_breaker("open", tripped=True)


class ConsensusServer:
    """The guarded online classify() path. Use as a context manager or
    call :meth:`start` / :meth:`stop` explicitly."""

    def __init__(self, model: Union[ConsensusModel, str],
                 config: Optional[ServeConfig] = None,
                 readonly: bool = False,
                 register_live: bool = True):
        if isinstance(model, str):
            # typed refusal path: ModelLoadError propagates — a server
            # must not come up on a model it cannot prove intact. The
            # default keeps the quarantine contract (a corrupt artifact
            # is renamed aside as a post-mortem); readonly=True serves a
            # frozen dir on a read-only mount and refuses WITHOUT
            # touching the operator's files.
            self.model_dir: Optional[str] = model
            self.model = load_consensus_model(model, readonly=readonly)
        else:
            self.model_dir = None
            self.model = model
        self.config = (config or ServeConfig()).resolved()
        self.stats = serve_metrics.ServingStats(
            queue_capacity=self.config.queue_capacity
        )
        self.breaker = CircuitBreaker(
            self.config.breaker_threshold,
            self.config.breaker_cooldown_s,
            self.stats,
        )
        qp = self.config.quarantine_path
        if qp is None and self.config.ledger_dir:
            # the writable sidecar dir (SCC_SERVE_LEDGER_DIR): the ONLY
            # way a server on a frozen read-only model dir accumulates
            # drift evidence — and where the reconsensus loop finds its
            # material (the ledger lines AND the quarantined cells)
            qp = os.path.join(self.config.ledger_dir,
                              QUARANTINE_LEDGER_NAME)
        if qp is None and self.model_dir is not None and not readonly:
            # never default the ledger INTO a readonly model dir: the
            # appends would all fail silently against the promise that a
            # frozen mount is never written — a readonly server needs an
            # explicit quarantine_path or ledger_dir, else the response
            # flag alone is the signal
            qp = os.path.join(self.model_dir, QUARANTINE_LEDGER_NAME)
        self.quarantine_path = qp
        self._register_live = bool(register_live)
        self._q_cells_saved = 0
        self._q_seq = 0
        self._queue: List[RequestHandle] = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = True
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        self._req_seq = 0
        self._batch_seq = 0
        # EWMA of recent batch walls — the retry_after hint's basis
        self._batch_wall_ewma = 0.01

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ConsensusServer":
        if self._thread is not None:
            return self
        self._closed = False
        self._draining = False
        if self._register_live:
            # fleet replicas pass register_live=False: the pool feeds the
            # heartbeat with ONE aggregated fleet summary instead of N
            # replicas last-write-wins clobbering each other
            serve_metrics.set_active(self.stats)
        self._thread = threading.Thread(
            target=self._worker, name="scc-serve", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Close admission, optionally drain the queue, stop the worker.
        With ``drain=False`` queued requests resolve as ServerClosed —
        still typed, still accounted. ``timeout_s`` bounds the worker
        join (the fleet's hot-swap drain budget flows through here)."""
        with self._lock:
            if self._closed and self._thread is None:
                return
            self._closed = True
            self._draining = drain
            self._not_empty.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=max(float(timeout_s), 0.1))
        self._thread = None
        with self._lock:
            leftovers = self._queue
            self._queue = []
        for r in leftovers:
            r._resolve(error=ServerClosed(
                f"server stopped before request {r.req_id} was served"
            ))
            # a drain refusal is a typed REJECTION, not a fatal error —
            # "failed" must stay the fatal-bug signal
            self.stats.note_outcome("rejected_closed")
        if serve_metrics.active_stats() is self.stats:
            serve_metrics.set_active(None)

    def __enter__(self) -> "ConsensusServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def closed(self) -> bool:
        """True when the driver is not accepting requests (never started,
        stopped, or draining) — the wire front's /healthz signal."""
        return self._closed

    # -- admission ---------------------------------------------------------
    def submit(self, cells: np.ndarray,
               deadline_s: Optional[float] = None,
               trace_id: Optional[str] = None) -> RequestHandle:
        """Enqueue one request ((n, G) genes-length rows). Typed refusals:
        ServerClosed, RequestInvalid, QueueFull(retry_after_s).
        ``trace_id`` rides in from the wire front (or is minted here
        with SCC_OBS_TRACE on, so a bare-driver request still has one).

        Guard overhead is self-measured in per-thread CPU time
        (``time.thread_time``, the r9 sampler-guard precedent): wall
        would charge admission for GIL waits caused by the worker's
        compute and overstate the guard cost by >10x on a busy
        interpreter."""
        t0 = time.thread_time()
        if trace_id is None and env_flag("SCC_OBS_TRACE"):
            from scconsensus_tpu.obs.trace import new_trace_id

            trace_id = new_trace_id()
        try:
            if self._closed:
                raise ServerClosed("server is not accepting requests")
            x = np.asarray(cells)
            if x.ndim != 2 or x.shape[0] < 1:
                raise RequestInvalid(
                    f"cells must be a non-empty (n, G) matrix, "
                    f"got shape {x.shape}"
                )
            if x.shape[1] != self.model.n_genes:
                raise RequestInvalid(
                    f"cells have {x.shape[1]} genes; the frozen model "
                    f"expects {self.model.n_genes}"
                )
            if x.shape[0] > self.config.max_batch_cells:
                raise RequestInvalid(
                    f"request of {x.shape[0]} cells exceeds the "
                    f"max batch of {self.config.max_batch_cells}; split it"
                )
            # NO full NaN/Inf scan here: a non-finite cell necessarily
            # produces a non-finite nearest-landmark distance, and the
            # classify computes those anyway (rows are independent, so a
            # poisoned request cannot corrupt its batch-mates) — the
            # finiteness guard rides the batch for free and resolves as
            # a typed RequestInvalid at resolution (see _process)
            dl = (self.config.default_deadline_s
                  if deadline_s is None else float(deadline_s))
            with self._lock:
                if self._closed:
                    # re-check UNDER the lock: a submit racing stop()
                    # must never append to a queue no worker will drain
                    # (the handle would hang unresolved and break the
                    # accounting contract)
                    raise ServerClosed("server is not accepting requests")
                depth = len(self._queue)
                if depth >= self.config.queue_capacity:
                    # retry-after: roughly the time to drain half the queue
                    per_req = self._batch_wall_ewma / max(
                        1.0, self.config.max_batch_cells / max(x.shape[0], 1)
                    )
                    retry = max(per_req * depth / 2.0, 0.001)
                    self.stats.note_outcome("rejected_queue")
                    self.stats.note_submit(depth)
                    raise QueueFull(depth, self.config.queue_capacity,
                                    retry_after_s=retry)
                self._req_seq += 1
                req = RequestHandle(self._req_seq, x,
                                    time.monotonic() + dl,
                                    trace_id=trace_id)
                self._queue.append(req)
                self.stats.note_submit(len(self._queue))
                self._not_empty.notify()
            return req
        except (RequestInvalid, ServerClosed):
            # invalid/closed submissions are accounted too — a typed
            # rejection is an outcome, not a disappearance
            self.stats.note_submit(len(self._queue))
            self.stats.note_outcome(
                "rejected_invalid" if not self._closed
                else "rejected_closed"
            )
            raise
        finally:
            self.stats.add_consumed(time.thread_time() - t0)

    def classify(self, cells: np.ndarray,
                 deadline_s: Optional[float] = None,
                 timeout: Optional[float] = None) -> ServeResponse:
        """submit + wait convenience for synchronous callers."""
        return self.submit(cells, deadline_s=deadline_s).result(
            timeout=timeout
        )

    # -- the worker --------------------------------------------------------
    def _collect(self) -> Optional[List[RequestHandle]]:
        """Block for the first request, then linger ``batch_window_s``
        (or until ``max_batch_cells``) coalescing concurrent arrivals —
        the micro-batch. None = shut down."""
        with self._not_empty:
            while not self._queue:
                if self._closed:
                    return None
                self._not_empty.wait(timeout=0.05)
            if self._closed and not self._draining:
                # stop(drain=False): leave the backlog for stop() to
                # resolve as typed ServerClosed — don't serve it
                return None
            batch = [self._queue.pop(0)]
            cells = batch[0].n
        window_end = time.monotonic() + self.config.batch_window_s
        while cells < self.config.max_batch_cells:
            with self._not_empty:
                if not self._queue:
                    remaining = window_end - time.monotonic()
                    if remaining <= 0 or (self._closed
                                          and not self._queue):
                        break
                    self._not_empty.wait(timeout=min(remaining, 0.05))
                if (self._queue and self._queue[0].n + cells
                        <= self.config.max_batch_cells):
                    r = self._queue.pop(0)
                    batch.append(r)
                    cells += r.n
                elif self._queue:
                    break  # next request would overflow the batch
                elif time.monotonic() >= window_end:
                    break
        with self._lock:
            self.stats.note_queue_depth(len(self._queue))
        return batch

    def _worker(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                # closed: with drain the queue is already empty (the
                # collect loop kept serving until then); without it the
                # backlog is stop()'s to refuse typed
                return
            try:
                self._process(batch)
            except BaseException as e:  # noqa: BLE001 - last-ditch guard
                # the accounting contract survives even a driver bug:
                # every in-flight request resolves typed, never hangs
                for r in batch:
                    if not r.done():
                        r._resolve(error=RequestFailed(
                            f"serving driver error: {e!r}",
                            error_class="fatal",
                        ))
                        self.stats.note_outcome("failed")

    def _device_classify(self, x: np.ndarray):
        """One guarded device call (fault site ``serve_device``); batches
        are padded to the next power of two so the jitted kernel compiles
        O(log max_batch) shapes, not one per batch size. Under
        ``SCC_INTEGRITY`` the injected ``serve_classify`` corruption
        site perturbs the device labels, and a seeded sample of batches
        (the first of every 64) is ghost-replayed against the model's
        float64 host mirror — a mismatch raises typed
        silent_corruption, which the in-batch retry loop recomputes
        (and the breaker counts, so a device that KEEPS answering wrong
        degrades to the host mirror exactly like one that keeps
        crashing)."""
        from scconsensus_tpu.robust import faults
        from scconsensus_tpu.robust import integrity as robust_integrity

        faults.fault_point("serve_device")
        n = x.shape[0]
        padded = 1
        while padded < n:
            padded <<= 1
        if padded > n:
            x = np.concatenate(
                [x, np.zeros((padded - n, x.shape[1]), x.dtype)]
            )
        labels, dist = self.model.classify(x)
        labels = faults.corrupt_value("serve_classify", labels)
        if robust_integrity.enabled() and \
                robust_integrity.current().want_replay(
                    "serve", self._batch_seq // 64):
            robust_integrity.replay_classify(
                "serve_classify", x[:n], labels[:n], self.model,
                unit=f"batch:{self._batch_seq}",
            )
        return labels[:n], dist[:n]

    def _process(self, batch: List[RequestHandle]) -> None:
        from scconsensus_tpu.obs import trace as obs_trace
        from scconsensus_tpu.robust import faults
        from scconsensus_tpu.robust import retry as robust_retry

        t_batch0 = time.thread_time()
        now = time.monotonic()
        self._batch_seq += 1
        n_cells = sum(r.n for r in batch)
        with obs_trace.span("serve_batch", kind="detail",
                            n_requests=len(batch), n_cells=n_cells):
            # deadline check at dequeue: a request that already missed its
            # deadline must not burn device time
            live: List[RequestHandle] = []
            for r in batch:
                if now > r.deadline_mono:
                    self._finish(r, error=DeadlineExceeded(
                        f"request {r.req_id} exceeded its deadline in the "
                        f"queue", late_by_s=now - r.deadline_mono,
                    ), outcome="deadline_exceeded")
                else:
                    live.append(r)
            if not live:
                return
            self.stats.note_batch(len(live), sum(r.n for r in live))
            for r in live:
                # queue_wait stage histogram: dequeue minus enqueue per
                # request — the half of the p99 batching owns
                self.stats.note_stage_latency(
                    "queue_wait", now - r.enqueued_mono
                )
            try:
                # batching-layer fault site: kill/stall/corrupt plans
                # land between dequeue and dispatch — mid-batch
                faults.fault_point("serve_batch")
            except Exception as e:
                err_class = robust_retry.classify_exception(e)
                if err_class == "fatal":
                    for r in live:
                        self._finish(r, error=RequestFailed(
                            f"batch assembly failed: {e}",
                            error_class=err_class,
                        ), outcome="failed")
                    return
                # non-fatal batch fault: treat like a device failure —
                # count it on the breaker and serve degraded below
                self.breaker.record_failure(err_class)
            x = (live[0].cells if len(live) == 1
                 else np.concatenate([r.cells for r in live]))
            x = np.asarray(x, np.float32)

            # Device path with in-batch typed retry: a device-class
            # failure (resource/transient/device_lost per the shared
            # classifier) counts one breaker failure and the batch
            # retries; once the breaker trips (threshold consecutive
            # failures, or any half-open probe failure), the batch —
            # and every batch until the cooldown probe succeeds — serves
            # from the HOST fallback, explicitly flagged degraded. A
            # transient blip therefore recovers invisibly; a broken
            # device degrades loudly; a bug (fatal class) fails typed.
            degraded = False
            t_dev0 = time.perf_counter()
            t_dev0_cpu = time.thread_time()
            labels = dist = None
            attempt = 0
            while True:
                if self.breaker.route() != "device":
                    from scconsensus_tpu.robust import record as rb_record

                    rb_record.note_degradation(
                        "serve_device", "host-fallback",
                        f"breaker {self.breaker.state} — serving degraded",
                    )
                    labels, dist = self.model.classify_host(x)
                    degraded = True
                    break
                try:
                    labels, dist = self._device_classify(x)
                    self.breaker.record_success()
                    break
                except Exception as e:
                    err_class = robust_retry.classify_exception(e)
                    if err_class == "fatal":
                        for r in live:
                            self._finish(r, error=RequestFailed(
                                f"device classify failed fatally: {e}",
                                error_class=err_class,
                            ), outcome="failed")
                        return
                    attempt += 1
                    self.breaker.record_failure(err_class)
                    time.sleep(min(0.01 * attempt, 0.1))
            batch_wall = time.perf_counter() - t_dev0
            classify_cpu = time.thread_time() - t_dev0_cpu
            self.stats.add_classify_wall(batch_wall)
            # compute stage histogram: the classify wall this batch paid
            self.stats.note_stage_latency("compute", batch_wall)
            self._batch_wall_ewma = (0.7 * self._batch_wall_ewma
                                     + 0.3 * batch_wall)

            # per-request resolution: slice, drift-score, deadline-check
            off = 0
            now2 = time.monotonic()
            quarantined_n = 0
            any_drift = False
            for r in live:
                lab = labels[off:off + r.n]
                d = dist[off:off + r.n]
                off += r.n
                if not np.isfinite(d).all():
                    # the free finiteness guard (see submit): NaN/Inf
                    # cells surface as non-finite distances on the (n,)
                    # result — reject typed, never label garbage
                    self._finish(r, error=RequestInvalid(
                        f"request {r.req_id} contains non-finite cells "
                        f"({int((~np.isfinite(d)).sum())} of {r.n})"
                    ), outcome="rejected_invalid")
                    continue
                if now2 > r.deadline_mono:
                    self._finish(r, error=DeadlineExceeded(
                        f"request {r.req_id} exceeded its deadline during "
                        f"compute", late_by_s=now2 - r.deadline_mono,
                    ), outcome="deadline_exceeded")
                    continue
                frac = self.model.drift_fraction(d)
                # a quarantine fraction > 1 is unreachable by construction
                # — the documented way to disable the drift gate
                if frac >= self.config.drift_quarantine_frac:
                    any_drift = True
                    quarantined_n += 1
                    self._quarantine_entry(r, frac, d)
                    self._finish(r, response=ServeResponse(
                        req_id=r.req_id, outcome="quarantined",
                        labels=None, distances=d, degraded=degraded,
                        quarantined=True, drift_fraction=frac,
                        latency_s=now2 - r.enqueued_mono,
                        batch_seq=self._batch_seq,
                        model_fp=self.model.fingerprint(),
                        trace_id=r.trace_id,
                    ), outcome="quarantined")
                    continue
                self._finish(r, response=ServeResponse(
                    req_id=r.req_id,
                    outcome="degraded" if degraded else "ok",
                    labels=lab, distances=d, degraded=degraded,
                    quarantined=False, drift_fraction=frac,
                    latency_s=now2 - r.enqueued_mono,
                    batch_seq=self._batch_seq,
                    model_fp=self.model.fingerprint(),
                    trace_id=r.trace_id,
                ), outcome="degraded" if degraded else "ok")
            if any_drift:
                self.stats.note_drift_batch(quarantined=quarantined_n)
        # guard bookkeeping = this thread's CPU across the batch minus
        # the classify call itself (thread CPU, not wall — see submit)
        self.stats.add_consumed(
            max(time.thread_time() - t_batch0 - classify_cpu, 0.0)
        )

    def _finish(self, r: RequestHandle,
                response: Optional[ServeResponse] = None,
                error: Optional[BaseException] = None,
                outcome: str = "ok") -> None:
        """Resolve one request: stats outcome + a back-dated
        ``serve_request`` span so every request rides the trace — span
        and stats both carry the request's trace id, which is how the
        heartbeat stream and the partial record join the wire story."""
        latency = time.monotonic() - r.enqueued_mono
        self.stats.note_outcome(outcome, latency_s=latency,
                                trace_id=r.trace_id)
        try:
            from scconsensus_tpu.obs import trace as obs_trace

            tr = obs_trace.last_tracer()
            if tr is not None:
                attrs: Dict[str, Any] = dict(
                    outcome=outcome, n_cells=r.n, req_id=r.req_id,
                )
                if r.trace_id:
                    attrs["trace_id"] = r.trace_id
                tr.add_completed_span(
                    "serve_request", wall_s=latency, kind="detail",
                    **attrs,
                )
        except Exception:
            pass  # tracing must never cost a response
        r._resolve(response=response, error=error)

    def _quarantine_entry(self, r: RequestHandle, frac: float,
                          dist: np.ndarray) -> None:
        """Append one quarantine-ledger line: the request's identity, its
        drift fraction, and a distance-quantile fingerprint (the r10
        fingerprint idiom) — enough for an operator to decide whether a
        re-consensus is warranted. Best-effort by contract: the RESPONSE
        flag is the source of truth, the ledger is the audit trail."""
        if not self.quarantine_path:
            return
        d = np.asarray(dist, np.float64)
        entry = {
            "ts": round(time.time(), 3),
            "req_id": r.req_id,
            # the trace id joins this ledger row to the wire response,
            # the serve_request span, and the heartbeat stream — the
            # postmortem bundle's key
            "trace_id": r.trace_id,
            "n_cells": r.n,
            "drift_fraction": round(float(frac), 6),
            "threshold": round(float(self.model.drift_threshold), 6),
            "dist_q": [round(float(q), 6) for q in np.quantile(
                d, (0.1, 0.5, 0.9, 0.99)
            )] if d.size else [],
            "model_fp": self.model.fingerprint(),
        }
        # Persist the quarantined CELLS beside the ledger (bounded by
        # SCC_SERVE_LEDGER_MAX_CELLS): the r15 ledger recorded only the
        # distance fingerprint, which starves the reconsensus loop — the
        # loop needs the actual expression rows to mini-refine. Ledger
        # lines keep appending past the cap; only the payloads stop.
        cells_file = self._save_quarantined_cells(r)
        if cells_file:
            entry["cells_file"] = cells_file
        try:
            with open(self.quarantine_path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:
            pass

    QUARANTINE_CELLS_DIR = QUARANTINE_CELLS_DIR  # module constant

    def _save_quarantined_cells(self, r: RequestHandle) -> Optional[str]:
        """Write one ``qcells_*.npy`` payload into the ledger dir's cells
        subdir; returns the ledger-relative path, or None (cap reached /
        write failed — the response flag and ledger line still stand)."""
        cap = int(env_flag("SCC_SERVE_LEDGER_MAX_CELLS"))
        if self._q_cells_saved + r.n > cap:
            return None
        base = os.path.dirname(os.path.abspath(self.quarantine_path))
        cdir = os.path.join(base, self.QUARANTINE_CELLS_DIR)
        self._q_seq += 1
        name = f"qcells_{os.getpid()}_{self._q_seq:06d}.npy"
        try:
            os.makedirs(cdir, exist_ok=True)
            with open(os.path.join(cdir, name), "wb") as f:
                np.save(f, np.asarray(r.cells, np.float32))
        except OSError:
            return None
        self._q_cells_saved += r.n
        return os.path.join(self.QUARANTINE_CELLS_DIR, name)

    # -- record ------------------------------------------------------------
    def serving_section(self) -> Dict[str, Any]:
        """The validated ``serving`` run-record section for this server's
        lifetime (``obs.export.build_run_record(serving=...)``)."""
        sec = self.stats.section()
        if self.quarantine_path and os.path.exists(self.quarantine_path):
            sec["drift"]["ledger_path"] = os.path.basename(
                self.quarantine_path
            )
        sec["model"] = {
            "fingerprint": self.model.fingerprint(),
            "k": self.model.k,
            "n_pcs": self.model.n_pcs,
            "deep_split": self.model.meta.get("deep_split"),
        }
        return sec
