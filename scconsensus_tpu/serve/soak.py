"""Runnable serve-soak worker: the chaos harness's serving workload.

    python -m scconsensus_tpu.serve.soak --dir DIR [--requests N]
        [--cells M] [--seed S] [--ood-requests K] [--summary PATH]
        [--fresh] [--expect-refusal] [--deadline S] [--window S]

Builds (or loads) a deterministic demo consensus model under ``DIR``,
drives a replayable request set through :class:`ConsensusServer` under
whatever ``SCC_FAULT_PLAN`` is ambient, and writes one summary JSON:
the schema-validated run record (``serving`` section included), a
per-request outcome list, and a sha256 over the returned labels in
request order. The exit code IS the chaos contract:

  0  every submitted request ended as exactly one typed outcome and the
     serving section validates (accounting holds);
  1  the contract broke (a request vanished, validation failed);
  3  with ``--expect-refusal``: the model DID load when a typed refusal
     was expected (or vice versa the refusal check's inverse).

Because the model build, the request set, and classify are all seeded
and the model is FROZEN, two clean runs over the same ``DIR`` produce
identical label hashes — the kill-and-restart durability check is
``sha(restart) == sha(reference)``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["build_demo_model", "make_requests", "run_soak", "main"]

# demo-model shape: small enough that a soak subprocess (jax import
# included) finishes in seconds, structured enough that labels are stable
_GENES = 120
_CLUSTERS = 4
_TRAIN_CELLS = 360
_LANDMARKS = 32


def _demo_training_set(seed: int):
    """Seeded well-separated gaussian clusters in gene space: (G, N)
    data + per-cell labels 1..K (0 is the unassigned convention)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(0.0, 4.0, size=(_CLUSTERS, _GENES))
    per = _TRAIN_CELLS // _CLUSTERS
    cells = np.concatenate([
        centers[c] + rng.normal(0.0, 0.6, size=(per, _GENES))
        for c in range(_CLUSTERS)
    ])
    labels = np.repeat(np.arange(1, _CLUSTERS + 1), per)
    return np.asarray(cells.T, np.float32), labels, centers


def build_demo_model(model_dir: str, seed: int = 7,
                     landmark_seed: Optional[int] = None):
    """Deterministic demo model through the REAL export path pieces
    (pca_basis → landmark_ward_linkage → the shared
    ``freeze_model_arrays`` assembly → ArtifactStore save), without
    running the full DE pipeline — the soak exercises the serving
    layer, not DE, and the shared freezer keeps the artifact schema
    from drifting between this and ``export_consensus_model``.

    ``landmark_seed`` reseeds ONLY the landmark fit: same training
    distribution, different centroids → a different fingerprint that
    still classifies the same request set — the fleet hot-swap soak's
    "v2" (swapping to a different-distribution model would read every
    in-flight request as drift)."""
    import jax.numpy as jnp

    from scconsensus_tpu.ops.pca import pca_basis
    from scconsensus_tpu.ops.pooling import landmark_ward_linkage
    from scconsensus_tpu.serve.model import (
        MODEL_STAGE,
        _assemble,
        freeze_model_arrays,
    )
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    data, labels, _ = _demo_training_set(seed)
    panel = np.arange(_GENES, dtype=np.int64)  # demo panel = all genes
    cells = np.asarray(data.T, np.float32)
    mean, comps = pca_basis(jnp.asarray(cells), 8)
    mean = np.asarray(mean, np.float32)
    comps = np.asarray(comps, np.float32)
    emb = (cells - mean) @ comps.T
    tree, assign, cents, _info = landmark_ward_linkage(
        emb, n_landmarks=_LANDMARKS,
        seed=seed if landmark_seed is None else int(landmark_seed),
    )
    arrays, meta = freeze_model_arrays(
        panel, mean, comps, emb, cents, assign, labels, tree,
        n_genes=_GENES, drift_margin=1.5,
        meta_extra={"deep_split": 2, "config_fp": "serve-soak-demo"},
    )
    ArtifactStore(model_dir).save(MODEL_STAGE, arrays, meta)
    return _assemble(arrays, meta)


def make_requests(n_requests: int, cells_per: int, seed: int,
                  n_ood: int = 0) -> List[np.ndarray]:
    """Replayable request set: in-distribution cells drawn around the
    training centers; the last ``n_ood`` requests are drawn far outside
    (the drift-quarantine targets)."""
    rng = np.random.default_rng(seed + 1)
    _, _, centers = _demo_training_set(seed)
    out: List[np.ndarray] = []
    for i in range(n_requests):
        if i >= n_requests - n_ood:
            x = rng.normal(40.0, 1.0, size=(cells_per, _GENES))
        else:
            c = centers[rng.integers(0, _CLUSTERS)]
            x = c + rng.normal(0.0, 0.6, size=(cells_per, _GENES))
        out.append(np.asarray(x, np.float32))
    return out


def run_soak(model_dir: str, n_requests: int = 24, cells_per: int = 16,
             seed: int = 7, n_ood: int = 0, fresh: bool = False,
             deadline_s: Optional[float] = None,
             window_s: Optional[float] = None,
             concurrency: int = 4) -> Dict[str, Any]:
    """Drive the request set through a server; returns the summary dict
    (see module doc). Raises ModelLoadError through — the caller decides
    whether a refusal was the expected outcome."""
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.serve.driver import ConsensusServer, ServeConfig
    from scconsensus_tpu.serve.errors import ServeError
    from scconsensus_tpu.serve.model import MODEL_STAGE, load_consensus_model
    from scconsensus_tpu.utils.artifacts import ArtifactStore

    model_built = False
    if fresh or not ArtifactStore(model_dir).has(MODEL_STAGE):
        build_demo_model(model_dir, seed=seed)
        model_built = True
    model = load_consensus_model(model_dir)

    requests = make_requests(n_requests, cells_per, seed, n_ood=n_ood)
    cfg = ServeConfig(
        default_deadline_s=deadline_s,
        batch_window_s=window_s,
    )
    outcomes: List[Optional[Dict[str, Any]]] = [None] * len(requests)
    label_blobs: List[bytes] = [b""] * len(requests)

    server = ConsensusServer(model, cfg)
    with server:
        lock = threading.Lock()
        next_i = [0]

        def _pump():
            while True:
                with lock:
                    if next_i[0] >= len(requests):
                        return
                    i = next_i[0]
                    next_i[0] += 1
                try:
                    resp = server.classify(requests[i], timeout=60.0)
                    outcomes[i] = {
                        "i": i, "outcome": resp.outcome,
                        "degraded": resp.degraded,
                        "quarantined": resp.quarantined,
                    }
                    if resp.labels is not None:
                        label_blobs[i] = np.ascontiguousarray(
                            resp.labels
                        ).tobytes()
                except ServeError as e:
                    outcomes[i] = {
                        "i": i, "outcome": type(e).__name__,
                        "error": str(e)[:200],
                    }
                except TimeoutError as e:
                    outcomes[i] = {"i": i, "outcome": "TimeoutError",
                                   "error": str(e)[:200]}

        threads = [threading.Thread(target=_pump, daemon=True)
                   for _ in range(max(1, concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        section = server.serving_section()

    rec = build_run_record(
        metric="serve soak p99 latency",
        value=(section.get("latency_ms") or {}).get("p99"),
        unit="ms",
        extra={"config": "serve-soak", "platform": "cpu"},
        serving=section,
    )
    validate_run_record(rec)

    resolved = [o for o in outcomes if o is not None]
    h = hashlib.sha256()
    for blob in label_blobs:
        h.update(blob)
    summary = {
        "ok": len(resolved) == len(requests),
        "requests": len(requests),
        "resolved": len(resolved),
        "model_built": model_built,
        "model_fp": model.fingerprint(),
        "labels_sha": h.hexdigest(),
        "outcome_counts": _tally(resolved),
        "outcomes": resolved,
        "record": rec,
    }
    return summary


def _tally(outcomes: List[Dict[str, Any]]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for o in outcomes:
        out[o["outcome"]] = out.get(o["outcome"], 0) + 1
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description="serve soak worker")
    ap.add_argument("--dir", required=True, help="model directory")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--cells", type=int, default=16)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--ood-requests", type=int, default=0,
                    help="trailing requests drawn out-of-distribution "
                         "(drift-quarantine targets)")
    ap.add_argument("--summary", default=None,
                    help="write the summary JSON here (default: "
                         "<dir>/SOAK_SUMMARY.json)")
    ap.add_argument("--fresh", action="store_true",
                    help="rebuild the demo model even if one exists")
    ap.add_argument("--expect-refusal", action="store_true",
                    help="expect a typed ModelLoadError (corrupt-model "
                         "plans); exit 0 on refusal, 3 on a load that "
                         "should not have succeeded")
    ap.add_argument("--deadline", type=float, default=None)
    ap.add_argument("--window", type=float, default=None)
    args = ap.parse_args(argv)

    from scconsensus_tpu.serve.errors import ModelLoadError

    summary_path = args.summary or os.path.join(args.dir,
                                                "SOAK_SUMMARY.json")
    os.makedirs(args.dir, exist_ok=True)
    try:
        summary = run_soak(
            args.dir, n_requests=args.requests, cells_per=args.cells,
            seed=args.seed, n_ood=args.ood_requests, fresh=args.fresh,
            deadline_s=args.deadline, window_s=args.window,
        )
    except ModelLoadError as e:
        refusal = {
            "ok": args.expect_refusal,
            "refused": True,
            "quarantined": bool(getattr(e, "quarantined", False)),
            "error": str(e)[:300],
        }
        with open(summary_path, "w") as f:
            json.dump(refusal, f, indent=1)
        print(json.dumps({k: v for k, v in refusal.items()
                          if k != "error"}))
        return 0 if args.expect_refusal else 1
    if args.expect_refusal:
        print(json.dumps({"ok": False,
                          "error": "model loaded but a refusal was "
                                   "expected"}))
        return 3
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "requests": summary["requests"],
        "resolved": summary["resolved"],
        "outcome_counts": summary["outcome_counts"],
        "labels_sha": summary["labels_sha"][:16],
        "model_built": summary["model_built"],
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
