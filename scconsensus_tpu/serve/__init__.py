"""Online serving: label new cells against a frozen consensus model.

ROADMAP item 4 realized as a guarded path: a **frozen consensus-model
artifact** (``serve.model`` — DE-gene panel, PCA basis, landmark
centroids + dendrogram, drift calibration; persisted and verified
through the ArtifactStore's sha256/quarantine machinery), a jitted
one-device-call ``classify``, and an **async micro-batching driver**
(``serve.driver``) whose robustness is the point: bounded admission with
typed backpressure, per-request deadlines, a circuit breaker over the
device path with an explicitly-flagged degraded host fallback, and drift
quarantine routing out-of-distribution batches to a ledger instead of
confidently mislabeling them. ``serve.metrics`` validates the run
record's ``serving`` section — every submitted request must be accounted
for by exactly one outcome.

Import discipline: the package root, ``errors``, and ``metrics`` are
jax-free (chaos harness + validators load them); ``model``/``driver``
pull jax in lazily on first classify.
"""

from scconsensus_tpu.serve.errors import (  # noqa: F401
    DeadlineExceeded,
    ModelLoadError,
    QueueFull,
    RequestFailed,
    RequestInvalid,
    ServeError,
    ServerClosed,
)
from scconsensus_tpu.serve.metrics import (  # noqa: F401
    OUTCOMES,
    ServingStats,
    validate_serving,
)

__all__ = [
    "ServeError",
    "ModelLoadError",
    "RequestInvalid",
    "QueueFull",
    "DeadlineExceeded",
    "ServerClosed",
    "RequestFailed",
    "OUTCOMES",
    "ServingStats",
    "validate_serving",
]


def __getattr__(name):
    # Lazy: ConsensusServer/ConsensusModel pull in numpy+jax paths.
    if name in ("ConsensusServer", "ServeConfig", "ServeResponse",
                "CircuitBreaker"):
        from scconsensus_tpu.serve import driver

        return getattr(driver, name)
    if name in ("ConsensusModel", "export_consensus_model",
                "load_consensus_model"):
        from scconsensus_tpu.serve import model

        return getattr(model, name)
    if name in ("ReplicaPool", "WireFront", "run_reconsensus"):
        # the serving fleet (round 16): wire front, replica hot-swap,
        # drift-to-reconsensus loop
        from scconsensus_tpu.serve import fleet

        return getattr(fleet, name)
    raise AttributeError(name)
