"""The frozen consensus-model artifact + the one-device-call classifier.

A consensus model is everything ``classify(new_cells)`` needs to label a
cell against a finished consensus run WITHOUT re-running DE + tree
(ROADMAP item 4), persisted as ONE ArtifactStore stage so it rides the
existing atomic-write + sha256-checksum + quarantine machinery:

  * the DE-gene **panel** (the union the pipeline re-embedded on),
  * the **PCA basis** (column mean + components) that projects panel
    expression into the training embedding space (``ops.pca.pca_basis``),
  * the **landmark centroids + occupancy-weighted dendrogram** from
    ``ops/pooling`` — closing the ROADMAP item-1 follow-up: the landmark
    artifacts ARE the frozen model's assignment structure,
  * per-landmark **cluster labels** (occupancy-weighted majority vote),
  * a **drift calibration**: quantiles of the training cells' distance to
    their own landmark, from which the serving driver's quarantine gate
    derives its "this batch no longer fits the model" threshold.

Load goes through ``ArtifactStore.load`` — a corrupt artifact (failed
checksum, truncated zip) is QUARANTINED by the store and surfaces here
as a typed :class:`~scconsensus_tpu.serve.errors.ModelLoadError`; a
wrong-schema or shape-incoherent artifact is refused the same way. The
server never starts on a model it cannot prove intact.

``classify`` is one jitted device call: gather panel columns → center →
project → nearest landmark (``ops.distance._sq_dists_raw``) → label +
distance. ``classify_host`` is the numpy mirror the driver's degraded
mode serves from when the circuit breaker is open.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from scconsensus_tpu.serve.errors import ModelLoadError

__all__ = [
    "MODEL_STAGE",
    "MODEL_SCHEMA",
    "MODEL_VERSION",
    "ConsensusModel",
    "freeze_model_arrays",
    "export_consensus_model",
    "load_consensus_model",
]

MODEL_STAGE = "consensus_model"
MODEL_SCHEMA = "scc-consensus-model"
MODEL_VERSION = 1

# Calibration quantiles of the training nearest-landmark distance
# (q50/q90/q99/max); the drift threshold is q99 × margin.
_CALIB_QS = (0.50, 0.90, 0.99, 1.0)


@dataclasses.dataclass
class ConsensusModel:
    """In-memory frozen model. Arrays are host numpy; ``device_buffers``
    uploads once and memoizes so every batch classify is one dispatch."""

    panel_idx: np.ndarray          # (F,) int64 gene rows of the DE union
    pca_mean: np.ndarray           # (F,) float32
    pca_components: np.ndarray     # (n_pcs, F) float32
    centroids: np.ndarray          # (k, n_pcs) float32 landmark centroids
    centroid_labels: np.ndarray    # (k,) int64 cluster label per landmark
    centroid_counts: np.ndarray    # (k,) int64 training occupancy
    tree_merge: np.ndarray         # landmark dendrogram (ops.linkage shape)
    tree_height: np.ndarray
    tree_order: np.ndarray
    calib_q: np.ndarray            # (len(_CALIB_QS),) distance quantiles
    drift_threshold: float         # distance beyond which a cell is foreign
    meta: Dict[str, Any]
    _dev: Optional[tuple] = dataclasses.field(default=None, repr=False)
    _fp: Optional[str] = dataclasses.field(default=None, repr=False)

    # -- derived -----------------------------------------------------------
    @property
    def n_genes(self) -> int:
        return int(self.meta["n_genes"])

    @property
    def n_pcs(self) -> int:
        return int(self.pca_components.shape[0])

    @property
    def k(self) -> int:
        return int(self.centroids.shape[0])

    def fingerprint(self) -> str:
        """Short content hash of the decision surface (panel + basis +
        centroids + labels): two servers answering from the same
        fingerprint answer identically — the kill-and-restart durability
        test pins this. Memoized: the fleet stamps it on every response
        (the hot-swap purity check), and the arrays are frozen."""
        if self._fp is None:
            import hashlib

            h = hashlib.sha256()
            for a in (self.panel_idx, self.pca_mean, self.pca_components,
                      self.centroids, self.centroid_labels):
                h.update(np.ascontiguousarray(a).tobytes())
            self._fp = h.hexdigest()[:16]
        return self._fp

    # -- classify ----------------------------------------------------------
    def _gather_panel(self, cells: np.ndarray) -> np.ndarray:
        x = np.asarray(cells, np.float32)
        if x.ndim != 2 or x.shape[1] != self.n_genes:
            raise ValueError(
                f"cells must be (n, {self.n_genes}) genes-length rows, "
                f"got {x.shape}"
            )
        return x[:, self.panel_idx]

    def device_buffers(self) -> tuple:
        if self._dev is None:
            import jax.numpy as jnp

            self._dev = (
                jnp.asarray(self.pca_mean, jnp.float32),
                jnp.asarray(self.pca_components, jnp.float32),
                jnp.asarray(self.centroids, jnp.float32),
                jnp.asarray(self.centroid_labels, jnp.int32),
            )
        return self._dev

    def classify(self, cells: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Project + assign ``cells`` (n, G) in ONE device call. Returns
        ``(labels (n,) int64, dist (n,) float64)`` where ``dist`` is the
        euclidean distance to the winning landmark (the drift gate's
        signal)."""
        import jax

        xp = self._gather_panel(cells)
        mean, comps, cents, clab = self.device_buffers()
        lab, dist = _classify_kernel(
            jax.numpy.asarray(xp), mean, comps, cents, clab
        )
        lab, dist = jax.device_get((lab, dist))
        return np.asarray(lab, np.int64), np.asarray(dist, np.float64)

    def classify_host(self, cells: np.ndarray
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Numpy mirror of :meth:`classify` — the degraded-mode fallback
        when the device path is broken. Same math, same labels on
        well-separated data (ties may break differently at float32 vs
        float64 margins; degraded responses are flagged, never silent)."""
        xp = self._gather_panel(cells).astype(np.float64)
        proj = (xp - self.pca_mean.astype(np.float64)) @ \
            self.pca_components.astype(np.float64).T
        c = self.centroids.astype(np.float64)
        d2 = (
            np.sum(proj * proj, axis=1, keepdims=True)
            - 2.0 * proj @ c.T
            + np.sum(c * c, axis=1)[None, :]
        )
        j = np.argmin(d2, axis=1)
        dist = np.sqrt(np.maximum(d2[np.arange(j.size), j], 0.0))
        return self.centroid_labels[j].astype(np.int64), dist

    def drift_fraction(self, dist: np.ndarray) -> float:
        """Share of a batch past the calibrated foreign-cell threshold."""
        d = np.asarray(dist, np.float64)
        if d.size == 0:
            return 0.0
        return float((d > self.drift_threshold).mean())


_KERNEL = None  # built on first use so the bare module import stays jax-free


def _classify_kernel(x, mean, comps, cents, cent_labels):
    global _KERNEL
    if _KERNEL is None:
        import jax
        import jax.numpy as jnp

        from scconsensus_tpu.ops.distance import _sq_dists_raw

        @jax.jit
        def _run(x, mean, comps, cents, cent_labels):
            proj = (x - mean[None, :]) @ comps.T
            d2 = _sq_dists_raw(proj, cents)
            j = jnp.argmin(d2, axis=1)
            d = jnp.sqrt(jnp.maximum(
                jnp.take_along_axis(d2, j[:, None], axis=1)[:, 0], 0.0
            ))
            return cent_labels[j], d

        _KERNEL = _run
    return _KERNEL(x, mean, comps, cents, cent_labels)


# --------------------------------------------------------------------------
# export
# --------------------------------------------------------------------------

def freeze_model_arrays(
    panel_idx: np.ndarray,
    pca_mean: np.ndarray,
    pca_components: np.ndarray,
    emb: np.ndarray,
    centroids: np.ndarray,
    assign: np.ndarray,
    cell_labels: np.ndarray,
    tree,
    n_genes: int,
    drift_margin: float,
    meta_extra: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """The ONE arrays+meta assembly behind every consensus-model writer
    (``export_consensus_model`` and the soak's demo builder): majority
    landmark labels, occupancy counts, drift calibration, schema stamp.
    Shared so the artifact schema cannot drift between the real export
    path and the chaos worker's model."""
    from scconsensus_tpu.ops.pooling import centroid_majority_labels

    k = int(centroids.shape[0])
    counts = np.bincount(assign, minlength=k).astype(np.int64)
    cent_labels = centroid_majority_labels(assign, cell_labels, k)
    d = np.linalg.norm(emb.astype(np.float64) - centroids[assign], axis=1)
    calib_q = (np.quantile(d, _CALIB_QS) if d.size
               else np.zeros(len(_CALIB_QS)))
    drift_threshold = float(calib_q[_CALIB_QS.index(0.99)] * drift_margin)
    meta: Dict[str, Any] = {
        "schema": MODEL_SCHEMA,
        "version": MODEL_VERSION,
        "created_unix": round(time.time(), 3),
        "n_cells": int(emb.shape[0]),
        "n_genes": int(n_genes),
        "n_pcs": int(pca_components.shape[0]),
        "k": k,
        "drift_margin": float(drift_margin),
        "drift_threshold": drift_threshold,
        "label_values": sorted(int(v) for v in np.unique(cent_labels)),
    }
    meta.update(meta_extra or {})
    arrays = {
        "panel_idx": np.asarray(panel_idx, np.int64),
        "pca_mean": np.asarray(pca_mean, np.float32),
        "pca_components": np.asarray(pca_components, np.float32),
        "centroids": np.asarray(centroids, np.float32),
        "centroid_labels": cent_labels,
        "centroid_counts": counts,
        "tree_merge": np.asarray(tree.merge),
        "tree_height": np.asarray(tree.height),
        "tree_order": np.asarray(tree.order),
        "calib_q": np.asarray(calib_q, np.float64),
    }
    return arrays, meta


def export_consensus_model(
    data,
    result,
    config,
    model_dir: str,
    deep_split: Optional[int] = None,
    n_landmarks: Optional[int] = None,
    drift_margin: Optional[float] = None,
    seed: Optional[int] = None,
) -> ConsensusModel:
    """Freeze a finished refinement into a servable consensus model.

    ``data`` is the training (G, N) matrix the pipeline ran on;
    ``result`` its :class:`~scconsensus_tpu.models.pipeline
    .ReclusterResult``; ``deep_split`` picks which cut's labels the model
    serves (default: the deepest configured). The PCA basis is re-derived
    with ``ops.pca.pca_basis`` (same algorithm + seed as the pipeline's
    ``pca_scores``) and the landmark structure with
    ``ops.pooling.landmark_ward_linkage`` over the basis-consistent
    embedding, so model-internal geometry is exactly self-consistent:
    a training cell replayed through ``classify`` lands on the landmark
    it was calibrated against.
    """
    import jax.numpy as jnp

    from scconsensus_tpu.config import env_flag
    from scconsensus_tpu.io.sparsemat import rows_dense
    from scconsensus_tpu.ops.pca import pca_basis
    from scconsensus_tpu.ops.pooling import landmark_ward_linkage
    from scconsensus_tpu.utils.artifacts import (
        ArtifactStore,
        config_fingerprint,
    )

    ds = int(deep_split if deep_split is not None
             else config.deep_split_values[-1])
    key = f"deepsplit: {ds}"
    if key not in result.dynamic_labels:
        raise ValueError(
            f"result has no cut for deep_split={ds} "
            f"(available: {sorted(result.dynamic_labels)})"
        )
    labels = np.asarray(result.dynamic_labels[key], np.int64)
    panel = np.asarray(result.de_gene_union_idx, np.int64)
    n_pcs = int(result.embedding.shape[1])
    margin = float(drift_margin if drift_margin is not None
                   else env_flag("SCC_SERVE_DRIFT_MARGIN"))

    cells = rows_dense(data, panel).T            # (N, F), host or device
    mean, comps = pca_basis(jnp.asarray(cells, jnp.float32), n_pcs)
    mean = np.asarray(mean, np.float32)
    comps = np.asarray(comps, np.float32)
    emb = (np.asarray(cells, np.float32) - mean) @ comps.T

    tree, assign, cents, info = landmark_ward_linkage(
        emb,
        n_landmarks=n_landmarks,
        seed=int(seed if seed is not None else config.random_seed),
    )
    arrays, meta = freeze_model_arrays(
        panel, mean, comps, emb, cents, assign, labels, tree,
        n_genes=int(data.shape[0]), drift_margin=margin,
        meta_extra={
            "deep_split": ds,
            "landmark_info": {kk: vv for kk, vv in info.items()
                              if isinstance(vv, (int, float, str))},
            "config_fp": config_fingerprint(json.loads(config.to_json())),
        },
    )
    ArtifactStore(model_dir).save(MODEL_STAGE, arrays, meta)
    return _assemble(arrays, meta)


# --------------------------------------------------------------------------
# load (the sha256/quarantine path + schema refusal)
# --------------------------------------------------------------------------

_REQUIRED_ARRAYS = (
    "panel_idx", "pca_mean", "pca_components", "centroids",
    "centroid_labels", "centroid_counts", "tree_merge", "tree_height",
    "tree_order", "calib_q",
)


def _assemble(arrays: Dict[str, np.ndarray],
              meta: Dict[str, Any]) -> ConsensusModel:
    return ConsensusModel(
        panel_idx=np.asarray(arrays["panel_idx"], np.int64),
        pca_mean=np.asarray(arrays["pca_mean"], np.float32),
        pca_components=np.asarray(arrays["pca_components"], np.float32),
        centroids=np.asarray(arrays["centroids"], np.float32),
        centroid_labels=np.asarray(arrays["centroid_labels"], np.int64),
        centroid_counts=np.asarray(arrays["centroid_counts"], np.int64),
        tree_merge=arrays["tree_merge"],
        tree_height=arrays["tree_height"],
        tree_order=arrays["tree_order"],
        calib_q=np.asarray(arrays["calib_q"], np.float64),
        drift_threshold=float(meta["drift_threshold"]),
        meta={k: v for k, v in meta.items() if k != "_integrity"},
    )


def load_consensus_model(model_dir: str,
                         readonly: bool = False) -> ConsensusModel:
    """Load a frozen consensus model, or refuse with a typed error.

    Refusal paths (all :class:`ModelLoadError`, never a served model):
    missing artifact; failed sha256 / unparseable npz (the store has
    QUARANTINED the files — ``quarantined=True``); wrong schema name or
    version; incoherent shapes. ``robust.faults`` site ``serve_load``
    fires here, so chaos plans can break the load the same way they
    break pipeline stages."""
    from scconsensus_tpu.robust import faults
    from scconsensus_tpu.utils.artifacts import ArtifactCorrupt, ArtifactStore

    faults.fault_point("serve_load")
    store = ArtifactStore(model_dir, readonly=readonly)
    if not store.has(MODEL_STAGE):
        raise ModelLoadError(
            f"no consensus model artifact at {model_dir!r} "
            f"(expected {MODEL_STAGE}.npz)"
        )
    try:
        arrays, meta = store.load(MODEL_STAGE)
    except ArtifactCorrupt as e:
        if readonly:
            # the readonly store refuses WITHOUT renaming: say so, and
            # don't claim a quarantine that never happened
            raise ModelLoadError(
                f"consensus model at {model_dir!r} failed verification; "
                f"readonly store — files left in place, load refused: "
                f"{e}", quarantined=False,
            ) from e
        raise ModelLoadError(
            f"consensus model at {model_dir!r} failed verification and "
            f"was quarantined: {e}", quarantined=True,
        ) from e
    if meta.get("schema") != MODEL_SCHEMA:
        raise ModelLoadError(
            f"artifact at {model_dir!r} is not a consensus model "
            f"(schema={meta.get('schema')!r}, want {MODEL_SCHEMA!r})"
        )
    if meta.get("version") != MODEL_VERSION:
        raise ModelLoadError(
            f"consensus model version {meta.get('version')!r} unsupported "
            f"(this build knows version {MODEL_VERSION})"
        )
    missing = [a for a in _REQUIRED_ARRAYS if a not in arrays]
    if missing:
        raise ModelLoadError(
            f"consensus model at {model_dir!r} missing arrays: {missing}"
        )
    model = _assemble(arrays, meta)
    f = model.pca_components.shape[1]
    if (model.panel_idx.shape[0] != f
            or model.pca_mean.shape[0] != f
            or model.centroids.shape[1] != model.pca_components.shape[0]
            or model.centroid_labels.shape[0] != model.centroids.shape[0]):
        raise ModelLoadError(
            f"consensus model at {model_dir!r} has incoherent shapes "
            f"(panel {model.panel_idx.shape}, mean {model.pca_mean.shape}, "
            f"components {model.pca_components.shape}, "
            f"centroids {model.centroids.shape})"
        )
    return model
