"""SLO objects, mergeable latency histograms, and OpenMetrics exposition.

The telemetry plane's math layer (stdlib-only by the same contract as
``serve.metrics`` — validators and the jax-free orchestrator load it):

* **Fixed-bucket latency histograms** — every replica observes into the
  SAME bucket boundaries (:data:`LATENCY_BUCKETS_MS`), so fleet-level
  histograms are exact per-bucket SUMS of replica histograms. Averaging
  quantiles across replicas is statistically meaningless; merging fixed
  buckets is not — that property is the whole reason the boundaries are
  frozen here instead of adapting per replica.
* **SLO objects** — availability (the non-5xx share of non-4xx-outcome
  requests: client-fault refusals are excluded from the denominator,
  server-fault outcomes burn the budget) and tail latency (p99 vs a
  target), with **multi-window burn rates** computed from the SAME
  cumulative counters the accounting contract validates: a burn rate of
  1.0 means the error budget is being consumed exactly at the rate that
  exhausts it at the window's end; the page-worthy threshold rides the
  section itself (``objectives.burn_limit``) so the perf gate never
  needs this process's env.
* **OpenMetrics text exposition** — :func:`render_openmetrics` turns
  telemetry snapshots into the OpenMetrics text format (``# TYPE``
  headers, ``_bucket{le=}``/``_count``/``_sum`` histogram series, the
  mandatory ``# EOF``), and :func:`parse_openmetrics` reads it back —
  the parity lint and the merge tests round-trip through the same
  parser a scraper would use.

The validated run-record section (:func:`validate_slo`): a record whose
availability counts don't sum, whose burn rates disagree with their own
error ratios, or whose histogram bucket counts don't sum to their count
is rejected — the SLO claim must carry its own arithmetic.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from scconsensus_tpu.config import env_flag

__all__ = [
    "LATENCY_BUCKETS_MS",
    "OUTCOME_STATUS",
    "OUTCOME_CLASS",
    "LatencyHistogram",
    "SLOTracker",
    "classify_counts",
    "resolve_objectives",
    "build_slo_section",
    "validate_slo",
    "render_openmetrics",
    "parse_openmetrics",
    "merge_histogram_dicts",
    "p99_ms",
]

# THE fixed bucket upper bounds (ms). Frozen: replica histograms merge
# by per-bucket addition ONLY while every emitter shares these edges.
# Changing them is a schema-level event (old and new records stop being
# mergeable), not a tuning knob.
LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
)

# One outcome, one status code — the r16 wire table, moved here so the
# wire front, the exposition, and the SLO classification share ONE copy
# (serve.fleet.wire re-exports it; the parity lint pins the coupling).
OUTCOME_STATUS: Dict[str, int] = {
    "ok": 200,
    "degraded": 200,
    "quarantined": 409,
    "rejected_queue": 429,
    "rejected_invalid": 422,
    "rejected_closed": 503,
    "deadline_exceeded": 504,
    "failed": 500,
}

# Availability classes derived from the status table: 2xx serve the
# request, 4xx are client-fault/consistency refusals (excluded from the
# SLO denominator), 5xx are server-fault (they burn the error budget).
OUTCOME_CLASS: Dict[str, str] = {
    o: ("good" if s < 400 else "client" if s < 500 else "bad")
    for o, s in OUTCOME_STATUS.items()
}


def classify_counts(counts: Dict[str, int]) -> Dict[str, int]:
    """Fold per-outcome counters into availability counts:
    ``{good, bad, client, total}`` where total = good + bad (the SLO
    denominator excludes client-fault refusals)."""
    good = bad = client = 0
    for o, n in counts.items():
        cls = OUTCOME_CLASS.get(o)
        if cls == "good":
            good += int(n)
        elif cls == "bad":
            bad += int(n)
        elif cls == "client":
            client += int(n)
    return {"good": good, "bad": bad, "client": client,
            "total": good + bad}


class LatencyHistogram:
    """Fixed-bucket latency histogram (counts per LATENCY_BUCKETS_MS
    bucket + one +Inf overflow bucket, running sum and count). NOT
    thread-safe — the owner's lock (ServingStats/WireStats) serializes
    observers, exactly like the existing counters."""

    __slots__ = ("counts", "sum_ms", "n")

    def __init__(self, counts: Optional[Sequence[int]] = None,
                 sum_ms: float = 0.0, n: int = 0):
        self.counts: List[int] = (list(int(c) for c in counts)
                                  if counts is not None
                                  else [0] * (len(LATENCY_BUCKETS_MS) + 1))
        if len(self.counts) != len(LATENCY_BUCKETS_MS) + 1:
            raise ValueError(
                f"histogram needs {len(LATENCY_BUCKETS_MS) + 1} buckets, "
                f"got {len(self.counts)}"
            )
        self.sum_ms = float(sum_ms)
        self.n = int(n)

    def observe(self, ms: float) -> None:
        ms = max(float(ms), 0.0)
        i = 0
        for i, le in enumerate(LATENCY_BUCKETS_MS):  # noqa: B007
            if ms <= le:
                break
        else:
            i = len(LATENCY_BUCKETS_MS)
        self.counts[i] += 1
        self.sum_ms += ms
        self.n += 1

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        for i, c in enumerate(other.counts):
            self.counts[i] += int(c)
        self.sum_ms += other.sum_ms
        self.n += other.n
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {"buckets": list(self.counts),
                "sum_ms": round(self.sum_ms, 4), "count": self.n}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LatencyHistogram":
        return cls(counts=d.get("buckets") or [],
                   sum_ms=float(d.get("sum_ms", 0.0)),
                   n=int(d.get("count", 0)))


def merge_histogram_dicts(dicts: Sequence[Dict[str, Any]]
                          ) -> Dict[str, Any]:
    """Merge serialized histograms (per-bucket sums) — the fleet-level
    series is exactly this over its replicas'."""
    out = LatencyHistogram()
    for d in dicts:
        out.merge(LatencyHistogram.from_dict(d))
    return out.to_dict()


def p99_ms(samples: Sequence[float]) -> Optional[float]:
    """The p99 of raw latency samples (ms), or None when empty — the
    ONE formula every slo-section emitter shares (pool, wire, driver),
    so the gated tail can never be computed three slightly different
    ways."""
    if not samples:
        return None
    s = sorted(float(v) for v in samples)
    return s[min(int(0.99 * len(s)), len(s) - 1)]


class SLOTracker:
    """Time series of cumulative (bad, total) availability counts, ring-
    bounded, for multi-window burn rates computed from the same counters
    the accounting contract validates. ``note`` is called under the
    owner's lock on every outcome; it appends at most one snapshot per
    ``snap_every_s`` so a request storm cannot grow the ring unboundedly
    faster than time passes."""

    _RING = 4096

    def __init__(self, windows_s: Optional[Sequence[float]] = None):
        self.windows_s = tuple(float(w) for w in (
            windows_s if windows_s is not None else resolve_windows()
        ))
        if not self.windows_s:
            raise ValueError("SLO needs at least one burn window")
        # snapshot cadence: fine enough that the SHORTEST window holds
        # ≥16 points, bounded below so a test-scale 0.1 s window still
        # works and above so a 1 h window doesn't snapshot every ms
        self.snap_every_s = min(max(min(self.windows_s) / 16.0, 0.005),
                                5.0)
        self._snaps: List[Tuple[float, int, int]] = []  # (ts, bad, total)
        self._last_snap = 0.0

    def note(self, bad: int, total: int,
             now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else float(now)
        if now - self._last_snap < self.snap_every_s and self._snaps:
            return
        self._snaps.append((now, int(bad), int(total)))
        self._last_snap = now
        if len(self._snaps) > self._RING:
            del self._snaps[: len(self._snaps) - self._RING]

    def window_deltas(self, bad: int, total: int,
                      now: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
        """Per-window (bad_delta, total_delta) vs the oldest snapshot
        inside each trailing window (or the process origin when the
        window is longer than the series — a young process's window IS
        its lifetime)."""
        now = time.monotonic() if now is None else float(now)
        out: List[Dict[str, Any]] = []
        for w in self.windows_s:
            cutoff = now - w
            base_bad = base_total = 0
            for ts, b, t in self._snaps:
                if ts >= cutoff:
                    break
                base_bad, base_total = b, t
            out.append({
                "window_s": w,
                "bad": max(int(bad) - base_bad, 0),
                "total": max(int(total) - base_total, 0),
            })
        return out


def resolve_windows() -> Tuple[float, ...]:
    """Burn windows from SCC_SLO_WINDOWS_S (comma-separated seconds)."""
    raw = str(env_flag("SCC_SLO_WINDOWS_S") or "").strip()
    ws: List[float] = []
    for part in raw.split(","):
        part = part.strip()
        if part:
            ws.append(float(part))
    return tuple(ws) or (300.0, 3600.0)


def resolve_objectives() -> Dict[str, Any]:
    """The process's SLO objectives from the env-flag registry — stamped
    onto the section so the record is self-describing (the gate reads
    the record's own objectives, never this process's env)."""
    return {
        "availability": float(env_flag("SCC_SLO_AVAIL_TARGET")),
        "p99_ms": float(env_flag("SCC_SLO_P99_MS")),
        "windows_s": [float(w) for w in resolve_windows()],
        "burn_limit": float(env_flag("SCC_SLO_BURN_LIMIT")),
    }


def build_slo_section(
    counts: Dict[str, int],
    p99_ms: Optional[float],
    window_deltas: List[Dict[str, Any]],
    latency_hist: Optional[Dict[str, Dict[str, Any]]] = None,
    stage_hist: Optional[Dict[str, Dict[str, Any]]] = None,
    objectives: Optional[Dict[str, Any]] = None,
    obs_overhead: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble the validated ``slo`` run-record section from per-outcome
    counters (+ the tracker's window deltas + serialized histograms)."""
    obj = dict(objectives or resolve_objectives())
    avail = classify_counts(counts)
    ratio = (avail["good"] / avail["total"]) if avail["total"] else 1.0
    budget = max(1.0 - float(obj["availability"]), 1e-9)
    burns: List[Dict[str, Any]] = []
    for wd in window_deltas:
        err = (wd["bad"] / wd["total"]) if wd["total"] else 0.0
        burns.append({
            "window_s": float(wd["window_s"]),
            "bad": int(wd["bad"]),
            "total": int(wd["total"]),
            "error_ratio": round(err, 6),
            "burn": round(err / budget, 4),
        })
    worst = max((b["burn"] for b in burns), default=0.0)
    sec: Dict[str, Any] = {
        "objectives": obj,
        "availability": {
            "good": avail["good"], "bad": avail["bad"],
            "client_excluded": avail["client"], "total": avail["total"],
            "ratio": round(ratio, 6),
        },
        "latency": {
            "p99_ms": (round(float(p99_ms), 4)
                       if p99_ms is not None else None),
            "target_ms": float(obj["p99_ms"]),
            "met": (p99_ms is None
                    or float(p99_ms) <= float(obj["p99_ms"])),
        },
        "burn_rates": burns,
        "worst_burn": round(worst, 4),
        "bucket_bounds_ms": list(LATENCY_BUCKETS_MS),
    }
    if latency_hist:
        sec["latency_hist"] = latency_hist
    if stage_hist:
        sec["stage_hist"] = stage_hist
    if obs_overhead:
        sec["obs_overhead"] = obs_overhead
    return sec


# --------------------------------------------------------------------------
# schema validation (obs.export.validate_run_record dispatches here)
# --------------------------------------------------------------------------

def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"slo section: {msg}")


def _validate_hist(h: Dict[str, Any], where: str) -> None:
    _require(isinstance(h, dict), f"{where} must be an object")
    buckets = h.get("buckets")
    _require(isinstance(buckets, list)
             and len(buckets) == len(LATENCY_BUCKETS_MS) + 1,
             f"{where}.buckets must list "
             f"{len(LATENCY_BUCKETS_MS) + 1} counts "
             f"(the frozen bucket grid + overflow)")
    _require(all(isinstance(c, int) and c >= 0 for c in buckets),
             f"{where}.buckets must be ints >= 0")
    n = h.get("count")
    _require(isinstance(n, int) and n >= 0,
             f"{where}.count must be an int >= 0")
    _require(sum(buckets) == n,
             f"{where}: bucket counts sum to {sum(buckets)} but count "
             f"claims {n} — a histogram must account for every "
             f"observation")
    s = h.get("sum_ms")
    _require(isinstance(s, (int, float)) and s >= 0,
             f"{where}.sum_ms must be a number >= 0")


def validate_slo(slo: Dict[str, Any]) -> None:
    """Structural validation of a record's ``slo`` section. Load-bearing
    rules: availability counts must sum (good + bad == total, the same
    no-lost-request contract one abstraction up), every burn rate must
    equal its own window's error ratio over the declared budget, the
    declared worst_burn must BE the worst, and histogram bucket counts
    must sum to their count — an SLO claim that contradicts its own
    arithmetic is rejected."""
    _require(isinstance(slo, dict), "must be an object")
    obj = slo.get("objectives")
    _require(isinstance(obj, dict), "objectives must be an object")
    tgt = obj.get("availability")
    _require(isinstance(tgt, (int, float)) and 0.0 < tgt <= 1.0,
             "objectives.availability must be in (0, 1]")
    p99t = obj.get("p99_ms")
    _require(isinstance(p99t, (int, float)) and p99t > 0,
             "objectives.p99_ms must be a number > 0")
    ws = obj.get("windows_s")
    _require(isinstance(ws, list) and ws
             and all(isinstance(w, (int, float)) and w > 0 for w in ws),
             "objectives.windows_s must be a non-empty list of "
             "positive seconds")
    lim = obj.get("burn_limit")
    _require(isinstance(lim, (int, float)) and lim > 0,
             "objectives.burn_limit must be a number > 0")
    av = slo.get("availability")
    _require(isinstance(av, dict), "availability must be an object")
    for k in ("good", "bad", "total"):
        v = av.get(k)
        _require(isinstance(v, int) and v >= 0,
                 f"availability.{k} must be an int >= 0")
    _require(
        av["good"] + av["bad"] == av["total"],
        f"availability accounting broken: good={av['good']} + "
        f"bad={av['bad']} != total={av['total']}",
    )
    ratio = av.get("ratio")
    _require(isinstance(ratio, (int, float)) and 0.0 <= ratio <= 1.0,
             "availability.ratio must be in [0, 1]")
    if av["total"]:
        want = av["good"] / av["total"]
        _require(abs(float(ratio) - want) < 1e-3,
                 f"availability.ratio={ratio} contradicts its own "
                 f"counts (good/total = {want:.6f})")
    lat = slo.get("latency")
    _require(isinstance(lat, dict), "latency must be an object")
    p99 = lat.get("p99_ms")
    if p99 is not None:
        _require(isinstance(p99, (int, float)) and p99 >= 0,
                 "latency.p99_ms must be a number >= 0 or null")
        _require(bool(lat.get("met")) == (float(p99)
                                          <= float(lat.get("target_ms",
                                                           p99t))),
                 "latency.met contradicts p99_ms vs target_ms")
    burns = slo.get("burn_rates")
    _require(isinstance(burns, list) and len(burns) == len(ws),
             "burn_rates must list exactly one entry per "
             "objectives.windows_s window")
    budget = max(1.0 - float(tgt), 1e-9)
    worst = 0.0
    for i, b in enumerate(burns):
        where = f"burn_rates[{i}]"
        _require(isinstance(b, dict), f"{where} must be an object")
        _require(b.get("window_s") == ws[i],
                 f"{where}.window_s must match objectives.windows_s[{i}]")
        for k in ("bad", "total"):
            v = b.get(k)
            _require(isinstance(v, int) and v >= 0,
                     f"{where}.{k} must be an int >= 0")
        err = b.get("error_ratio")
        _require(isinstance(err, (int, float)) and 0.0 <= err <= 1.0,
                 f"{where}.error_ratio must be in [0, 1]")
        if b["total"]:
            want = b["bad"] / b["total"]
            _require(abs(float(err) - want) < 1e-3,
                     f"{where}.error_ratio={err} contradicts its own "
                     f"counts (bad/total = {want:.6f})")
        burn = b.get("burn")
        _require(isinstance(burn, (int, float)) and burn >= 0,
                 f"{where}.burn must be a number >= 0")
        _require(abs(float(burn) - float(err) / budget) < 0.01
                 * max(1.0, float(burn)),
                 f"{where}.burn={burn} contradicts error_ratio/budget "
                 f"({float(err) / budget:.4f})")
        worst = max(worst, float(burn))
    wb = slo.get("worst_burn")
    _require(isinstance(wb, (int, float)) and abs(float(wb) - worst)
             < 0.01 * max(1.0, worst),
             f"worst_burn={wb} is not the worst burn rate ({worst})")
    bounds = slo.get("bucket_bounds_ms")
    _require(bounds == list(LATENCY_BUCKETS_MS),
             "bucket_bounds_ms must be the frozen grid "
             "(histograms are only mergeable on shared edges)")
    for fam in ("latency_hist", "stage_hist"):
        hists = slo.get(fam)
        if hists is None:
            continue
        _require(isinstance(hists, dict), f"{fam} must be an object")
        for key, h in hists.items():
            _validate_hist(h, f"{fam}[{key}]")
    oh = slo.get("obs_overhead")
    if oh is not None:
        _require(isinstance(oh, dict), "obs_overhead must be an object")
        for k in ("on_ms", "off_ms"):
            v = oh.get(k)
            _require(isinstance(v, (int, float)) and v >= 0,
                     f"obs_overhead.{k} must be a number >= 0")


# --------------------------------------------------------------------------
# OpenMetrics text exposition
# --------------------------------------------------------------------------

def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _labels(d: Dict[str, Any]) -> str:
    if not d:
        return ""
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(d.items()))
    return "{" + inner + "}"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class _Expo:
    """Accumulates families in declaration order, renders once."""

    def __init__(self):
        self._fams: List[Tuple[str, str, str, List[str]]] = []
        self._index: Dict[str, int] = {}

    def family(self, name: str, mtype: str, help_text: str) -> None:
        if name not in self._index:
            self._index[name] = len(self._fams)
            self._fams.append((name, mtype, help_text, []))

    def sample(self, name: str, labels: Dict[str, Any], value: float,
               suffix: str = "") -> None:
        self._fams[self._index[name]][3].append(
            f"{name}{suffix}{_labels(labels)} {_fmt(value)}"
        )

    def histogram(self, name: str, labels: Dict[str, Any],
                  h: Dict[str, Any]) -> None:
        cum = 0
        buckets = h.get("buckets") or []
        for i, le in enumerate(LATENCY_BUCKETS_MS):
            cum += int(buckets[i]) if i < len(buckets) else 0
            self.sample(name, {**labels, "le": _fmt(le)}, cum,
                        suffix="_bucket")
        cum += int(buckets[-1]) if len(buckets) == len(
            LATENCY_BUCKETS_MS) + 1 else 0
        self.sample(name, {**labels, "le": "+Inf"}, cum,
                    suffix="_bucket")
        self.sample(name, labels, int(h.get("count", 0)),
                    suffix="_count")
        self.sample(name, labels, float(h.get("sum_ms", 0.0)),
                    suffix="_sum")

    def render(self) -> str:
        out: List[str] = []
        for name, mtype, help_text, samples in self._fams:
            out.append(f"# TYPE {name} {mtype}")
            if help_text:
                out.append(f"# HELP {name} {help_text}")
            out.extend(samples)
        out.append("# EOF")
        return "\n".join(out) + "\n"


def render_openmetrics(snapshot: Dict[str, Any]) -> str:
    """OpenMetrics text from one telemetry snapshot:

    ``snapshot = {"scopes": [scope...], "wire": wire?, "slo": slo?}``
    where each scope is ``{"labels": {replica, model?}, "counts",
    "queue_depth", "queue_cap", "breaker", "trips", "latency_hist":
    {outcome: hist}, "stage_hist": {stage: hist}}`` — per-replica scopes
    plus the pre-merged ``replica="fleet"`` aggregate, all taken under
    ONE lock (the hot-swap torn-read fix lives in the snapshot, not
    here). Every OUTCOMES entry gets exactly one counter and one
    histogram series per scope — zero-valued series are emitted on
    purpose; the parity lint reads them."""
    from scconsensus_tpu.serve import metrics as serve_metrics

    e = _Expo()
    e.family("scc_requests_total", "counter",
             "typed request outcomes (one per OUTCOMES entry)")
    e.family("scc_request_latency_ms", "histogram",
             "request latency by outcome (frozen bucket grid)")
    e.family("scc_stage_latency_ms", "histogram",
             "per-stage latency (queue_wait, compute)")
    e.family("scc_queue_depth", "gauge", "admission queue depth")
    e.family("scc_queue_capacity", "gauge", "admission queue capacity")
    e.family("scc_breaker_state", "gauge",
             "circuit breaker (0=closed 1=half_open 2=open)")
    e.family("scc_breaker_trips_total", "counter", "breaker trips")
    for scope in snapshot.get("scopes") or []:
        labels = dict(scope.get("labels") or {})
        counts = scope.get("counts") or {}
        for o in serve_metrics.OUTCOMES:
            e.sample("scc_requests_total", {**labels, "outcome": o},
                     int(counts.get(o, 0)))
        lh = scope.get("latency_hist") or {}
        for o in serve_metrics.OUTCOMES:
            e.histogram("scc_request_latency_ms",
                        {**labels, "outcome": o},
                        lh.get(o) or LatencyHistogram().to_dict())
        for stage, h in sorted((scope.get("stage_hist") or {}).items()):
            e.histogram("scc_stage_latency_ms",
                        {**labels, "stage": stage}, h)
        if scope.get("queue_depth") is not None:
            e.sample("scc_queue_depth", labels,
                     int(scope["queue_depth"]))
            e.sample("scc_queue_capacity", labels,
                     int(scope.get("queue_cap", 0)))
        state = scope.get("breaker")
        if state is not None:
            e.sample("scc_breaker_state", labels,
                     serve_metrics.BREAKER_SEVERITY.get(state, 0))
            e.sample("scc_breaker_trips_total", labels,
                     int(scope.get("trips", 0)))
    wire = snapshot.get("wire")
    if wire is not None:
        e.family("scc_wire_requests_total", "counter",
                 "wire outcomes (one per outcome, with its one "
                 "status code)")
        counts = wire.get("counts") or {}
        for o, code in sorted(OUTCOME_STATUS.items()):
            e.sample("scc_wire_requests_total",
                     {"outcome": o, "code": str(code)},
                     int(counts.get(o, 0)))
    slo = snapshot.get("slo")
    if slo is not None:
        e.family("scc_slo_availability", "gauge",
                 "availability ratio (good / (good+bad))")
        e.family("scc_slo_burn_rate", "gauge",
                 "error-budget burn rate per trailing window")
        av = slo.get("availability") or {}
        if av.get("ratio") is not None:
            e.sample("scc_slo_availability", {}, float(av["ratio"]))
        for b in slo.get("burn_rates") or []:
            e.sample("scc_slo_burn_rate",
                     {"window_s": _fmt(b["window_s"])},
                     float(b["burn"]))
        oh = slo.get("obs_overhead")
        if oh and oh.get("ratio") is not None:
            e.family("scc_obs_overhead_ratio", "gauge",
                     "telemetry-plane overhead: mean latency with the "
                     "plane on / off")
            e.sample("scc_obs_overhead_ratio", {}, float(oh["ratio"]))
    return e.render()


def parse_openmetrics(text: str) -> Dict[str, Any]:
    """Minimal OpenMetrics reader for tests/tools: returns
    ``{"types": {family: type}, "samples": {(name, (sorted label
    pairs...)): value}}``. Raises ValueError on a malformed line or a
    missing ``# EOF`` — 'parseable' is an acceptance criterion, so the
    checker must be strict."""
    types: Dict[str, str] = {}
    samples: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    saw_eof = False
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if saw_eof:
            raise ValueError(f"line {lineno}: content after # EOF")
        if line == "# EOF":
            saw_eof = True
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            types[name] = mtype.strip()
            continue
        if line.startswith("#"):
            continue  # HELP/UNIT
        brace = line.find("{")
        if brace >= 0:
            name = line[:brace]
            end = line.rfind("}")
            if end < brace:
                raise ValueError(f"line {lineno}: unterminated labels")
            body, value_s = line[brace + 1:end], line[end + 1:].strip()
            labels: List[Tuple[str, str]] = []
            for part in _split_labels(body):
                k, _, v = part.partition("=")
                if not (v.startswith('"') and v.endswith('"')):
                    raise ValueError(
                        f"line {lineno}: unquoted label value in {part!r}"
                    )
                labels.append((k, _unescape(v[1:-1])))
        else:
            name, _, value_s = line.partition(" ")
            labels = []
        try:
            value = float(value_s.split()[0])
        except (ValueError, IndexError):
            raise ValueError(f"line {lineno}: bad sample value "
                             f"{value_s!r}")
        key = (name, tuple(sorted(labels)))
        if key in samples:
            raise ValueError(f"line {lineno}: duplicate series {key}")
        samples[key] = value
    if not saw_eof:
        raise ValueError("missing # EOF terminator")
    return {"types": types, "samples": samples}


def _unescape(v: str) -> str:
    """Left-to-right escape decoding (the inverse of _esc). Sequential
    str.replace passes decode r'\\n' (backslash-then-n in the source
    value) to a real newline; a single scan cannot."""
    out: List[str] = []
    i = 0
    while i < len(v):
        if v[i] == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(v[i])
        i += 1
    return "".join(out)


def _split_labels(body: str) -> List[str]:
    """Split a label body on commas outside quotes."""
    parts: List[str] = []
    cur: List[str] = []
    in_q = False
    prev = ""
    for ch in body:
        if ch == '"' and prev != "\\":
            in_q = not in_q
        if ch == "," and not in_q:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        prev = ch
    if cur:
        parts.append("".join(cur))
    return [p for p in (p.strip() for p in parts) if p]


# --------------------------------------------------------------------------
# obs-overhead gauge (the plane accounting for itself)
# --------------------------------------------------------------------------

_OVERHEAD_LOCK = threading.Lock()
_OVERHEAD: Optional[Dict[str, Any]] = None


def set_obs_overhead(gauge: Optional[Dict[str, Any]]) -> None:
    """Publish (or clear) the process's measured obs-overhead gauge —
    the soak's on/off measurement writes it; the exposition and the
    slo section read it."""
    global _OVERHEAD
    with _OVERHEAD_LOCK:
        _OVERHEAD = dict(gauge) if gauge else None


def obs_overhead() -> Optional[Dict[str, Any]]:
    with _OVERHEAD_LOCK:
        return dict(_OVERHEAD) if _OVERHEAD else None
