"""Sparse-matrix helpers behind the engine's never-densify contract.

Every function accepts a ``scipy.sparse`` matrix, a dense ndarray (the dense
path is a passthrough), or a **device-resident ``jax.Array``** — the third
form exists so a matrix generated or loaded straight into HBM never crosses
the host↔device link at all (the flagship matrix is ~1.5 GB; over the axon
tunnel that transfer alone dwarfs the compute). ``is_sparse`` / ``is_jax``
gate the few places where the code paths differ; jax branches keep the math
on device and pull only O(N) or scalar results.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

try:  # scipy is available in this environment; gate defensively anyway
    import scipy.sparse as _sp
except ImportError:  # pragma: no cover
    _sp = None

__all__ = [
    "is_sparse",
    "is_jax",
    "as_csr",
    "row_chunk_dense",
    "padded_row_chunk",
    "rows_dense",
    "expm1_sparse",
    "mean_expm1",
    "mean_value",
    "nodg",
    "csr_to_device",
    "csr_window_rows",
    "aggregates_from_sparse",
]


def is_sparse(x) -> bool:
    return _sp is not None and _sp.issparse(x)


def is_jax(x) -> bool:
    """True for a jax.Array (device-resident dense matrix). Checked without
    importing jax at module load: numpy-only consumers never pay for it."""
    mod = type(x).__module__
    if not (mod.startswith("jax") or mod.startswith("jaxlib")):
        return False
    import jax

    return isinstance(x, jax.Array)


def as_csr(x):
    """Canonicalize any scipy-sparse format to CSR (summing duplicate COO
    entries); dense input passes through. Entry points call this once so the
    helpers below may assume a sliceable, canonical matrix."""
    if is_sparse(x):
        return x.tocsr()
    return x


def row_chunk_dense(x, g0: int, g1: int):
    """Dense float32 slice of rows [g0, g1) — the only densification the
    engine performs (one gene-chunk × all-cells tile at a time). Device
    inputs slice on device (no transfer)."""
    if is_sparse(x):
        return np.asarray(x[g0:g1].toarray(), dtype=np.float32)
    if is_jax(x):
        return x[g0:g1]
    return np.ascontiguousarray(x[g0:g1], dtype=np.float32)


def padded_row_chunk(x, g0: int, width: int):
    """Dense float32 rows [g0, g0+width), zero-padded to exactly ``width``
    rows (keeps every chunk shape identical so jit caches hold one entry).
    The shared chunk primitive for the engine and NB driver loops."""
    g1 = min(g0 + width, x.shape[0])
    chunk = row_chunk_dense(x, g0, g1)
    if chunk.shape[0] < width:
        if is_jax(chunk):
            import jax.numpy as jnp

            chunk = jnp.pad(chunk, ((0, width - chunk.shape[0]), (0, 0)))
        else:
            chunk = np.pad(chunk, ((0, width - chunk.shape[0]), (0, 0)))
    return chunk


def rows_dense(x, idx: np.ndarray):
    """Dense float32 gather of arbitrary gene rows (sparse-safe). Device
    inputs gather on device and stay there."""
    if is_sparse(x):
        return np.asarray(x[idx].toarray(), dtype=np.float32)
    if is_jax(x):
        import jax.numpy as jnp

        return x[jnp.asarray(np.asarray(idx, np.int32))]
    return np.asarray(x[idx], dtype=np.float32)


def expm1_sparse(x):
    """expm1 applied to stored values only (expm1(0) = 0 keeps sparsity)."""
    if is_sparse(x):
        out = x.copy()
        out.data = np.expm1(out.data)
        return out
    if is_jax(x):
        import jax.numpy as jnp

        return jnp.expm1(x)
    return np.expm1(x)


def mean_expm1(x) -> float:
    """mean(expm1(x)) over all entries (the slow path's global threshold
    base, R/reclusterDEConsensus.R:36) without densifying."""
    if is_sparse(x):
        total = float(np.expm1(x.data).sum())
        return total / float(x.shape[0] * x.shape[1])
    if is_jax(x):
        import jax.numpy as jnp

        return float(jnp.mean(jnp.expm1(x)))
    return float(np.mean(np.expm1(x)))


def mean_value(x) -> float:
    """Mean over all entries without densifying."""
    if is_sparse(x):
        return float(x.sum()) / float(x.shape[0] * x.shape[1])
    if is_jax(x):
        import jax.numpy as jnp

        return float(jnp.mean(x))
    return float(np.mean(x))


def nodg(x) -> np.ndarray:
    """Number of detected genes per cell: column-wise nonzero counts
    (the reference's O(N·G) interpreted loop, R/reclusterDEConsensus.R:272)."""
    if is_sparse(x):
        return np.asarray(x.astype(bool).sum(axis=0)).ravel().astype(np.int64)
    if is_jax(x):
        import jax.numpy as jnp

        return np.asarray(jnp.sum(x > 0, axis=0), dtype=np.int64)
    return (x > 0).sum(axis=0).astype(np.int64)


def csr_to_device(m):
    """Densify a scipy CSR/CSC matrix INTO device HBM, shipping only the
    compressed triplet (data f32 + indices i32 ≈ nnz·8 bytes + indptr)
    across the host↔device link — at typical scRNA sparsity (~90 % zeros)
    that is ~10× less link traffic than uploading the dense (G, N) f32
    matrix, which matters when the accelerator sits behind a thin tunnel.
    Row ids are recovered on device (searchsorted over indptr) and the
    values scattered into a zero matrix. Returns a (G, N) f32 jax.Array
    ready for the pipeline's device-resident input path."""
    import jax.numpy as jnp

    if is_jax(m):
        return m  # already device-resident: re-routing it would round-trip
    if not is_sparse(m):
        return jnp.asarray(np.ascontiguousarray(m, dtype=np.float32))
    m = m.tocsr()
    if not m.has_canonical_format:
        m = m.copy()  # tocsr() may alias the input; don't mutate the caller
        m.sum_duplicates()
    G, N = m.shape
    if m.nnz >= np.iinfo(np.int32).max:
        # int32 device indices (jax default without x64); a matrix this
        # dense would not fit HBM as (G, N) f32 anyway at realistic G·N
        raise ValueError(
            f"csr_to_device supports nnz < 2^31 (got {m.nnz}); use the "
            "host-sparse chunked path instead"
        )
    vals = jnp.asarray(m.data.astype(np.float32, copy=False))
    cols = jnp.asarray(m.indices.astype(np.int32, copy=False))
    iptr = jnp.asarray(m.indptr.astype(np.int32, copy=False))
    rows = (
        jnp.searchsorted(
            iptr, jnp.arange(vals.size, dtype=jnp.int32), side="right"
        ) - 1
    )
    return (
        jnp.zeros((G, N), jnp.float32)
        .at[rows, cols]
        .set(vals, mode="drop", unique_indices=True)
    )


def csr_window_rows(
    x, gene_ids: np.ndarray, width: int, cid: np.ndarray,
    pad_rows: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Compacted rank-sum windows straight from CSR storage: for each gene
    in ``gene_ids`` (all with ≤ ``width`` stored entries), a (B, width) f32
    row of its stored values plus a matching (B, width) int32 row of the
    owning cells' cluster ids (``cid[col]``; padding slots are 0 / −1).

    This is what lets the all-pairs rank-sum ladder scale with nnz instead
    of N on sparse input: only a gene's stored entries ever enter the
    device sort — absent cells are implicit zeros the kernel's zero-block
    corrections account for in closed form (ops.ranksum_allpairs). At the
    1M-cell 2.85 %-nnz shape this replaces a 1M-wide sort per gene with a
    ~32k-wide one. ``pad_rows`` ≥ B appends inert all-padding rows so the
    caller can hit a pow-2 compiled shape without a second pad pass.
    """
    B = int(gene_ids.size)
    rows = max(B, int(pad_rows))
    vals = np.zeros((rows, width), np.float32)
    wcid = np.full((rows, width), -1, np.int32)
    indptr, indices, data = x.indptr, x.indices, x.data
    for b, g in enumerate(np.asarray(gene_ids)):
        s, e = int(indptr[g]), int(indptr[g + 1])
        n = e - s
        if n > width:
            raise ValueError(
                f"gene {int(g)} has {n} stored entries > window {width}"
            )
        vals[b, :n] = data[s:e]
        wcid[b, :n] = cid[indices[s:e]]
    return vals, wcid


def aggregates_from_sparse(x, onehot: np.ndarray) -> Tuple[np.ndarray, ...]:
    """Per-cluster sufficient statistics (Σx, Σexpm1 x, Σx², Σ[x>0], counts)
    as host sparse matmuls against the membership one-hot — the sparse analog
    of ops.gates.compute_aggregates' four MXU matmuls."""
    counts = onehot.sum(axis=0)
    if is_sparse(x):
        sum_log = np.asarray(x @ onehot, dtype=np.float32)
        sum_expm1 = np.asarray(expm1_sparse(x) @ onehot, dtype=np.float32)
        sum_sq = np.asarray(x.multiply(x) @ onehot, dtype=np.float32)
        nnz_mat = x.astype(bool).astype(np.float32)
        nnz = np.asarray(nnz_mat @ onehot, dtype=np.float32)
    else:
        sum_log = x @ onehot
        sum_expm1 = np.expm1(x) @ onehot
        sum_sq = (x * x) @ onehot
        nnz = (x > 0).astype(np.float32) @ onehot
    return sum_log, sum_expm1, sum_sq, nnz, counts.astype(np.float32)
