"""Sparse-first data IO.

The reference densifies everything at entry (``as.matrix`` at
R/reclusterDEConsensus.R:32, per-call at R/reclusterDEConsensusFast.R:368);
its only sparse-aware line is a ``Matrix::rowSums`` (SURVEY.md §2b N12). Here
the contract is the opposite: matrices load as CSR (genes × cells), stay
sparse on host, and only gene-chunk × cell-tile slices are densified onto the
device — a 1M×20k matrix never materializes in full.

For datasets that DO fit HBM dense, ``csr_to_device`` instead ships the
compressed triplet across the host↔device link and densifies in HBM,
producing a device-resident matrix the pipeline consumes with zero further
host round-trips (models.pipeline's jax-input path).
"""

from scconsensus_tpu.io.loaders import (
    load_h5ad,
    load_mtx,
    load_npz,
    log_normalize,
)
from scconsensus_tpu.io.sparsemat import (
    aggregates_from_sparse,
    csr_to_device,
    expm1_sparse,
    is_jax,
    is_sparse,
    mean_expm1,
    nodg,
    row_chunk_dense,
)

__all__ = [
    "load_mtx",
    "load_npz",
    "load_h5ad",
    "log_normalize",
    "is_sparse",
    "is_jax",
    "row_chunk_dense",
    "expm1_sparse",
    "mean_expm1",
    "nodg",
    "csr_to_device",
    "aggregates_from_sparse",
]
