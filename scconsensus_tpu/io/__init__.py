"""Sparse-first data IO.

The reference densifies everything at entry (``as.matrix`` at
R/reclusterDEConsensus.R:32, per-call at R/reclusterDEConsensusFast.R:368);
its only sparse-aware line is a ``Matrix::rowSums`` (SURVEY.md §2b N12). Here
the contract is the opposite: matrices load as CSR (genes × cells), stay
sparse on host, and only gene-chunk × cell-tile slices are densified onto the
device — a 1M×20k matrix never materializes in full.
"""

from scconsensus_tpu.io.loaders import (
    load_h5ad,
    load_mtx,
    load_npz,
    log_normalize,
)
from scconsensus_tpu.io.sparsemat import (
    aggregates_from_sparse,
    expm1_sparse,
    is_sparse,
    mean_expm1,
    nodg,
    row_chunk_dense,
)

__all__ = [
    "load_mtx",
    "load_npz",
    "load_h5ad",
    "log_normalize",
    "is_sparse",
    "row_chunk_dense",
    "expm1_sparse",
    "mean_expm1",
    "nodg",
    "aggregates_from_sparse",
]
