"""Format loaders: MatrixMarket, scipy .npz, and a minimal AnnData .h5ad
reader (h5py-based, no anndata dependency). All return CSR genes × cells
float32 plus names, matching the pipeline's (G, N) input contract
(R/reclusterDEConsensus.R:5 — "log-transformed, normalised" genes × cells).
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional, Sequence

import numpy as np

import scipy.io as _sio
import scipy.sparse as _sp

__all__ = ["ExpressionData", "load_mtx", "load_npz", "load_h5ad", "log_normalize"]


class ExpressionData(NamedTuple):
    """CSR genes × cells matrix with row/column names."""

    matrix: "_sp.csr_matrix"
    gene_names: Optional[np.ndarray] = None
    cell_names: Optional[np.ndarray] = None


def _read_lines(path: Optional[str]) -> Optional[np.ndarray]:
    if path is None or not os.path.exists(path):
        return None
    with open(path) as f:
        first = [line.rstrip("\n").split("\t")[0] for line in f if line.strip()]
    return np.asarray(first)


def load_mtx(
    mtx_path: str,
    genes_path: Optional[str] = None,
    barcodes_path: Optional[str] = None,
    genes_as_rows: bool = True,
) -> ExpressionData:
    """MatrixMarket triplet (10x-style: genes.tsv / barcodes.tsv alongside)."""
    m = _sio.mmread(mtx_path)
    if not genes_as_rows:
        m = m.T
    return ExpressionData(
        matrix=_sp.csr_matrix(m, dtype=np.float32),
        gene_names=_read_lines(genes_path),
        cell_names=_read_lines(barcodes_path),
    )


def load_npz(path: str) -> ExpressionData:
    """scipy.sparse.save_npz archive (genes × cells)."""
    return ExpressionData(matrix=_sp.load_npz(path).tocsr().astype(np.float32))


def load_h5ad(path: str) -> ExpressionData:
    """Minimal AnnData .h5ad reader via h5py: X (sparse CSR/CSC groups or
    dense dataset), var index as gene names, obs index as cell names.

    AnnData stores X as cells × genes; transposed here to genes × cells.
    """
    try:
        import h5py
    except ImportError as e:  # pragma: no cover
        raise NotImplementedError(
            "load_h5ad requires h5py, which is not installed"
        ) from e

    with h5py.File(path, "r") as f:
        x = f["X"]
        if isinstance(x, h5py.Group):
            data = np.asarray(x["data"])
            indices = np.asarray(x["indices"])
            indptr = np.asarray(x["indptr"])
            enc = x.attrs.get("encoding-type")
            if isinstance(enc, bytes):
                enc = enc.decode()
            shape = tuple(int(v) for v in x.attrs["shape"])
            if enc is None:
                # Older h5ad files may omit encoding-type; infer the layout
                # from the indptr length (CSR: shape[0]+1, CSC: shape[1]+1)
                # rather than guessing — a wrong guess can yield a
                # shape-valid but scrambled matrix.
                csr_len, csc_len = shape[0] + 1, shape[1] + 1
                if indptr.size == csr_len and indptr.size != csc_len:
                    enc = "csr_matrix"
                elif indptr.size == csc_len and indptr.size != csr_len:
                    enc = "csc_matrix"
                elif indptr.size == csr_len:  # square: either is consistent
                    import warnings

                    warnings.warn(
                        f"X is square ({shape}) with no encoding-type attr; "
                        "CSR and CSC are indistinguishable from indptr — "
                        "assuming CSR. If the file is CSC the result is the "
                        "transpose.",
                        stacklevel=2,
                    )
                    enc = "csr_matrix"
                else:
                    raise ValueError(
                        f"cannot infer sparse layout of X: indptr length "
                        f"{indptr.size} matches neither CSR ({csr_len}) nor "
                        f"CSC ({csc_len}) for shape {shape}"
                    )
            cls = _sp.csr_matrix if "csr" in enc else _sp.csc_matrix
            mat = cls((data, indices, indptr), shape=shape)
        else:
            mat = _sp.csr_matrix(np.asarray(x))

        def index_of(group_name: str) -> Optional[np.ndarray]:
            if group_name not in f:
                return None
            g = f[group_name]
            key = g.attrs.get("_index", "index" if "index" in g else None)
            if isinstance(key, bytes):
                key = key.decode()
            if key is None or key not in g:
                return None
            vals = np.asarray(g[key])
            if vals.dtype.kind in ("S", "O"):
                vals = vals.astype(str)
            return vals

        cells = index_of("obs")
        genes = index_of("var")
    return ExpressionData(
        matrix=mat.T.tocsr().astype(np.float32),
        gene_names=genes,
        cell_names=cells,
    )


def log_normalize(
    counts, scale: float = 10_000.0
):
    """log1p(counts / libsize · scale): the standard normalization producing
    the "log-transformed, normalised" matrix the reference expects as input
    (README workflow; sparse-preserving — zero entries stay zero)."""
    if _sp.issparse(counts):
        c = counts.tocsc(copy=True).astype(np.float32)
        lib = np.asarray(c.sum(axis=0)).ravel()
        lib = np.maximum(lib, 1.0)
        scale_per_cell = (scale / lib).astype(np.float32)
        # scale each column's stored values, then log1p them
        c.data *= np.repeat(scale_per_cell, np.diff(c.indptr))
        c.data = np.log1p(c.data)
        return c.tocsr()
    counts = np.asarray(counts, np.float32)
    lib = np.maximum(counts.sum(axis=0, keepdims=True), 1.0)
    return np.log1p(counts / lib * scale)
