"""All-pairs edgeR-style NB DE driver (method="edger" of the engine).

Reference pipeline being replaced (R/reclusterDEConsensus.R:123-156):
per pair, DGEList(group ±1) → estimateCommonDisp → estimateTagwiseDisp →
calcNormFactors("none") (identity — kept as a no-op, including the quirk
that dispersions are estimated before it) → exactTest.

TPU-native redesign (round 3). The round-2 driver materialized a
(pairs × genes × cells) tile per pair bucket and evaluated every
conditional-likelihood grid point as a dense lgamma sweep over it — at the
26k-cell flagship that is ~10¹³ tile elements × ~10² transcendentals each:
it OOM'd in the pilot phase and ran pass 2 at 0.01% MFU (judge-measured,
VERDICT r2). This rewrite removes the pair × cell tensor entirely; every
per-pair quantity is assembled from per-cluster structures:

  1. **Global library equalization** (once, not per pair): per-cluster NB
     rates from the Poisson MLE (cluster raw sums over cluster library
     sums — one MXU matmul), then every cell's count is quantile-mapped to
     the global geometric-mean library size with the cheap normal map
     (``q2q_normal``; sums downstream, skewness washes out). Cluster
     pseudo-count sums Z (G, K) are one more matmul, and a pair's group
     sums are just Z columns. edgeR equalizes per-pair to the pair's own
     common library size; equalizing once to the global one is the
     multi-group design edgeR itself uses for >2 groups — divergence
     documented and tested against the direct per-pair oracle
     (de.edger_direct, tests/test_edger_parity.py).

  2. **Conditional-likelihood node table**: dispersion estimation needs
     Σ_{cells∈cluster} lgamma(pseudo + r) at many r per pair. Per-gene,
     per-cluster sums are evaluated on a seeded ≤``_SUB_CELLS``-per-cluster
     subsample (full q2q map) at ``_NODE_COUNT`` log-spaced r nodes — a
     stacked (genes·nodes, cells)·(cells, K) MXU contraction — and every
     per-pair grid point (24-point qCML common grid, 11-point tagwise grid
     × P pairs) is a 4-point Lagrange interpolation in log r, applied as a
     tiny dense (grid, nodes) weight matmul. Dispersion information is
     O(cells); at 64 cells/group the qCML estimates are already tight and
     the EB prior (prior.df = 10) dominates gene-wise uncertainty —
     subsampling here is a documented divergence, validated in tests.

  3. **qCML common dispersion per pair** (estimateCommonDisp semantics):
     the pair's keep-filtered (pooled raw rowsum > 5 — exact per pair,
     because pooled sums are sums of cluster sums) conditional LL summed
     over genes at each of 24 δ grid points, argmax + quadratic refinement
     (ops.negbin.common_dispersion_grid).

  4. **Tagwise EB shrinkage** (estimateTagwiseDisp, trend="none",
     prior.df = 10): per-gene grids at common·2^[−6..6] from the same node
     table; weighted likelihood + quadratic refinement
     (ops.negbin.tagwise_dispersion). Pseudo-counts are re-equalized once
     at the median common dispersion (edgeR re-equalizes per pair at its
     own estimate — documented divergence).

  5. **Exact test** (ops.negbin.nb_exact_test_logp): Beta-Binomial tails on
     the rounded group pseudo-sums at the tagwise dispersion. (pair, gene)
     entries with small totals run the exact cumulative-pmf-ratio kernel on
     a host-compacted task list; the rest take the moment-matched normal
     branch — so the (tasks × s_max) tail tensor only ever covers entries
     that need it.

Note the reference feeds *log-normalized* values to DGEList as if they were
counts (R/reclusterDEConsensus.R:133 passes `data` directly). Compat mode
reproduces that literal arithmetic; fixed mode tests on expm1(data).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.obs import quality as obs_quality
from scconsensus_tpu.obs.cost import attach_cost
from scconsensus_tpu.obs.graphs import instrument as _passport
from scconsensus_tpu.ops.negbin import (
    common_dispersion_grid,
    delta_grid,
    lgamma_shift,
    nb_exact_test_logp,
    nb_exact_test_logp_normal,
    q2q_gamma_raw,
    q2q_nbinom,
    q2q_normal,
    q2q_normal_raw,
    tagwise_dispersion,
    TAGWISE_GRID_EXPONENTS,
)

__all__ = ["run_edger_pairs", "EdgerPairResult"]

_PILOT_DISPERSION = 0.01
_ROWSUM_FILTER = 5.0
_PRIOR_DF = 10.0
_LOGFC_PRIOR_COUNT = 0.125
_EXACT_SMAX = 4096
_SUB_CELLS = 64          # dispersion-estimation cells per cluster
_NODE_COUNT = 24         # log-r conditional-likelihood node table size
_DELTA_GRID = 24         # qCML common-dispersion δ grid
_CHUNK_ELEMS = 32_000_000  # budget for (Gc, N) full-matrix sweeps
_EXACT_TASK_ELEMS = 64_000_000  # budget for the (tasks, s_max) tail tensor
_PAIR_CHUNK = 64         # pairs per device call in grid/tagwise assembly


@dataclasses.dataclass
class EdgerPairResult:
    log_p: np.ndarray        # (P, G)
    log_fc: np.ndarray       # (P, G) natural-log fold change group1 vs group2
    common_disp: np.ndarray  # (P,)
    tagwise_disp: np.ndarray  # (P, G)


class _PhaseProfiler:
    """NB-driver phase marks as ambient ``obs.trace`` child spans.

    This used to be the repo's third private profiler (stderr prints +
    ad-hoc gauges behind SCC_EDGER_PROFILE). Each ``mark(label)`` now
    closes the phase that began at the previous mark and records it as a
    completed ``detail`` child span (``edger_<label>``) of the ambient
    span — so NB phase walls ride run records, heartbeat open-span
    context, and Chrome traces like every other span, on EVERY traced run.

    Sync semantics follow the tracer policy for detail spans: phase
    boundaries device-drain only when SCC_EDGER_PROFILE=1 (the classic
    synced stderr profile) or under SCC_TRACE_SYNC=all; the default traced
    run records dispatch-interval walls with ``synced=False`` and pays no
    drains. With no ambient tracer and the flag off, ``mark`` is free."""

    def __init__(self) -> None:
        from scconsensus_tpu.config import env_flag
        from scconsensus_tpu.obs.trace import current_tracer

        self.print_enabled = bool(env_flag("SCC_EDGER_PROFILE"))
        self._tracer = current_tracer()
        self._sync = self.print_enabled or (
            self._tracer is not None and self._tracer.sync == "all"
        )
        self.enabled = self.print_enabled or self._tracer is not None
        self._t = time.perf_counter() if self.enabled else 0.0

    def mark(self, label: str) -> None:
        if not self.enabled:
            return
        if self._sync:
            from scconsensus_tpu.obs.trace import device_drain

            device_drain()  # phase boundary: retire the queued phase work
        now = time.perf_counter()
        wall = now - self._t
        if self._tracer is not None:
            self._tracer.add_completed_span(
                f"edger_{label}", wall, kind="detail", synced=self._sync
            )
        if self.print_enabled:
            print(f"[edger-profile] {label}: {wall:.3f}s", flush=True)
        self._t = now


# --------------------------------------------------------------------------
# device kernels
# --------------------------------------------------------------------------

_HI = jax.lax.Precision.HIGHEST


@jax.jit
def _raw_sums_chunk(chunk, onehot):
    """(Gc, N) @ (N, K) raw cluster sums."""
    return jnp.dot(chunk, onehot, precision=_HI)


@jax.jit
def _pseudo_sums_chunk(chunk, onehot, lib, cid_safe, kept, rates, common_lib,
                       phi):
    """Normal-map global equalization of one gene chunk → cluster sums.

    chunk (Gc, N); rates (Gc, K); cid_safe (N,) with excluded cells → 0;
    kept (N,) mask. Returns (Gc, K) equalized pseudo-count sums."""
    lam = jnp.maximum(jnp.take(rates, cid_safe, axis=1), 1e-10)  # (Gc, N)
    pseudo = q2q_normal(chunk, lam * lib, lam * common_lib, phi)
    pseudo = jnp.where(kept, pseudo, 0.0)
    return jnp.dot(pseudo, onehot, precision=_HI)


@jax.jit
def _sub_pseudo_chunk(sub_chunk, lib_sub, cid_sub_safe, rates, common_lib,
                      phi):
    """Full (normal+gamma average) q2q map for the subsample columns."""
    lam = jnp.maximum(jnp.take(rates, cid_sub_safe, axis=1), 1e-10)
    return q2q_nbinom(sub_chunk, lam * lib_sub, lam * common_lib, phi)


@partial(jax.jit, static_argnames=("window", "n_clusters"))
def _sub_table_sorted_chunk(sc, lib_sub, cid_sub, rates_chunk, common_lib,
                            phi, r_nodes, window, n_clusters):
    """Zero-compacted q2q + node table for one nnz-bucketed gene block.

    ``gammainc`` costs ~60× a ``gammaln`` here and the gamma half of the
    q2q map spends 4 of them per element — by far the NB engine's hottest
    op — yet the gamma-quantile of a zero count is exactly 0
    (ops.negbin.q2q_gamma_raw). So: sort each row descending carrying
    (cluster id, library size), run the gamma half only on the leading
    ``window`` columns (every positive lands there; window ≥ the block's
    max subsample nnz), and give the zero tail its closed-form 0. The
    cheap normal half runs full-width; the node-table lgamma contraction
    runs in sorted order against the carried-cid one-hot (row order is
    irrelevant under the per-cluster sum). Produces the same table as
    ``_sub_pseudo_chunk`` + ``_table_chunk`` (pinned in
    tests/test_edger_parity.py) at a fraction of the igamma volume —
    42 % density on the synthetic flagship, ~5-10 % on real scRNA."""
    sv, scid, slib = jax.lax.sort(
        (-sc, jnp.broadcast_to(cid_sub, sc.shape),
         jnp.broadcast_to(lib_sub, sc.shape)),
        dimension=1, num_keys=1,
    )
    x = -sv
    oh = (scid[:, :, None]
          == jnp.arange(n_clusters, dtype=jnp.int32)[None, None, :]
          ).astype(jnp.float32)
    # per-cell rate via the one-hot contraction, not take_along_axis: the
    # same (Gb, Ns, K) one-hot feeds the table contraction below, and a
    # matmul stays fast where TPU gathers do not
    lam = jnp.maximum(
        jnp.einsum("gnk,gk->gn", oh, rates_chunk, precision=_HI), 1e-10
    )
    mu_in = lam * slib
    mu_out = lam * common_lib
    qn = q2q_normal_raw(x, mu_in, mu_out, phi)
    qg = q2q_gamma_raw(x[:, :window], mu_in[:, :window], mu_out[:, :window],
                       phi)
    qg_full = jnp.pad(qg, ((0, 0), (0, sc.shape[1] - window)))
    psub = jnp.maximum(0.5 * (qn + qg_full), 0.0)
    lg = lgamma_shift(psub[..., None], r_nodes[None, None, :])
    table = jnp.einsum("gnr,gnk->gkr", lg, oh, precision=_HI)
    zs = jnp.einsum("gn,gnk->gk", psub, oh, precision=_HI)
    return table, zs


@jax.jit
def _table_chunk(psub_chunk, sub_onehot, r_nodes):
    """Conditional-LL node table for one gene chunk.

    psub_chunk (Gc, Ns); r_nodes (R,). Returns (table (Gc, K, R), zs
    (Gc, K)) with table[g, k, m] = Σ_{n∈k} lgamma_shift(psub[g, n], r_m).

    A static small/large node split (decide lgamma_shift's branch at trace
    time, pay only one branch per node) was measured and REJECTED: XLA CPU
    vectorizes these elementwise ops only at multiple-of-8 inner widths, so
    the 13/11 split tensors fell off the SIMD path and ran 2-3× slower
    than the fused-select full-width tensor, whose gammaln costs the same
    ~9 ns/elem as a plain log here (see ROUND5_NOTES.md)."""
    lg = lgamma_shift(psub_chunk[..., None], r_nodes[None, None, :])
    table = jnp.einsum("gnr,nk->gkr", lg, sub_onehot, precision=_HI)
    zs = jnp.dot(psub_chunk, sub_onehot, precision=_HI)
    return table, zs


@jax.jit
def _cl_grid_pairs(table_i, table_j, w_grid, zs_i, zs_j, ns_i, ns_j,
                   keep, r_grid):
    """Keep-masked conditional LL summed over genes at each δ grid point.

    table_i/j (G, Pc, R) node values for each pair's two clusters;
    w_grid (D, R) interpolation weights; zs (G, Pc); ns (Pc,); keep
    (G, Pc); r_grid (D,). Returns (Pc, D)."""
    m = jnp.einsum("gpr,dr->gpd", table_i + table_j, w_grid)  # (G, Pc, D)
    r = r_grid[None, None, :]
    zterm = lgamma_shift(zs_i[..., None], ns_i[None, :, None] * r) + \
        lgamma_shift(zs_j[..., None], ns_j[None, :, None] * r)
    cl = jnp.where(keep[..., None], m - zterm, 0.0)
    return jnp.sum(cl, axis=0)  # (Pc, D)


@jax.jit
def _tagwise_pairs(table_i, table_j, w_tag, zs_i, zs_j, ns_i, ns_j,
                   keep, r_tag, common, prior_n):
    """Per-gene tagwise dispersion for a pair chunk.

    w_tag (Pc, T, R); r_tag (Pc, T); common, prior_n (Pc,). Returns
    (Pc, G) tagwise dispersions."""
    m = jnp.einsum("gpr,ptr->gpt", table_i + table_j, w_tag)  # (G, Pc, T)
    r = r_tag[None, :, :]
    zterm = lgamma_shift(zs_i[..., None], ns_i[None, :, None] * r) + \
        lgamma_shift(zs_j[..., None], ns_j[None, :, None] * r)
    ll = jnp.moveaxis(m - zterm, 0, 1)                        # (Pc, G, T)
    return tagwise_dispersion(ll, common, prior_n, keep.T)


# graph passports (obs.graphs, SCC_GRAPHS): the NB engine's CSR-window and
# node-table stage programs (the zero-compacted window op is the q2q
# hotpath; the table chunk is its legacy full-width form)
_sub_table_sorted_chunk = _passport(
    "edger.sub_table_sorted_chunk", _sub_table_sorted_chunk
)
_table_chunk = _passport("edger.table_chunk", _table_chunk)


# --------------------------------------------------------------------------
# host-side helpers
# --------------------------------------------------------------------------

def _lagrange_weights(x: np.ndarray, n_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    """4-point Lagrange weights on a uniform node grid.

    x: query positions in node units. Returns (base index (…) int, weights
    (…, 4)); queries outside the grid clamp to the boundary stencils."""
    i = np.clip(np.floor(x).astype(np.int64), 1, n_nodes - 3)
    f = np.clip(x - i, -1.0, 2.0)
    w = np.stack([
        -f * (f - 1.0) * (f - 2.0) / 6.0,
        (f + 1.0) * (f - 1.0) * (f - 2.0) / 2.0,
        -(f + 1.0) * f * (f - 2.0) / 2.0,
        (f + 1.0) * f * (f - 1.0) / 6.0,
    ], axis=-1)
    return i, w


def _dense_weights(rho: np.ndarray, rho0: float, h: float,
                   n_nodes: int) -> np.ndarray:
    """Dense (…, R) interpolation-weight rows for query points rho —
    4 Lagrange weights scattered at their node stencil (host-built; applied
    on device as a plain matmul, no gathers)."""
    i, w4 = _lagrange_weights((rho - rho0) / h, n_nodes)
    out = np.zeros(rho.shape + (n_nodes,), np.float32)
    idx = np.indices(rho.shape)
    for q in range(4):
        out[(*idx, i - 1 + q)] += w4[..., q]
    return out


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def run_edger_pairs(
    counts,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    n_genes: int,
    seed: int = 0,
    jcounts=None,
) -> EdgerPairResult:
    """Run the NB pipeline for every cluster pair.

    counts: (G, N) matrix handed to DGEList (log-normalized data in compat
    mode — the reference's literal behavior — or expm1 of it); dense or
    scipy-sparse. cell_idx_of: per-cluster cell index lists (post
    subsampling); pair_i/pair_j: (P,) cluster indices per pair.
    ``jcounts``: optional already-on-device (G, N) copy of ``counts`` (the
    engine re-uses its aggregate upload) — without it a dense matrix is
    uploaded here, once.

    The returned log_p/tagwise_disp are ALWAYS device arrays — on the
    sparse path too, since they are assembled from device parts (z1/tw
    chunks); log_fc/common_disp are host numpy. Through a slow device→host
    link only the consumer-touched fields should ever cross
    (engine.PairwiseDEResult materializes per field, lazily).
    """
    from scconsensus_tpu.de.engine import (
        _cid_from_groups,
        _gene_chunks,
        _next_pow2,
    )
    from scconsensus_tpu.io.sparsemat import as_csr, is_jax, is_sparse

    prof = _PhaseProfiler()
    G = n_genes
    N = counts.shape[1]
    K = len(cell_idx_of)
    P = int(pair_i.shape[0])
    sparse = is_sparse(counts)
    if sparse:
        counts = as_csr(counts)
    elif is_jax(counts):
        # Device-resident input: stays in HBM (pulling it to host here was
        # the exact whole-matrix transfer the jax-input path eliminates).
        counts = counts.astype(jnp.float32)
        if jcounts is None:
            jcounts = counts
    else:
        counts = np.ascontiguousarray(counts, np.float32)
        # Dense input crosses host→device exactly once (or zero times, when
        # the engine hands over its aggregate upload); chunk loops reuse it.
        if jcounts is None:
            jcounts = jnp.asarray(counts)

    # ---- host geometry -------------------------------------------------
    cid = _cid_from_groups(cell_idx_of, N)
    kept = cid >= 0
    cid_safe = np.where(kept, cid, 0).astype(np.int32)
    if sparse:
        lib_all = np.asarray(counts.sum(axis=0), np.float32).ravel()
    else:
        # (N,) library sizes: reduce on device, fetch 4N bytes.
        lib_all = np.asarray(jnp.sum(jcounts, axis=0))
    libsum_c = np.array(
        [lib_all[ci].sum() for ci in cell_idx_of], np.float32
    )
    n_of = np.array([ci.size for ci in cell_idx_of], np.float32)
    with np.errstate(divide="ignore"):
        loglib = np.log(np.maximum(lib_all[kept], 1e-30))
    common_lib = float(np.exp(loglib.mean())) if kept.any() else 1.0

    rng = np.random.default_rng(seed)
    sub_idx_of = [
        rng.choice(ci, size=_SUB_CELLS, replace=False)
        if ci.size > _SUB_CELLS else ci
        for ci in cell_idx_of
    ]
    sub_cells = np.concatenate(sub_idx_of)
    ns_of = np.array([s.size for s in sub_idx_of], np.float32)
    cid_sub = np.concatenate(
        [np.full(s.size, k, np.int32) for k, s in enumerate(sub_idx_of)]
    )
    # (no subsample one-hot here: the zero-compacted table builder derives
    # its one-hot from the sorted carried cids, _sub_table_sorted_chunk)

    onehot = np.zeros((N, K), np.float32)
    onehot[kept, cid[kept]] = 1.0
    j_onehot = jnp.asarray(onehot)
    j_lib = jnp.asarray(lib_all)
    j_cid_safe = jnp.asarray(cid_safe)
    j_kept = jnp.asarray(kept)
    j_lib_sub = jnp.asarray(lib_all[sub_cells])
    j_cid_sub = jnp.asarray(cid_sub)
    if sparse:
        j_sub_counts = jnp.asarray(
            np.asarray(counts[:, sub_cells].todense(), np.float32)
        )
    else:
        # Column gather on device — the host copy is never touched again.
        j_sub_counts = jnp.take(jcounts, jnp.asarray(sub_cells), axis=1)

    prof.mark("setup")
    gc = max(256, _next_pow2(_CHUNK_ELEMS // max(N, 1)) >> 1)
    gc = min(gc, _next_pow2(G))  # never pad beyond the gene count

    # ---- pass A: raw cluster sums, rates -------------------------------
    Zy_parts = [
        (g0, g1, _raw_sums_chunk(chunk, j_onehot))
        for g0, g1, chunk in _gene_chunks(counts, gc, jdata=jcounts)
    ]
    Zy = np.zeros((G, K), np.float32)
    for g0, g1, part in Zy_parts:
        Zy[g0:g1] = np.asarray(part)[: g1 - g0]
    prof.mark("pass_a_raw_sums")
    rates = Zy / np.maximum(libsum_c, 1e-30)[None, :]  # Poisson MLE (G, K)
    j_rates = jnp.asarray(rates)

    # ---- pilot subsample table + per-pair common dispersion -------------
    deltas = np.asarray(delta_grid(_DELTA_GRID))
    r_grid = (1.0 - deltas) / deltas
    # node range: the δ grid ∪ tagwise band around any grid value, in log r
    rho_lo = float(np.log(r_grid.min())) - 6.0 * np.log(2.0) - 0.5
    rho_hi = float(np.log(r_grid.max())) + 6.0 * np.log(2.0) + 0.5
    rho_nodes = np.linspace(rho_lo, rho_hi, _NODE_COUNT).astype(np.float32)
    h = float(rho_nodes[1] - rho_nodes[0])
    j_r_nodes = jnp.asarray(np.exp(rho_nodes))

    # nnz-bucketed gene order for the zero-compacted table builds: blocks
    # ascend in subsample nnz so each block's gamma-map window (the igamma
    # part) hugs its actual positive count. Shared by both table builds.
    Ns = int(sub_cells.size)
    sub_nnz = np.asarray(jnp.sum(j_sub_counts > 0, axis=1)).astype(np.int64)
    sub_order = np.argsort(sub_nnz, kind="stable")
    j_sub_inv = jnp.asarray(np.argsort(sub_order))

    def _build_table(phi: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(G, K, R) node table + (G, K) subsample pseudo sums at phi."""
        tabs, zss = [], []
        # the (Gc, Ns, R) lgamma node tensor dominates memory: budget for it
        sgc = max(256, _next_pow2(
            _CHUNK_ELEMS // max(sub_cells.size * _NODE_COUNT, 1)
        ))
        sgc = min(sgc, _next_pow2(G))  # never pad beyond the gene count
        for b0 in range(0, G, sgc):
            b1 = min(b0 + sgc, G)
            ids = sub_order[b0:b1]
            # window floor 256 bounds the distinct compiled (sgc, w) shapes
            w = min(_next_pow2(max(int(sub_nnz[ids[-1]]), 256)), Ns)
            sc = jnp.take(j_sub_counts, jnp.asarray(ids), axis=0)
            rc = jnp.take(j_rates, jnp.asarray(ids), axis=0)
            if b1 - b0 < sgc:  # pad the tail block: one compiled shape
                sc = jnp.pad(sc, ((0, sgc - (b1 - b0)), (0, 0)))
                rc = jnp.pad(rc, ((0, sgc - (b1 - b0)), (0, 0)))
            kargs = (sc, j_lib_sub, j_cid_sub, rc,
                     jnp.float32(common_lib), jnp.float32(phi),
                     j_r_nodes, w, K)
            # NB node-table build is the driver's hot kernel: price it on
            # the ambient (edger_nb) stage span when SCC_OBS_COST is on
            attach_cost(None, _sub_table_sorted_chunk, *kargs)
            t, z = _sub_table_sorted_chunk(*kargs)
            tabs.append(t[: b1 - b0])
            zss.append(z[: b1 - b0])
        # un-permute back to input gene order (device gathers, axis 0)
        return (
            jnp.take(jnp.concatenate(tabs, axis=0), j_sub_inv, axis=0),
            jnp.take(jnp.concatenate(zss, axis=0), j_sub_inv, axis=0),
        )

    table0, zs0 = _build_table(_PILOT_DISPERSION)
    prof.mark("pilot_table")

    w_grid = jnp.asarray(_dense_weights(
        np.log(r_grid).astype(np.float32), rho_nodes[0], h, _NODE_COUNT
    ))  # (D, R)
    j_r_grid = jnp.asarray(r_grid.astype(np.float32))
    j_Zy = jnp.asarray(Zy)
    j_zs0 = zs0
    j_ns = jnp.asarray(ns_of)

    def _pair_chunks():
        """Yield (p0, p1, padded pi, padded pj) — fixed-size chunks so each
        assembly kernel compiles once."""
        for p0 in range(0, P, _PAIR_CHUNK):
            p1 = min(p0 + _PAIR_CHUNK, P)
            pi = np.pad(pair_i[p0:p1], (0, _PAIR_CHUNK - (p1 - p0)),
                        mode="edge")
            pj = np.pad(pair_j[p0:p1], (0, _PAIR_CHUNK - (p1 - p0)),
                        mode="edge")
            yield p0, p1, pi, pj

    j_deltas = jnp.asarray(deltas)
    common_parts = []
    for p0, p1, pi, pj in _pair_chunks():
        keep = (j_Zy[:, pi] + j_Zy[:, pj]) > _ROWSUM_FILTER
        kargs = (table0[:, pi, :], table0[:, pj, :], w_grid,
                 j_zs0[:, pi], j_zs0[:, pj], j_ns[pi], j_ns[pj],
                 keep, j_r_grid)
        attach_cost(None, _cl_grid_pairs, *kargs)
        cl = _cl_grid_pairs(*kargs)
        common_parts.append(common_dispersion_grid(cl, j_deltas)[: p1 - p0])
    # chunks dispatch async; ONE (P,) fetch instead of a sync per chunk
    common = np.asarray(jnp.concatenate(common_parts))
    if obs_quality.enabled():
        # a NaN/Inf dispersion here poisons every downstream tagwise grid
        # and exact test — catch it at the phase boundary, span-attributed
        obs_quality.check_array("common_dispersion", common,
                                where="edger_nb")

    prof.mark("common_grid")

    # ---- re-equalize at the median common dispersion --------------------
    phi_req = float(np.median(common))
    table1, zs1 = _build_table(phi_req)
    prof.mark("table1")
    z1_parts = []
    for g0, g1, chunk in _gene_chunks(counts, gc, jdata=jcounts):
        part = _pseudo_sums_chunk(
            chunk, j_onehot, j_lib, j_cid_safe, j_kept,
            jnp.asarray(rates[g0:g1] if g1 - g0 == chunk.shape[0]
                        else np.pad(rates[g0:g1],
                                    ((0, chunk.shape[0] - (g1 - g0)), (0, 0)))),
            jnp.float32(common_lib), jnp.float32(phi_req),
        )
        z1_parts.append(part[: g1 - g0])
    j_Z1 = jnp.concatenate(z1_parts, axis=0)  # (G, K) stays on device
    Z1 = np.asarray(j_Z1)  # one small (G, K) fetch drives host task geometry

    prof.mark("z1_sweep")

    # ---- tagwise dispersions -------------------------------------------
    prior_n = (_PRIOR_DF / np.maximum(
        ns_of[pair_i] + ns_of[pair_j] - 2.0, 1.0
    )).astype(np.float32)
    expo = np.asarray(TAGWISE_GRID_EXPONENTS)
    tw_parts = []
    for p0, p1, pi, pj in _pair_chunks():
        common_c = np.pad(common[p0:p1], (0, _PAIR_CHUNK - (p1 - p0)),
                          constant_values=1.0)
        prior_c = np.pad(prior_n[p0:p1], (0, _PAIR_CHUNK - (p1 - p0)),
                         constant_values=1.0)
        phi_t = common_c[:, None] * np.exp2(expo)[None, :]  # (Pc, T)
        rho_t = -np.log(phi_t)
        w_tag = jnp.asarray(_dense_weights(
            rho_t.astype(np.float32), rho_nodes[0], h, _NODE_COUNT
        ))
        keep = (j_Zy[:, pi] + j_Zy[:, pj]) > _ROWSUM_FILTER
        tw = _tagwise_pairs(
            table1[:, pi, :], table1[:, pj, :], w_tag,
            zs1[:, pi], zs1[:, pj], j_ns[pi], j_ns[pj],
            keep, jnp.asarray((1.0 / phi_t).astype(np.float32)),
            jnp.asarray(common_c), jnp.asarray(prior_c),
        )
        tw_parts.append(tw[: p1 - p0])
    # (P, G) tagwise dispersions stay on device: the exact test gathers its
    # per-task dispersions here, and the caller exposes the full array only
    # through a lazy fetch.
    j_tagwise = jnp.concatenate(tw_parts, axis=0)
    if obs_quality.enabled():
        obs_quality.check_array("tagwise_dispersion", j_tagwise,
                                where="edger_nb")

    prof.mark("tagwise")

    # ---- exact test (device end-to-end) ---------------------------------
    # Host side only builds the task geometry from the tiny (G, K) Z1 fetch;
    # statistics never cross to host (the old per-chunk fetch pattern cost
    # ~47 s at flagship scale through the 10 MB/s device→host tunnel).
    s1 = Z1[:, pair_i].T  # (P, G) host copies: task bucketing + logFC only
    s2 = Z1[:, pair_j].T
    tot = np.round(s1) + np.round(s2)
    max_total = float(tot.max(initial=0.0))
    s_max = int(min(_EXACT_SMAX, _next_pow2(max(int(max_total) + 2, 64))))

    j_pair_i = jnp.asarray(pair_i.astype(np.int32))
    j_pair_j = jnp.asarray(pair_j.astype(np.int32))
    j_n_of = jnp.asarray(n_of)
    j_s1 = jnp.take(j_Z1, j_pair_i, axis=1).T  # (P, G)
    j_s2 = jnp.take(j_Z1, j_pair_j, axis=1).T
    j_n1 = j_n_of[j_pair_i][:, None]
    j_n2 = j_n_of[j_pair_j][:, None]

    # normal branch for everything, vectorized…
    j_log_p = nb_exact_test_logp_normal(j_s1, j_s2, j_n1, j_n2, j_tagwise)
    prof.mark("exact_normal")

    # …then the exact kernel on host-compacted small-total task lists,
    # bucketed by each task's own total: a task only pays for the support
    # width it needs (pow-4 ladder up to s_max), instead of every task
    # paying the global worst case. Results scatter back ON DEVICE.
    n1_host = n_of[pair_i]
    n2_host = n_of[pair_j]
    # pow-2 ladder (was pow-4): a task pays ≤2× its own support width. The
    # extra compiled bucket variants (7 vs 4 at s_max=4096) amortize across
    # runs via the persistent compile cache.
    s_buckets = []
    sb = 64
    while sb < s_max:
        s_buckets.append(sb)
        sb *= 2
    s_buckets.append(s_max)
    lower = 0.5  # tot == 0 is a point mass (p = 1): the normal branch's value
    all_rows, all_vals = [], []
    for sb in s_buckets:
        mask = (tot >= lower) & (tot < float(sb))
        lower = float(sb)
        rows, cols = np.nonzero(mask)
        if not rows.size:
            continue
        flat = jnp.asarray(rows.astype(np.int32) * G + cols.astype(np.int32))
        tag_b = jnp.take(j_tagwise.reshape(-1), flat)
        s1_b = jnp.asarray(s1[rows, cols])
        s2_b = jnp.asarray(s2[rows, cols])
        n1_b = jnp.asarray(n1_host[rows])
        n2_b = jnp.asarray(n2_host[rows])
        tb_budget = max(1024, _EXACT_TASK_ELEMS // sb)
        outs = []
        for t0 in range(0, rows.size, tb_budget):
            t1 = min(t0 + tb_budget, rows.size)
            # pad to the pow-2 of the ACTUAL count (shape reuse), not the
            # full budget: a 500-task bucket must not compute 500k rows
            tb = min(tb_budget, _next_pow2(t1 - t0))
            pad = tb - (t1 - t0)
            pw = [(0, pad)]
            lp = nb_exact_test_logp(
                jnp.pad(s1_b[t0:t1], pw),
                jnp.pad(s2_b[t0:t1], pw),
                jnp.pad(n1_b[t0:t1], pw),
                jnp.pad(n2_b[t0:t1], pw),
                jnp.pad(tag_b[t0:t1], pw, constant_values=1.0),
                s_max=sb,
            )
            outs.append(lp[: t1 - t0])
        all_rows.append(flat)
        all_vals.append(jnp.concatenate(outs) if len(outs) > 1 else outs[0])
    if all_rows:
        j_log_p = j_log_p.reshape(-1).at[
            jnp.concatenate(all_rows)
        ].set(jnp.concatenate(all_vals)).reshape(P, G)

    if obs_quality.enabled():
        obs_quality.check_array("exact_test_log_p", j_log_p, kinds=("nan",),
                                where="edger_nb")
    prof.mark("exact_small")

    # ---- logFC from equalized abundances --------------------------------
    ab1 = s1 / np.maximum(n_of[pair_i][:, None], 1.0) + _LOGFC_PRIOR_COUNT
    ab2 = s2 / np.maximum(n_of[pair_j][:, None], 1.0) + _LOGFC_PRIOR_COUNT
    log_fc = np.log(ab1) - np.log(ab2)

    return EdgerPairResult(
        log_p=j_log_p,           # device on every path; lazy-fetched upstream
        log_fc=log_fc.astype(np.float32),
        common_disp=common,
        tagwise_disp=j_tagwise,  # device on every path; lazy-fetched upstream
    )
