from scconsensus_tpu.de.engine import (
    PairwiseDEResult,
    pairwise_de,
    filter_clusters,
    de_gene_union,
)

__all__ = ["PairwiseDEResult", "pairwise_de", "filter_clusters", "de_gene_union"]
