"""Direct per-pair NB reference engine (test oracle for ``de.edger``).

This is the round-2 production driver, retained verbatim as the small-scale
reference implementation: it equalizes library sizes per pair and evaluates
every conditional-likelihood grid densely over the pair's cells — the
literal shape of the reference pipeline (R/reclusterDEConsensus.R:123-156:
per pair, DGEList(group ±1) → estimateCommonDisp → estimateTagwiseDisp →
calcNormFactors("none") → exactTest). It is O(pairs × genes × cells ×
grid) and memory-unbounded in the pilot phase, so it is NOT reachable from
the production engine — ``de.edger`` (global equalization + node-table
grids) is validated against it in tests/test_edger_parity.py.

TPU shape of the computation (SURVEY.md §7 stage 4): cluster pairs are
bucketed by padded width exactly like the Wilcoxon path; genes ride a vmapped
chunk axis. Two device phases per bucket:

  phase 1 (pilot): on a strided gene subsample, equalize library sizes at the
    pilot dispersion 0.01, score the conditional log-likelihood over a φ grid,
    and take the per-pair qCML **common dispersion** (grid + quadratic refine
    stands in for R's optimize(); the subsample — the common dispersion is a
    single scalar pooled over thousands of genes — is a documented divergence
    from edgeR, which uses every gene passing the rowsum filter).

  phase 2 (full): re-equalize at the common dispersion, accumulate per-gene
    conditional-LL grids for the tagwise EB shrinkage, group pseudo-count
    sums, and the mean-expression/abundance numbers; then the Beta-Binomial
    exact test per gene.

Note the reference feeds *log-normalized* values to DGEList as if they were
counts (R/reclusterDEConsensus.R:133 passes `data` directly). Compat mode
reproduces that literal arithmetic; fixed mode tests on expm1(data).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.ops.negbin import (
    common_dispersion_grid,
    delta_grid,
    equalize_pseudo,
    nb_cond_log_lik,
    nb_exact_test_logp,
    tagwise_dispersion,
    TAGWISE_GRID_EXPONENTS,
)

__all__ = ["run_edger_pairs", "EdgerPairResult"]

_PILOT_DISPERSION = 0.01
_PILOT_MAX_GENES = 2048
_ROWSUM_FILTER = 5.0
_PRIOR_DF = 10.0
_LOGFC_PRIOR_COUNT = 0.125
_EXACT_SMAX = 4096
# Per-chunk element budget for (B, Gc, W) tiles (transcendental-heavy).
_NB_CHUNK_ELEMS = 8_000_000


@dataclasses.dataclass
class EdgerPairResult:
    log_p: np.ndarray      # (P, G)
    log_fc: np.ndarray     # (P, G) natural-log fold change group1 vs group2
    common_disp: np.ndarray  # (P,)
    tagwise_disp: np.ndarray  # (P, G)


@jax.jit
def _pilot_kernel(sub_counts, idx, m1, m2, lib_tile, common_lib, deltas):
    """Pilot-phase conditional-LL grid. sub_counts: (Gs, N); idx/m1/m2:
    (B, W); lib_tile: (B, W); common_lib: (B,); deltas: (D,).
    Returns (B, D) LL sums over filtered subsample genes."""
    y = jnp.swapaxes(jnp.take(sub_counts, idx, axis=1), 0, 1)  # (B, Gs, W)
    m1e = m1[:, None, :]
    m2e = m2[:, None, :]
    lib = lib_tile[:, None, :]
    ps = equalize_pseudo(
        y, lib, m1e, m2e, common_lib[:, None], jnp.float32(_PILOT_DISPERSION)
    )
    pooled = m1e | m2e
    z = jnp.sum(jnp.where(pooled, y, 0.0), axis=-1)  # (B, Gs)
    keep = z > _ROWSUM_FILTER

    def ll_at(delta):
        r = (1.0 - delta) / delta
        ll = nb_cond_log_lik(ps.pseudo, m1e, r) + nb_cond_log_lik(
            ps.pseudo, m2e, r
        )
        return jnp.sum(jnp.where(keep, ll, 0.0), axis=-1)  # (B,)

    grid = jax.lax.map(ll_at, deltas)  # (D, B)
    return grid.T


@jax.jit
def _pass2_kernel(chunk, idx, m1, m2, lib_tile, common_lib, common_disp):
    """Full-phase per-gene statistics at the common dispersion.

    chunk: (Gc, N); common_disp: (B,). Returns
    (s1, s2, ll_grid (B, Gc, T), keep (B, Gc))."""
    y = jnp.swapaxes(jnp.take(chunk, idx, axis=1), 0, 1)  # (B, Gc, W)
    m1e = m1[:, None, :]
    m2e = m2[:, None, :]
    lib = lib_tile[:, None, :]
    ps = equalize_pseudo(
        y, lib, m1e, m2e, common_lib[:, None], common_disp[:, None]
    )
    s1 = jnp.sum(jnp.where(m1e, ps.pseudo, 0.0), axis=-1)  # (B, Gc)
    s2 = jnp.sum(jnp.where(m2e, ps.pseudo, 0.0), axis=-1)
    pooled = m1e | m2e
    z = jnp.sum(jnp.where(pooled, y, 0.0), axis=-1)
    keep = z > _ROWSUM_FILTER

    def ll_at(expo):
        phi = common_disp[:, None] * jnp.exp2(expo)  # (B, 1)
        r = 1.0 / jnp.maximum(phi, 1e-10)
        return nb_cond_log_lik(ps.pseudo, m1e, r) + nb_cond_log_lik(
            ps.pseudo, m2e, r
        )  # (B, Gc)

    ll_grid = jax.lax.map(ll_at, TAGWISE_GRID_EXPONENTS)  # (T, B, Gc)
    return s1, s2, jnp.moveaxis(ll_grid, 0, -1), keep


def run_edger_pairs(
    counts: np.ndarray,
    buckets,
    n_genes: int,
    n_pairs: int,
) -> EdgerPairResult:
    """Run the NB pipeline for every bucketed pair.

    counts: (G, N) the matrix handed to DGEList (log-normalized data in
    compat mode — the reference's literal behavior — or expm1 of it); may be
    dense or scipy-sparse (gene chunks densified on demand);
    buckets: list of engine _PairBucket.
    """
    from scconsensus_tpu.io.sparsemat import (
        as_csr,
        is_sparse,
        padded_row_chunk,
        rows_dense,
    )

    sparse = is_sparse(counts)
    if sparse:
        counts = as_csr(counts)
    else:
        counts = np.ascontiguousarray(counts, np.float32)
    G = n_genes
    jcounts = None if sparse else jnp.asarray(counts)
    if sparse:
        lib_all = jnp.asarray(
            np.asarray(counts.sum(axis=0), np.float32).ravel()
        )
    else:
        lib_all = jnp.sum(jcounts, axis=0)  # (N,) library sizes

    log_p = np.full((n_pairs, G), np.nan, np.float32)
    log_fc = np.full((n_pairs, G), np.nan, np.float32)
    common_out = np.zeros(n_pairs, np.float32)
    tagwise_out = np.full((n_pairs, G), np.nan, np.float32)

    stride = max(1, G // _PILOT_MAX_GENES)
    sub_idx = np.arange(0, G, stride, dtype=np.int64)[:_PILOT_MAX_GENES]
    if sparse:
        jsub = jnp.asarray(rows_dense(counts, sub_idx))
    else:
        jsub = jcounts[jnp.asarray(sub_idx)]
    deltas = delta_grid(24)

    for bucket in buckets:
        B, W = bucket.cell_idx.shape
        idx = jnp.asarray(bucket.cell_idx)
        m1 = jnp.asarray(bucket.mask1)
        m2 = jnp.asarray(bucket.mask2)
        n1 = jnp.asarray(bucket.n1).astype(jnp.float32)
        n2 = jnp.asarray(bucket.n2).astype(jnp.float32)
        lib_tile = jnp.take(lib_all, idx)  # (B, W)
        pooled = bucket.mask1 | bucket.mask2
        # Geometric mean of the pooled cells' library sizes (common lib size).
        lib_np = np.asarray(lib_tile)
        with np.errstate(divide="ignore"):
            loglib = np.where(pooled, np.log(np.maximum(lib_np, 1e-30)), 0.0)
        common_lib = jnp.asarray(
            np.exp(loglib.sum(axis=1) / np.maximum(pooled.sum(axis=1), 1))
        )

        # Phase 1: pilot common dispersion.
        grid = _pilot_kernel(jsub, idx, m1, m2, lib_tile, common_lib, deltas)
        common = common_dispersion_grid(grid, deltas)  # (B,)
        common_out[bucket.rows] = np.asarray(common)

        # Phase 2: per-gene LL grids + pseudo sums, chunked over genes.
        from scconsensus_tpu.de.engine import _next_pow2

        gc = max(128, _NB_CHUNK_ELEMS // max(B * W, 1))
        gc = min(_next_pow2(gc), _next_pow2(G))
        s1_full = np.zeros((B, G), np.float32)
        s2_full = np.zeros((B, G), np.float32)
        ll_full = np.zeros((B, G, TAGWISE_GRID_EXPONENTS.shape[0]), np.float32)
        keep_full = np.zeros((B, G), bool)
        for g0 in range(0, G, gc):
            if sparse:
                chunk = jnp.asarray(padded_row_chunk(counts, g0, gc))
            else:
                chunk = jcounts[g0 : g0 + gc]
                if chunk.shape[0] < gc:
                    chunk = jnp.pad(chunk, ((0, gc - chunk.shape[0]), (0, 0)))
            s1, s2, ll_g, keep = _pass2_kernel(
                chunk, idx, m1, m2, lib_tile, common_lib, common
            )
            g1 = min(g0 + gc, G)
            s1_full[:, g0:g1] = np.asarray(s1)[:, : g1 - g0]
            s2_full[:, g0:g1] = np.asarray(s2)[:, : g1 - g0]
            ll_full[:, g0:g1] = np.asarray(ll_g)[:, : g1 - g0]
            keep_full[:, g0:g1] = np.asarray(keep)[:, : g1 - g0]

        # Tagwise EB shrinkage (prior.df = 10, trend="none" semantics).
        prior_n = jnp.asarray(
            _PRIOR_DF / np.maximum(bucket.n1 + bucket.n2 - 2, 1)
        ).astype(jnp.float32)
        tagwise = tagwise_dispersion(
            jnp.asarray(ll_full), common, prior_n, jnp.asarray(keep_full)
        )  # (B, G)
        tagwise_out[bucket.rows] = np.asarray(tagwise)

        # Exact test, chunked to bound the (B, Gc, s_max) tail tensor.
        # s_max adapts to the largest rounded total actually present (pow2 so
        # the jit cache stays small): in compat mode the "counts" are
        # log-normalized values whose sums are tiny, and a fixed 4096-wide
        # tail tensor would be ~10× wasted bandwidth on every platform.
        max_total = float(np.max(np.round(s1_full) + np.round(s2_full), initial=0.0))
        s_max = int(min(_EXACT_SMAX, _next_pow2(max(int(max_total) + 2, 64))))
        gce = max(64, _NB_CHUNK_ELEMS // max(B * s_max, 1))
        tagwise_np = np.asarray(tagwise)
        for g0 in range(0, G, gce):
            g1 = min(g0 + gce, G)
            pad = gce - (g1 - g0)
            pad_w = ((0, 0), (0, pad))
            lp = nb_exact_test_logp(
                jnp.asarray(np.pad(s1_full[:, g0:g1], pad_w)),
                jnp.asarray(np.pad(s2_full[:, g0:g1], pad_w)),
                n1[:, None],
                n2[:, None],
                jnp.asarray(np.pad(tagwise_np[:, g0:g1], pad_w, constant_values=1.0)),
                s_max=s_max,
            )
            log_p[bucket.rows, g0:g1] = np.asarray(lp)[:, : g1 - g0]

        # logFC (natural log) from equalized group abundances with the small
        # prior count (edgeR exactTest reports log2; the engine thresholds in
        # natural log — §2d-1's unit mismatch resolved explicitly here).
        ab1 = s1_full / np.maximum(bucket.n1[:, None], 1) + _LOGFC_PRIOR_COUNT
        ab2 = s2_full / np.maximum(bucket.n2[:, None], 1) + _LOGFC_PRIOR_COUNT
        log_fc[bucket.rows] = np.log(ab1) - np.log(ab2)

    return EdgerPairResult(
        log_p=log_p,
        log_fc=log_fc,
        common_disp=common_out,
        tagwise_disp=tagwise_out,
    )
