"""All-pairs differential-expression engine.

The reference fans the outer cluster index over R worker processes with a
triangular load imbalance (R/reclusterDEConsensusFast.R:61-65; SURVEY.md §3
E3). Here every statistic is computed for all K(K−1)/2 pairs at once from
per-cluster structures — the TPU equivalent of the reference's doParallel
backend (SURVEY.md §2b N10):

  * rank tests (wilcox/roc) ride the sorted-cumsum all-pairs engine
    (ops.ranksum_allpairs): one sort per gene, cross-cluster dominance
    counts via MXU contractions, zero per-pair gathers;
  * moment tests (bimod/t) and all gates come straight from the per-cluster
    aggregate matmuls (ops.gates, ops.seurat_tests) — per-cell data is
    touched exactly once;
  * the NB/edgeR path buckets pairs by padded width (de.edger).

Engine flow:
  1. cluster filter (count > min_cluster_size, drop 'grey'; reference
     R/reclusterDEConsensus.R:39-49),
  2. per-cluster aggregates: four matmuls against the membership one-hot,
  3. per-pair gates from aggregates (masks, no ragged selection),
  4. per-pair statistical test over gene chunks (device),
  5. per-pair BH (masked or explicit-n, per path semantics),
  6. DE call + top-N union.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.config import ReclusterConfig
from scconsensus_tpu.obs import quality as obs_quality
from scconsensus_tpu.ops.gates import (
    compute_aggregates_cid,
    pair_gates_fast,
    pair_gates_slow,
)
from scconsensus_tpu.ops.multipletests import bh_adjust, bh_adjust_masked
from scconsensus_tpu.ops.seurat_tests import bimod_lrt_pairs, welch_t_pairs
from scconsensus_tpu.ops.wilcoxon import EXACT_N_LIMIT, wilcoxon_exact_host

__all__ = ["PairwiseDEResult", "pairwise_de", "filter_clusters",
           "de_gene_union", "streaming_wilcox_block"]


@dataclasses.dataclass
class PairwiseDEResult:
    """Dense all-pairs DE summary (P = #pairs, G = #genes).

    The big (P, G) fields may be constructed as DEVICE arrays: each one
    materializes to numpy on first attribute access, per field. Through a
    slow device→host link (the axon tunnel moves ~36 MB/s) this matters:
    the refinement pipeline only ever touches ``de_mask`` and ``log_fc``
    (~70 MB at flagship scale), while an eager fetch of all seven arrays is
    ~310 MB — measured 38 s of the round-3 flagship wilcox wall-clock.
    Consumers always see plain numpy; persistence (``to_store``) touches
    every field and therefore materializes everything, as resume requires.
    """

    cluster_names: List[str]
    pair_i: np.ndarray  # (P,) index into cluster_names
    pair_j: np.ndarray
    log_p: np.ndarray   # (P, G); NaN where untested/degenerate
    log_q: np.ndarray   # (P, G); NaN where not adjusted
    log_fc: np.ndarray  # (P, G) natural-log fold change (path convention)
    tested: np.ndarray  # (P, G) bool: entered the statistical test
    de_mask: np.ndarray  # (P, G) bool: final DE call
    pair_skipped: np.ndarray = None  # (P,) bool: skipped by group-size validation
    pct1: Optional[np.ndarray] = None  # (P, G) fast path only
    pct2: Optional[np.ndarray] = None
    aux: Optional[Dict[str, np.ndarray]] = None  # extra per-test stats (AUC...)
    skip_reasons: Optional[List[str]] = None  # one per skipped pair

    # Fields allowed to arrive as device arrays (lazily fetched, see above).
    _LAZY_FIELDS = frozenset(
        {"log_p", "log_q", "log_fc", "tested", "de_mask", "pct1", "pct2"}
    )

    def __getattribute__(self, name):
        v = object.__getattribute__(self, name)
        if (
            name in PairwiseDEResult._LAZY_FIELDS
            and v is not None
            and not isinstance(v, np.ndarray)
        ):
            # Materialization mutates on read: serialize it so concurrent
            # readers (e.g. a background store.save racing the pipeline's
            # de_mask access) can't issue duplicate device_gets (ADVICE r3).
            with object.__getattribute__(self, "_fetch_lock"):
                v = object.__getattribute__(self, name)  # re-check under lock
                if not isinstance(v, np.ndarray):
                    from scconsensus_tpu.obs.residency import boundary

                    # declared crossing: a host consumer asked for this
                    # (P, G) field — the documented lazy materialization
                    with boundary("de_result_fetch"):
                        v = np.asarray(jax.device_get(v))
                    object.__setattr__(self, name, v)
        elif name == "aux" and v is not None and any(
            not isinstance(a, np.ndarray) for a in v.values()
        ):
            with object.__getattribute__(self, "_fetch_lock"):
                v = object.__getattribute__(self, name)
                if any(not isinstance(a, np.ndarray) for a in v.values()):
                    from scconsensus_tpu.obs.residency import boundary

                    with boundary("de_result_fetch"):
                        v = {k: np.asarray(a)
                             for k, a in jax.device_get(v).items()}
                    object.__setattr__(self, name, v)
        return v

    @property
    def n_pairs(self) -> int:
        return int(self.pair_i.shape[0])

    def de_counts(self) -> np.ndarray:
        """Per-pair DE gene counts (the reference's progress printout,
        R/reclusterDEConsensus.R:172-178 — here a returned metric)."""
        raw = object.__getattribute__(self, "de_mask")
        if not isinstance(raw, np.ndarray):
            from scconsensus_tpu.obs.residency import boundary

            # reduce on device: fetch P ints, not the (P, G) mask — the
            # allowlisted (P,)-sized funnel-count crossing
            with boundary("funnel_counts"):
                return np.asarray(jnp.sum(raw, axis=1))
        return raw.sum(axis=1)

    _ARRAY_FIELDS = ("pair_i", "pair_j", "log_p", "log_q", "log_fc",
                     "tested", "de_mask", "pair_skipped")
    _OPT_ARRAY_FIELDS = ("pct1", "pct2")

    def __post_init__(self):
        object.__setattr__(self, "_fetch_lock", threading.Lock())
        if self.pair_skipped is None:
            self.pair_skipped = np.zeros(self.pair_i.shape[0], bool)

    def _materialize_all(self) -> None:
        """Fetch every still-on-device lazy field in ONE batched device_get
        (per-field getattr would pay a blocking link round-trip each)."""
        with object.__getattribute__(self, "_fetch_lock"):
            pending = {
                f: object.__getattribute__(self, f)
                for f in self._LAZY_FIELDS
                if object.__getattribute__(self, f) is not None
                and not isinstance(object.__getattribute__(self, f), np.ndarray)
            }
            if pending:
                from scconsensus_tpu.obs.residency import boundary

                with boundary("de_result_fetch"):
                    for f, v in jax.device_get(pending).items():
                        object.__setattr__(self, f, np.asarray(v))

    def to_store(self) -> Tuple[Dict[str, np.ndarray], Dict]:
        """(arrays, meta) for ArtifactStore — the single serialization point,
        so the field list cannot drift from the dataclass."""
        self._materialize_all()
        arrays = {f: getattr(self, f) for f in self._ARRAY_FIELDS}
        for f in self._OPT_ARRAY_FIELDS:
            v = getattr(self, f)
            if v is not None:
                arrays[f] = v
        if self.aux:
            for k, v in self.aux.items():
                arrays[f"aux_{k}"] = np.asarray(v)
        return arrays, {
            "cluster_names": self.cluster_names,
            "skip_reasons": self.skip_reasons or [],
        }

    @classmethod
    def from_store(cls, arrays: Dict[str, np.ndarray], meta: Dict
                   ) -> "PairwiseDEResult":
        """Inverse of to_store. Raises ValueError on incomplete artifacts
        (e.g. a missing meta sidecar) so callers recompute instead of
        resuming into a corrupt state."""
        if "cluster_names" not in meta:
            raise ValueError("de artifact incomplete: missing cluster_names meta")
        # pair_skipped may be absent in stores written before group-size
        # validation existed; __post_init__ synthesizes the all-False default.
        required = [f for f in cls._ARRAY_FIELDS if f != "pair_skipped"]
        missing = [f for f in required if f not in arrays]
        if missing:
            raise ValueError(f"de artifact incomplete: missing arrays {missing}")
        aux = {
            k[len("aux_"):]: v for k, v in arrays.items() if k.startswith("aux_")
        }
        return cls(
            cluster_names=list(meta["cluster_names"]),
            **{f: arrays.get(f) for f in cls._ARRAY_FIELDS},
            **{f: arrays.get(f) for f in cls._OPT_ARRAY_FIELDS},
            aux=aux or None,
            skip_reasons=list(meta.get("skip_reasons", [])) or None,
        )


def filter_cluster_names(
    names: np.ndarray, counts: np.ndarray, min_cluster_size: int,
    drop_grey: bool = True
) -> List[str]:
    """The cluster-survival rule alone (count strictly greater than the
    floor, §2d-7; 'grey' substring dropped) over pre-computed unique
    names + counts — shared by :func:`filter_clusters` and the
    input-contract pre-flight, which already holds the unique pass and
    must not pay the O(N) per-cell index just to ask who survives.
    ``names``/``counts`` are ``np.unique(..., return_counts=True)``
    output over str-cast labels (host arrays by construction)."""
    keep = counts > min_cluster_size
    if drop_grey:
        keep &= np.char.find(names, "grey") == -1
    return [str(n) for n in names[keep]]


def filter_clusters(
    labels: Sequence, min_cluster_size: int, drop_grey: bool = True
) -> Tuple[List[str], np.ndarray]:
    """Clusters with count > min_cluster_size (strictly greater, §2d-7),
    'grey' substring dropped; returns (sorted names, per-cell index into
    names, -1 for dropped cells)."""
    lab = np.asarray(labels).astype(str)
    names, counts = np.unique(lab, return_counts=True)
    kept = filter_cluster_names(names, counts, min_cluster_size, drop_grey)
    index = {n: i for i, n in enumerate(kept)}
    cell_idx = np.array([index.get(v, -1) for v in lab], dtype=np.int32)
    return kept, cell_idx


def _all_pairs(k: int) -> Tuple[np.ndarray, np.ndarray]:
    ii, jj = np.triu_indices(k, k=1)
    return ii.astype(np.int32), jj.astype(np.int32)


def _next_pow2(x: int) -> int:
    return 1 << (int(x) - 1).bit_length()


def _expand_rows(sub: np.ndarray, ok_rows: np.ndarray, n_rows: int) -> np.ndarray:
    """Scatter per-run-pair results back onto the full pair axis; rows of
    pairs skipped by group-size validation stay NaN (float) / False (bool)."""
    if ok_rows.size == n_rows:
        return sub
    fill = False if sub.dtype == bool else np.nan
    out = np.full((n_rows,) + sub.shape[1:], fill, sub.dtype)
    out[ok_rows] = sub
    return out


def _expand_rows_any(sub, ok_rows: np.ndarray, n_rows: int):
    """``_expand_rows`` for host OR device arrays (device scatter keeps the
    result on device for the lazy-fetch result fields)."""
    if isinstance(sub, np.ndarray):
        return _expand_rows(sub, ok_rows, n_rows)
    if ok_rows.size == n_rows:
        return sub
    fill = False if sub.dtype == bool else np.nan
    out = jnp.full((n_rows,) + sub.shape[1:], fill, sub.dtype)
    return out.at[jnp.asarray(ok_rows)].set(sub)


@dataclasses.dataclass
class _PairBucket:
    rows: np.ndarray      # (B,) indices into the global pair list
    cell_idx: np.ndarray  # (B, W) gather indices into columns of data
    mask1: np.ndarray     # (B, W) group-1 membership among gathered cells
    mask2: np.ndarray
    n1: np.ndarray        # (B,)
    n2: np.ndarray


def _bucket_pairs(
    cell_idx_of: List[np.ndarray], pair_i: np.ndarray, pair_j: np.ndarray
) -> List[_PairBucket]:
    """Group pairs by padded width so each bucket runs with one static shape."""
    widths = {}
    for r in range(pair_i.shape[0]):
        w = _next_pow2(
            cell_idx_of[pair_i[r]].size + cell_idx_of[pair_j[r]].size
        )
        widths.setdefault(w, []).append(r)
    buckets = []
    for w, rows in sorted(widths.items()):
        B = len(rows)
        idx = np.zeros((B, w), np.int32)
        m1 = np.zeros((B, w), bool)
        m2 = np.zeros((B, w), bool)
        n1 = np.zeros(B, np.int32)
        n2 = np.zeros(B, np.int32)
        for b, r in enumerate(rows):
            ci = cell_idx_of[pair_i[r]]
            cj = cell_idx_of[pair_j[r]]
            idx[b, : ci.size] = ci
            idx[b, ci.size : ci.size + cj.size] = cj
            m1[b, : ci.size] = True
            m2[b, ci.size : ci.size + cj.size] = True
            n1[b], n2[b] = ci.size, cj.size
        buckets.append(_PairBucket(np.asarray(rows), idx, m1, m2, n1, n2))
    return buckets


def _cid_from_groups(cell_idx_of: List[np.ndarray], n_cells: int) -> np.ndarray:
    """Per-cell cluster index (−1 = excluded) from the per-cluster cell lists
    — the post-subsampling group definition every statistical test uses."""
    cid = np.full(n_cells, -1, np.int32)
    for k, ci in enumerate(cell_idx_of):
        cid[ci] = k
    return cid


def _gene_chunks(data, gc: int, jdata=None):
    """Yield (g0, g1, device chunk padded to gc rows). Sparse inputs densify
    one chunk at a time (never-densify contract, SURVEY.md §2b N12); dense
    callers pass the already-uploaded ``jdata`` so the matrix crosses
    host→device exactly once per pipeline run."""
    from scconsensus_tpu.io.sparsemat import is_sparse, padded_row_chunk
    from scconsensus_tpu.obs.residency import boundary as _rbound

    G = data.shape[0]
    sparse = is_sparse(data)
    if jdata is None and not sparse:
        with _rbound("input_staging"):
            jdata = jnp.asarray(data)
    for g0 in range(0, G, gc):
        if sparse:
            with _rbound("input_staging"):  # per-chunk sparse densify+upload
                chunk = jnp.asarray(padded_row_chunk(data, g0, gc))
        else:
            chunk = jdata[g0 : g0 + gc]
            if chunk.shape[0] < gc:
                chunk = jnp.pad(chunk, ((0, gc - chunk.shape[0]), (0, 0)))
        yield g0, min(g0 + gc, G), chunk


def _exact_host_update(
    log_p: np.ndarray, row: int, cols: np.ndarray, u_vals: np.ndarray,
    n1: int, n2: int,
) -> None:
    """Overwrite log_p[row, cols] with R's exact-branch p-values (one shared
    implementation so the policy and arithmetic cannot drift)."""
    pe = wilcoxon_exact_host(u_vals, n1, n2)
    log_p[row, cols] = np.log(pe).astype(np.float32)


def _redo_overflow_genes(parts, overflow, refetch, jn, jpi, jpj, K,
                         run_cap, probe=None):
    """Windowed path: re-route genes whose tie-run count overflowed the
    run-space table to the scan kernel and splice the corrected rows back
    into the collected block outputs. ONE batched n_runs fetch for all
    blocks, after every block has been dispatched — keeps the main loop's
    async pipelining intact (rare path: counts-derived data stays under
    the cap; continuous data overflows and pays one cheap wasted pass).
    ``refetch(ids, window)`` rebuilds kernel inputs for a gene subset —
    dense-device rows or CSR-compacted windows, the caller knows which."""
    from scconsensus_tpu.obs.residency import boundary as _rbound
    from scconsensus_tpu.ops.ranksum_allpairs import allpairs_ranksum_chunk

    with _rbound("overflow_redo"):
        all_nr = jax.device_get([nr for _, _, _, nr in overflow])
    for (idx, ids, weff, _), nr in zip(overflow, all_nr):
        bad = np.nonzero(nr[: ids.size] > run_cap)[0]
        if probe is not None and idx < len(probe.get("buckets", [])):
            probe["buckets"][idx]["overflow_genes"] = int(bad.size)
        if not bad.size:
            continue
        rows, kcid, win = refetch(ids[bad], weff)
        lp_r, u_r, ts_r = allpairs_ranksum_chunk(
            rows, kcid, jn, jpi, jpj, K, window=win,
        )
        sel = jnp.asarray(bad)
        ids0, (lp0, u0, ts0) = parts[idx]
        parts[idx] = (ids0, (
            lp0.at[sel].set(lp_r[: bad.size]),
            u0.at[sel].set(u_r[: bad.size]),
            ts0.at[sel].set(ts_r[: bad.size]),
        ))


def _redo_overflow_dense(outs, overflow, data, gc, jdata, jcid, jn, jpi,
                         jpj, K, run_cap):
    """Dense-path twin of ``_redo_overflow_genes``: chunks are re-
    materialized from the source matrix (sparse inputs densify the chunk
    again) and fully re-run through the scan kernel when any gene in the
    chunk overflowed — dense chunks are span-shaped, so per-gene splicing
    would re-gather anyway."""
    from scconsensus_tpu.io.sparsemat import is_sparse, padded_row_chunk
    from scconsensus_tpu.obs.residency import boundary as _rbound
    from scconsensus_tpu.ops.ranksum_allpairs import allpairs_ranksum_chunk

    with _rbound("overflow_redo"):
        all_nr = jax.device_get([nr for _, _, _, nr in overflow])
    sparse = is_sparse(data)
    if jdata is None and not sparse:
        # mirror _gene_chunks's defensive rebuild: its contract lets dense
        # callers omit jdata (it uploads on demand), and this redo twin
        # must not crash on the same inputs in the rare overflow case
        jdata = jnp.asarray(data)
    for (idx, g0, g1, _), nr in zip(overflow, all_nr):
        bad = np.nonzero(nr[: g1 - g0] > run_cap)[0]
        if not bad.size:
            continue
        if sparse:
            chunk = jnp.asarray(padded_row_chunk(data, g0, gc))
        else:
            chunk = jdata[g0: g0 + gc]
            if chunk.shape[0] < gc:
                chunk = jnp.pad(chunk, ((0, gc - chunk.shape[0]), (0, 0)))
        lp_r, u_r, ts_r = allpairs_ranksum_chunk(
            chunk, jcid, jn, jpi, jpj, K
        )
        sel = jnp.asarray(bad)
        _, _, (lp0, u0, ts0) = outs[idx]
        outs[idx] = (g0, g1, (
            lp0.at[sel].set(lp_r[sel]),
            u0.at[sel].set(u_r[sel]),
            ts0.at[sel].set(ts_r[sel]),
        ))


class _WilcoxCkpt:
    """Mid-stage checkpoint handle for the wilcox window ladder.

    Each completed ladder bucket persists its (log_p, u, ties[, n_runs])
    block into the pipeline's ArtifactStore under a content-addressed
    stage name (``de_wilcox_<sha>``: gene ids + window + kernel variant),
    so a SIGKILL mid-stage resumes from completed buckets instead of
    recomputing the whole DE stage — at 1M cells the stage is 59 % of
    the remaining wall and was all-or-nothing. The blocks are deleted by
    the pipeline once the covering ``de`` artifact lands; content
    addressing means a degraded re-entry (different block decomposition)
    can never resume the wrong genes. Gated by ``SCC_ROBUST_DE_CKPT``
    and only ever active when the run has an artifact store.
    """

    PREFIX = "de_wilcox_"

    def __init__(self, store, mesh=None):
        self.store = store
        self.mesh = mesh  # the RUN's mesh; blocks stamp it as provenance
        self.resumed = 0
        # shape-polymorphic resume bookkeeping: stored mesh shapes larger
        # than this run's, and the checkpoint bytes adopted from them
        self._resumed_shapes: Dict[tuple, int] = {}

    def key(self, ids: np.ndarray, window: int, variant: str) -> str:
        import hashlib

        h = hashlib.sha256()
        h.update(np.ascontiguousarray(ids, np.int64).tobytes())
        h.update(f":{window}:{variant}".encode())
        return f"{self.PREFIX}{h.hexdigest()[:16]}"

    def load(self, key: str):
        """(lp, u, ts, nr|None) as device arrays, or None (absent or
        quarantined-corrupt — recompute either way)."""
        from scconsensus_tpu.utils.artifacts import ArtifactCorrupt

        if not self.store.has(key):
            return None
        try:
            arrays, meta = self.store.load(key)
        except ArtifactCorrupt:
            return None
        if not all(k in arrays for k in ("lp", "u", "ts")):
            return None
        out = (jnp.asarray(arrays["lp"]), jnp.asarray(arrays["u"]),
               jnp.asarray(arrays["ts"]))
        nr = (jnp.asarray(arrays["nr"]) if "nr" in arrays else None)
        self.resumed += 1
        self._track_shape(meta)
        return out, nr

    def _track_shape(self, meta) -> None:
        """Remember a resumed block written on a LARGER mesh than this
        run's (one entry per distinct shape; bytes accumulate) so the
        ladder can stamp the shape-polymorphic crossing once. The
        crossing rule itself lives in robust.elastic — one rule, every
        consumer."""
        from scconsensus_tpu.parallel.mesh import mesh_device_ids
        from scconsensus_tpu.robust.elastic import resume_crossing_from_ids

        from_ids = resume_crossing_from_ids(
            meta, mesh_device_ids(self.mesh)
        )
        if from_ids is None:
            return  # same mesh, growth, or no stamp — not a crossing
        size = int(((meta or {}).get("_integrity") or {}).get("size") or 0)
        from_t = tuple(from_ids)
        self._resumed_shapes[from_t] = (
            self._resumed_shapes.get(from_t, 0) + size
        )

    def note_transitions(self) -> None:
        """Stamp one ``cause: "resume"`` mesh transition per distinct
        larger-mesh shape the resumed blocks were written on — the
        ledger evidence that an 8-device checkpoint ladder re-entered on
        this run's smaller mesh."""
        if not self._resumed_shapes:
            return
        from scconsensus_tpu.parallel.mesh import mesh_device_ids
        from scconsensus_tpu.robust import elastic as robust_elastic
        from scconsensus_tpu.robust import record as robust_record

        if not robust_elastic.elastic_enabled():
            return
        to_ids = mesh_device_ids(self.mesh)
        for from_t, nbytes in sorted(self._resumed_shapes.items()):
            robust_record.note_mesh_transition(
                stage="wilcox_test", from_devices=list(from_t),
                to_devices=to_ids, recovered_state_bytes=nbytes,
                cause="resume",
            )

    def save(self, key: str, ids_n: int, out, nr) -> None:
        """Persist one completed bucket (trimmed to the real gene rows),
        stamped with the mesh shape it was computed on (the resume side
        reads the stamp to record shape-polymorphic crossings). The
        (Gb, P) fetch is a declared residency crossing — the cost of
        mid-stage durability, paid only when a store is active."""
        from scconsensus_tpu.obs.residency import boundary as _rbound
        from scconsensus_tpu.parallel.mesh import mesh_shape_meta

        arrays = {}
        with _rbound("de_ckpt_fetch"):
            lp, u, ts = jax.device_get(
                (out[0][:ids_n], out[1][:ids_n], out[2][:ids_n])
            )
            arrays = {"lp": np.asarray(lp), "u": np.asarray(u),
                      "ts": np.asarray(ts)}
            if nr is not None:
                arrays["nr"] = np.asarray(jax.device_get(nr[:ids_n]))
        self.store.save(key, arrays,
                        meta={"mesh_shape": mesh_shape_meta(self.mesh)})


class _LadderRecovery:
    """Loop-level typed recovery for the wilcox window ladder.

    Used as ``with recover, obs_trace.span("wilcox_bucket", ...):`` — on
    an Exception escaping the bucket it classifies (robust.retry), and
    when admissible suppresses the exception, sets ``retry`` (the loop
    re-enters at the same g0 — i.e. from the last completed bucket), and
    for resource-class failures doubles ``budget_div``, adaptively
    halving every later block's element budget. Fatal errors, exhausted
    per-bucket attempts, and an exhausted per-run budget re-raise.
    KeyboardInterrupt/SystemExit pass through untouched.
    """

    MAX_BUCKET_ATTEMPTS = 4
    MAX_BUDGET_DIV = 64

    def __init__(self, site: str = "wilcox_bucket"):
        from scconsensus_tpu.robust import retry as robust_retry

        self.site = site
        self.budget_div = 1
        self.attempt = 0          # retries consumed by the current bucket
        self.backoff_total = 0.0
        self.retry = False
        self.err_class: Optional[str] = None
        self._policy = robust_retry.default_policy()

    def bucket_done(self) -> None:
        """Called when the current bucket lands: close out its retry
        bookkeeping (a recovered bucket records one aggregated entry)."""
        from scconsensus_tpu.robust import record as robust_record

        if self.attempt:
            robust_record.note_retry(
                self.site, self.err_class or "transient", self.attempt + 1,
                recovered=True, backoff_s=self.backoff_total,
            )
            if self.err_class == "silent_corruption":
                # the corrupted bucket recomputed clean — recompute-the-
                # unit is the silent_corruption recovery, and this is
                # its evidence on the integrity section
                from scconsensus_tpu.robust import (
                    integrity as robust_integrity,
                )

                robust_integrity.current().note_recompute()
                robust_integrity.current().reset_streak(self.site)
        self.attempt = 0
        self.backoff_total = 0.0

    def __enter__(self):
        self.retry = False
        return self

    def __exit__(self, et, ev, tb) -> bool:
        import time as _time

        from scconsensus_tpu.obs import trace as obs_trace
        from scconsensus_tpu.robust import record as robust_record
        from scconsensus_tpu.robust import retry as robust_retry

        if et is None or not issubclass(et, Exception):
            return False
        err_class = robust_retry.classify_exception(ev)
        run = robust_record.current_run()
        if err_class == "device_lost":
            # the ladder cannot rebuild its own mesh: propagate to the
            # stage-level guard, whose elastic supervisor shrinks the
            # mesh and re-enters the WHOLE stage — completed buckets
            # short-circuit through their checkpoints, so the re-entry
            # resumes from exactly where the mesh died (no note_retry
            # here: the stage-level policy records the recovery)
            return False
        if err_class == "silent_corruption":
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )

            if robust_integrity.should_evict(self.site):
                # repeated miscompute at this site: the in-place
                # recompute keeps producing corrupt answers, so the
                # right adaptation is the elastic one — propagate to
                # the stage guard, whose device-loss hook shrinks the
                # mesh off the suspect chip (completed buckets resume
                # through their checkpoints, exactly like device loss)
                return False
        if (err_class == "fatal"
                or self.attempt >= self.MAX_BUCKET_ATTEMPTS
                or not run.budget_take()):
            if err_class != "fatal":
                robust_record.note_retry(
                    self.site, err_class, self.attempt + 1,
                    recovered=False, backoff_s=self.backoff_total,
                )
            return False
        self.attempt += 1
        self.err_class = err_class
        if (err_class == "resource"
                and self.budget_div < self.MAX_BUDGET_DIV):
            self.budget_div *= 2
            robust_record.note_degradation(
                self.site, "halve-chunk-budget",
                f"element budget /{self.budget_div} after "
                f"{et.__name__}; re-entering from the last completed "
                "bucket",
            )
        backoff = self._policy.backoff_s(self.site, self.attempt)
        self.backoff_total += backoff
        sp = obs_trace.current_span()
        if sp is not None:
            sp.metrics.counter("robust_retries").add(1)
        with obs_trace.span(
            "robust_retry", site=self.site, error_class=err_class,
            attempt=self.attempt, backoff_s=round(backoff, 4),
        ):
            _time.sleep(backoff)
        self.retry = True
        return True


def _wilcox_ckpt_for(config_store, mesh=None) -> Optional[_WilcoxCkpt]:
    """The ladder's checkpoint handle: store present + flag on."""
    from scconsensus_tpu.config import env_flag

    if (config_store is not None and getattr(config_store, "enabled", False)
            and env_flag("SCC_ROBUST_DE_CKPT")):
        return _WilcoxCkpt(config_store, mesh=mesh)
    return None


def _window_floor(n_cells: int) -> int:
    """Window-ladder floor: 1024 bounds the distinct compiled shapes (cold
    compiles cross the remote-compile tunnel) and scans below 1k lanes are
    dispatch-bound anyway; at large N the floor rises (N/256, capped 16k)
    so the sparse tail of the ladder doesn't shatter into dispatch-bound
    microbuckets — at N = 1M the floor is 4096 (occupancy-probe finding,
    PROFILE_r06_wilcox_1m)."""
    return int(min(max(1024, _next_pow2(max(n_cells // 256, 1))), 16384))


def _run_wilcox_device(
    data: np.ndarray,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    exact: str = "auto",
    mesh=None,
    jdata=None,
    probe_out: Optional[Dict] = None,
    ckpt: Optional[_WilcoxCkpt] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank-sum for every (pair, gene) via the all-pairs sorted-cumsum
    engine (ops.ranksum_allpairs — one sort per gene, zero per-pair
    gathers). Returns DEVICE arrays (log_p (P, G), u (P, G)) — device→host
    transfer through the axon tunnel runs at ~36 MB/s, so results stay on
    device until the caller's single batched fetch.

    ``exact``: 'auto' applies R's exact branch on host for pairs with both
    groups < 50 cells and tie-free genes (only those pairs' rows are
    fetched); 'never' keeps everything on the normal-approximation device
    path. ``mesh``: optional device mesh — gene chunks are sharded across
    it (genes are embarrassingly parallel).

    Window ladder: genes bucket by nonzero count onto a pow-2 window
    ladder (floor `_window_floor(N)`) and each bucket runs the rank-sum
    kernel at its own window width (zero-block decomposition,
    ops.ranksum_allpairs) — expression data is mostly zeros, so most genes
    pay a fraction of the full N-cell scan. Dense device input measures
    nnz on device and sorts full-N rows per bucket; CSR input (r6) builds
    PRE-COMPACTED windows holding only each gene's stored entries
    (io.sparsemat.csr_window_rows), so the sort itself shrinks from N to
    ~nnz — the lever the r5 1M artifact was missing (its sparse input
    bypassed the ladder entirely and paid 2765 s of full-width sorts).

    ``probe_out``: optional dict or Span (e.g. the wilcox stage's tracer
    span) — receives an ``occupancy`` sub-dict with per-bucket gene
    counts, window widths, padded-vs-real element ratios, tied-run table
    heights and overflow counts. Each ladder bucket additionally runs
    inside a ``wilcox_bucket`` child span carrying the same quantities as
    first-class gauges (obs.metrics), with ladder-level histograms
    aggregated onto the stage span. With SCC_WILCOX_PROBE=1 (env-flag
    registry, config.py) each bucket is additionally synced and walled
    (serializes dispatch — diagnosis runs only), and tied-run counts + a
    separate sort-only timing are fetched per bucket so sort cost is
    split out of the contraction attribution.

    ``ckpt``: optional :class:`_WilcoxCkpt` — each completed ladder
    bucket persists its output block so a killed run resumes from
    completed buckets (robust round: mid-stage checkpoint/resume). The
    ladder additionally runs under :class:`_LadderRecovery`: transient
    failures retry a bucket in place, RESOURCE_EXHAUSTED halves the
    element budget and re-enters from the last completed bucket.
    """
    import time

    from scconsensus_tpu.config import env_flag
    from scconsensus_tpu.obs import trace as obs_trace
    from scconsensus_tpu.obs.cost import attach_cost
    from scconsensus_tpu.io.sparsemat import csr_window_rows, is_sparse
    from scconsensus_tpu.ops.ranksum_allpairs import (
        _ALLPAIRS_ELEM_BUDGET,
        RUN_CAP,
        allpairs_ranksum_chunk,
        allpairs_ranksum_runspace_chunk,
        chunk_genes_for_budget,
        sort_probe,
    )

    G, N = data.shape
    K = len(cell_idx_of)
    n_of = np.array([ci.size for ci in cell_idx_of], np.int32)
    cid = _cid_from_groups(cell_idx_of, N)
    jcid = jnp.asarray(cid)
    jn = jnp.asarray(n_of)
    jpi = jnp.asarray(pair_i)
    jpj = jnp.asarray(pair_j)
    gc = chunk_genes_for_budget(N, K)
    gc = min(gc, _next_pow2(G))
    # Tied-run kernel on the single-device CPU path: the scan kernel's
    # cummax/cummin fills lower to ~43 ns/element scans on XLA:CPU (92 % of
    # its wall there, ROUND5_NOTES.md) while the tied-run formulation needs
    # one cumsum + scatter-built per-run tables; genes whose tied-run count
    # overflows the table are re-run through the scan kernel below. TPU
    # keeps the scan body everywhere (its scan lowerings are fast, the
    # layout was tuned on v5e, and TPU scatters are not); the mesh path
    # likewise (one shard_mapped program, no host redo round-trip).
    use_runspace = (
        mesh is None
        and jax.default_backend() == "cpu"
        and not env_flag("SCC_NO_RUNSPACE")
    )
    if mesh is not None:
        from scconsensus_tpu.parallel.sharded_de import sharded_allpairs_ranksum

        n_dev = int(mesh.devices.size)
        gc = max(gc, n_dev * 8)
    # live device ids for the corruption fault class's device-pinned
    # rules (robust.faults.corrupt_value): a rule modeling one bad chip
    # stops firing once the elastic supervisor evicts that chip
    live_dev_ids = ([int(d.id) for d in mesh.devices.flat]
                    if mesh is not None else [0])

    sparse_in = is_sparse(data)
    windowed = False
    src = None
    if jdata is not None:
        # nnz over ALL cells (excluded cells still occupy window slots) and
        # a negativity check (the decomposition needs zeros as the minimum).
        # Declared crossing (TODO(item-2)): O(G) ints to plan the ladder.
        from scconsensus_tpu.obs.residency import boundary as _rbound

        with _rbound("wilcox_ladder_plan"):
            nnz_g, any_neg = jax.device_get((
                jnp.sum(jdata > 0, axis=1), jnp.any(jdata < 0)
            ))
        windowed = not bool(any_neg)
        src = "dense-device"
    elif sparse_in:
        # CSR route: stored-entry counts bound the window (explicit zeros
        # waste a slot but stay inert — the kernel masks them) and the
        # negativity check reads only the value array.
        any_neg = bool(data.nnz and data.data.min() < 0)
        if not any_neg:
            nnz_g = np.diff(data.indptr).astype(np.int64)
            windowed = True
            src = "csr-compacted"

    probe_on = bool(env_flag("SCC_WILCOX_PROBE"))
    probe: Optional[Dict] = None
    if probe_out is not None:
        probe = {
            "windowed": bool(windowed),
            "input": src or ("sparse-chunked" if sparse_in else "dense"),
            "kernel": ("mesh-scan" if mesh is not None
                       else "runspace" if use_runspace else "scan"),
            "n_genes": int(G), "n_cells": int(N), "n_clusters": int(K),
            "probe_synced": probe_on,
            "buckets": [],
        }
        probe_out["occupancy"] = probe

    if windowed:
        floor = _window_floor(N)
        if probe is not None:
            probe["window_floor"] = floor
        order = np.argsort(nnz_g, kind="stable").astype(np.int64)
        nnz_sorted = nnz_g[order]
        compact = src == "csr-compacted"

        def refetch(ids_bad: np.ndarray, window: int):
            """Kernel inputs for a gene subset (the overflow redo path)."""
            pad_to = _next_pow2(max(int(ids_bad.size), 256))
            if compact:
                vals, wcid = csr_window_rows(
                    data, ids_bad, window, cid, pad_rows=pad_to
                )
                return jnp.asarray(vals), jnp.asarray(wcid), window
            rows = jnp.take(jdata, jnp.asarray(ids_bad), axis=0)
            if ids_bad.size < pad_to:
                rows = jnp.pad(rows, ((0, pad_to - ids_bad.size), (0, 0)))
            return rows, jcid, window

        # Typed recovery for the ladder (robust.retry policy semantics,
        # as a loop-level context manager because recovery here means
        # RE-ENTERING the loop at the last completed bucket): a
        # resource-class failure halves the element budget (-> smaller
        # gene blocks, smaller sort buffers) and retries from g0; a
        # transient failure retries the bucket unchanged; fatal
        # re-raises. Every retry burns the per-run budget and is
        # recorded as a span event + counter.
        recover = _LadderRecovery()
        parts = []  # (gene_ids, (log_p, u, ties)) in sorted-gene order
        overflow = []  # (part idx, ids, window, device n_runs)
        t_ladder = time.perf_counter()
        g0 = 0
        while g0 < G:
            elem_budget = max(
                _ALLPAIRS_ELEM_BUDGET // recover.budget_div, 1 << 12
            )
            w = int(min(_next_pow2(max(int(nnz_sorted[g0]), floor)),
                        _next_pow2(N)))
            # the width every (Gc, K, ·) scan/contraction tensor runs at:
            # compacted chunks are w wide and the kernel runs the full w
            # even when w > N (pow-2 rounding); dense chunks clamp to N
            scan_w = w if compact else min(w, N)
            # compacted rows sort only the window; dense rows sort full N
            sort_w = w if compact else N
            # block size respects BOTH working sets: the (gcb, K, scan_w)
            # kernel tensors and the (gcb, sort_w) sort buffers — w·K alone
            # ignores the sort and could pad a small-K run to a >10 GB sort.
            gcb = max(8, min(
                elem_budget // max(scan_w * K, 1),
                (elem_budget // 2) // max(sort_w, 1),
            ))
            gcb = 1 << (int(gcb).bit_length() - 1)
            gcb = min(gcb, _next_pow2(G))
            # every gene in the block must fit the block's window
            g1 = g0
            while (g1 < G and g1 - g0 < gcb
                   and (w >= N or nnz_sorted[g1] <= w)):
                g1 += 1
            ids = order[g0:g1]
            # pad to the pow-2 of the ACTUAL block population, not the full
            # budget: a 50-gene window bucket must not sort/scan thousands
            # of padded rows (same fix as the NB exact-task chunks). Floor
            # 256 bounds the distinct compiled (gcb, w) shapes — each cold
            # compile crosses the remote-compile tunnel (cf. the window
            # floor above)
            gcb_eff = min(gcb, _next_pow2(max(int(ids.size), 256)))
            # Mid-stage resume: a bucket persisted by a prior (killed)
            # run short-circuits here. Content-addressed keys (gene ids
            # + window + kernel variant), so a degraded re-entry with
            # different block boundaries can only hit blocks holding
            # exactly these genes at this window.
            weff_pre = w if compact else (w if w < N else 0)
            ck_key = None
            if ckpt is not None:
                ck_key = ckpt.key(
                    ids, weff_pre,
                    "mesh" if mesh is not None
                    else "runspace" if use_runspace else "scan",
                )
                cached_part = ckpt.load(ck_key)
                if cached_part is not None:
                    out, nr_cached = cached_part
                    parts.append((ids, out))
                    if use_runspace and nr_cached is not None:
                        overflow.append(
                            (len(parts) - 1, ids, weff_pre, nr_cached)
                        )
                    g0 = g1
                    recover.bucket_done()
                    continue
            nr_b = None
            t_bucket = time.perf_counter()
            with recover, obs_trace.span(
                "wilcox_bucket", window=int(w), n_genes=int(ids.size),
            ) as bspan:
                from scconsensus_tpu.robust.faults import fault_point

                fault_point("wilcox_bucket")
                if compact:
                    vals, wcid = csr_window_rows(
                        data, ids, w, cid, pad_rows=gcb_eff
                    )
                    from scconsensus_tpu.obs.residency import (
                        boundary as _rb,
                    )

                    with _rb("input_staging"):  # compacted-window upload
                        rows = jnp.asarray(vals)
                        # the mesh path pads/uploads cid itself (int-
                        # preserving, sharded_de) — uploading here would
                        # round-trip it back to host first
                        kcid = (wcid if mesh is not None
                                else jnp.asarray(wcid))
                    weff = w  # compacted input ALWAYS runs zero-block mode
                else:
                    rows = jnp.take(jdata, jnp.asarray(ids), axis=0)
                    if ids.size < gcb_eff:
                        rows = jnp.pad(
                            rows, ((0, gcb_eff - ids.size), (0, 0))
                        )
                    kcid = jcid
                    weff = w if w < N else 0
                nr_b = None
                if mesh is not None:
                    out = sharded_allpairs_ranksum(
                        rows, kcid, jn, jpi, jpj, K, mesh=mesh, window=weff,
                    )
                elif use_runspace:
                    attach_cost(bspan, allpairs_ranksum_runspace_chunk,
                                rows, kcid, jn, jpi, jpj, K, window=weff)
                    lp_b, u_b, ts_b, nr_b = allpairs_ranksum_runspace_chunk(
                        rows, kcid, jn, jpi, jpj, K, window=weff,
                    )
                    out = (lp_b, u_b, ts_b)
                    # overflow entry appended AFTER the recovery check
                    # below: a retried bucket must not leave a stale
                    # (idx, ids, nr) that the redo would splice into the
                    # re-entered (possibly smaller) block
                else:
                    attach_cost(bspan, allpairs_ranksum_chunk,
                                rows, kcid, jn, jpi, jpj, K, window=weff)
                    out = allpairs_ranksum_chunk(
                        rows, kcid, jn, jpi, jpj, K, window=weff,
                    )
                # Computation-integrity tier (robust.integrity, r18):
                # the injected in-computation corruption site, the
                # fused rank-sum conservation invariant, and — on the
                # seeded sample bucket of each window rung — the
                # float64 ghost replay. All inside the recovery
                # context: a detection raises typed silent_corruption
                # and the bucket recomputes (repeated detection
                # propagates to the elastic eviction path).
                from scconsensus_tpu.robust import (
                    integrity as robust_integrity,
                )
                from scconsensus_tpu.robust.faults import corrupt_value

                out = corrupt_value("wilcox_bucket_out", out,
                                    live_devices=live_dev_ids)
                if robust_integrity.enabled():
                    robust_integrity.check_wilcox_bucket(
                        "wilcox_bucket", out[0], out[1], out[2],
                        n_of[pair_i], n_of[pair_j],
                    )
                    if robust_integrity.current().want_replay(
                            "wilcox", int(w)):
                        robust_integrity.replay_wilcox_window(
                            "wilcox_bucket", f"window:{int(w)}",
                            vals if compact else rows,
                            wcid if compact else kcid,
                            n_of, pair_i, pair_j,
                            out[0], out[1], int(ids.size),
                            full_rows=not compact,
                        )
                # the former SCC_WILCOX_PROBE payload, as first-class span
                # metrics (always on — these are cheap host-side stats)
                real = int(nnz_sorted[g0:g1].sum())
                padded = int(gcb_eff) * int(scan_w)
                pad_ratio = round(padded / max(real, 1), 3)
                bm = bspan.metrics
                bm.gauge("window").set(int(w))
                bm.gauge("scan_width").set(int(scan_w))
                bm.gauge("sort_width").set(int(sort_w))
                bm.gauge("padded_rows").set(int(gcb_eff))
                bm.gauge("pad_ratio").set(pad_ratio)
                bm.gauge("nnz_min").set(int(nnz_sorted[g0]))
                bm.gauge("nnz_max").set(int(nnz_sorted[g1 - 1]))
                bm.counter("genes").add(int(ids.size))
                bm.counter("real_elems").add(real)
                bm.counter("padded_elems").add(padded)
                if probe is not None:
                    brec = {
                        "window": int(w), "scan_width": int(scan_w),
                        "sort_width": int(sort_w), "n_genes": int(ids.size),
                        "padded_rows": int(gcb_eff),
                        "real_elems": real,
                        "padded_elems": padded,
                        "pad_ratio": pad_ratio,
                        "nnz_min": int(nnz_sorted[g0]),
                        "nnz_max": int(nnz_sorted[g1 - 1]),
                        "table_height": int(min(
                            RUN_CAP, 1 << max(scan_w // 2 - 1, 1).bit_length()
                        )) if use_runspace else None,
                        "overflow_genes": 0,
                    }
                    if probe_on:
                        jax.block_until_ready(out)
                        brec["wall_s"] = round(
                            time.perf_counter() - t_bucket, 4
                        )
                        # split the sort out of the contraction attribution:
                        # time the same rows through a sort-only jit — warmed
                        # untimed first, since every bucket shape is distinct
                        # and a cold compile inside the timed region would
                        # inflate every sort_s in the committed PROFILE
                        jax.block_until_ready(sort_probe(rows, kcid))
                        t_s = time.perf_counter()
                        jax.block_until_ready(sort_probe(rows, kcid))
                        brec["sort_s"] = round(time.perf_counter() - t_s, 4)
                        if nr_b is not None:
                            from scconsensus_tpu.obs.residency import (
                                boundary as _rbound,
                            )

                            # SCC_WILCOX_PROBE diagnosis fetch — measurement
                            # overhead, billed to the obs boundary
                            with _rbound("obs_internal"):
                                nr = np.asarray(
                                    jax.device_get(nr_b)
                                )[: ids.size]
                            if nr.size:
                                brec["tied_runs_p50"] = int(np.median(nr))
                                brec["tied_runs_max"] = int(nr.max())
                    probe["buckets"].append(brec)
            if recover.retry:
                # recovered failure: re-enter at the same g0 (the last
                # completed bucket) with the possibly-halved budget
                continue
            if ckpt is not None:
                try:
                    ckpt.save(ck_key, int(ids.size), out,
                              nr_b if use_runspace else None)
                except Exception as e:
                    # the durability feature must never become a new
                    # fatal failure mode: a full disk / unwritable store
                    # skips THIS block's checkpoint and the ladder keeps
                    # computing (resume just recomputes the block)
                    from scconsensus_tpu.robust import (
                        record as robust_record,
                    )

                    robust_record.note_degradation(
                        "wilcox_bucket", "ckpt-skip",
                        f"bucket checkpoint write failed ({e!r}); "
                        "continuing without mid-stage durability for "
                        "this block",
                    )
            if use_runspace and nr_b is not None:
                overflow.append((len(parts), ids, weff, nr_b))
            parts.append((ids, out))
            g0 = g1
            recover.bucket_done()
        if ckpt is not None and ckpt.resumed:
            from scconsensus_tpu.robust import record as robust_record

            robust_record.note_resume_point(
                "wilcox_test", "bucket", ckpt.resumed, len(parts)
            )
            # blocks written on a larger mesh: stamp the shape-
            # polymorphic crossing (one transition per stored shape)
            ckpt.note_transitions()
        if use_runspace and overflow:
            _redo_overflow_genes(
                parts, overflow, refetch, jn, jpi, jpj, K, RUN_CAP,
                probe=probe,
            )
        if probe is not None and probe_on:
            jax.block_until_ready([o for _, o in parts])
            probe["ladder_wall_s"] = round(time.perf_counter() - t_ladder, 4)
        if probe is not None and hasattr(probe_out, "metrics"):
            # ladder-level aggregates on the wilcox stage span: the
            # occupancy payload's distributional view as typed metrics
            sm = probe_out.metrics
            sm.counter("ladder_buckets").add(len(probe["buckets"]))
            sm.counter("genes").add(
                sum(b["n_genes"] for b in probe["buckets"])
            )
            hw = sm.histogram("bucket_window")
            hp = sm.histogram("bucket_pad_ratio")
            for b in probe["buckets"]:
                hw.observe(b["window"])
                hp.observe(b["pad_ratio"])
        inv = np.empty(G, np.int64)
        inv[np.concatenate([ids for ids, _ in parts])] = np.arange(G)
        jinv = jnp.asarray(inv)
        # concat in sorted order, un-permute rows once, transpose to (P, G)
        log_p = jnp.take(jnp.concatenate(
            [o[0][: ids.size] for ids, o in parts], axis=0
        ), jinv, axis=0).T
        u_stat = jnp.take(jnp.concatenate(
            [o[1][: ids.size] for ids, o in parts], axis=0
        ), jinv, axis=0).T
        outs = None
    else:
        from scconsensus_tpu.robust import integrity as robust_integrity
        from scconsensus_tpu.robust.faults import corrupt_value

        outs = []
        overflow = []  # (outs idx, g0, g1, device n_runs)
        for g0, g1, chunk in _gene_chunks(data, gc, jdata=jdata):
            with obs_trace.span(
                "wilcox_chunk", g0=int(g0), g1=int(g1),
            ) as csp:
                csp.metrics.counter("genes").add(int(g1 - g0))
                if mesh is not None:
                    cout = sharded_allpairs_ranksum(
                        chunk, jcid, jn, jpi, jpj, K, mesh=mesh
                    )
                elif use_runspace:
                    attach_cost(csp, allpairs_ranksum_runspace_chunk,
                                chunk, jcid, jn, jpi, jpj, K)
                    lp_b, u_b, ts_b, nr_b = allpairs_ranksum_runspace_chunk(
                        chunk, jcid, jn, jpi, jpj, K
                    )
                    overflow.append((len(outs), g0, g1, nr_b))
                    cout = (lp_b, u_b, ts_b)
                else:
                    attach_cost(csp, allpairs_ranksum_chunk,
                                chunk, jcid, jn, jpi, jpj, K)
                    cout = allpairs_ranksum_chunk(
                        chunk, jcid, jn, jpi, jpj, K
                    )
                # integrity tier on the non-windowed chunk path: same
                # corruption site, conservation invariant, and one
                # sampled ghost replay per run (rung key "chunk")
                cout = corrupt_value("wilcox_bucket_out", cout,
                                     live_devices=live_dev_ids)
                if robust_integrity.enabled():
                    robust_integrity.check_wilcox_bucket(
                        "wilcox_bucket", cout[0], cout[1], cout[2],
                        n_of[pair_i], n_of[pair_j],
                    )
                    if robust_integrity.current().want_replay(
                            "wilcox", "chunk"):
                        robust_integrity.replay_wilcox_window(
                            "wilcox_bucket", f"chunk:{int(g0)}",
                            chunk, jcid, n_of, pair_i, pair_j,
                            cout[0], cout[1], int(g1 - g0),
                            full_rows=True,
                        )
                outs.append((g0, g1, cout))
        if use_runspace and overflow:
            _redo_overflow_dense(
                outs, overflow, data, gc, jdata, jcid, jn, jpi, jpj, K,
                RUN_CAP,
            )
        log_p = jnp.concatenate(
            [lp[: g1 - g0] for g0, g1, (lp, _, _) in outs], axis=0
        ).T  # (P, G)
        u_stat = jnp.concatenate(
            [u[: g1 - g0] for g0, g1, (_, u, _) in outs], axis=0
        ).T

    if exact == "auto":
        small = np.nonzero(
            (n_of[pair_i] < EXACT_N_LIMIT) & (n_of[pair_j] < EXACT_N_LIMIT)
        )[0]
        if small.size:
            from scconsensus_tpu.obs.residency import boundary as _rbound

            # Fetch only the small pairs' rows (u + tie indicator) —
            # R's exact branch runs on host by statistical design
            # (declared boundary, obs.residency.BOUNDARIES).
            with _rbound("exact_small_pairs"):
                if outs is None:
                    ties = jnp.take(jnp.concatenate(
                        [o[2][: ids.size] for ids, o in parts], axis=0
                    ), jinv, axis=0).T
                else:
                    ties = jnp.concatenate(
                        [ts[: g1 - g0] for g0, g1, (_, _, ts) in outs],
                        axis=0,
                    ).T
                rows = jnp.asarray(small)
                u_small, tie_small = jax.device_get(
                    (u_stat[rows], ties[rows])
                )
                lp_small = np.array(log_p[rows])  # writable host copy
                for r, p in enumerate(small):
                    tiefree = tie_small[r] == 0
                    if tiefree.any():
                        cols = np.nonzero(tiefree)[0]
                        _exact_host_update(
                            lp_small, r, cols, u_small[r][tiefree],
                            int(n_of[pair_i[p]]), int(n_of[pair_j[p]]),
                        )
                log_p = log_p.at[rows].set(jnp.asarray(lp_small))
    return log_p, u_stat


def streaming_wilcox_block(
    block,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    mesh=None,
    probe_out: Optional[Dict] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Rank-sum log-p / U for ONE disk chunk's gene rows — the
    out-of-core runner's per-shard entry (stream.runner, round 17).

    ``block`` is a (Gb, N) CSR slab holding ALL cells for a gene window
    (exactly what a ChunkedCSRStore chunk is), so the full window ladder
    — nnz-compacted windows, zero-block decomposition, R's exact branch
    for small pairs — runs per chunk with the SAME per-gene outputs the
    in-memory engine produces for those rows: rank tests are per-gene,
    so chunking the gene axis changes nothing but peak memory. Returns
    DEVICE arrays (log_p (P, Gb), u (P, Gb)); the caller owns the
    single batched fetch (its declared ``stream_block_fetch`` crossing)
    and the durable per-chunk store.

    Exists as a named seam (rather than the runner poking
    ``_run_wilcox_device`` directly) so the streaming layer's contract
    with the engine is one auditable function whose signature the
    engine owns.
    """
    return _run_wilcox_device(
        block, cell_idx_of, pair_i, pair_j, exact="auto", mesh=mesh,
        jdata=None, probe_out=probe_out,
    )


def _run_wilcox(
    data: np.ndarray,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    exact: str = "auto",
    mesh=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-array form of ``_run_wilcox_device`` (tests, small callers)."""
    from scconsensus_tpu.io.sparsemat import is_jax, is_sparse

    jdata = None
    if mesh is None and not is_sparse(data):
        jdata = (data.astype(jnp.float32) if is_jax(data)
                 else jnp.asarray(np.ascontiguousarray(data, np.float32)))
    lp, u = _run_wilcox_device(
        data, cell_idx_of, pair_i, pair_j, exact=exact, mesh=mesh, jdata=jdata
    )
    return np.asarray(lp), np.asarray(u)


def pairwise_de(
    data: np.ndarray,
    labels: Sequence,
    config: ReclusterConfig,
    timer=None,
    mesh=None,
    store=None,
) -> PairwiseDEResult:
    """Run the configured all-pairs DE test.

    data: (G, N) log-normalized expression; labels: per-cell cluster names.
    ``mesh``: optional jax.sharding.Mesh — the rank-sum gene chunks shard
    across it (the product pipeline's dp analog of the reference's
    doParallel fan-out, R/reclusterDEConsensusFast.R:61-65).
    ``store``: optional ArtifactStore — with one active (and
    SCC_ROBUST_DE_CKPT on), the wilcox window ladder persists each
    completed bucket so a kill mid-stage resumes from completed buckets;
    the pipeline discards the blocks once the covering ``de`` artifact
    lands.
    """
    from scconsensus_tpu.io.sparsemat import as_csr, is_jax, is_sparse, mean_expm1
    from scconsensus_tpu.utils.logging import StageTimer

    timer = timer or StageTimer()
    if is_sparse(data):
        data = as_csr(data)  # canonicalize COO/CSC; sums duplicate entries
    elif is_jax(data):
        data = data.astype(jnp.float32)  # stays in HBM; no host round-trip
    else:
        data = np.ascontiguousarray(data, dtype=np.float32)
    G, N = data.shape

    with timer.stage("cluster_filter"):
        names, cell_idx = filter_clusters(
            labels, config.min_cluster_size, config.drop_grey
        )
        K = len(names)
        if K < 2:
            raise ValueError(
                f"need >= 2 clusters above min_cluster_size={config.min_cluster_size}, got {K}"
            )
        cell_idx_of = [np.nonzero(cell_idx == k)[0].astype(np.int32) for k in range(K)]
        subsampled = False
        if config.max_cells_per_ident is not None:
            rng = np.random.default_rng(config.random_seed)
            cap = config.max_cells_per_ident
            subsampled = any(ci.size > cap for ci in cell_idx_of)
            cell_idx_of = [
                rng.choice(ci, size=cap, replace=False) if ci.size > cap else ci
                for ci in cell_idx_of
            ]
        pair_i, pair_j = _all_pairs(K)
        # Group-size validation: the reference hard-errors on pairs with <3
        # cells per group (R/reclusterDEConsensusFast.R:201-226); here such
        # pairs are skipped with a recorded reason instead of killing the run.
        n_of = np.array([ci.size for ci in cell_idx_of], np.int32)
        pair_ok = (n_of[pair_i] >= config.min_cells_group) & (
            n_of[pair_j] >= config.min_cells_group
        )
        skip_reasons = [
            f"{names[i]} vs {names[j]}: group sizes ({n_of[i]}, {n_of[j]}) "
            f"below min_cells_group={config.min_cells_group}"
            for i, j in zip(pair_i[~pair_ok], pair_j[~pair_ok])
        ]
        ok_rows = np.nonzero(pair_ok)[0]
        run_i, run_j = pair_i[pair_ok], pair_j[pair_ok]
        if run_i.size == 0:
            raise ValueError(
                "every cluster pair has a group below "
                f"min_cells_group={config.min_cells_group}; nothing to test"
            )

    with timer.stage("aggregates", n_clusters=K, n_pairs=int(pair_i.size)):
        # The matrix crosses host→device exactly once per run; every later
        # stage reuses jdata.
        from scconsensus_tpu.utils.devcache import device_put_cached

        jdata = None if is_sparse(data) else device_put_cached(data)
        if is_sparse(data):
            from scconsensus_tpu.io.sparsemat import aggregates_from_sparse
            from scconsensus_tpu.ops.gates import ClusterAggregates

            onehot = np.zeros((N, K), np.float32)
            valid = cell_idx >= 0
            onehot[np.nonzero(valid)[0], cell_idx[valid]] = 1.0
            from scconsensus_tpu.obs.residency import boundary as _rbound

            with _rbound("input_staging"):  # host-computed (G, K) aggregates
                agg = ClusterAggregates(
                    *(jnp.asarray(a)
                      for a in aggregates_from_sparse(data, onehot))
                )
        else:
            # cid form: CPU segment sums are O(G·N) vs the one-hot matmul's
            # O(G·N·K) — the K²-shaped blowup the r5 tm100k artifact measured
            # (9.8 s at K=44 → 93.5 s at K=80); TPU builds the one-hot on
            # device (ops.gates.compute_aggregates_cid)
            agg = compute_aggregates_cid(jdata, jnp.asarray(cell_idx), K)

    method = config.method.lower()
    pi, pj = jnp.asarray(pair_i), jnp.asarray(pair_j)
    P = int(pair_i.size)

    if method in ("wilcox", "wilcoxon", "roc", "bimod", "t"):
        slow = method == "wilcoxon"
        j_ok = jnp.asarray(pair_ok)
        funnel_gate = None
        with timer.stage("gates"):
            if slow:
                mean_gate, log_fc = pair_gates_slow(
                    agg, pi, pj,
                    mean_exprs_thrs=float(
                        config.mean_scaling_factor * mean_expm1(data)
                    ),
                    mixed_spaces=config.compat.mean_gate_mixed_spaces,
                )
                tested = jnp.broadcast_to(j_ok[:, None], (P, G))
                pct1 = pct2 = None
            else:
                gate, log_fc, pct1, pct2 = pair_gates_fast(
                    agg, pi, pj,
                    min_pct=config.min_pct,
                    min_diff_pct=config.min_diff_pct,
                    log_fc_thrs=config.log_fc_thrs,
                    mean_exprs_thrs=config.mean_exprs_thrs,
                    pseudocount=config.pseudocount,
                    only_pos=config.only_pos,
                )
                tested = gate & j_ok[:, None]
                # per-pair survivors of the FULL Seurat gate battery
                # (pct ∧ mean-expression ∧ |logFC|) — the funnel's
                # logfc_gate stage. The mean gate lives inside the jitted
                # composite; counting here is the only place the engine's
                # literal gating is observable, so the funnel's
                # tested-stage drop measures group-size skips ONLY
                funnel_gate = jnp.sum(gate, axis=1)
        aux: Optional[Dict[str, np.ndarray]] = None
        stage_name = (
            "wilcox_test" if method in ("wilcox", "wilcoxon") else f"{method}_test"
        )

        # The moment tests run on the post-subsampling groups
        # (max_cells_per_ident, reference R/reclusterDEConsensusFast.R:293-303
        # — applied after the gates, which use the full-cluster aggregates).
        # Skipped when no cluster exceeded the cap (identical agg) and for
        # the rank tests, which consume cell_idx_of directly.
        test_agg = agg
        if subsampled and method in ("bimod", "t"):
            if is_sparse(data):
                from scconsensus_tpu.io.sparsemat import aggregates_from_sparse
                from scconsensus_tpu.ops.gates import ClusterAggregates

                sub_onehot = np.zeros((N, K), np.float32)
                for k, ci in enumerate(cell_idx_of):
                    sub_onehot[ci, k] = 1.0
                from scconsensus_tpu.obs.residency import (
                    boundary as _rbound,
                )

                with _rbound("input_staging"):
                    test_agg = ClusterAggregates(*(
                        jnp.asarray(a)
                        for a in aggregates_from_sparse(data, sub_onehot)
                    ))
            else:
                # folded rebuild: the subsampled groups re-enter as a (N,)
                # cid vector through the same K-pruned kernel — no second
                # host (N, K) one-hot materialization/upload
                test_agg = compute_aggregates_cid(
                    jdata, jnp.asarray(_cid_from_groups(cell_idx_of, N)), K
                )

        # All (pair, gene) statistics stay on device through BH and the DE
        # call; ONE batched device_get at the end (the axon tunnel moves
        # device→host at ~36 MB/s — per-stage np.asarray round-trips were
        # the round-2 engine's hidden cost). The all-pairs kernels price
        # every pair anyway, so group-size-skipped pairs are computed and
        # masked to NaN rather than sliced out.
        with timer.stage(stage_name) as srec:
            u_dev = None
            if method == "bimod":
                log_p = bimod_lrt_pairs(test_agg, pi, pj)
            elif method == "t":
                log_p = welch_t_pairs(test_agg, pi, pj)
            else:
                # the stage record doubles as the occupancy probe sink: the
                # window-ladder diagnosis rides the ordinary metrics channel
                # into logs, bench artifacts, and PROFILE_r06_wilcox_1m
                log_p, u_dev = _run_wilcox_device(
                    data, cell_idx_of, pair_i, pair_j,
                    mesh=mesh, jdata=jdata, probe_out=srec,
                    ckpt=_wilcox_ckpt_for(store, mesh=mesh),
                )
            if method == "roc":
                # The reference's roc branch never produces a p-value usable
                # downstream (dead Seurat helpers, SURVEY.md §2c); fixed
                # behavior: AUC/power as the marker stats (N9: AUC falls out
                # of the rank-sum statistic), rank-sum p for significance.
                from scconsensus_tpu.ops.seurat_tests import auc_from_u

                n1s = jnp.asarray(
                    np.array([cell_idx_of[i].size for i in pair_i],
                             np.float32)[:, None]
                )
                n2s = jnp.asarray(
                    np.array([cell_idx_of[j].size for j in pair_j],
                             np.float32)[:, None]
                )
                auc, power = auc_from_u(u_dev, n1s, n2s)
                aux = {"auc": auc, "power": power}
            # Fast-path contract: untested entries surface as NaN (they are
            # additionally masked out of BH and the DE call); skipped pairs
            # are NaN on every path.
            log_p = jnp.where(tested if not slow else j_ok[:, None],
                              log_p, jnp.nan)
            if obs_quality.enabled():
                # Legitimate NaN budget: untested entries, PLUS tested
                # entries whose (pair, gene) slice is degenerate — pooled
                # variance ~0 (constant/all-zero genes) NaNs the rank
                # test (all ties → sigma 0) and Welch t (0/0) by
                # documented contract. Without this the slow path, which
                # gates nothing, would false-trip on every all-zero gene
                # of a sparse matrix.
                npool = jnp.maximum(
                    agg.counts[pi] + agg.counts[pj], 1.0
                )[:, None]
                pmean = (agg.sum_log[:, pi].T + agg.sum_log[:, pj].T) \
                    / npool
                pvar = (agg.sum_sq[:, pi].T + agg.sum_sq[:, pj].T) \
                    / npool - pmean * pmean
                degen = pvar <= 1e-4 * jnp.maximum(pmean * pmean, 1e-6)
                obs_quality.check_array(
                    "log_p", log_p, kinds=("nan",),
                    expected_nan=log_p.size - jnp.sum(tested & ~degen),
                    span=srec,
                )
        with timer.stage("bh_adjust") as bh_rec:
            if slow:
                # BH with explicit n = G over all genes (§2d-4 slow semantics).
                log_q = (
                    bh_adjust(log_p, n=jnp.asarray(float(G)))
                    if config.compat.bh_reference_n
                    else bh_adjust(log_p)
                )
            else:
                log_q = bh_adjust_masked(log_p, tested)
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )
            from scconsensus_tpu.robust.faults import corrupt_value

            log_q = corrupt_value("bh_logq", log_q)
            if robust_integrity.enabled():
                # BH-threshold monotonicity: q >= p and q <= 1 over the
                # finite entries — fused at the stage boundary, inside
                # the stage guard so an enforce-mode violation
                # recomputes the unit typed (silent_corruption)
                robust_integrity.check_bh("bh_adjust", log_p, log_q)
            if obs_quality.enabled():
                # BH masks out non-FINITE p (a -inf underflow gets NaN q
                # by design), so the legitimate-NaN budget is everything
                # outside tested-and-finite
                obs_quality.check_array(
                    "log_q", log_q, kinds=("nan",),
                    expected_nan=log_q.size - jnp.sum(
                        tested & jnp.isfinite(log_p)
                    ),
                    span=bh_rec,
                )
        with timer.stage("de_call"):
            log_thr = float(np.log(np.float32(config.q_val_thrs)))
            if slow:
                de = (
                    (log_q < log_thr)
                    & (jnp.abs(log_fc) > config.log_fc_thrs)
                    & mean_gate
                )
            else:
                de = tested & (log_q < log_thr)
            de = de & ~jnp.isnan(log_q)
        if funnel_gate is not None:
            # (P,)-sized, rides aux so de_funnel can report the engine's
            # LITERAL gate battery instead of re-deriving part of it
            aux = {**(aux or {}), "funnel_gate_full": funnel_gate}
        # The (P, G) statistics stay DEVICE arrays inside the result and
        # materialize per field on first access (class docstring) — the
        # pipeline consumes only de_mask + log_fc; nothing forces the other
        # five through the slow device→host link unless someone reads them.
        return PairwiseDEResult(
            cluster_names=names,
            pair_i=pair_i,
            pair_j=pair_j,
            log_p=log_p,
            log_q=log_q,
            log_fc=log_fc,
            tested=tested,
            de_mask=de,
            pair_skipped=~pair_ok,
            pct1=pct1,
            pct2=pct2,
            aux=aux,
            skip_reasons=skip_reasons or None,
        )

    if method == "edger":
        from scconsensus_tpu.de.edger import run_edger_pairs

        # The reference passes the log-normalized matrix to DGEList as-is
        # (R/reclusterDEConsensus.R:133) — counts in log space. Compat keeps
        # that literal arithmetic; fixed mode tests on expm1(data).
        from scconsensus_tpu.io.sparsemat import expm1_sparse, mean_value

        if config.compat.edger_log_counts:
            counts = data
            gate_mean = mean_expm1(data)
            jnb = jdata  # engine's aggregate upload doubles as NB input
        else:
            counts = expm1_sparse(data)
            gate_mean = mean_value(counts)  # counts IS expm1(data): reuse it
            jnb = None if jdata is None else jnp.expm1(jdata)
        with timer.stage("edger_nb"):
            nb = run_edger_pairs(
                counts, cell_idx_of, run_i, run_j, G,
                seed=config.random_seed, jcounts=jnb,
            )
        with timer.stage("gates"):
            mean_gate, _slow_fc = pair_gates_slow(
                agg, pi, pj,
                mean_exprs_thrs=config.mean_scaling_factor * gate_mean,
                mixed_spaces=config.compat.mean_gate_mixed_spaces,
            )
        # log_p/tagwise arrive as device arrays regardless of input sparsity
        # (assembled from device chunks in de.edger); log_fc is numpy.
        # _expand_rows_any accepts both forms.
        log_p = _expand_rows_any(nb.log_p, ok_rows, P)
        log_fc = _expand_rows(nb.log_fc, ok_rows, P)
        if obs_quality.enabled():
            # rows of group-size-skipped pairs are legitimate NaN
            skipped_nan = int(P - ok_rows.size) * G
            obs_quality.check_array(
                "nb_log_p", jnp.asarray(log_p), kinds=("nan",),
                expected_nan=skipped_nan, where="edger_nb",
            )
            obs_quality.check_array(
                "nb_log_fc", log_fc, kinds=("inf",), where="edger_nb",
            )
        with timer.stage("bh_adjust") as bh_rec:
            log_q = (
                bh_adjust(jnp.asarray(log_p), n=jnp.asarray(float(G)))
                if config.compat.bh_reference_n
                else bh_adjust(jnp.asarray(log_p))
            )
            from scconsensus_tpu.robust import (
                integrity as robust_integrity,
            )
            from scconsensus_tpu.robust.faults import corrupt_value

            log_q = corrupt_value("bh_logq", log_q)
            if robust_integrity.enabled():
                robust_integrity.check_bh(
                    "bh_adjust", jnp.asarray(log_p), log_q
                )
            if obs_quality.enabled():
                # non-finite p (skipped pairs' NaN, -inf underflow) is
                # masked out of BH and legitimately NaN in q
                obs_quality.check_array(
                    "log_q", log_q, kinds=("nan",),
                    expected_nan=log_q.size - jnp.sum(
                        jnp.isfinite(jnp.asarray(log_p))
                    ),
                    span=bh_rec,
                )
        with timer.stage("de_call"):
            log_thr = float(np.log(np.float32(config.q_val_thrs)))
            if config.compat.edger_drop_logfc:
                # §2d-1: the reference stores edgeR's fold-changes into a dead
                # variable; the criterion reads scalar-NA `logfc`, so the
                # whole mask is NA → no gene is ever *selected*. Reproduced
                # as an all-false DE mask (NA indexes select nothing usable).
                de = jnp.zeros((P, G), bool)
            else:
                de = (
                    (log_q < log_thr)
                    & (jnp.abs(jnp.asarray(log_fc)) > config.log_fc_thrs)
                    & mean_gate
                )
                de = de & ~jnp.isnan(log_q)
        tested = np.ones((P, G), bool)
        tested[~pair_ok] = False
        return PairwiseDEResult(
            cluster_names=names,
            pair_i=pair_i,
            pair_j=pair_j,
            log_p=log_p,
            log_q=log_q,
            log_fc=log_fc,
            tested=tested,
            de_mask=de,
            pair_skipped=~pair_ok,
            aux={
                "common_dispersion": _expand_rows(nb.common_disp, ok_rows, P),
                "tagwise_dispersion": _expand_rows_any(
                    nb.tagwise_disp, ok_rows, P
                ),
            },
            skip_reasons=skip_reasons or None,
        )

    raise NotImplementedError(f"DE method '{config.method}' not implemented yet")


def de_gene_union(
    result: PairwiseDEResult, n_top: int = 30
) -> np.ndarray:
    """Top-``n_top`` DE genes per pair by |logFC|, unioned
    (R/reclusterDEConsensus.R:209-227; fast path :386-392).

    Returns sorted unique gene indices."""
    raw_mask = object.__getattribute__(result, "de_mask")
    raw_fc = object.__getattribute__(result, "log_fc")
    if not (isinstance(raw_mask, np.ndarray) and isinstance(raw_fc, np.ndarray)):
        from scconsensus_tpu.obs.residency import boundary

        # Device fast path: per-pair top-k on device, fetch (P, n_top) ints
        # instead of materializing two (P, G) arrays through the slow link
        # — the allowlisted de_union_topk crossing.
        masked = jnp.where(
            jnp.asarray(raw_mask), jnp.abs(jnp.asarray(raw_fc)), -jnp.inf
        )
        k = min(n_top, masked.shape[1])
        vals, idx = jax.lax.top_k(masked, k)
        with boundary("de_union_topk"):
            vals, idx = jax.device_get((vals, idx))
        return np.unique(idx[vals > -np.inf]).astype(np.int64)
    union: set = set()
    for p in range(result.n_pairs):
        de_idx = np.nonzero(result.de_mask[p])[0]
        if de_idx.size == 0:
            continue
        fc = np.abs(result.log_fc[p, de_idx])
        order = np.argsort(-fc, kind="stable")
        union.update(de_idx[order[:n_top]].tolist())
    return np.array(sorted(union), dtype=np.int64)
