"""All-pairs differential-expression engine.

The reference fans the outer cluster index over R worker processes with a
triangular load imbalance (R/reclusterDEConsensusFast.R:61-65; SURVEY.md §3
E3). Here all K(K−1)/2 pairs are flattened into one batch axis, bucketed by
padded pair width so shapes stay static, and driven through vmapped kernels —
the TPU equivalent of the reference's doParallel backend (SURVEY.md §2b N10).

Engine flow:
  1. cluster filter (count > min_cluster_size, drop 'grey'; reference
     R/reclusterDEConsensus.R:39-49),
  2. per-cluster aggregates: three matmuls against the membership one-hot,
  3. per-pair gates from aggregates (masks, no ragged selection),
  4. per-pair statistical test over gene chunks (device),
  5. per-pair BH (masked or explicit-n, per path semantics),
  6. DE call + top-N union.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scconsensus_tpu.config import ReclusterConfig
from scconsensus_tpu.ops.gates import (
    compute_aggregates,
    pair_gates_fast,
    pair_gates_slow,
)
from scconsensus_tpu.ops.multipletests import bh_adjust, bh_adjust_masked
from scconsensus_tpu.ops.seurat_tests import bimod_lrt_tile, welch_t_tile
from scconsensus_tpu.ops.wilcoxon import (
    EXACT_N_LIMIT,
    wilcoxon_exact_host,
    wilcoxon_pairs_tile,
)

__all__ = ["PairwiseDEResult", "pairwise_de", "filter_clusters", "de_gene_union"]

# Per-chunk element budget for the (pairs × genes × cells) test tensor.
_CHUNK_ELEM_BUDGET = 24_000_000


@dataclasses.dataclass
class PairwiseDEResult:
    """Dense all-pairs DE summary (host arrays; P = #pairs, G = #genes)."""

    cluster_names: List[str]
    pair_i: np.ndarray  # (P,) index into cluster_names
    pair_j: np.ndarray
    log_p: np.ndarray   # (P, G); NaN where untested/degenerate
    log_q: np.ndarray   # (P, G); NaN where not adjusted
    log_fc: np.ndarray  # (P, G) natural-log fold change (path convention)
    tested: np.ndarray  # (P, G) bool: entered the statistical test
    de_mask: np.ndarray  # (P, G) bool: final DE call
    pair_skipped: np.ndarray = None  # (P,) bool: skipped by group-size validation
    pct1: Optional[np.ndarray] = None  # (P, G) fast path only
    pct2: Optional[np.ndarray] = None
    aux: Optional[Dict[str, np.ndarray]] = None  # extra per-test stats (AUC...)
    skip_reasons: Optional[List[str]] = None  # one per skipped pair

    @property
    def n_pairs(self) -> int:
        return int(self.pair_i.shape[0])

    def de_counts(self) -> np.ndarray:
        """Per-pair DE gene counts (the reference's progress printout,
        R/reclusterDEConsensus.R:172-178 — here a returned metric)."""
        return self.de_mask.sum(axis=1)

    _ARRAY_FIELDS = ("pair_i", "pair_j", "log_p", "log_q", "log_fc",
                     "tested", "de_mask", "pair_skipped")
    _OPT_ARRAY_FIELDS = ("pct1", "pct2")

    def __post_init__(self):
        if self.pair_skipped is None:
            self.pair_skipped = np.zeros(self.pair_i.shape[0], bool)

    def to_store(self) -> Tuple[Dict[str, np.ndarray], Dict]:
        """(arrays, meta) for ArtifactStore — the single serialization point,
        so the field list cannot drift from the dataclass."""
        arrays = {f: getattr(self, f) for f in self._ARRAY_FIELDS}
        for f in self._OPT_ARRAY_FIELDS:
            v = getattr(self, f)
            if v is not None:
                arrays[f] = v
        if self.aux:
            for k, v in self.aux.items():
                arrays[f"aux_{k}"] = np.asarray(v)
        return arrays, {
            "cluster_names": self.cluster_names,
            "skip_reasons": self.skip_reasons or [],
        }

    @classmethod
    def from_store(cls, arrays: Dict[str, np.ndarray], meta: Dict
                   ) -> "PairwiseDEResult":
        """Inverse of to_store. Raises ValueError on incomplete artifacts
        (e.g. a missing meta sidecar) so callers recompute instead of
        resuming into a corrupt state."""
        if "cluster_names" not in meta:
            raise ValueError("de artifact incomplete: missing cluster_names meta")
        # pair_skipped may be absent in stores written before group-size
        # validation existed; __post_init__ synthesizes the all-False default.
        required = [f for f in cls._ARRAY_FIELDS if f != "pair_skipped"]
        missing = [f for f in required if f not in arrays]
        if missing:
            raise ValueError(f"de artifact incomplete: missing arrays {missing}")
        aux = {
            k[len("aux_"):]: v for k, v in arrays.items() if k.startswith("aux_")
        }
        return cls(
            cluster_names=list(meta["cluster_names"]),
            **{f: arrays.get(f) for f in cls._ARRAY_FIELDS},
            **{f: arrays.get(f) for f in cls._OPT_ARRAY_FIELDS},
            aux=aux or None,
            skip_reasons=list(meta.get("skip_reasons", [])) or None,
        )


def filter_clusters(
    labels: Sequence, min_cluster_size: int, drop_grey: bool = True
) -> Tuple[List[str], np.ndarray]:
    """Clusters with count > min_cluster_size (strictly greater, §2d-7),
    'grey' substring dropped; returns (sorted names, per-cell index into
    names, -1 for dropped cells)."""
    lab = np.asarray(labels).astype(str)
    names, counts = np.unique(lab, return_counts=True)
    keep = counts > min_cluster_size
    if drop_grey:
        keep &= np.char.find(names, "grey") == -1
    kept = [str(n) for n in names[keep]]
    index = {n: i for i, n in enumerate(kept)}
    cell_idx = np.array([index.get(v, -1) for v in lab], dtype=np.int32)
    return kept, cell_idx


def _all_pairs(k: int) -> Tuple[np.ndarray, np.ndarray]:
    ii, jj = np.triu_indices(k, k=1)
    return ii.astype(np.int32), jj.astype(np.int32)


def _next_pow2(x: int) -> int:
    return 1 << (int(x) - 1).bit_length()


def _expand_rows(sub: np.ndarray, ok_rows: np.ndarray, n_rows: int) -> np.ndarray:
    """Scatter per-run-pair results back onto the full pair axis; rows of
    pairs skipped by group-size validation stay NaN (float) / False (bool)."""
    if ok_rows.size == n_rows:
        return sub
    fill = False if sub.dtype == bool else np.nan
    out = np.full((n_rows,) + sub.shape[1:], fill, sub.dtype)
    out[ok_rows] = sub
    return out


@dataclasses.dataclass
class _PairBucket:
    rows: np.ndarray      # (B,) indices into the global pair list
    cell_idx: np.ndarray  # (B, W) gather indices into columns of data
    mask1: np.ndarray     # (B, W) group-1 membership among gathered cells
    mask2: np.ndarray
    n1: np.ndarray        # (B,)
    n2: np.ndarray


def _bucket_pairs(
    cell_idx_of: List[np.ndarray], pair_i: np.ndarray, pair_j: np.ndarray
) -> List[_PairBucket]:
    """Group pairs by padded width so each bucket runs with one static shape."""
    widths = {}
    for r in range(pair_i.shape[0]):
        w = _next_pow2(
            cell_idx_of[pair_i[r]].size + cell_idx_of[pair_j[r]].size
        )
        widths.setdefault(w, []).append(r)
    buckets = []
    for w, rows in sorted(widths.items()):
        B = len(rows)
        idx = np.zeros((B, w), np.int32)
        m1 = np.zeros((B, w), bool)
        m2 = np.zeros((B, w), bool)
        n1 = np.zeros(B, np.int32)
        n2 = np.zeros(B, np.int32)
        for b, r in enumerate(rows):
            ci = cell_idx_of[pair_i[r]]
            cj = cell_idx_of[pair_j[r]]
            idx[b, : ci.size] = ci
            idx[b, ci.size : ci.size + cj.size] = cj
            m1[b, : ci.size] = True
            m2[b, ci.size : ci.size + cj.size] = True
            n1[b], n2[b] = ci.size, cj.size
        buckets.append(_PairBucket(np.asarray(rows), idx, m1, m2, n1, n2))
    return buckets


# Rank-sum test for one gene-chunk × pair-bucket tile; the shared
# implementation lives in ops.wilcoxon so the sharded and fused paths
# cannot diverge from the serial engine.
_wilcox_chunk = jax.jit(wilcoxon_pairs_tile)


@jax.jit
def _bimod_chunk(chunk, idx, m1, m2):
    return bimod_lrt_tile(jnp.swapaxes(jnp.take(chunk, idx, axis=1), 0, 1), m1, m2)


@jax.jit
def _ttest_chunk(chunk, idx, m1, m2):
    return welch_t_tile(jnp.swapaxes(jnp.take(chunk, idx, axis=1), 0, 1), m1, m2)


def _chunk_tiles(data, cell_idx_of, pair_i, pair_j):
    """Shared bucket/gene-chunk iteration for every tile test: yields
    (bucket, (idx, m1, m2, n1, n2) device tensors, g0, g1, padded chunk).
    Chunks are padded to a fixed width so each bucket shape compiles once.

    ``data`` may be dense or scipy-sparse: only the current gene-chunk is
    ever densified (the never-densify contract, SURVEY.md §2b N12). The
    dense path keeps the whole matrix device-resident across buckets.
    """
    from scconsensus_tpu.io.sparsemat import is_sparse, padded_row_chunk

    sparse = is_sparse(data)
    jdata = None if sparse else jnp.asarray(data)
    G = data.shape[0]
    for bucket in _bucket_pairs(cell_idx_of, pair_i, pair_j):
        B, W = bucket.cell_idx.shape
        gc = max(256, _CHUNK_ELEM_BUDGET // max(B * W, 1))
        gc = min(_next_pow2(gc), _next_pow2(G))
        tensors = (
            jnp.asarray(bucket.cell_idx),
            jnp.asarray(bucket.mask1),
            jnp.asarray(bucket.mask2),
            jnp.asarray(bucket.n1),
            jnp.asarray(bucket.n2),
        )
        for g0 in range(0, G, gc):
            if sparse:
                chunk = jnp.asarray(padded_row_chunk(data, g0, gc))
            else:
                chunk = jdata[g0 : g0 + gc]
                if chunk.shape[0] < gc:
                    chunk = jnp.pad(chunk, ((0, gc - chunk.shape[0]), (0, 0)))
            yield bucket, tensors, g0, min(g0 + gc, G), chunk


def _run_tile_test(
    data: np.ndarray,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    chunk_fn,
) -> np.ndarray:
    """Generic moment-based tile test (bimod / t): same bucketing and gene
    chunking as the rank-sum path, no exact branch. Returns log_p (P, G)."""
    G, _ = data.shape
    log_p = np.full((pair_i.shape[0], G), np.nan, np.float32)
    for bucket, (idx, m1, m2, _n1, _n2), g0, g1, chunk in _chunk_tiles(
        data, cell_idx_of, pair_i, pair_j
    ):
        lp = chunk_fn(chunk, idx, m1, m2)
        log_p[bucket.rows, g0:g1] = np.asarray(lp)[:, : g1 - g0]
    return log_p


@jax.jit
def _wilcox_task_chunk(
    data: jnp.ndarray,   # (G, N) device-resident full matrix
    gid: jnp.ndarray,    # (T,) gene index per task
    pidx: jnp.ndarray,   # (T,) bucket-local pair index per task
    idx: jnp.ndarray,    # (B, W) pair cell gathers
    m1: jnp.ndarray,     # (B, W)
    m2: jnp.ndarray,
    n1: jnp.ndarray,     # (B,)
    n2: jnp.ndarray,
):
    """Rank-sum over a flat (pair, gene) task list — the gated fast path.

    Each task is one gene of one pair; batching tasks instead of (pairs ×
    all-genes) tiles means only gate-surviving genes are ever ranked (the
    reference's fast path tests only survivors,
    R/reclusterDEConsensusFast.R:306-333) and load is balanced across pairs.
    Returns (log_p, u, tie_sum), each (T,).
    """
    cell_rows = jnp.take(idx, pidx, axis=0)          # (T, W)
    vals = data[gid[:, None], cell_rows]             # (T, W) double gather
    mask1 = jnp.take(m1, pidx, axis=0)
    mask2 = jnp.take(m2, pidx, axis=0)
    from scconsensus_tpu.ops.ranks import masked_midranks

    ranks, tie_sum = masked_midranks(vals, mask1 | mask2)
    rs1 = jnp.sum(jnp.where(mask1, ranks, 0.0), axis=-1)
    from scconsensus_tpu.ops.wilcoxon import wilcoxon_from_ranks

    log_p, u = wilcoxon_from_ranks(
        rs1, tie_sum, jnp.take(n1, pidx), jnp.take(n2, pidx)
    )
    return log_p, u, tie_sum


def _exact_host_update(
    log_p: np.ndarray, row: int, cols: np.ndarray, u_vals: np.ndarray,
    n1: int, n2: int,
) -> None:
    """Overwrite log_p[row, cols] with R's exact-branch p-values (shared by
    the tile and task paths so the policy and arithmetic cannot drift)."""
    pe = wilcoxon_exact_host(u_vals, n1, n2)
    log_p[row, cols] = np.log(pe).astype(np.float32)


def _run_wilcox_gated(
    data: np.ndarray,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    tested: np.ndarray,
    exact: str = "auto",
) -> Tuple[np.ndarray, np.ndarray]:
    """Rank-sum log-p over only the gate-surviving (pair, gene) tasks.

    Dense-input fast path; results for untested entries stay NaN (they are
    masked out of BH and the DE call anyway — fast-path semantics). Returns
    (log_p (P, G), u (P, G)).
    """
    G, _ = data.shape
    P = pair_i.shape[0]
    log_p = np.full((P, G), np.nan, np.float32)
    u_stat = np.full((P, G), np.nan, np.float32)
    jdata = jnp.asarray(data)
    for bucket in _bucket_pairs(cell_idx_of, pair_i, pair_j):
        B, W = bucket.cell_idx.shape
        pr, gi = np.nonzero(tested[bucket.rows])  # bucket-local task list
        if pr.size == 0:
            continue
        # Chunk width depends only on W (never on the data-dependent task
        # count) so each bucket shape compiles exactly once across calls.
        tb = min(_next_pow2(max(256, _CHUNK_ELEM_BUDGET // max(W, 1))), 16384)
        idx = jnp.asarray(bucket.cell_idx)
        m1 = jnp.asarray(bucket.mask1)
        m2 = jnp.asarray(bucket.mask2)
        n1 = jnp.asarray(bucket.n1)
        n2 = jnp.asarray(bucket.n2)
        for t0 in range(0, pr.size, tb):
            t1 = min(t0 + tb, pr.size)
            pad = tb - (t1 - t0)
            prt = np.pad(pr[t0:t1], (0, pad))
            git = np.pad(gi[t0:t1], (0, pad))
            lp, u, ties = _wilcox_task_chunk(
                jdata, jnp.asarray(git), jnp.asarray(prt),
                idx, m1, m2, n1, n2,
            )
            lp_h = np.asarray(lp)[: t1 - t0]
            u_h = np.asarray(u)[: t1 - t0]
            rows = bucket.rows[pr[t0:t1]]
            cols = gi[t0:t1]
            log_p[rows, cols] = lp_h
            u_stat[rows, cols] = u_h
            if exact == "auto":
                prt_real = pr[t0:t1]
                small = (bucket.n1[prt_real] < EXACT_N_LIMIT) & (
                    bucket.n2[prt_real] < EXACT_N_LIMIT
                )
                if small.any():
                    ties_h = np.asarray(ties)[: t1 - t0]
                    pick = small & (ties_h == 0)
                    # one vectorized exact call per pair, as the tile path does
                    for b in np.unique(prt_real[pick]):
                        sel = pick & (prt_real == b)
                        _exact_host_update(
                            log_p, bucket.rows[b], gi[t0:t1][sel], u_h[sel],
                            int(bucket.n1[b]), int(bucket.n2[b]),
                        )
    return log_p, u_stat


def _run_wilcox(
    data: np.ndarray,
    cell_idx_of: List[np.ndarray],
    pair_i: np.ndarray,
    pair_j: np.ndarray,
    exact: str = "auto",
) -> Tuple[np.ndarray, np.ndarray]:
    """Rank-sum log-p for every (pair, gene). Returns (log_p (P,G), u (P,G)).

    ``exact``: 'auto' applies R's exact branch on host for pairs with both
    groups < 50 cells and tie-free genes; 'never' keeps everything on the
    normal-approximation device path.
    """
    G, _ = data.shape
    P = pair_i.shape[0]
    log_p = np.full((P, G), np.nan, np.float32)
    u_stat = np.full((P, G), np.nan, np.float32)
    for bucket, (idx, m1, m2, n1, n2), g0, g1, chunk in _chunk_tiles(
        data, cell_idx_of, pair_i, pair_j
    ):
        lp, u, ties = _wilcox_chunk(chunk, idx, m1, m2, n1, n2)
        lp_h = np.asarray(lp)[:, : g1 - g0]
        u_h = np.asarray(u)[:, : g1 - g0]
        log_p[bucket.rows, g0:g1] = lp_h
        u_stat[bucket.rows, g0:g1] = u_h
        if exact == "auto":
            small = (bucket.n1 < EXACT_N_LIMIT) & (bucket.n2 < EXACT_N_LIMIT)
            if small.any():
                ties_h = np.asarray(ties)[:, : g1 - g0]
                for b in np.nonzero(small)[0]:
                    tiefree = ties_h[b] == 0
                    if tiefree.any():
                        cols = g0 + np.nonzero(tiefree)[0]
                        _exact_host_update(
                            log_p, bucket.rows[b], cols, u_h[b][tiefree],
                            int(bucket.n1[b]), int(bucket.n2[b]),
                        )
    return log_p, u_stat


def pairwise_de(
    data: np.ndarray,
    labels: Sequence,
    config: ReclusterConfig,
    timer=None,
) -> PairwiseDEResult:
    """Run the configured all-pairs DE test.

    data: (G, N) log-normalized expression; labels: per-cell cluster names.
    """
    from scconsensus_tpu.io.sparsemat import as_csr, is_sparse, mean_expm1
    from scconsensus_tpu.utils.logging import StageTimer

    timer = timer or StageTimer()
    if is_sparse(data):
        data = as_csr(data)  # canonicalize COO/CSC; sums duplicate entries
    else:
        data = np.ascontiguousarray(data, dtype=np.float32)
    G, N = data.shape

    with timer.stage("cluster_filter"):
        names, cell_idx = filter_clusters(
            labels, config.min_cluster_size, config.drop_grey
        )
        K = len(names)
        if K < 2:
            raise ValueError(
                f"need >= 2 clusters above min_cluster_size={config.min_cluster_size}, got {K}"
            )
        cell_idx_of = [np.nonzero(cell_idx == k)[0].astype(np.int32) for k in range(K)]
        if config.max_cells_per_ident is not None:
            rng = np.random.default_rng(config.random_seed)
            cell_idx_of = [
                rng.choice(ci, size=config.max_cells_per_ident, replace=False)
                if ci.size > config.max_cells_per_ident
                else ci
                for ci in cell_idx_of
            ]
        pair_i, pair_j = _all_pairs(K)
        # Group-size validation: the reference hard-errors on pairs with <3
        # cells per group (R/reclusterDEConsensusFast.R:201-226); here such
        # pairs are skipped with a recorded reason instead of killing the run.
        n_of = np.array([ci.size for ci in cell_idx_of], np.int32)
        pair_ok = (n_of[pair_i] >= config.min_cells_group) & (
            n_of[pair_j] >= config.min_cells_group
        )
        skip_reasons = [
            f"{names[i]} vs {names[j]}: group sizes ({n_of[i]}, {n_of[j]}) "
            f"below min_cells_group={config.min_cells_group}"
            for i, j in zip(pair_i[~pair_ok], pair_j[~pair_ok])
        ]
        ok_rows = np.nonzero(pair_ok)[0]
        run_i, run_j = pair_i[pair_ok], pair_j[pair_ok]
        if run_i.size == 0:
            raise ValueError(
                "every cluster pair has a group below "
                f"min_cells_group={config.min_cells_group}; nothing to test"
            )

    with timer.stage("aggregates", n_clusters=K, n_pairs=int(pair_i.size)):
        onehot = np.zeros((N, K), np.float32)
        valid = cell_idx >= 0
        onehot[np.nonzero(valid)[0], cell_idx[valid]] = 1.0
        if is_sparse(data):
            from scconsensus_tpu.io.sparsemat import aggregates_from_sparse
            from scconsensus_tpu.ops.gates import ClusterAggregates

            agg = ClusterAggregates(
                *(jnp.asarray(a) for a in aggregates_from_sparse(data, onehot))
            )
        else:
            agg = compute_aggregates(jnp.asarray(data), jnp.asarray(onehot))

    method = config.method.lower()
    pi, pj = jnp.asarray(pair_i), jnp.asarray(pair_j)
    P = int(pair_i.size)

    if method in ("wilcox", "wilcoxon", "roc", "bimod", "t"):
        slow = method == "wilcoxon"
        with timer.stage("gates"):
            if slow:
                mean_gate, log_fc = pair_gates_slow(
                    agg, pi, pj,
                    mean_exprs_thrs=config.mean_scaling_factor * mean_expm1(data),
                    mixed_spaces=config.compat.mean_gate_mixed_spaces,
                )
                tested = np.ones((P, G), bool)
                tested[~pair_ok] = False
                pct1 = pct2 = None
            else:
                gate, log_fc, p1, p2 = pair_gates_fast(
                    agg, pi, pj,
                    min_pct=config.min_pct,
                    min_diff_pct=config.min_diff_pct,
                    log_fc_thrs=config.log_fc_thrs,
                    mean_exprs_thrs=config.mean_exprs_thrs,
                    pseudocount=config.pseudocount,
                    only_pos=config.only_pos,
                )
                tested = np.array(gate)  # copy: jax buffers are read-only
                tested[~pair_ok] = False
                pct1, pct2 = np.asarray(p1), np.asarray(p2)
        aux: Optional[Dict[str, np.ndarray]] = None
        stage_name = (
            "wilcox_test" if method in ("wilcox", "wilcoxon") else f"{method}_test"
        )

        def _rank_sum(need_all_genes: bool = False):
            """Fast path tests only gate survivors (dense input); the slow
            path, sparse inputs, and callers needing per-gene statistics for
            every gene (roc's AUC) rank full tiles. Skipped pairs never run."""
            if not slow and not need_all_genes and not is_sparse(data):
                lp, u = _run_wilcox_gated(
                    data, cell_idx_of, run_i, run_j, tested[ok_rows]
                )
            else:
                lp, u = _run_wilcox(data, cell_idx_of, run_i, run_j)
            return _expand_rows(lp, ok_rows, P), _expand_rows(u, ok_rows, P)

        with timer.stage(stage_name):
            if method == "bimod":
                log_p = _expand_rows(
                    _run_tile_test(data, cell_idx_of, run_i, run_j, _bimod_chunk),
                    ok_rows, P,
                )
            elif method == "t":
                log_p = _expand_rows(
                    _run_tile_test(data, cell_idx_of, run_i, run_j, _ttest_chunk),
                    ok_rows, P,
                )
            elif method == "roc":
                # The reference's roc branch never produces a p-value usable
                # downstream (dead Seurat helpers, SURVEY.md §2c); fixed
                # behavior: AUC/power as the marker stats (N9: AUC falls out
                # of the rank-sum statistic), rank-sum p for significance.
                from scconsensus_tpu.ops.seurat_tests import auc_from_u

                # AUC/power are marker statistics reported for every gene —
                # rank full tiles so dense and sparse inputs agree exactly.
                log_p, u = _rank_sum(need_all_genes=True)
                n1s = np.array(
                    [cell_idx_of[i].size for i in pair_i], np.float32
                )[:, None]
                n2s = np.array(
                    [cell_idx_of[j].size for j in pair_j], np.float32
                )[:, None]
                auc, power = auc_from_u(jnp.asarray(u), n1s, n2s)
                aux = {"auc": np.asarray(auc), "power": np.asarray(power)}
            else:
                log_p, _u = _rank_sum()
        with timer.stage("bh_adjust"):
            if slow:
                # BH with explicit n = G over all genes (§2d-4 slow semantics).
                log_q = np.asarray(
                    bh_adjust(jnp.asarray(log_p), n=jnp.asarray(float(G)))
                    if config.compat.bh_reference_n
                    else bh_adjust(jnp.asarray(log_p))
                )
            else:
                log_q = np.asarray(
                    bh_adjust_masked(jnp.asarray(log_p), jnp.asarray(tested))
                )
        log_fc = np.asarray(log_fc)
        with timer.stage("de_call"):
            log_thr = np.log(np.float32(config.q_val_thrs))
            if slow:
                de = (
                    (log_q < log_thr)
                    & (np.abs(log_fc) > config.log_fc_thrs)
                    & np.asarray(mean_gate)
                )
                de &= ~np.isnan(log_q)
            else:
                de = tested & (log_q < log_thr) & ~np.isnan(log_q)
        return PairwiseDEResult(
            cluster_names=names,
            pair_i=pair_i,
            pair_j=pair_j,
            log_p=log_p,
            log_q=log_q,
            log_fc=log_fc,
            tested=tested,
            de_mask=de,
            pair_skipped=~pair_ok,
            pct1=pct1,
            pct2=pct2,
            aux=aux,
            skip_reasons=skip_reasons or None,
        )

    if method == "edger":
        from scconsensus_tpu.de.edger import run_edger_pairs

        # The reference passes the log-normalized matrix to DGEList as-is
        # (R/reclusterDEConsensus.R:133) — counts in log space. Compat keeps
        # that literal arithmetic; fixed mode tests on expm1(data).
        from scconsensus_tpu.io.sparsemat import expm1_sparse, mean_value

        if config.compat.edger_log_counts:
            counts = data
            gate_mean = mean_expm1(data)
        else:
            counts = expm1_sparse(data)
            gate_mean = mean_value(counts)  # counts IS expm1(data): reuse it
        with timer.stage("edger_nb"):
            buckets = _bucket_pairs(cell_idx_of, run_i, run_j)
            nb = run_edger_pairs(counts, buckets, G, int(run_i.size))
        with timer.stage("gates"):
            mean_gate, _slow_fc = pair_gates_slow(
                agg, pi, pj,
                mean_exprs_thrs=config.mean_scaling_factor * gate_mean,
                mixed_spaces=config.compat.mean_gate_mixed_spaces,
            )
        log_p = _expand_rows(nb.log_p, ok_rows, P)
        log_fc = _expand_rows(nb.log_fc, ok_rows, P)
        with timer.stage("bh_adjust"):
            log_q = np.asarray(
                bh_adjust(jnp.asarray(log_p), n=jnp.asarray(float(G)))
                if config.compat.bh_reference_n
                else bh_adjust(jnp.asarray(log_p))
            )
        with timer.stage("de_call"):
            log_thr = np.log(np.float32(config.q_val_thrs))
            if config.compat.edger_drop_logfc:
                # §2d-1: the reference stores edgeR's fold-changes into a dead
                # variable; the criterion reads scalar-NA `logfc`, so the
                # whole mask is NA → no gene is ever *selected*. Reproduced
                # as an all-false DE mask (NA indexes select nothing usable).
                de = np.zeros((P, G), bool)
            else:
                de = (
                    (log_q < log_thr)
                    & (np.abs(log_fc) > config.log_fc_thrs)
                    & np.asarray(mean_gate)
                )
                de &= ~np.isnan(log_q)
        tested = np.ones((P, G), bool)
        tested[~pair_ok] = False
        return PairwiseDEResult(
            cluster_names=names,
            pair_i=pair_i,
            pair_j=pair_j,
            log_p=log_p,
            log_q=log_q,
            log_fc=log_fc,
            tested=tested,
            de_mask=de,
            pair_skipped=~pair_ok,
            aux={
                "common_dispersion": _expand_rows(nb.common_disp, ok_rows, P),
                "tagwise_dispersion": _expand_rows(nb.tagwise_disp, ok_rows, P),
            },
            skip_reasons=skip_reasons or None,
        )

    raise NotImplementedError(f"DE method '{config.method}' not implemented yet")


def de_gene_union(
    result: PairwiseDEResult, n_top: int = 30
) -> np.ndarray:
    """Top-``n_top`` DE genes per pair by |logFC|, unioned
    (R/reclusterDEConsensus.R:209-227; fast path :386-392).

    Returns sorted unique gene indices."""
    union: set = set()
    for p in range(result.n_pairs):
        de_idx = np.nonzero(result.de_mask[p])[0]
        if de_idx.size == 0:
            continue
        fc = np.abs(result.log_fc[p, de_idx])
        order = np.argsort(-fc, kind="stable")
        union.update(de_idx[order[:n_top]].tolist())
    return np.array(sorted(union), dtype=np.int64)
