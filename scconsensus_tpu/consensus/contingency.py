"""Consensus layer: contingency table + automated label-merge grammar.

Reproduces the *behavior* of the reference's consensus entry point
(``R/plotContingencyTable.R:15-116``) with a host-side numpy implementation —
this stage is O(N) once per run, so it stays on host by design (SURVEY.md §3 E1).

Semantics implemented (anchors into the reference for parity checking):
  * contingency table = cross-tab of two label vectors, rows/cols in sorted
    label order (R ``table`` factor-level order) — plotContingencyTable.R:21-26.
  * base-labeling selection: the labeling with more distinct labels wins; on a
    tie, the one with the larger median cluster size — :70-84.
  * orientation: the matrix is transposed so rows correspond to the base
    labeling (cols > rows → transpose; square → transpose unless row names
    already match the base label set) — :86-99.
  * merge grammar: for every (base row i, remainder col j) cell holding >= 10%
    of row i's cells AND strictly more than ``min_clust_size`` cells, those
    cells are split out under the compound label ``"<row>_<col>"`` — :102-113.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

__all__ = [
    "ContingencyResult",
    "contingency_table",
    "automated_consensus",
    "plot_contingency_table",
]


@dataclasses.dataclass
class ContingencyResult:
    """Cross-tabulation of two clusterings.

    Attributes:
      matrix: (K1, K2) int64 counts, rows = labels_1 levels, cols = labels_2 levels.
      row_labels: sorted unique labels of the first clustering.
      col_labels: sorted unique labels of the second clustering.
    """

    matrix: np.ndarray
    row_labels: np.ndarray
    col_labels: np.ndarray

    def transpose(self) -> "ContingencyResult":
        return ContingencyResult(self.matrix.T, self.col_labels, self.row_labels)


def _as_label_array(labels: Sequence) -> np.ndarray:
    arr = np.asarray(labels)
    if arr.ndim != 1:
        raise ValueError(f"label vector must be 1-D, got shape {arr.shape}")
    return arr.astype(str)


def contingency_table(labels_1: Sequence, labels_2: Sequence) -> ContingencyResult:
    """Cross-tabulate two label vectors (R ``table(l1, l2)`` semantics).

    Levels are the sorted unique labels of each vector, matching R's default
    factor-level ordering used at plotContingencyTable.R:21.
    """
    l1 = _as_label_array(labels_1)
    l2 = _as_label_array(labels_2)
    if l1.shape != l2.shape:
        raise ValueError(
            f"label vectors disagree in length: {l1.shape[0]} vs {l2.shape[0]}"
        )
    row_labels, ridx = np.unique(l1, return_inverse=True)
    col_labels, cidx = np.unique(l2, return_inverse=True)
    k1, k2 = row_labels.size, col_labels.size
    mat = np.zeros((k1, k2), dtype=np.int64)
    np.add.at(mat, (ridx, cidx), 1)
    # Computation-integrity tier (robust.integrity, r18): the injected
    # in-computation corruption site and the conservation invariant —
    # row sums must equal the first labeling's cluster sizes, column
    # sums the second's, the grand total N. Every consensus label the
    # merge grammar emits descends from these counts.
    from scconsensus_tpu.robust import integrity as robust_integrity
    from scconsensus_tpu.robust.faults import corrupt_value

    mat = corrupt_value("contingency_table", mat)
    if robust_integrity.enabled():
        robust_integrity.check_contingency(
            "contingency_table", mat, ridx, cidx
        )
    return ContingencyResult(mat, row_labels, col_labels)


def _median_cluster_size(labels: np.ndarray) -> float:
    _, counts = np.unique(labels, return_counts=True)
    return float(np.median(counts))


def automated_consensus(
    labels_1: Sequence,
    labels_2: Sequence,
    min_clust_size: int = 10,
    ctg: Optional[ContingencyResult] = None,
) -> np.ndarray:
    """Automated consensus labeling (plotContingencyTable.R:69-115).

    The finer-grained labeling becomes the base (tie broken by larger median
    cluster size); each base cluster is split by any remainder cluster that
    overlaps it by >=10% of the base cluster's cells and more than
    ``min_clust_size`` cells, producing compound ``"base_remainder"`` labels.

    Returns the consensus label vector (same length/order as the inputs).
    """
    l1 = _as_label_array(labels_1)
    l2 = _as_label_array(labels_2)
    if ctg is None:
        ctg = contingency_table(l1, l2)

    k1 = np.unique(l1).size
    k2 = np.unique(l2).size
    if k1 > k2:
        base, remainder = l1, l2
    elif k1 < k2:
        base, remainder = l2, l1
    else:
        if _median_cluster_size(l1) > _median_cluster_size(l2):
            base, remainder = l1, l2
        else:
            base, remainder = l2, l1

    # Orient the matrix so rows = base labels (reference :86-99).
    mat, rows, cols = ctg.matrix, ctg.row_labels, ctg.col_labels
    r, c = mat.shape
    base_levels = np.unique(base)
    if c > r:
        mat, rows, cols = mat.T, cols, rows
    elif c == r:
        if np.intersect1d(base_levels, rows).size != r:
            mat, rows, cols = mat.T, cols, rows

    consensus = base.copy().astype(object)
    row_sums = mat.sum(axis=1)
    for i in range(mat.shape[0]):
        row = mat[i]
        total = row_sums[i]
        if total == 0:
            continue
        percent_row = 100.0 * row / total
        for j in range(mat.shape[1]):
            if percent_row[j] >= 10.0 and row[j] > min_clust_size:
                sel = (base == rows[i]) & (remainder == cols[j])
                consensus[sel] = f"{rows[i]}_{cols[j]}"
    return consensus.astype(str)


def plot_contingency_table(
    cluster_labels_1: Sequence = None,
    cluster_labels_2: Sequence = None,
    automate_consensus: bool = True,
    min_clust_size: int = 10,
    filename: Optional[str] = None,
) -> Optional[np.ndarray]:
    """Reference-shaped entry point (plotContingencyTable.R:15).

    Renders the contingency heatmap to ``filename`` when given (PDF/PNG via the
    report layer) and, when ``automate_consensus`` is set, returns the automated
    consensus label vector.
    """
    if cluster_labels_1 is None or cluster_labels_2 is None:
        raise ValueError("Incomplete parameters provided.")
    ctg = contingency_table(cluster_labels_1, cluster_labels_2)
    if filename is not None:
        from scconsensus_tpu.report import plot_contingency_heatmap

        plot_contingency_heatmap(ctg, filename)
    if automate_consensus:
        return automated_consensus(
            cluster_labels_1, cluster_labels_2, min_clust_size=min_clust_size, ctg=ctg
        )
    return None
