from scconsensus_tpu.consensus.contingency import (
    contingency_table,
    automated_consensus,
    plot_contingency_table,
    ContingencyResult,
)

__all__ = [
    "contingency_table",
    "automated_consensus",
    "plot_contingency_table",
    "ContingencyResult",
]
