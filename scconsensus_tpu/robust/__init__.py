"""Survivable-pipeline layer: fault injection + typed recovery (round 13),
elastic mesh execution + input contracts (round 14).

The pieces, one contract:

* :mod:`~scconsensus_tpu.robust.faults` — deterministic, plan-driven
  injection of named fault classes (device OOM, transient backend error,
  worker SIGKILL, artifact corruption, stall) at named sites, so every
  recovery path is tier-1-testable without a flaky box
  (``SCC_FAULT_PLAN`` points at a JSON plan).
* :mod:`~scconsensus_tpu.robust.retry` — the ONE retry/degradation
  policy engine: error-class classification (transient / resource /
  fatal), exponential backoff with deterministic jitter, a per-run retry
  budget, every attempt recorded as a span event + counter.
  ``utils.devcache``'s old ad-hoc evict-and-retry now rides this policy.
* :mod:`~scconsensus_tpu.robust.record` — the per-run robustness log and
  the validated ``robustness`` run-record section (faults injected,
  retries, degradations, resume points, mesh transitions) that flows
  through the ledger, ``tools/explain_run.py`` and
  ``tools/tail_run.py``. A record claiming recovery without
  retry/resume/transition evidence is REJECTED by
  ``validate_run_record``; so is a mesh transition whose device set
  does not shrink.
* :mod:`~scconsensus_tpu.robust.elastic` — the elastic mesh supervisor:
  device-loss classification + mesh rebuild on survivors (the
  8 → 4 → 2 → 1 shrink ladder), shape-polymorphic checkpoint resume
  (mesh_shape provenance on every artifact), every movement stamped as
  a validated mesh transition.
* :mod:`~scconsensus_tpu.robust.contract` — input-contract pre-flight
  at the ``refine()`` boundary: named repair-or-reject policies that
  turn degenerate inputs into one-line typed diagnoses.
* :mod:`~scconsensus_tpu.robust.integrity` — computation-integrity
  sentinels (round 18, ``SCC_INTEGRITY`` off/audit/enforce): algebraic
  invariant checks fused at stage boundaries, a seeded ghost-replay
  sample recomputed through the float64 host oracle, the typed
  ``silent_corruption`` error class (recompute-the-unit recovery,
  repeated detection evicts the miscomputing device via the elastic
  supervisor), the validated ``integrity`` run-record section, and the
  in-computation ``corruption`` fault class that makes every detection
  path tier-1-testable. ``robust.soak`` is its replayable worker;
  ``tools/verify_run.py`` the cross-shape determinism auditor.

The recovery *surfaces* live where the work lives: the wilcox ladder
persists per-bucket completion into the ``ArtifactStore`` (mid-stage
resume), ``utils.artifacts`` checksums every artifact and quarantines
corruption, and ``bench.py``'s orchestrator adapts its attempt ladder to
the observed termination cause (stall -> capture armed, oom -> degraded,
repeated crash -> poisoned config).
"""

from scconsensus_tpu.robust.contract import (  # noqa: F401
    InputContractError,
)
from scconsensus_tpu.robust.elastic import (  # noqa: F401
    DeviceLossUnrecoverable,
    ElasticMeshSupervisor,
)
from scconsensus_tpu.robust.faults import (  # noqa: F401
    FAULT_CLASSES,
    InjectedDeviceLoss,
    InjectedFault,
    InjectedResourceExhausted,
    InjectedTransientError,
    corrupt_artifact,
    fault_point,
)
from scconsensus_tpu.robust.record import (  # noqa: F401
    begin_run,
    current_run,
    live_summary,
    note_mesh_transition,
    note_resume_point,
    validate_robustness,
)
from scconsensus_tpu.robust.integrity import (  # noqa: F401
    GhostReplayMismatch,
    IntegrityError,
    InvariantViolation,
    validate_integrity,
)
from scconsensus_tpu.robust.retry import (  # noqa: F401
    ERROR_CLASSES,
    RetryPolicy,
    call,
    classify_exception,
    classify_text,
)
