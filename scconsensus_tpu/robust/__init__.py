"""Survivable-pipeline layer: fault injection + typed recovery (round 13).

Three pieces, one contract:

* :mod:`~scconsensus_tpu.robust.faults` — deterministic, plan-driven
  injection of named fault classes (device OOM, transient backend error,
  worker SIGKILL, artifact corruption, stall) at named sites, so every
  recovery path is tier-1-testable without a flaky box
  (``SCC_FAULT_PLAN`` points at a JSON plan).
* :mod:`~scconsensus_tpu.robust.retry` — the ONE retry/degradation
  policy engine: error-class classification (transient / resource /
  fatal), exponential backoff with deterministic jitter, a per-run retry
  budget, every attempt recorded as a span event + counter.
  ``utils.devcache``'s old ad-hoc evict-and-retry now rides this policy.
* :mod:`~scconsensus_tpu.robust.record` — the per-run robustness log and
  the validated ``robustness`` run-record section (faults injected,
  retries, degradations, resume points) that flows through the ledger,
  ``tools/explain_run.py`` and ``tools/tail_run.py``. A record claiming
  recovery without retry/resume evidence is REJECTED by
  ``validate_run_record``.

The recovery *surfaces* live where the work lives: the wilcox ladder
persists per-bucket completion into the ``ArtifactStore`` (mid-stage
resume), ``utils.artifacts`` checksums every artifact and quarantines
corruption, and ``bench.py``'s orchestrator adapts its attempt ladder to
the observed termination cause (stall -> capture armed, oom -> degraded,
repeated crash -> poisoned config).
"""

from scconsensus_tpu.robust.faults import (  # noqa: F401
    FAULT_CLASSES,
    InjectedFault,
    InjectedResourceExhausted,
    InjectedTransientError,
    corrupt_artifact,
    fault_point,
)
from scconsensus_tpu.robust.record import (  # noqa: F401
    begin_run,
    current_run,
    live_summary,
    note_resume_point,
    validate_robustness,
)
from scconsensus_tpu.robust.retry import (  # noqa: F401
    ERROR_CLASSES,
    RetryPolicy,
    call,
    classify_exception,
    classify_text,
)
