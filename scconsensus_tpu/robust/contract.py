"""Input-contract pre-flight for the ``refine()`` boundary.

Degenerate inputs used to surface as deep-stack crashes — an all-NaN
matrix dies inside the rank-sum kernel, a labels/matrix shape mismatch
inside an indexing op, a labeling with every cluster below the size
floor as a ``K < 2`` ValueError three frames into ``pairwise_de``. The
pre-flight turns each into a one-line, typed diagnosis at the boundary,
under a NAMED policy per check:

  ====================  ======  =============================================
  check                 policy  behavior
  ====================  ======  =============================================
  shape                 reject  data must be 2-D with G, N >= 1 and
                                len(labels) == N
  nonfinite_matrix      reject  any NaN/Inf in the expression matrix (one
                                bandwidth-bound sum pass; a full scan runs
                                only to diagnose an already-failed check)
  nan_labels            reject  float-NaN label values (they would collapse
                                into a single "nan" pseudo-cluster)
  degenerate_clusters   reject  fewer than 2 clusters survive the engine's
                                size filter (empty/singleton/sub-floor
                                clusters cannot be paired for DE)
  noncontiguous_ids     repair  integer label ids with gaps are accepted
                                as-is (labels are categorical NAMES here;
                                the repair is the canonical str-cast every
                                stage already applies) — recorded on the
                                robustness log so the normalization is
                                visible
  small_clusters        repair  clusters at/below min_cluster_size are
                                dropped by the engine (reference semantics);
                                pre-flight names them up front
  ====================  ======  =============================================

Reject-policy violations raise :class:`InputContractError` (a ValueError
subclass, so callers that guarded the old deep-stack errors keep
working); repair-policy findings are logged and — when they changed
anything — recorded as ``input_contract`` degradations on the run's
robustness trail.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

__all__ = ["InputContractError", "CHECKS", "preflight"]


class InputContractError(ValueError):
    """A refine() input violated a reject-policy contract check. The
    message is the one-line diagnosis; ``check`` names the failed check
    (a key of :data:`CHECKS`)."""

    def __init__(self, check: str, msg: str):
        super().__init__(f"input contract [{check}]: {msg}")
        self.check = check


# check name -> policy; the docs table and the tests read this registry
CHECKS: Dict[str, str] = {
    "shape": "reject",
    "nonfinite_matrix": "reject",
    "nan_labels": "reject",
    "degenerate_clusters": "reject",
    "noncontiguous_ids": "repair",
    "small_clusters": "repair",
}


def _matrix_sum(data) -> float:
    """One bandwidth-bound reduction whose result is non-finite iff the
    matrix holds any NaN/Inf (inf - inf folds to NaN; both stay
    non-finite through the sum). float64 accumulation so a large finite
    matrix cannot overflow into a false positive."""
    from scconsensus_tpu.io.sparsemat import is_jax, is_sparse

    if is_sparse(data):
        return float(np.sum(data.data, dtype=np.float64)) if data.nnz \
            else 0.0
    if is_jax(data):
        import jax.numpy as jnp

        # float32 accumulation (x64 is typically disabled): log-normalized
        # expression sums sit ~30 orders of magnitude under f32 overflow,
        # so a finite matrix cannot false-positive
        return float(jnp.sum(data))
    return float(np.sum(data, dtype=np.float64))


def _nonfinite_counts(data) -> Dict[str, int]:
    """Full diagnostic scan — only runs once the cheap sum already failed
    the check, so the one-line diagnosis can say HOW the matrix is bad."""
    from scconsensus_tpu.io.sparsemat import is_jax, is_sparse

    if is_sparse(data):
        vals = np.asarray(data.data)
    elif is_jax(data):
        import jax.numpy as jnp

        return {"nan": int(jnp.isnan(data).sum()),
                "inf": int(jnp.isinf(data).sum())}
    else:
        vals = np.asarray(data)
    return {"nan": int(np.isnan(vals).sum()),
            "inf": int(np.isinf(vals).sum())}


def preflight(data, labels, config) -> List[Dict[str, Any]]:
    """Run every contract check against a refine() call's inputs.

    Raises :class:`InputContractError` on the first reject-policy
    violation; returns the list of repair records (possibly empty) —
    each ``{"check", "policy", "detail"}`` — which the pipeline also
    notes on the robustness log so a repaired run says so.
    """
    from scconsensus_tpu.robust import record as robust_record

    repairs: List[Dict[str, Any]] = []

    # shape — everything downstream indexes (G, N) against labels
    shape = getattr(data, "shape", None)
    if shape is None or len(shape) != 2:
        raise InputContractError(
            "shape", f"expression matrix must be 2-D (genes × cells), "
                     f"got shape {shape!r}")
    G, N = int(shape[0]), int(shape[1])
    if G < 1 or N < 1:
        raise InputContractError(
            "shape", f"expression matrix must be non-empty, got "
                     f"({G} genes × {N} cells)")
    if len(labels) != N:
        raise InputContractError(
            "shape", f"labels length {len(labels)} != n_cells {N}")

    # nan_labels — float NaN would str()-collapse into one "nan" cluster
    lab_arr = np.asarray(labels)
    if lab_arr.dtype.kind == "f" and bool(np.isnan(lab_arr).any()):
        n_bad = int(np.isnan(lab_arr).sum())
        raise InputContractError(
            "nan_labels", f"{n_bad} of {N} labels are NaN — every one "
                          "would alias into a single 'nan' pseudo-cluster")

    # nonfinite_matrix — one reduction; full scan only for the diagnosis
    s = _matrix_sum(data)
    if not np.isfinite(s):
        c = _nonfinite_counts(data)
        raise InputContractError(
            "nonfinite_matrix",
            f"expression matrix contains {c['nan']} NaN and {c['inf']} "
            f"Inf value(s) — clean or mask them before refine()")

    # noncontiguous_ids (repair) — integer labelings with gaps are legal
    # (labels are categorical names), but the gap usually means an
    # upstream filter dropped clusters; say so once
    if lab_arr.dtype.kind in "iu":
        uniq = np.unique(lab_arr)
        lo, hi = int(uniq.min()), int(uniq.max())
        if uniq.size and uniq.size != hi - lo + 1:
            repairs.append({
                "check": "noncontiguous_ids", "policy": "repair",
                "detail": f"integer label ids have gaps ({uniq.size} "
                          f"distinct ids spanning [{lo}, {hi}]); treated "
                          "as categorical names",
            })

    # degenerate_clusters / small_clusters — the engine's own survival
    # rule, applied at the boundary so the failure is one line, not a
    # stack. One unique pass; no O(N) per-cell index (pairwise_de builds
    # that itself right after).
    from scconsensus_tpu.de.engine import filter_cluster_names

    lab_str = lab_arr.astype(str)
    all_names, counts = np.unique(lab_str, return_counts=True)
    names = filter_cluster_names(
        all_names, counts, config.min_cluster_size, config.drop_grey
    )
    dropped = [
        f"{n!s}({c})" for n, c in zip(all_names, counts)
        if str(n) not in names
    ]
    if len(names) < 2:
        raise InputContractError(
            "degenerate_clusters",
            f"only {len(names)} cluster(s) survive the size filter "
            f"(min_cluster_size={config.min_cluster_size}, "
            f"drop_grey={config.drop_grey}); dropped: "
            f"{', '.join(dropped) if dropped else 'none'} — pairwise DE "
            "needs at least 2 clusters")
    if dropped:
        repairs.append({
            "check": "small_clusters", "policy": "repair",
            "detail": f"dropped {len(dropped)} empty/singleton/sub-floor "
                      f"cluster(s) before DE: {', '.join(dropped[:8])}"
                      + (" …" if len(dropped) > 8 else ""),
        })

    for r in repairs:
        robust_record.note_degradation(
            "input_contract", f"repair:{r['check']}", r["detail"]
        )
    return repairs
