"""Runnable integrity-soak worker: the chaos harness's in-memory
workload and the cross-shape determinism auditor's unit of replay.

    python -m scconsensus_tpu.robust.soak --dir DIR [--cells N]
        [--genes G] [--clusters K] [--seed S] [--summary PATH]
        [--stream] [--stream-window W] [--mesh auto|none]

Builds the SAME deterministic planted-marker dataset as the streaming
soak (``stream.soak.chunk_generator`` — every row a pure function of
(seed, gene), independent of chunk boundaries) and runs one full
``refine()`` over it: in-memory CSR by default, or out-of-core through
a ``ChunkedCSRStore`` with ``--stream`` (``--stream-window`` sets the
chunk shape). Writes one summary JSON whose ``labels_sha`` is a pure
function of (seed, shape) — which is exactly what makes it a reusable
auditor:

  * ``tools/chaos_run.py``'s ``INTEGRITY_SOAK_MATRIX`` runs it under
    injected in-computation corruption (``SCC_FAULT_PLAN`` +
    ``SCC_INTEGRITY=enforce``) and pins the corrupted-then-recovered
    run's sha equal to a clean reference run's — detection, typed
    silent_corruption recompute, byte-identical labels;
  * ``tools/verify_run.py`` replays the same workload under different
    chunk/mesh/batch shapes (stream windows, a forced 8-virtual-device
    mesh, the scan-vs-runspace kernel family) and pins ONE sha across
    all of them — the scattered per-PR identity tests as one auditor.

The exit code IS the contract: 0 = the run completed, the run record
(integrity/robustness/streaming sections included) validates, and
labels were produced for every deepSplit; 1 = the contract broke.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time
from typing import Any, Dict, Optional

import numpy as np

__all__ = ["run_integrity_soak", "main"]


def run_integrity_soak(
    workdir: str, n_cells: int = 3000, n_genes: int = 120,
    n_clusters: int = 3, seed: int = 7, stream: bool = False,
    stream_window: Optional[int] = None, mesh: str = "none",
    fresh: bool = False,
) -> Dict[str, Any]:
    """One deterministic refine; returns the summary dict (module doc)."""
    from scconsensus_tpu.config import ReclusterConfig
    from scconsensus_tpu.models.pipeline import refine
    from scconsensus_tpu.obs.export import (
        build_run_record,
        validate_run_record,
    )
    from scconsensus_tpu.stream.soak import (
        _labels_sha,
        chunk_generator,
        consensus_input,
    )

    gen = chunk_generator(n_genes, n_cells, n_clusters, seed)
    labels = consensus_input(n_cells, n_clusters, seed)
    config = ReclusterConfig(
        method="wilcox", q_val_thrs=0.1, log_fc_thrs=0.25, min_pct=5.0,
        deep_split_values=(1, 2), min_cluster_size=10,
        n_top_de_genes=20, random_seed=seed,
    )
    t0 = time.perf_counter()
    if stream:
        from scconsensus_tpu.stream.store import ChunkedCSRStore

        chunks_dir = os.path.join(workdir, "chunks")
        stages_dir = os.path.join(workdir, "stages")
        if fresh:
            for d in (chunks_dir, stages_dir):
                shutil.rmtree(d, ignore_errors=True)
        win = int(stream_window or 32)
        store = ChunkedCSRStore.create(chunks_dir, n_genes, n_cells, win)
        config.artifact_dir = stages_dir
        from scconsensus_tpu.stream.runner import streaming_refine

        result = streaming_refine(store, labels, config,
                                  stage_dir=stages_dir, regen=gen)
    else:
        data = gen(0, n_genes)  # one scipy CSR matrix, seed-pure
        result = refine(data, labels, config,
                        mesh=("auto" if mesh == "auto" else None))
    wall = time.perf_counter() - t0
    ig = result.metrics.get("integrity")
    rb = result.metrics.get("robustness")
    rec = build_run_record(
        metric=f"integrity soak: {n_cells}-cell refine",
        value=round(wall, 3), unit="seconds",
        extra={"config": "integrity-soak", "platform": "cpu",
               "n_cells": n_cells, "n_genes": n_genes,
               "stream": bool(stream)},
        spans=result.metrics.get("spans") or [],
        robustness=rb,
        integrity=ig,
        streaming=result.metrics.get("streaming"),
    )
    invalid = None
    try:
        validate_run_record(rec)
    except ValueError as e:
        invalid = str(e)
    have_all_cuts = all(
        f"deepsplit: {d}" in result.dynamic_labels
        for d in config.deep_split_values
    )
    gh = (ig or {}).get("ghost") or {}
    sc_retries = [r for r in (rb or {}).get("retries") or []
                  if r.get("error_class") == "silent_corruption"
                  and r.get("recovered")]
    mesh_transitions = (rb or {}).get("mesh_transitions") or []
    return {
        "ok": bool(invalid is None and have_all_cuts),
        "invalid": invalid,
        "wall_s": round(wall, 3),
        "labels_sha": _labels_sha(result.dynamic_labels),
        "integrity": ig,
        "detections": (len((ig or {}).get("violations") or [])
                       + len(gh.get("mismatches") or [])),
        "recomputes": gh.get("recomputes", 0),
        "sc_retries_recovered": len(sc_retries),
        "mesh_transitions": len(mesh_transitions),
        "mesh_final_devices": (
            len(mesh_transitions[-1].get("to_devices") or [])
            if mesh_transitions else None
        ),
        "record": rec,
    }


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(description="integrity soak worker")
    ap.add_argument("--dir", required=True, help="work directory")
    ap.add_argument("--cells", type=int, default=3000)
    ap.add_argument("--genes", type=int, default=120)
    ap.add_argument("--clusters", type=int, default=3)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--stream", action="store_true",
                    help="run out-of-core through a ChunkedCSRStore")
    ap.add_argument("--stream-window", type=int, default=None)
    ap.add_argument("--mesh", choices=("none", "auto"), default="none",
                    help="'auto' uses every visible device (force a "
                         "virtual mesh via XLA_FLAGS in the parent)")
    ap.add_argument("--summary", default=None)
    ap.add_argument("--fresh", action="store_true")
    args = ap.parse_args(argv)

    summary_path = args.summary or os.path.join(
        args.dir, "INTEGRITY_SOAK_SUMMARY.json"
    )
    os.makedirs(args.dir, exist_ok=True)
    summary = run_integrity_soak(
        args.dir, n_cells=args.cells, n_genes=args.genes,
        n_clusters=args.clusters, seed=args.seed, stream=args.stream,
        stream_window=args.stream_window, mesh=args.mesh,
        fresh=args.fresh,
    )
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=1, default=str)
    print(json.dumps({
        "ok": summary["ok"],
        "detections": summary["detections"],
        "recomputes": summary["recomputes"],
        "mesh_transitions": summary["mesh_transitions"],
        "labels_sha": summary["labels_sha"][:16],
    }))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
