"""Elastic mesh execution: device-loss recovery + shape-polymorphic resume.

The round-13 ``robust/`` subsystem made the single-process pipeline
survivable; this module makes the MESH layer survivable. One
:class:`ElasticMeshSupervisor` per pipeline run owns mesh construction
for the sharded paths (wrapping ``parallel.mesh.auto_mesh``/``make_mesh``)
and implements the two halves of elasticity:

**In-process device loss.** A stage failing with a ``device_lost``-class
error (robust.retry classification: real XLA device-loss signatures, the
injected ``device_loss`` fault class) retries through the typed policy
with the supervisor's :meth:`loss_handler` as the ``on_device_loss``
hook: the supervisor probes the current mesh's devices, rebuilds the
mesh on survivors (an indistinct failure — every device still answers
the probe, which is what an injected fault looks like — shrinks
deterministically by halving onto the lowest-id devices: 8 → 4 → 2 → 1),
records the transition on the run's robustness log, and the stage
re-enters. Live sharded state needs no explicit migration: every sharded
entry point lays its operands out per call (``pad_and_shard`` against
the mesh it is handed), so the re-entered stage re-pads onto the new
shard count by construction — the supervisor only has to hand it the
smaller mesh and account the bytes.

**Shape-polymorphic resume.** Stage artifacts and the ``_WilcoxCkpt``
bucket checkpoints carry a ``mesh_shape`` stamp
(``parallel.mesh.mesh_shape_meta``). Artifacts hold only mesh-invariant
results (the mesh-vs-serial parity tests pin that), so a checkpoint
written on an 8-device mesh resumes with identical labels on 4, 2, or 1
devices; when a resume adopts state computed on a LARGER mesh, the
supervisor stamps a ``cause: "resume"`` mesh transition (from the stored
device set to the live one) so the ledger record proves the
shape-polymorphic crossing happened.

Both transition kinds flow through the validated ``robustness``
run-record section (``mesh_transitions``), the ledger manifest summary,
``explain_run.py``, and the heartbeat stream's robust panel.

Gated by ``SCC_ELASTIC`` (default on — with no fault the supervisor is
one attribute read per stage); ``SCC_ELASTIC_MIN_DEVICES`` floors the
shrink ladder (below it a device loss is fatal, for runs whose working
set genuinely needs a minimum HBM footprint).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from scconsensus_tpu.config import env_flag
from scconsensus_tpu.robust import record as robust_record

__all__ = [
    "ElasticMeshSupervisor",
    "elastic_enabled",
    "resume_crossing_from_ids",
    "DeviceLossUnrecoverable",
]


class DeviceLossUnrecoverable(RuntimeError):
    """A device was lost and there is no smaller mesh to shrink to (the
    floor is ``SCC_ELASTIC_MIN_DEVICES``, default 1). Classified fatal by
    robust.retry — retrying cannot help."""


def elastic_enabled() -> bool:
    return bool(env_flag("SCC_ELASTIC"))


def resume_crossing_from_ids(meta: Optional[Dict[str, Any]],
                             to_ids: List[int]) -> Optional[List[int]]:
    """THE shape-polymorphic crossing rule, in one place: the sorted
    stored device ids when ``meta``'s ``mesh_shape`` stamp names a
    STRICTLY larger device set than the live ``to_ids`` (i.e. this
    resume shrinks), else None — same shape, growth, and unstamped
    legacy artifacts are not elastic crossings. Both consumers (the
    supervisor's artifact resumes and the wilcox bucket checkpoints)
    route through here so the ledger evidence cannot diverge."""
    shape = (meta or {}).get("mesh_shape")
    if not isinstance(shape, dict):
        return None
    from_ids = shape.get("device_ids")
    if not isinstance(from_ids, list) or not from_ids:
        n = shape.get("n_devices")
        if not isinstance(n, int) or n < 1:
            return None
        from_ids = list(range(n))
    from_ids = sorted(int(d) for d in from_ids)
    if not (set(int(d) for d in to_ids) < set(from_ids)):
        return None
    return from_ids


class ElasticMeshSupervisor:
    """Owns the mesh for one pipeline run and shrinks it on device loss.

    Stage closures must read :attr:`mesh` at CALL time (not capture the
    mesh object): after a loss the property serves the rebuilt, smaller
    mesh, and the retrying stage re-enters against it. A mesh shrunk to
    one device serves ``None`` — the serial path, which the parity tests
    pin as result-identical to every mesh size.
    """

    def __init__(self, devices: Optional[List[Any]] = None,
                 axis_name: Optional[str] = None,
                 auto: bool = True):
        from scconsensus_tpu.parallel.mesh import CELL_AXIS

        self.axis_name = axis_name or CELL_AXIS
        # auto=True follows the auto_mesh policy (all visible devices,
        # serial below 2); an explicit device list pins the starting mesh
        self._auto = devices is None and auto
        self._devices = list(devices) if devices is not None else None
        self._mesh = None
        self._mesh_built = False
        self.min_devices = max(int(env_flag("SCC_ELASTIC_MIN_DEVICES")), 1)
        self.live_state_bytes = 0
        self.transitions = 0
        self._resume_stamped: set = set()

    # -- construction ------------------------------------------------------
    @classmethod
    def resolve(cls, mesh) -> Tuple[Optional["ElasticMeshSupervisor"], Any]:
        """The pipeline's mesh policy, supervised.

        ``mesh`` is refine()'s argument: "auto", an explicit Mesh, or
        None. Returns ``(supervisor, initial_mesh)`` — supervisor is None
        when SCC_ELASTIC is off (the pre-elastic behavior, byte-for-byte:
        the caller uses ``initial_mesh`` directly). A serial (None) run
        still gets a supervisor: it cannot lose a device, but it CAN
        resume artifacts checkpointed on a larger mesh, and that shrink
        must be stamped.
        """
        if not elastic_enabled():
            if mesh == "auto":
                from scconsensus_tpu.parallel.mesh import auto_mesh

                mesh = auto_mesh()
            return None, mesh
        if mesh == "auto":
            sup = cls(auto=True)
        elif mesh is None:
            sup = cls(devices=[], auto=False)
        else:
            sup = cls(devices=list(mesh.devices.flat), auto=False,
                      axis_name=(str(mesh.axis_names[0])
                                 if mesh.axis_names else None))
        return sup, sup.mesh

    def _device_list(self) -> List[Any]:
        if self._devices is None:
            import jax

            self._devices = list(jax.devices())
        return self._devices

    @property
    def mesh(self):
        """The current mesh (None = serial). Rebuilt lazily after a
        shrink; repeat reads between transitions return the same Mesh
        object so the sharded engines' jit caches keep hitting."""
        if not self._mesh_built:
            devs = self._device_list()
            if len(devs) < 2:
                self._mesh = None  # the auto_mesh serial policy
            else:
                from scconsensus_tpu.parallel.mesh import make_mesh

                self._mesh = make_mesh(devices=devs,
                                       axis_name=self.axis_name)
            self._mesh_built = True
        return self._mesh

    @property
    def n_devices(self) -> int:
        return max(len(self._device_list()), 1)

    def device_ids(self) -> List[int]:
        from scconsensus_tpu.parallel.mesh import mesh_device_ids

        return mesh_device_ids(self.mesh)

    def shape_meta(self) -> Dict[str, Any]:
        from scconsensus_tpu.parallel.mesh import mesh_shape_meta

        return mesh_shape_meta(self.mesh, self.axis_name)

    # -- live-state accounting --------------------------------------------
    def note_live_state(self, *arrays) -> None:
        """Declare the sharded working set (re-laid-out on every shrink);
        its byte count rides each transition's recovered_state_bytes."""
        total = 0
        for x in arrays:
            if x is None:
                continue
            nbytes = getattr(x, "nbytes", None)
            if nbytes is None and hasattr(x, "data"):  # scipy sparse
                nbytes = getattr(x.data, "nbytes", 0)
            total += int(nbytes or 0)
        self.live_state_bytes = total

    # -- in-process device loss -------------------------------------------
    @staticmethod
    def _probe_device(dev) -> bool:
        """One tiny round-trip through the device. A lost/preempted chip
        raises out of device_put or the ready-wait; a WEDGED (silently
        hanging) device is the stall watchdog's territory, not ours."""
        import jax

        try:
            x = jax.device_put(np.zeros(8, np.float32), dev)
            jax.block_until_ready(x)
            return True
        except Exception:
            return False

    def survivors(self) -> List[Any]:
        devs = self._device_list()
        return [d for d in devs if self._probe_device(d)]

    def shrink(self, stage: str) -> None:
        """Rebuild the mesh on surviving devices after a device_lost
        failure at ``stage``. Probe-identified casualties are dropped
        exactly; an indistinct loss (every device still answers — the
        injected-fault case, and real transient mesh wedges) halves onto
        the lowest-id devices, so the shrink ladder is deterministic:
        8 → 4 → 2 → 1. Raises :class:`DeviceLossUnrecoverable` at the
        ``SCC_ELASTIC_MIN_DEVICES`` floor."""
        with robust_record.timed():
            before = self._device_list()
            from_ids = sorted(
                int(d.id) for d in before
            ) if before else [0]
            alive = self.survivors()
            if len(alive) >= len(before):
                # indistinct failure: deterministic halving, keep low ids
                alive = sorted(before, key=lambda d: int(d.id))
                alive = alive[: max(len(alive) // 2, 1)]
            if len(alive) < self.min_devices or not alive or (
                len(alive) >= len(before) and before
            ):
                raise DeviceLossUnrecoverable(
                    f"device lost at {stage} with no smaller mesh to "
                    f"shrink to ({len(before)} -> {len(alive)} devices; "
                    f"floor SCC_ELASTIC_MIN_DEVICES={self.min_devices})"
                )
            self._devices = list(alive)
            self._mesh_built = False  # next .mesh read rebuilds
            try:
                # pinned upload-cache buffers may live on the lost
                # device; evict so the re-entered stage re-stages its
                # inputs instead of consuming a dead buffer
                from scconsensus_tpu.utils.devcache import clear_cache

                clear_cache()
            except Exception:
                pass
            to_ids = sorted(int(d.id) for d in alive)
            self.transitions += 1
            robust_record.note_mesh_transition(
                stage=stage, from_devices=from_ids, to_devices=to_ids,
                recovered_state_bytes=self.live_state_bytes,
                cause="device_loss",
            )
            from scconsensus_tpu.utils.logging import get_logger

            get_logger().warning(
                "elastic mesh: device loss at %s — mesh shrunk %d -> %d "
                "devices (%s); stage re-enters from its last completed "
                "checkpoint", stage, len(before), len(alive), to_ids,
            )

    def loss_handler(self, stage: str):
        """The ``on_device_loss`` hook for robust.retry at ``stage``."""
        def _handle(_attempt: int) -> None:
            self.shrink(stage)

        return _handle

    # -- shape-polymorphic resume -----------------------------------------
    def note_artifact_meta(self, stage: str,
                           meta: Optional[Dict[str, Any]]) -> None:
        """Called when a stage resumes from a stored artifact: if the
        artifact was computed on a LARGER mesh than this run's, stamp the
        shape-polymorphic crossing as a ``cause: "resume"`` transition
        (once per (stage, shape) pair — a ladder of bucket checkpoints
        from one dead run is one transition, not fifty)."""
        to_ids = self.device_ids()
        from_ids = resume_crossing_from_ids(meta, to_ids)
        if from_ids is None:
            return  # same shape, growth, or no stamp — not a crossing
        key = (stage, tuple(from_ids), tuple(to_ids))
        if key in self._resume_stamped:
            return
        self._resume_stamped.add(key)
        self.transitions += 1
        size = int(((meta or {}).get("_integrity") or {}).get("size") or 0)
        robust_record.note_mesh_transition(
            stage=stage, from_devices=from_ids, to_devices=to_ids,
            recovered_state_bytes=size, cause="resume",
        )
        from scconsensus_tpu.utils.logging import get_logger

        get_logger().info(
            "elastic mesh: stage %r resumed a checkpoint written on %d "
            "device(s) onto %d device(s) — shape-polymorphic resume",
            stage, len(from_ids), len(to_ids),
        )
