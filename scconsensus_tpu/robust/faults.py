"""Deterministic fault injection at named sites (``SCC_FAULT_PLAN``).

A fault plan is a JSON file::

    {"seed": 1,
     "faults": [
       {"site": "stage:embed", "class": "oom",       "times": 1},
       {"site": "wilcox_bucket", "class": "transient", "after": 2},
       {"site": "stage:cuts",  "class": "kill"},
       {"site": "artifact:tree", "class": "corrupt", "mode": "truncate"},
       {"site": "stage:de",    "class": "stall", "stall_s": 0.5}
     ]}

Each rule fires on hits ``after <= n < after + times`` of its site
(0-based, ``after`` defaults 0, ``times`` defaults 1) — fully
deterministic, so the fault-matrix test can assert exact recovery
behavior. Sites are the pipeline's stage boundaries (``stage:<name>``),
the DE ladder's buckets (``wilcox_bucket``), the devcache upload
(``input_staging``), artifact writes (``artifact:<stage>``, consumed
by :func:`corrupt_artifact` rather than :func:`fault_point`), and the
mesh engines' entries (``sharded:aggregates``, ``sharded:ranksum``,
``ring:distance_sums``, ``refine_step``) — the elastic plans' way of
killing a mesh INSIDE a collective rather than at a stage boundary —
and the serving driver's three sites (``serve_load`` at model load,
``serve_batch`` at micro-batch assembly, ``serve_device`` inside the
device classify call), so ``tools/chaos_run.py`` soaks the online path
the same way it soaks the pipeline; the serving model's write-time
corruption rides the generic ``artifact:consensus_model`` site. The
serving FLEET (round 16) adds three more: ``wire_request`` (the HTTP
front's classify handler, before admission), ``fleet_route`` (the
pool's shared admission layer, before a replica is picked) and
``fleet_swap`` (the start of a hot-swap, before v2 loads) — a fault
there must surface as a typed, counted wire outcome, never a dead
socket. The out-of-core STREAMING layer (round 17) registers three
disk-axis sites: ``stream_chunk_write`` (each chunk/stage write of the
ChunkedCSRStore — where ``kill`` plans prove mid-ingest durability and
``disk`` plans prove the ENOSPC degradation ladder),
``stream_chunk_read`` (each chunk load — torn-chunk plans ride the
generic ``artifact:stream_chunk`` corrupt site instead, since the
corruption happens post-write), and ``stream_stage`` (the streaming
runner's per-stage boundary, the ``stage:<name>`` analog of the
out-of-core pipeline).

Fault classes and what they do at a compute site:

  oom        raise :class:`InjectedResourceExhausted` (message carries
             ``RESOURCE_EXHAUSTED`` so the classifier sees exactly what a
             real XLA allocation failure looks like)
  transient  raise :class:`InjectedTransientError` (``UNAVAILABLE``)
  device_loss
             raise :class:`InjectedDeviceLoss` (message carries the
             ``device lost``/``FAILED_PRECONDITION`` signature a real
             lost/preempted chip stringifies to) — the elastic mesh
             supervisor's test vector (robust.elastic)
  kill       SIGKILL the process — no handler runs, the artifact store's
             atomicity and the mid-stage checkpoints are what survive
  stall      sleep ``stall_s`` (default 1.0) without raising — exercises
             the r9 stall watchdog path
  corrupt    no-op at compute sites; at ``artifact:<stage>`` sites the
             store calls :func:`corrupt_artifact` after a successful
             write, which truncates or bit-flips the file on disk — the
             checksum/quarantine path's test vector
  disk       raise :class:`InjectedDiskFault` (message carries the exact
             ``ENOSPC``/``No space left on device`` text a full
             filesystem raises) — the streaming layer's disk-class
             test vector (stream.store, robust.retry "disk")
  corruption no-op at :func:`fault_point`; consumed by
             :func:`corrupt_value` at the named IN-COMPUTATION sites
             (``wilcox_bucket_out``, ``embed_scores``, ``bh_logq``,
             ``landmark_assign``, ``stream_block``, ``serve_classify``,
             ``contingency_table``) — a seeded perturbation of freshly
             computed VALUES (scale / sign-bit flip / index shift),
             distinct from the post-write artifact ``corrupt`` class.
             The computation-integrity layer's test vector
             (robust.integrity, round 18): every documented corruption
             site must be detected by an invariant or ghost-replay
             check and recovered via the typed ``silent_corruption``
             recompute. A rule with ``"device": D`` only fires while
             device D is in the caller's live mesh — a specific chip
             that computes wrong until the elastic supervisor evicts
             it.

With ``SCC_FAULT_PLAN`` unset every entry point is a single registry
lookup returning immediately — the zero-fault overhead contract.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from scconsensus_tpu.config import env_flag

__all__ = [
    "FAULT_CLASSES",
    "InjectedFault",
    "InjectedResourceExhausted",
    "InjectedTransientError",
    "InjectedDeviceLoss",
    "InjectedDiskFault",
    "fault_point",
    "corrupt_artifact",
    "corrupt_value",
    "active",
    "reset",
]

FAULT_CLASSES = ("oom", "transient", "kill", "stall", "corrupt",
                 "device_loss", "disk", "corruption")


class InjectedFault(Exception):
    """Base of every plan-injected exception (so tests can catch the
    family while the classifier sees only the message text, exactly as
    it would for the real error)."""


class InjectedResourceExhausted(InjectedFault):
    """Mimics an XLA device allocation failure."""


class InjectedTransientError(InjectedFault):
    """Mimics a transient backend/RPC error."""


class InjectedDeviceLoss(InjectedFault):
    """Mimics a lost/preempted accelerator device (the XLA runtime
    stringifies these as FAILED_PRECONDITION/INTERNAL errors naming the
    device)."""


class InjectedDiskFault(InjectedFault):
    """Mimics a disk fault (ENOSPC by default — the message carries the
    exact ``No space left on device`` strerror text a real full
    filesystem raises, so the classifier sees what the OS would say).
    The out-of-core streaming layer's test vector (stream.store)."""


# plan cache: (path, mtime) -> parsed plan; hit counters reset on reload
_LOADED: Optional[Dict[str, Any]] = None
_LOADED_KEY: Optional[tuple] = None
_HITS: Dict[int, int] = {}


def reset() -> None:
    """Drop the cached plan + hit counters (tests switch plans in-process)."""
    global _LOADED, _LOADED_KEY
    _LOADED = None
    _LOADED_KEY = None
    _HITS.clear()


def _plan() -> Optional[Dict[str, Any]]:
    global _LOADED, _LOADED_KEY
    path = env_flag("SCC_FAULT_PLAN")
    if not path:
        if _LOADED is not None:
            reset()
        return None
    try:
        key = (path, os.path.getmtime(path))
    except OSError:
        return None
    if key != _LOADED_KEY:
        try:
            with open(path) as f:
                plan = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            # a malformed plan must be loud: silently running WITHOUT the
            # requested faults would let a chaos run pass vacuously
            raise ValueError(f"SCC_FAULT_PLAN {path!r} unreadable: {e}")
        faults = plan.get("faults")
        if not isinstance(faults, list):
            raise ValueError(
                f"SCC_FAULT_PLAN {path!r}: 'faults' must be a list"
            )
        for i, r in enumerate(faults):
            if r.get("class") not in FAULT_CLASSES:
                raise ValueError(
                    f"SCC_FAULT_PLAN {path!r}: faults[{i}].class must be "
                    f"one of {FAULT_CLASSES}, got {r.get('class')!r}"
                )
            if not r.get("site"):
                raise ValueError(
                    f"SCC_FAULT_PLAN {path!r}: faults[{i}] missing site"
                )
        _LOADED = plan
        _LOADED_KEY = key
        _HITS.clear()
    return _LOADED


def active() -> bool:
    """True iff a fault plan is loaded for this process."""
    return _plan() is not None


def _matches(site: str) -> List[tuple]:
    plan = _plan()
    if plan is None:
        return []
    out = []
    for i, rule in enumerate(plan.get("faults", ())):
        if rule.get("site") == site:
            out.append((i, rule))
    return out


def _fire(idx: int, rule: Dict[str, Any]) -> bool:
    """Advance the rule's hit counter; True when this hit is in the
    rule's firing window."""
    n = _HITS.get(idx, 0)
    _HITS[idx] = n + 1
    after = int(rule.get("after", 0))
    times = int(rule.get("times", 1))
    return after <= n < after + times


def fault_point(site: str) -> None:
    """The injection hook compute code calls at a named site. No plan ->
    immediate return. A firing rule acts per its class (see module doc);
    every injection is recorded on the run's robustness log BEFORE the
    action, so even a SIGKILL leaves the fault attributable (the partial
    flight record carries the log's live summary)."""
    rules = [(i, r) for i, r in _matches(site)
             if r.get("class") != "corruption"]
    # "corruption" rules are excluded BEFORE the counters advance: they
    # are consumed (and counted) by corrupt_value at the value sites, so
    # a site carrying both hooks cannot double-advance their windows
    if not rules:
        return
    from scconsensus_tpu.robust import record as _record

    # advance EVERY matching rule's hit counter before acting: a firing
    # rule raises, and skipping the siblings' bookkeeping would desync
    # their windows (hit counts must mean "times this site was reached",
    # independent of which rule acted)
    firing = [(idx, rule) for idx, rule in rules if _fire(idx, rule)]
    for idx, rule in firing[:1]:  # at most one action per visit
        fclass = rule["class"]
        _record.note_fault(site, fclass, seq=_HITS[idx] - 1)
        if fclass == "oom":
            raise InjectedResourceExhausted(
                f"RESOURCE_EXHAUSTED: injected device allocation failure "
                f"at {site} (SCC_FAULT_PLAN)"
            )
        if fclass == "transient":
            raise InjectedTransientError(
                f"UNAVAILABLE: injected transient backend error at {site} "
                "(SCC_FAULT_PLAN)"
            )
        if fclass == "device_loss":
            raise InjectedDeviceLoss(
                f"FAILED_PRECONDITION: device lost: injected device "
                f"preemption at {site} (SCC_FAULT_PLAN)"
            )
        if fclass == "disk":
            raise InjectedDiskFault(
                f"ENOSPC: No space left on device: injected disk fault "
                f"at {site} (SCC_FAULT_PLAN)"
            )
        if fclass == "kill":
            import signal

            # flush the robustness trail first: the whole point of the
            # kill class is testing what the NEXT process can resume from
            try:
                from scconsensus_tpu.obs.live import flush_active

                flush_active("signal")
            except Exception:
                pass
            os.kill(os.getpid(), signal.SIGKILL)
        if fclass == "stall":
            time.sleep(float(rule.get("stall_s", 1.0)))
        # "corrupt" rules are inert at compute sites (corrupt_artifact
        # consumes them at artifact:<stage> sites)


def corrupt_artifact(stage: str, path: str) -> bool:
    """Apply any ``artifact:<stage>`` corrupt rule to a just-written
    artifact file — called by the ArtifactStore AFTER its atomic replace,
    so the corruption models a post-write disk/transport fault that the
    load-time checksum must catch. ``mode``: 'truncate' (default — cut
    the file to 60%) or 'flip' (xor one mid-file byte). Returns True when
    a corruption was applied."""
    applied = False
    for idx, rule in _matches(f"artifact:{stage}"):
        if rule["class"] != "corrupt" or not _fire(idx, rule):
            continue
        from scconsensus_tpu.robust import record as _record

        _record.note_fault(f"artifact:{stage}", "corrupt",
                           seq=_HITS[idx] - 1)
        try:
            size = os.path.getsize(path)
            mode = rule.get("mode", "truncate")
            if mode == "flip" and size:
                with open(path, "r+b") as f:
                    f.seek(size // 2)
                    b = f.read(1)
                    f.seek(size // 2)
                    f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
            else:
                with open(path, "r+b") as f:
                    f.truncate(max(1, int(size * 0.6)))
            applied = True
        except OSError:
            pass
    return applied


def _perturb_one(x, mode: str, factor: float):
    """One array perturbed per ``mode`` — device arrays stay on device
    (jnp ops), host arrays stay host. Modes:

      scale     multiply every element by ``factor`` (float arrays) —
                a wrong-but-finite global scale, the signature of a
                shape-dependent code path gone wrong;
      signflip  flip the IEEE sign bit of the max-|x| FINITE element —
                one corrupted number (deterministic: the most
                significant entry, so detection cannot depend on where
                a random flip landed);
      shift     integer arrays: (x + 1) mod (max + 1) — every index
                wrong by one, occupancy totals conserved (the case
                only a ghost replay catches).
    """
    import numpy as _np

    is_host = isinstance(x, _np.ndarray)
    if is_host:
        xp = _np
        arr = x
    else:
        import jax.numpy as xp  # device array: perturb in place on device

        arr = x
    if mode == "shift" or not xp.issubdtype(arr.dtype, xp.floating):
        k = xp.max(arr) + 1
        return ((arr + 1) % xp.maximum(k, 1)).astype(arr.dtype)
    if mode == "scale":
        return arr * xp.asarray(factor, dtype=arr.dtype)
    # signflip
    flat = xp.ravel(arr)
    mag = xp.where(xp.isfinite(flat), xp.abs(flat), -xp.inf)
    idx = xp.argmax(mag)
    if is_host:
        flat = flat.copy()
        flat[idx] = -flat[idx]
        return flat.reshape(arr.shape)
    return xp.reshape(flat.at[idx].set(-flat[idx]), arr.shape)


def corrupt_value(site: str, value, live_devices=None):
    """Apply any ``corruption``-class rule at an in-computation ``site``
    to freshly computed VALUES — the silent-corruption test vector the
    integrity layer (robust.integrity) must detect. ``value`` is one
    array or a tuple of arrays; the FIRST array is perturbed (rule key
    ``"index"`` picks another). Returns the same structure.

    ``live_devices``: the caller's current mesh device ids — a rule
    carrying ``"device": D`` fires only while D is live, so an evicted
    chip stops corrupting (the elastic-eviction soak's contract). Rules
    without a device pin always fire in their window. No plan → one
    registry lookup and return, like :func:`fault_point`."""
    rules = [(i, r) for i, r in _matches(site)
             if r.get("class") == "corruption"]
    if not rules:
        return value
    from scconsensus_tpu.robust import record as _record

    firing = [(idx, rule) for idx, rule in rules if _fire(idx, rule)]

    def _live(rule) -> bool:
        dev = rule.get("device")
        return (dev is None or live_devices is None
                or int(dev) in [int(d) for d in live_devices])

    # the liveness gate filters BEFORE one rule is picked: a rule
    # pinned to an evicted chip goes clean (the soak's contract)
    # without masking a co-firing unpinned rule at the same site
    for idx, rule in [fr for fr in firing if _live(fr[1])][:1]:
        _record.note_fault(site, "corruption", seq=_HITS[idx] - 1)
        mode = rule.get("mode", "scale")
        factor = float(rule.get("factor", 1.5))
        if isinstance(value, tuple):
            i = int(rule.get("index", 0))
            return tuple(
                _perturb_one(v, mode, factor) if k == i else v
                for k, v in enumerate(value)
            )
        return _perturb_one(value, mode, factor)
    return value
