"""Per-run robustness log + the validated ``robustness`` record section.

One module-level :class:`RunLog` per pipeline run (``begin_run()`` resets
it at ``refine()`` entry; engine/devcache code appends through the module
functions without threading a handle). The log becomes the run record's
additive ``robustness`` section::

    robustness: {
      faults_injected: [{site, class, seq}],
      retries:      [{site, error_class, attempts, recovered, backoff_s}],
      degradations: [{site, action, detail}],
      resume_points: [{stage, unit, completed, total}],
      mesh_transitions: [{stage, from_devices, to_devices,
                          recovered_state_bytes, cause}],
      recovered: bool,          # recovered retry, resume point, or
                                # mesh transition
      budget: {limit, used},
      consumed_s: float,        # self-measured robustness-layer overhead
      orchestration?: {...}     # bench.py attempt-ladder adaptations
    }

Validation contract (the perf-gate smoke pins it): ``recovered: true``
without evidence — no recovered retry AND no resume point AND no mesh
transition — is REJECTED, so a record cannot *claim* survival the run
never demonstrated. A ``mesh_transitions`` entry whose device set does
not SHRINK (to_devices must be a non-empty proper subset of
from_devices) is likewise rejected: elastic recovery only ever moves
onto survivors, so a growing or disjoint "transition" is evidence of a
corrupted record, not of recovery.

Budget persistence: with an artifact store active the pipeline arms
``set_budget_persist`` so every consumed retry lands in the store's
``robust_state`` sidecar; a kill-and-resume cycle re-seeds
``budget_used`` from it (``restore_budget``) instead of refreshing the
allowance.

Import discipline: this module must stay importable without jax (the
bench orchestrator and ``validate_run_record`` load it) — stdlib only.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from scconsensus_tpu.config import env_flag

__all__ = [
    "RunLog",
    "begin_run",
    "current_run",
    "note_fault",
    "note_retry",
    "note_degradation",
    "note_resume_point",
    "note_mesh_transition",
    "add_consumed",
    "section",
    "live_summary",
    "validate_robustness",
]

# capped: a retry storm must not grow a run record without bound (the
# counts stay exact; only the event lists truncate)
_LIST_CAP = 64


class RunLog:
    """Append-only robustness trail for one run (thread-safe: retries can
    fire from worker threads; the heartbeat sampler reads live)."""

    def __init__(self) -> None:
        self.faults: List[Dict[str, Any]] = []
        self.retries: List[Dict[str, Any]] = []
        self.degradations: List[Dict[str, Any]] = []
        self.resume_points: List[Dict[str, Any]] = []
        self.mesh_transitions: List[Dict[str, Any]] = []
        self.budget_limit = int(env_flag("SCC_ROBUST_BUDGET"))
        self.budget_used = 0
        self.consumed_s = 0.0
        self._n_dropped = 0
        self._budget_persist = None  # set_budget_persist (stdlib contract)
        self._lock = threading.Lock()

    def _append(self, lst: List[Dict[str, Any]], item: Dict[str, Any]):
        with self._lock:
            if len(lst) < _LIST_CAP:
                lst.append(item)
            else:
                self._n_dropped += 1

    def budget_take(self) -> bool:
        """Consume one retry from the per-run budget; False = exhausted
        (the caller must re-raise instead of retrying). Every take is
        mirrored through the persist hook (when armed), so a killed run
        cannot resurrect with a fresh allowance."""
        with self._lock:
            if self.budget_used >= self.budget_limit:
                return False
            self.budget_used += 1
            used, persist = self.budget_used, self._budget_persist
        if persist is not None:
            try:  # durability must not become a new failure mode
                persist(used)
            except Exception:
                pass
        return True

    def restore_budget(self, used: int) -> None:
        """Seed ``budget_used`` from a persisted resume checkpoint — takes
        the max so an in-process restore can never LOWER the count."""
        with self._lock:
            self.budget_used = max(self.budget_used, int(used))

    def set_budget_persist(self, fn) -> None:
        """Arm ``fn(used)`` to run after every budget take (the pipeline
        points this at the artifact store's robust_state sidecar)."""
        with self._lock:
            self._budget_persist = fn

    def empty(self) -> bool:
        return not (self.faults or self.retries or self.degradations
                    or self.resume_points or self.mesh_transitions
                    or self.budget_used)

    def section(self) -> Optional[Dict[str, Any]]:
        """The run record's ``robustness`` section, or None when nothing
        robustness-related happened (healthy runs carry no section —
        absence IS the healthy signal, and zero bytes of overhead)."""
        with self._lock:
            if self.empty():
                return None
            recovered = (
                any(r.get("recovered") for r in self.retries)
                or bool(self.resume_points)
                or bool(self.mesh_transitions)
            )
            out: Dict[str, Any] = {
                "faults_injected": [dict(f) for f in self.faults],
                "retries": [dict(r) for r in self.retries],
                "degradations": [dict(d) for d in self.degradations],
                "resume_points": [dict(p) for p in self.resume_points],
                "recovered": recovered,
                "budget": {"limit": self.budget_limit,
                           "used": self.budget_used},
                "consumed_s": round(self.consumed_s, 4),
            }
            if self.mesh_transitions:
                # absent on mesh-stable runs: the list only exists when
                # elastic execution actually moved the run between meshes
                out["mesh_transitions"] = [
                    dict(t) for t in self.mesh_transitions
                ]
            if self._n_dropped:
                out["events_dropped"] = self._n_dropped
            return out


_RUN: Optional[RunLog] = None


def begin_run() -> RunLog:
    """Fresh log for a new run (refine()/bench worker entry)."""
    global _RUN
    _RUN = RunLog()
    return _RUN


def current_run() -> RunLog:
    """The active run's log, lazily created so engine-level retries
    outside a pipeline (direct pairwise_de callers, devcache in tests)
    still record somewhere."""
    global _RUN
    if _RUN is None:
        _RUN = RunLog()
    return _RUN


def note_fault(site: str, fclass: str, seq: int = 0) -> None:
    current_run()._append(current_run().faults,
                          {"site": site, "class": fclass, "seq": int(seq)})


def note_retry(site: str, error_class: str, attempts: int,
               recovered: bool, backoff_s: float) -> None:
    current_run()._append(current_run().retries, {
        "site": site, "error_class": error_class,
        "attempts": int(attempts), "recovered": bool(recovered),
        "backoff_s": round(float(backoff_s), 4),
    })


def note_degradation(site: str, action: str, detail: str = "") -> None:
    current_run()._append(current_run().degradations, {
        "site": site, "action": action, "detail": detail,
    })


def note_resume_point(stage: str, unit: str, completed: int,
                      total: int) -> None:
    """Record that ``stage`` re-entered from persisted mid-stage state:
    ``completed`` of ``total`` ``unit``s were loaded instead of
    recomputed. This is the evidence ``recovered: true`` requires."""
    current_run()._append(current_run().resume_points, {
        "stage": stage, "unit": unit,
        "completed": int(completed), "total": int(total),
    })


def note_mesh_transition(stage: str, from_devices, to_devices,
                         recovered_state_bytes: int = 0,
                         cause: str = "device_loss") -> None:
    """Record an elastic mesh transition: the run moved from the
    ``from_devices`` mesh onto the smaller ``to_devices`` mesh at
    ``stage`` — either in-process (a lost device, cause="device_loss")
    or across a checkpoint boundary (a shape-polymorphic resume onto a
    smaller mesh, cause="resume"). ``recovered_state_bytes`` counts the
    live sharded state re-laid-out / checkpoint bytes re-adopted."""
    current_run()._append(current_run().mesh_transitions, {
        "stage": stage,
        "from_devices": [int(d) for d in from_devices],
        "to_devices": [int(d) for d in to_devices],
        "recovered_state_bytes": int(recovered_state_bytes),
        "cause": str(cause),
    })


def add_consumed(dt: float) -> None:
    run = current_run()
    with run._lock:
        run.consumed_s += max(float(dt), 0.0)


class timed:
    """``with timed():`` accumulates the block's wall onto the run's
    self-measured overhead (the <2% zero-fault guard reads it)."""

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        add_consumed(time.perf_counter() - self._t0)
        return False


def section() -> Optional[Dict[str, Any]]:
    return _RUN.section() if _RUN is not None else None


def live_summary() -> Optional[Dict[str, Any]]:
    """Compact counters for one heartbeat tick (None = nothing to say)."""
    run = _RUN
    if run is None or run.empty():
        return None
    with run._lock:
        out: Dict[str, Any] = {}
        if run.faults:
            out["faults"] = len(run.faults)
        if run.retries:
            out["retries"] = len(run.retries)
            out["last_retry"] = dict(run.retries[-1])
        if run.degradations:
            out["degradations"] = len(run.degradations)
        if run.resume_points:
            out["resumes"] = len(run.resume_points)
        if run.mesh_transitions:
            # live mesh panel feed (tail_run): current device count =
            # the latest transition's destination
            last = run.mesh_transitions[-1]
            out["mesh"] = {
                "transitions": len(run.mesh_transitions),
                "devices": len(last.get("to_devices") or []),
                "path": " → ".join(
                    [str(len(run.mesh_transitions[0].get("from_devices")
                             or []))]
                    + [str(len(t.get("to_devices") or []))
                       for t in run.mesh_transitions]
                ),
            }
        return out or None


# --------------------------------------------------------------------------
# schema validation
# --------------------------------------------------------------------------

_ERROR_CLASSES = ("transient", "resource", "disk", "silent_corruption",
                  "device_lost", "fatal")
_TRANSITION_CAUSES = ("device_loss", "resume")


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"robustness section: {msg}")


def validate_robustness(rb: Dict[str, Any]) -> None:
    """Structural validation of a record's ``robustness`` section;
    ``export.validate_run_record`` calls this. The load-bearing rule: a
    section claiming ``recovered: true`` must carry evidence — at least
    one recovered retry or one resume point — or it is rejected."""
    from scconsensus_tpu.robust.faults import FAULT_CLASSES

    _require(isinstance(rb, dict), "must be an object")
    for key in ("faults_injected", "retries", "degradations",
                "resume_points", "mesh_transitions"):
        v = rb.get(key, [])
        _require(isinstance(v, list), f"{key} must be a list")
        for i, item in enumerate(v):
            _require(isinstance(item, dict), f"{key}[{i}] must be an object")
    for i, f in enumerate(rb.get("faults_injected", [])):
        _require(bool(f.get("site")), f"faults_injected[{i}] missing site")
        _require(f.get("class") in FAULT_CLASSES,
                 f"faults_injected[{i}].class must be one of "
                 f"{FAULT_CLASSES}, got {f.get('class')!r}")
    for i, r in enumerate(rb.get("retries", [])):
        _require(bool(r.get("site")), f"retries[{i}] missing site")
        _require(r.get("error_class") in _ERROR_CLASSES,
                 f"retries[{i}].error_class must be one of "
                 f"{_ERROR_CLASSES}, got {r.get('error_class')!r}")
        att = r.get("attempts")
        _require(isinstance(att, int) and att >= 1,
                 f"retries[{i}].attempts must be an int >= 1")
        _require(isinstance(r.get("recovered"), bool),
                 f"retries[{i}].recovered must be a bool")
    for i, d in enumerate(rb.get("degradations", [])):
        _require(bool(d.get("site")) and bool(d.get("action")),
                 f"degradations[{i}] needs site and action")
    for i, p in enumerate(rb.get("resume_points", [])):
        _require(bool(p.get("stage")), f"resume_points[{i}] missing stage")
        comp, tot = p.get("completed"), p.get("total")
        _require(isinstance(comp, int) and comp >= 0,
                 f"resume_points[{i}].completed must be an int >= 0")
        _require(isinstance(tot, int) and tot >= comp,
                 f"resume_points[{i}].total must be an int >= completed")
    for i, t in enumerate(rb.get("mesh_transitions", [])):
        where = f"mesh_transitions[{i}]"
        _require(bool(t.get("stage")), f"{where} missing stage")
        src, dst = t.get("from_devices"), t.get("to_devices")
        _require(isinstance(src, list) and isinstance(dst, list),
                 f"{where}: from_devices/to_devices must be lists")
        _require(len(dst) >= 1, f"{where}: to_devices must be non-empty "
                                "(a mesh cannot shrink to zero devices)")
        # the shrink rule: elastic recovery only ever moves onto
        # SURVIVORS, so the destination must be a strict subset of the
        # source — anything else (growth, disjoint sets, same set) is a
        # corrupted or fabricated transition, not recovery evidence
        _require(
            set(dst) < set(src),
            f"{where}: device sets must shrink (to_devices must be a "
            f"proper subset of from_devices; got {src} -> {dst})",
        )
        rsb = t.get("recovered_state_bytes", 0)
        _require(isinstance(rsb, int) and rsb >= 0,
                 f"{where}.recovered_state_bytes must be an int >= 0")
        cause = t.get("cause", "device_loss")
        _require(cause in _TRANSITION_CAUSES,
                 f"{where}.cause must be one of {_TRANSITION_CAUSES}, "
                 f"got {cause!r}")
    if rb.get("recovered"):
        has_evidence = (
            any(r.get("recovered") for r in rb.get("retries", []))
            or bool(rb.get("resume_points"))
            or bool(rb.get("mesh_transitions"))
        )
        _require(
            has_evidence,
            "recovered claimed without evidence (no recovered retry and "
            "resume_points empty) — a run cannot claim survival it never "
            "demonstrated",
        )
    budget = rb.get("budget")
    if budget is not None:
        _require(isinstance(budget, dict), "budget must be an object")
        lim, used = budget.get("limit"), budget.get("used")
        _require(isinstance(lim, int) and lim >= 0,
                 "budget.limit must be an int >= 0")
        _require(isinstance(used, int) and 0 <= used,
                 "budget.used must be an int >= 0")
    orch = rb.get("orchestration")
    if orch is not None:
        _require(isinstance(orch, dict), "orchestration must be an object")
        _require(isinstance(orch.get("attempts", []), list),
                 "orchestration.attempts must be a list")
